"""Training orchestration (reference `alphatriangle/training/`).

The reference's orchestration is Ray plumbing: actor fan-out, object
store weight broadcasts, `ray.wait` harvesting (`loop.py:298-416`,
`worker_manager.py:39-209`). Device-batched self-play removes all of it:
one process alternates rollout chunks with learner steps, and the only
"broadcast" is a device-buffer swap. What remains — cadences, stop
conditions, checkpoint triggers, metric events, exit codes — is
capability parity.
"""

from .components import TrainingComponents
from .loop import LoopStatus, TrainingLoop
from .runner import run_training
from .setup import setup_training_components

__all__ = [
    "LoopStatus",
    "TrainingComponents",
    "TrainingLoop",
    "run_training",
    "setup_training_components",
]
