"""Top-level `run_training` (reference `training/runner.py:166-307`).

Responsibilities kept at parity: logging setup, auto-resume resolution,
component setup, initial-state load (train state + buffer + counters),
loop run, final save, exit-code mapping. Dropped by design: Ray init/
shutdown, actor kill fallbacks, MLflow bootstrapping (TensorBoard only
in this environment).
"""

import json
import logging
import os
import signal
import threading

from ..config.env_config import EnvConfig
from ..config.mcts_config import MCTSConfig
from ..config.mesh_config import MeshConfig
from ..config.model_config import ModelConfig
from ..config.persistence_config import PersistenceConfig
from ..config.telemetry_config import TelemetryConfig
from ..config.train_config import TrainConfig
from ..logging_config import setup_logging
from ..parallel.distributed import (
    DistributedConfig,
    initialize_distributed,
    is_primary,
)
from ..stats.persistence import CheckpointManager
from ..telemetry.flight import PREEMPT_EXIT_CODE
from ..utils.helpers import (
    enable_persistent_compilation_cache,
    enforce_platform,
)
from .loop import LoopStatus, TrainingLoop
from .setup import setup_training_components

logger = logging.getLogger(__name__)

EXIT_CODES = {
    LoopStatus.COMPLETED: 0,
    LoopStatus.STOPPED: 0,
    LoopStatus.ERROR: 1,
    LoopStatus.PREEMPTED: PREEMPT_EXIT_CODE,
}

#: JSON env var of TrainConfig field overrides injected by
#: `cli supervise` (supervise/supervisor.py OVERRIDES_ENV): the
#: recovery policy's degraded/quarantined knobs reach the child here,
#: regardless of which CLI flags spawned it. `<FIELD>__scale` keys
#: multiply the current value (min 1) instead of replacing it.
SUPERVISE_OVERRIDES_ENV = "ALPHATRIANGLE_SUPERVISE_OVERRIDES"


def _apply_supervise_overrides(train_config: TrainConfig) -> TrainConfig:
    raw = os.environ.get(SUPERVISE_OVERRIDES_ENV)
    if not raw:
        return train_config
    try:
        overrides = json.loads(raw)
    except ValueError:
        logger.warning(
            "Unparseable %s=%r; ignoring.", SUPERVISE_OVERRIDES_ENV, raw
        )
        return train_config
    if not isinstance(overrides, dict) or not overrides:
        return train_config
    # Reserved telemetry directives ride the same override channel but
    # are NOT TrainConfig fields — pop them before construction. The
    # only one today: `TELEMETRY__BEACONS` (the policy sets it on a
    # wedge respawn) arms progress beacons process-wide BEFORE any
    # engine compiles, so the rebuilt programs phase themselves into
    # beacons.jsonl (telemetry/device_stats.py).
    telemetry_keys = {
        k: overrides.pop(k)
        for k in [k for k in overrides if k.startswith("TELEMETRY__")]
    }
    if telemetry_keys.get("TELEMETRY__BEACONS"):
        from ..telemetry.device_stats import arm_beacons

        arm_beacons()
        logger.warning(
            "Supervisor directive TELEMETRY__BEACONS: progress beacons "
            "armed for this respawn."
        )
    if not overrides:
        return train_config
    resolved: dict = {}
    for key, value in overrides.items():
        if key.endswith("__scale"):
            field = key[: -len("__scale")]
            current = getattr(train_config, field)
            resolved[field] = max(1, round(current * float(value)))
        else:
            resolved[key] = value
    logger.warning(
        "Supervisor recovery overrides active: %s", resolved
    )
    # Rebuild through the constructor so pydantic validation runs
    # (mirrors cli.merge_train_overrides) and derived schedule lengths
    # stay untouched — the horizon is not a recovery knob.
    base = train_config.model_dump()
    base.update(resolved)
    return TrainConfig(**base)


def _install_preempt_handler(loop: TrainingLoop):
    """Route SIGTERM into `loop.request_preempt()` (main thread only —
    signal.signal raises elsewhere, and library callers embedding
    run_training in a thread keep their own handling). Returns a
    restore callback. SIGINT keeps its KeyboardInterrupt semantics
    (exit 0, reference behavior); SIGTERM is the preemption contract."""
    if threading.current_thread() is not threading.main_thread():
        return lambda: None

    def _on_sigterm(signum, frame):
        logger.warning(
            "SIGTERM received: preempting (emergency checkpoint, then "
            "exit %d).",
            PREEMPT_EXIT_CODE,
        )
        loop.request_preempt()

    previous = signal.signal(signal.SIGTERM, _on_sigterm)
    return lambda: signal.signal(signal.SIGTERM, previous)


def _resolve_auto_resume(
    train_config: TrainConfig, persistence: PersistenceConfig
) -> tuple[TrainConfig, PersistenceConfig]:
    """Point RUN_NAME at the newest checkpointed run when auto-resume is
    on and that run isn't this one already (reference `README.md:23`,
    `setup.py:174-176`)."""
    if not train_config.AUTO_RESUME_LATEST:
        return train_config, persistence
    latest = CheckpointManager.find_latest_run(persistence)
    if latest is None or latest == train_config.RUN_NAME:
        return train_config, persistence
    logger.info("Auto-resume: continuing latest run '%s'.", latest)
    return (
        train_config.model_copy(update={"RUN_NAME": latest}),
        persistence.model_copy(update={"RUN_NAME": latest}),
    )


def run_training(
    train_config: TrainConfig | None = None,
    env_config: EnvConfig | None = None,
    model_config: ModelConfig | None = None,
    mcts_config: MCTSConfig | None = None,
    mesh_config: MeshConfig | None = None,
    persistence_config: PersistenceConfig | None = None,
    distributed_config: DistributedConfig | None = None,
    telemetry_config: TelemetryConfig | None = None,
    log_level: str = "INFO",
    use_tensorboard: bool = True,
    dry_setup: bool = False,
) -> int:
    """Run a full training session; returns a process exit code.

    `dry_setup` stops after component construction (mesh, network,
    buffer, trainer, telemetry) and returns 0 without training — the
    cheapest end-to-end proof that a config (e.g. a `cli tune` preset)
    is actually runnable on this backend (`cli train --dry-setup`)."""
    setup_logging(log_level)
    train_config = train_config or TrainConfig()
    train_config = _apply_supervise_overrides(train_config)
    # Must precede any backend init (a site hook can override the env
    # var and point a CPU-intended run at a possibly-wedged TPU).
    enforce_platform(train_config.DEVICE)
    if train_config.DEVICE_REPLAY == "on" or train_config.FUSED_MEGASTEP:
        # Forced device replay may land on the CPU backend (tests,
        # smokes). XLA:CPU's async dispatch deadlocks under the
        # device-replay thread topology, and the flag is latched at CPU
        # client creation — so it must be set HERE, before any backend
        # touch (see rl/device_buffer.py module docstring). No effect
        # on accelerator backends.
        import jax

        jax.config.update("jax_cpu_enable_async_dispatch", False)
    # Cluster membership must also precede backend init.
    multi_host = initialize_distributed(distributed_config)
    if multi_host and not is_primary():
        # Secondary hosts run compute + collective saves, no dashboards.
        use_tensorboard = False
    persistence_config = persistence_config or PersistenceConfig(
        RUN_NAME=train_config.RUN_NAME
    )
    train_config, persistence_config = _resolve_auto_resume(
        train_config, persistence_config
    )
    # Backend resolves here anyway (setup compiles programs next); with
    # it known, the persistent compile cache can be gated correctly —
    # an auto run that landed on CPU must NOT cache (XLA:CPU AOT
    # reloads carry a SIGILL risk), an accelerator run should.
    import jax

    enable_persistent_compilation_cache(backend=jax.default_backend())

    try:
        components = setup_training_components(
            train_config=train_config,
            env_config=env_config,
            model_config=model_config,
            mcts_config=mcts_config,
            mesh_config=mesh_config,
            persistence_config=persistence_config,
            telemetry_config=telemetry_config,
            use_tensorboard=use_tensorboard,
        )
    except Exception:
        logger.exception("Component setup failed.")
        return 1

    if dry_setup:
        components.stats.close()
        components.checkpoints.close()
        logger.info(
            "Dry setup OK: components constructed for run '%s' "
            "(no training performed).",
            train_config.RUN_NAME,
        )
        return 0

    loop = TrainingLoop(components)
    try:
        if train_config.LOAD_CHECKPOINT_PATH:
            loaded = components.checkpoints.restore_path(
                train_config.LOAD_CHECKPOINT_PATH, components.trainer.state
            )
        else:
            loaded = components.checkpoints.restore(
                components.trainer.state, buffer=components.buffer
            )
        if train_config.LOAD_BUFFER_PATH:
            components.checkpoints.restore_buffer_path(
                components.buffer, train_config.LOAD_BUFFER_PATH
            )
        if loaded.train_state is not None:
            components.trainer.set_state(loaded.train_state)
            components.trainer.sync_to_network()
            loop.set_initial_state(
                loaded.global_step,
                int(loaded.counters.get("episodes_played", 0)),
                int(loaded.counters.get("total_simulations", 0)),
            )
            loop.weight_updates = int(
                loaded.counters.get("weight_updates", 0)
            )
            logger.info(
                "Resumed at step %d (%d episodes, buffer %s).",
                loaded.global_step,
                loop.episodes_played,
                len(components.buffer),
            )
    except Exception:
        # Training a fresh model into an existing run's directory would
        # pollute its checkpoints; abort instead (the user can disable
        # AUTO_RESUME_LATEST or fix the path).
        logger.exception(
            "State restore failed for run '%s'; aborting rather than "
            "writing a fresh model into its run directory.",
            train_config.RUN_NAME,
        )
        return 1

    restore_handler = _install_preempt_handler(loop)
    try:
        status = loop.run()
    finally:
        restore_handler()
    components.stats.close()
    components.checkpoints.close()
    logger.info("Training finished: %s", status.value)
    return EXIT_CODES[status]
