"""The training loop (reference `training/loop.py:23-416`).

Three orchestration modes over device-batched self-play:

- **Synchronous** (default): each iteration plays a rollout chunk
  (`ROLLOUT_CHUNK_MOVES` moves of all `SELF_PLAY_BATCH_SIZE` games),
  folds the harvest into the replay buffer, then runs learner steps —
  auto-matched to the production rate unless
  `LEARNER_STEPS_PER_ROLLOUT` pins it.
- **Overlapped** (`ASYNC_ROLLOUTS=True`): a producer thread plays
  chunks into a bounded queue while the main thread folds harvests and
  runs learner steps gated by an explicit `REPLAY_RATIO` — the
  reference's async producer/consumer topology
  (`training/loop.py:298-416`, `worker_manager.py:106-167`)
  re-expressed for one process; queue depth and achieved replay ratio
  are exported as gauges.
- **Fused megastep** (`FUSED_MEGASTEP=True`, rl/megastep.py): rollout
  chunk + device-ring ingest + on-device PER sampling + K fused
  learner steps as ONE device program per iteration (Anakin,
  arXiv:2104.06272) — one dispatch, one stats fetch, zero-staleness
  weights. The dispatches-per-iteration gauge (telemetry/perf.py)
  makes the difference visible across all three modes.

Cadences are parity knobs:
weight sync every `WORKER_UPDATE_FREQ_STEPS` learner steps
(`loop.py:271-287`), checkpoint every `CHECKPOINT_SAVE_FREQ_STEPS`
(`loop.py:333-339`), buffer spill every `BUFFER_SAVE_FREQ_STEPS`
(`loop.py:341-349`), metric tick per iteration (`loop.py:390-391`).
"""

import logging
import os
import queue
import threading
import time
from collections import deque
from enum import Enum

import jax
import numpy as np

from ..profiling import ProfileSession
from ..stats.events import RawMetricEvent
from ..telemetry import RunTelemetry
from ..utils.helpers import format_eta
from .components import TrainingComponents

logger = logging.getLogger(__name__)


class LoopStatus(str, Enum):
    COMPLETED = "completed"
    STOPPED = "stopped"
    ERROR = "error"
    # SIGTERM absorbed: emergency checkpoint + buffer spill + telemetry
    # flush all ran; the runner exits PREEMPT_EXIT_CODE (114) so a
    # supervisor distinguishes a survivable preemption from a crash.
    PREEMPTED = "preempted"


class TrainingLoop:
    """Drives produce -> buffer -> train -> sync -> persist."""

    def __init__(self, components: TrainingComponents):
        self.c = components
        self.cfg = components.train_config
        self.stop_event = threading.Event()
        self._preempt_requested = False
        # Device-resident replay (rl/device_buffer.py): rollout payloads
        # stay on device and training batches are gathered there; the
        # loop moves only indices, counts and metrics over the link.
        self._device_replay = bool(
            getattr(components.buffer, "is_device", False)
        )

        self.global_step = 0
        self.episodes_played = 0
        self.total_simulations = 0
        # Root visits inherited through MCTS subtree reuse (0 unless
        # MCTSConfig.tree_reuse): feeds the leaf-evals/s gauge.
        self.total_reused_visits = 0
        self.weight_updates = 0
        self.experiences_added = 0  # this run (resume-independent)
        self._steps_this_run = 0
        self._producer_error: BaseException | None = None
        # Producer supervision (overlapped mode): crashed streams
        # report here and the consumer respawns them with backoff —
        # bounded retries, then the run aborts with the original error
        # (the reference only removes dead actors and degrades,
        # `worker_manager.py:153-159`; SURVEY §7.9 asked for restart).
        self._producer_failures: "queue.Queue" = queue.Queue()
        self._streams: dict[int, dict] = {}
        self.producer_restarts = 0
        # Pipelined learner (overlapped mode): fused groups dispatched
        # but not yet fetched, oldest first. Each entry is
        # (trainer handle, samples list).
        self._inflight: deque = deque()
        # Fused-megastep bookkeeping: the runner (setup-built or lazily
        # created), steady-state iteration count (one device dispatch
        # each — the counter the megastep tests assert on), and the
        # loop-wide iteration counter feeding the dispatches-per-
        # iteration gauge in every mode.
        self._megastep_runner = components.megastep
        self.megastep_iterations = 0
        self.iterations = 0
        # Async chunk auto-tune: producers publish one shared tuned
        # move count (first accurate measurement wins).
        self._tune_lock = threading.Lock()
        self._tuned_chunk_moves: int | None = None
        self._last_saved_step: int | None = None
        self._last_buffer_saved_step: int | None = None
        self._cadence_anchor = 0  # resume step; cadence baseline
        self._last_progress_time = time.monotonic()
        self._last_progress_step = 0
        # Telemetry (span tracer + heartbeat + watchdog + anomaly
        # screening) always runs unless configured off; manually
        # assembled components get a default instance.
        self.telemetry = components.telemetry or RunTelemetry(
            components.telemetry_config,
            run_dir=components.persistence_config.get_run_base_dir(),
            stats=components.stats,
            run_name=components.persistence_config.RUN_NAME,
        )
        components.telemetry = self.telemetry
        # Manually assembled components (tests, bench harnesses) skip
        # training/setup.py's flight attach; wire the recorder here so
        # every construction path records dispatches.
        for c in (components.self_play, components.trainer):
            if c is not None and getattr(c, "flight", None) is None:
                c.flight = self.telemetry.flight
        # Per-phase timers always run (ns-level overhead); the device
        # trace + metric export + json dump activate under --profile
        # (reference `worker.py:99-104`, TrainConfig.PROFILE_WORKERS).
        # The attached tracer records each phase occurrence as a span.
        self.profile = ProfileSession(
            enabled=self.cfg.PROFILE_WORKERS,
            profile_dir=components.persistence_config.get_profile_dir(),
            tracer=self.telemetry.tracer,
        )
        if self.cfg.FUSED_LEARNER_STEPS > self.cfg.WORKER_UPDATE_FREQ_STEPS:
            logger.warning(
                "FUSED_LEARNER_STEPS=%d > WORKER_UPDATE_FREQ_STEPS=%d: "
                "weights can only sync at group boundaries, so the "
                "effective sync cadence is the group size.",
                self.cfg.FUSED_LEARNER_STEPS,
                self.cfg.WORKER_UPDATE_FREQ_STEPS,
            )

    # --- preemption -------------------------------------------------------

    def request_preempt(self) -> None:
        """Ask the loop to stop for a preemption (SIGTERM): every mode
        checks `stop_event` per beat, so the loop falls through to the
        `run()` finally — emergency checkpoint, buffer spill, ledger/
        flight flush — then reports PREEMPTED instead of COMPLETED.
        Signal-handler safe (a bool + Event.set, no locks)."""
        self._preempt_requested = True
        self.stop_event.set()

    def _write_preempt_report(self) -> None:
        """Atomic preempt_report.json: the evidence `cli doctor` and
        the supervisor classify a 114 exit on. Written AFTER the
        emergency checkpoint so `checkpointed_step` is the step a
        restart actually resumes from."""
        from ..telemetry.flight import (
            PREEMPT_EXIT_CODE,
            PREEMPT_REPORT_FILENAME,
            write_preempt_report,
        )

        run_dir = self.c.persistence_config.get_run_base_dir()
        write_preempt_report(
            run_dir / PREEMPT_REPORT_FILENAME,
            {
                "kind": "preempt",
                "time": time.time(),
                "pid": os.getpid(),
                "step": self.global_step,
                "checkpointed_step": self._last_saved_step,
                "exit_code": PREEMPT_EXIT_CODE,
            },
        )

    # --- resume -----------------------------------------------------------

    def set_initial_state(
        self, global_step: int, episodes_played: int, total_simulations: int
    ) -> None:
        """Install resumed counters (reference `loop.py:72-86`)."""
        self.global_step = global_step
        self.episodes_played = episodes_played
        self.total_simulations = total_simulations
        self._last_progress_step = global_step
        self._cadence_anchor = global_step

    # --- iteration pieces -------------------------------------------------

    def _play_rollout(self, engine, moves: int) -> tuple:
        """One rollout chunk on `engine`: (stats result, device payload
        or None) — the device-replay branch expressed once."""
        if self._device_replay:
            return engine.play_moves_device(moves)
        return engine.play_moves(moves), None

    def _process_rollout(self) -> int:
        """One rollout chunk -> buffer. Returns experiences added."""
        result, payload = self._play_rollout(
            self.c.self_play, self.cfg.ROLLOUT_CHUNK_MOVES
        )
        return self._fold_result(result, payload=payload)

    def _fold_result(self, result, trace=None, payload=None, added=None) -> int:
        """Fold one self-play harvest into the buffer + metrics.

        `trace` is the producing engine's per-chunk diagnostics; when
        None (sync mode, single producer) the primary engine's
        `last_trace` is read directly. `payload` is the device-resident
        experience block in device-replay mode (scattered into the
        on-device ring; `result` then carries stats only). `added`
        short-circuits the buffer write entirely — megastep mode, where
        the rows were already scattered in-program and only the count
        came back.
        """
        c = self.c
        if added is not None:
            pass  # rows landed in the device ring inside the megastep
        elif payload is not None:
            added = c.buffer.ingest_payload(payload)
        else:
            c.buffer.add_dense(
                result.grid,
                result.other_features,
                result.policy_target,
                result.value_target,
                policy_weight=result.policy_weight,
            )
            added = result.num_experiences
        self.episodes_played += result.num_episodes
        self.total_simulations += result.total_simulations
        self.total_reused_visits += result.total_reused_visits
        step = self.global_step
        events = [
            RawMetricEvent(
                name="Buffer/Size", value=len(c.buffer), global_step=step
            ),
            RawMetricEvent(
                name="SelfPlay/Experiences_Per_Chunk",
                value=added,
                global_step=step,
            ),
        ]
        if result.num_episodes:
            events += [
                RawMetricEvent(
                    name="SelfPlay/Episode_Score",
                    value=float(np.mean(result.episode_scores)),
                    global_step=step,
                ),
                RawMetricEvent(
                    name="SelfPlay/Episode_Length",
                    value=float(np.mean(result.episode_lengths)),
                    global_step=step,
                ),
                RawMetricEvent(
                    name="Progress/Episodes_Played",
                    value=self.episodes_played,
                    global_step=step,
                ),
                RawMetricEvent(
                    name="SelfPlay/Truncated_Fraction",
                    value=result.num_truncated / result.num_episodes,
                    global_step=step,
                ),
                RawMetricEvent(
                    name="SelfPlay/Staleness_Steps",
                    value=(
                        self._version_clock()
                        - float(np.mean(result.episode_start_versions))
                        if result.episode_start_versions
                        else self._version_clock()
                        - result.trainer_step_at_episode_start
                    ),
                    global_step=step,
                ),
            ]
        if trace is None:
            trace = getattr(c.self_play, "last_trace", None)
        if trace is not None and "wasted_slots" in trace:
            # Per-move diagnostics, chunk-aggregated (the reference's
            # per-move mcts_step/step_reward events, `worker.py:141-164`,
            # at per-chunk granularity). Wasted slots per
            # docs/MCTS_DESIGN.md §c.
            events += [
                RawMetricEvent(
                    name="SelfPlay/Wasted_Slot_Fraction",
                    # Normalize per move by the sims that actually ran
                    # (varies per move under playout cap randomization).
                    value=float(
                        np.mean(
                            trace["wasted_slots"]
                            / np.maximum(
                                np.asarray(trace["sims"])[:, None], 1
                            )
                        )
                    ),
                    global_step=step,
                ),
                RawMetricEvent(
                    name="SelfPlay/Step_Reward",
                    value=float(np.mean(trace["reward"])),
                    global_step=step,
                ),
                RawMetricEvent(
                    name="SelfPlay/Root_Value",
                    value=float(np.mean(trace["root_value"])),
                    global_step=step,
                ),
            ]
            if c.self_play.mcts_fast is not None:
                # Playout-cap randomization: achieved full-search rate
                # this chunk (target = MCTSConfig.full_search_prob).
                # Gated on PCR being enabled — without it the fraction
                # is a constant 1.0 and only pollutes dashboards.
                events.append(
                    RawMetricEvent(
                        name="SelfPlay/Full_Search_Fraction",
                        value=float(np.mean(trace["is_full"])),
                        global_step=step,
                    )
                )
        c.stats.log_batch_events(events)
        self.experiences_added += added
        self.telemetry.on_rollout(added, result.num_episodes)
        return added

    def _version_clock(self) -> int:
        """The weights-version clock staleness is measured against:
        the eval wrapper's sync version normally, the learner step in
        megastep mode (episodes there are tagged with the live step —
        zero-staleness by construction, and `net.weights_version` only
        advances at the unrelated sync cadence)."""
        if self.cfg.FUSED_MEGASTEP:
            return self.c.trainer.global_step
        return self.c.net.weights_version

    def _record_step(self, metrics: dict, td_errors, indices, step: int) -> None:
        """Per-learner-step bookkeeping: priorities, counters, events.

        `step` is the learner step this result belongs to — within a
        fused group the trainer's counter is already at the group end,
        so events must carry their own per-step x-value. `indices` is
        None in megastep mode: the runner already reconciled the host
        PER mirror from the device program's sampled slots.
        """
        c = self.c
        if indices is not None:
            c.buffer.update_priorities(indices, td_errors)
        self.global_step = step
        self._steps_this_run += 1
        events = [
            RawMetricEvent(
                name=f"Loss/{key}", value=val, global_step=step
            )
            for key, val in metrics.items()
            if key.endswith("loss")
        ]
        events += [
            RawMetricEvent(
                name="LearningRate",
                value=metrics["learning_rate"],
                global_step=step,
            ),
            RawMetricEvent(
                name="Loss/Entropy", value=metrics["entropy"], global_step=step
            ),
            RawMetricEvent(
                name="Loss/Grad_Norm",
                value=metrics["grad_norm"],
                global_step=step,
            ),
        ]
        if self.cfg.USE_PER:
            events.append(
                RawMetricEvent(
                    name="PER/Beta",
                    value=c.buffer.beta(step),
                    global_step=step,
                )
            )
        c.stats.log_batch_events(events)
        # Liveness beat + streaming anomaly screen (loss spikes,
        # grad-norm explosions, non-finite values, entropy collapse)
        # over this step's metrics, under their stats-pipeline names.
        self.telemetry.on_learner_step(
            step,
            {
                **{
                    f"Loss/{key}": val
                    for key, val in metrics.items()
                    if key.endswith("loss")
                },
                "Loss/Grad_Norm": metrics["grad_norm"],
                "Loss/Entropy": metrics["entropy"],
            },
        )
        if os.environ.get("ALPHATRIANGLE_FAULTS"):
            # Chaos-harness hook (supervise/faults.py): step-indexed
            # faults (sigterm/sigkill/crash at step N) fire here, after
            # the step's bookkeeping is complete.
            from ..supervise.faults import fault_point

            fault_point("step", step)

    def _maybe_sync_weights(self, prev_step: int) -> None:
        """Push learner params when (prev_step, global_step] crossed a
        WORKER_UPDATE_FREQ_STEPS multiple (reference `loop.py:271-287`).

        One sync per call regardless of how many multiples the group
        crossed — only the group-end params exist to install, so with
        FUSED_LEARNER_STEPS > WORKER_UPDATE_FREQ_STEPS the effective
        sync cadence is the group size (warned at loop start)."""
        freq = self.cfg.WORKER_UPDATE_FREQ_STEPS
        if self._crossed(self.global_step, freq, prev_step):
            with self.profile.phase("weight_sync"):
                self.c.trainer.sync_to_network()
            self.weight_updates += 1
            self.c.stats.log_scalar(
                "Progress/Weight_Updates_Total",
                self.weight_updates,
                self.global_step,
            )

    def _run_training_step(self) -> bool:
        """One sample -> train -> priority-update -> maybe sync cycle.

        Returns False when the buffer could not produce a batch
        (reference `loop.py:213-296`).
        """
        return self._run_training_steps(1) == 1

    def _learner_budget(self, allowed: int) -> int:
        """Steps the learner may still dispatch: the caller's allowance
        capped by MAX_TRAINING_STEPS, counting steps already inflight
        (inflight is empty outside the pipelined pump)."""
        budget = allowed
        if self.cfg.MAX_TRAINING_STEPS is not None:
            budget = min(
                budget,
                self.cfg.MAX_TRAINING_STEPS
                - self.global_step
                - self._inflight_steps(),
            )
        return budget

    def _sample_group(self, group: int) -> list:
        """Sample up to `group` training batches from the buffer.

        BATCH_SIZE is the GLOBAL batch; in a multi-host run each host
        samples its share from its local buffer and shard_batch
        assembles the global array (trainer returns local TD rows).
        The PER-beta clock is the trainer's dispatch-time step (equal
        to `global_step` whenever nothing is inflight).
        """
        local_batch = max(1, self.cfg.BATCH_SIZE // jax.process_count())
        with self.profile.phase("sample"):
            samples = []
            for _ in range(group):
                s = self.c.buffer.sample(
                    local_batch,
                    current_train_step=self.c.trainer.global_step,
                )
                if s is None:
                    break
                samples.append(s)
        return samples

    def _run_training_steps(self, max_steps: int) -> int:
        """Up to `max_steps` learner steps, dispatched in fused groups
        of `FUSED_LEARNER_STEPS`. Returns the number of steps run.

        Within a group, PER priorities update after the group's single
        dispatch (staleness bounded by the group size); sampling,
        checkpoint and weight-sync cadences run at group boundaries.
        """
        c = self.c
        k = max(1, self.cfg.FUSED_LEARNER_STEPS)
        ran = 0
        while ran < max_steps and not self.stop_event.is_set():
            budget = self._learner_budget(max_steps - ran)
            if budget <= 0:
                break
            group = min(k, budget)
            samples = self._sample_group(group)
            if not samples:
                break
            prev_step = self.global_step
            with self.profile.phase("train"):
                if self._device_replay:
                    if len(samples) == k and k > 1:
                        outs = c.trainer.train_steps_from(c.buffer, samples)
                    else:
                        # Tail groups ride K=1 programs one at a time
                        # (a fused program per distinct K would
                        # recompile), matching the host-path guard.
                        outs = []
                        for s in samples:
                            outs.extend(
                                c.trainer.train_steps_from(c.buffer, [s])
                            )
                elif len(samples) == k and k > 1:
                    outs = c.trainer.train_steps(
                        [s["batch"] for s in samples]
                    )
                else:
                    # Tail / short groups run as single steps: the
                    # per-step program is already compiled, while a
                    # fused program per distinct K would recompile.
                    outs = []
                    for s in samples:
                        out = c.trainer.train_step(s["batch"])
                        if out is None:
                            break
                        outs.append(out)
            if not outs:
                break
            for i, (s, (metrics, td_errors)) in enumerate(
                zip(samples, outs)
            ):
                self._record_step(
                    metrics, td_errors, s["indices"], prev_step + i + 1
                )
            ran += len(outs)
            self._maybe_sync_weights(prev_step)
            with self.profile.phase("checkpoint"):
                self._maybe_checkpoint()
            if len(outs) < group:
                break
        return ran

    def _crossed(self, step: int, freq: int, last: int | None) -> bool:
        """Did `step` cross a `freq` multiple since `last`? (Cadence
        check robust to steps advancing by more than 1 per call, as
        fused learner groups do.)"""
        anchor = last if last is not None else self._cadence_anchor
        return step > 0 and step // freq > anchor // freq

    def _ckpt_save_due(self, force: bool = False) -> bool:
        return force or self._crossed(
            self.global_step,
            self.cfg.CHECKPOINT_SAVE_FREQ_STEPS,
            self._last_saved_step,
        )

    def _buffer_save_due(self, force: bool = False) -> bool:
        return self.c.persistence_config.SAVE_BUFFER and (
            force
            or self._crossed(
                self.global_step,
                self.c.persistence_config.BUFFER_SAVE_FREQ_STEPS,
                self._last_buffer_saved_step,
            )
        )

    def _checkpoint_due(self) -> bool:
        """Either save cadence pending? The pipelined pump drains the
        inflight groups before `_maybe_checkpoint` whenever this is
        True; both sides call the same per-cadence predicates, so the
        drain decision and the save decision cannot drift apart."""
        return self._ckpt_save_due() or self._buffer_save_due()

    def _maybe_checkpoint(self, force: bool = False) -> None:
        c = self.c
        step = self.global_step
        due = self._ckpt_save_due(force)
        if due and self._last_saved_step != step:
            self._last_saved_step = step
            c.checkpoints.save(
                step,
                c.trainer.state,
                counters={
                    "episodes_played": self.episodes_played,
                    "total_simulations": self.total_simulations,
                    "weight_updates": self.weight_updates,
                },
            )
        save_buffer = self._buffer_save_due(force)
        # On force, always spill: late harvests may have been folded
        # into the buffer after a cadence save at this same step (the
        # async shutdown path does exactly that).
        if save_buffer and (force or self._last_buffer_saved_step != step):
            self._last_buffer_saved_step = step
            c.checkpoints.save_buffer(step, c.buffer)

    def _log_progress(self) -> None:
        now = time.monotonic()
        elapsed = now - self._last_progress_time
        if elapsed < 10.0:
            return
        steps = self.global_step - self._last_progress_step
        rate = steps / elapsed if elapsed > 0 else 0.0
        max_steps = self.cfg.MAX_TRAINING_STEPS
        eta = (
            format_eta((max_steps - self.global_step) / rate)
            if rate > 0 and max_steps
            else "?"
        )
        logger.info(
            "step %d/%s | %.2f steps/s | buffer %d | episodes %d | ETA %s",
            self.global_step,
            max_steps,
            rate,
            len(self.c.buffer),
            self.episodes_played,
            eta,
        )
        self._last_progress_time = now
        self._last_progress_step = self.global_step

    # --- main loop --------------------------------------------------------

    def _max_steps_reached(self) -> bool:
        max_steps = self.cfg.MAX_TRAINING_STEPS
        return max_steps is not None and self.global_step >= max_steps

    def run(self) -> LoopStatus:
        """Run until MAX_TRAINING_STEPS / stop / error
        (reference `loop.py:298-416`)."""
        status = LoopStatus.COMPLETED
        self.telemetry.start()
        try:
            if self.cfg.FUSED_MEGASTEP:
                self._run_megastep_mode()
            elif self.cfg.ASYNC_ROLLOUTS:
                self._run_async()
            else:
                self._run_sync()
        except KeyboardInterrupt:
            logger.warning("Interrupted; saving final state.")
            status = LoopStatus.STOPPED
        except Exception:
            logger.exception("Training loop error.")
            status = LoopStatus.ERROR
        finally:
            self.stop_event.set()
            try:
                self.profile.close()
                self._maybe_checkpoint(force=True)
                self.c.checkpoints.wait_until_finished()
                self.c.stats.force_process_and_log(self.global_step)
            except Exception:
                logger.exception("Final save failed.")
                status = LoopStatus.ERROR
            if self._preempt_requested:
                if status is not LoopStatus.ERROR:
                    status = LoopStatus.PREEMPTED
                self._write_preempt_report()
                logger.warning(
                    "Preempted at step %d (emergency checkpoint at "
                    "step %s); exiting for restart.",
                    self.global_step,
                    self._last_saved_step,
                )
            # Last: the final heartbeat + span-trace export cover the
            # shutdown work above too.
            try:
                self.telemetry.close(self.global_step)
            except Exception:
                logger.exception("Telemetry shutdown failed.")
        return status

    def _run_sync(self) -> None:
        cfg = self.cfg
        iteration = 0
        while not self.stop_event.is_set():
            if self._max_steps_reached():
                logger.info(
                    "Reached MAX_TRAINING_STEPS=%d.", cfg.MAX_TRAINING_STEPS
                )
                break
            self.profile.on_iteration(iteration)
            iteration += 1
            with self.profile.phase("rollout"):
                added = self._process_rollout()
            n_steps = cfg.LEARNER_STEPS_PER_ROLLOUT or max(
                1, round(added / cfg.BATCH_SIZE)
            )
            self._run_training_steps(n_steps)
            self._iteration_tail()

    # --- fused megastep (Anakin) ------------------------------------------

    def _megastep_ready(self, need: int) -> bool:
        """Warmup exit test: the ring can produce a training batch.

        Sharded ring: EVERY shard must additionally cover its B/dp
        stratum — the fused program samples per shard from device-local
        priorities, so one under-filled shard would sample garbage rows
        even when the global fill clears the threshold. (Warmup ingests
        stripe each device's own lanes into its own shard, so shards
        fill together; this is a correctness gate, not a throttle.)
        """
        buf = self.c.buffer
        if len(buf) < need:
            return False
        if getattr(buf, "is_sharded", False):
            b_local = self.cfg.BATCH_SIZE // buf.dp
            return int(buf._sizes.min()) >= b_local
        return True

    def _run_megastep_mode(self) -> None:
        """One device program per iteration: rollout chunk + ring
        ingest + on-device sampling + K learner steps (rl/megastep.py).

        Warm-up is host-orchestrated (rollout + ingest, no training)
        until the ring can produce a batch — the megastep program
        always trains, so dispatching it against a not-ready ring would
        sample garbage rows. From then on, ONE dispatch and ONE stats
        fetch per iteration; `megastep_iterations` vs the runner's
        `dispatch_count` is the counter the tests assert equal.
        """
        cfg = self.cfg
        runner = self._megastep_runner
        if runner is None:
            from ..rl.megastep import MegastepRunner

            runner = MegastepRunner(
                self.c.self_play, self.c.trainer, self.c.buffer, cfg
            )
            runner.flight = getattr(self.telemetry, "flight", None)
            self.c.megastep = self._megastep_runner = runner
        need = max(cfg.MIN_BUFFER_SIZE_TO_TRAIN, cfg.BATCH_SIZE)
        iteration = 0
        while not self.stop_event.is_set() and not self._megastep_ready(
            need
        ):
            self.profile.on_iteration(iteration)
            iteration += 1
            with self.profile.phase("rollout"):
                self._process_rollout()
            self._iteration_tail()
        # Device priorities pick up everything the warmup (and any
        # checkpoint restore before it) wrote into the host mirror.
        runner.sync_priorities_from_host()
        k_cfg = cfg.LEARNER_STEPS_PER_ROLLOUT or max(
            1, cfg.FUSED_LEARNER_STEPS
        )
        while not self.stop_event.is_set():
            if self._max_steps_reached():
                logger.info(
                    "Reached MAX_TRAINING_STEPS=%d.", cfg.MAX_TRAINING_STEPS
                )
                break
            # Tail groups shrink K to the remaining budget (a per-(T,K)
            # program compiles once, same contract as the fused paths).
            k = self._learner_budget(k_cfg)
            if k <= 0:
                break
            self.profile.on_iteration(iteration)
            iteration += 1
            prev_step = self.global_step
            with self.profile.phase("megastep"):
                outs, added = runner.run_megastep(
                    cfg.ROLLOUT_CHUNK_MOVES, k
                )
            self.megastep_iterations += 1
            self._fold_result(self.c.self_play.harvest(), added=added)
            for i, (metrics, td_errors) in enumerate(outs):
                self._record_step(
                    metrics, td_errors, None, prev_step + i + 1
                )
            self._maybe_sync_weights(prev_step)
            with self.profile.phase("checkpoint"):
                self._maybe_checkpoint()
            self._iteration_tail()

    # --- overlapped producer/consumer ------------------------------------

    def _producer_chunk_moves(self) -> int:
        """Current per-dispatch move count for producers (tuned or
        configured)."""
        with self._tune_lock:
            if self._tuned_chunk_moves is not None:
                return self._tuned_chunk_moves
        return self.cfg.ROLLOUT_CHUNK_MOVES

    def _maybe_tune_chunk(self, moves: int, dt: float, warmed: bool) -> None:
        """Auto-size async rollout dispatches from one clean measurement.

        A single flagship chunk is a multi-second device program; every
        learner dispatch queues behind it (device programs run FIFO),
        so the chunk length directly sets the learner's worst-case
        queue wait. The first post-compile chunk's wall time gives
        seconds/move; producers then dispatch
        `ASYNC_CHUNK_SECONDS / seconds_per_move` moves at a time. The
        measurement may include learner time slices (conservative:
        over-shrinks, never starves). One shared tuned size — streams
        reuse one compiled program.
        """
        target = self.cfg.ASYNC_CHUNK_SECONDS
        if target is None or not warmed:
            return
        with self._tune_lock:
            if self._tuned_chunk_moves is not None:
                return
            per_move = dt / max(moves, 1)
            tuned = max(
                1,
                min(self.cfg.ROLLOUT_CHUNK_MOVES, round(target / per_move)),
            )
            # Build the tuned size's jit wrapper here, inside the lock,
            # so producer threads don't race the engine's program cache
            # with concurrent first misses.
            if tuned != moves:
                self.c.self_play._chunk_fn(tuned)
                logger.info(
                    "Async chunk auto-tune: %.2fs/%d moves measured "
                    "(%.2fs/move) -> %d moves/dispatch for the %.1fs "
                    "target.",
                    dt,
                    moves,
                    per_move,
                    tuned,
                    target,
                )
            self._tuned_chunk_moves = tuned

    def _producer_loop(self, engine, out: "queue.Queue", stream: int = 0) -> None:
        """Self-play producer: play chunks, enqueue (harvest, trace).

        Runs in a daemon thread (one per rollout stream — the
        reference's NUM_SELF_PLAY_WORKERS actors, `setup.py:106-151`,
        become N independent device-batched streams sharing one queue).
        JAX dispatch is thread-safe; device compute serializes with the
        learner's, but the host-side work on all sides (harvest
        compaction here, PER sampling/priority updates there) overlaps
        with it. Weight syncs are picked up at the next chunk via
        `net.variables` (no broadcast; replaces reference
        `worker_manager.py:169-209`).
        """
        try:
            while not self.stop_event.is_set():
                moves = self._producer_chunk_moves()
                # Timed as "rollout" here — in async mode the producers
                # own the self-play device time; the consumer's queue
                # drain is timed separately as "fold". Chunk sizing is
                # settled before producers start (`_run_async`'s
                # uncontended measurement) — a producer-side sample
                # would include the other streams' queued programs.
                with self.profile.phase("rollout"):
                    result, payload = self._play_rollout(engine, moves)
                item = (result, engine.last_trace, payload)
                # Backpressure wait, timed per stream: persistent high
                # wait here means the consumer (fold + learner) is the
                # bottleneck, not self-play.
                with self.profile.phase(f"enqueue_wait/stream{stream}"):
                    while not self.stop_event.is_set():
                        try:
                            out.put(item, timeout=0.2)
                            break
                        except queue.Full:
                            continue
        except BaseException as exc:
            # Report to the supervisor (consumer thread), which
            # respawns the stream with backoff or — retries exhausted —
            # aborts the run with this error. Shutdown-time noise
            # (threads interrupted mid-dispatch by stop_event) is not
            # a crash.
            if not self.stop_event.is_set():
                self._producer_failures.put((stream, exc))

    # --- producer supervision (overlapped mode) ---------------------------

    def _spawn_producer_thread(
        self, engine, harvests: "queue.Queue", stream: int
    ) -> threading.Thread:
        t = threading.Thread(
            target=self._producer_loop,
            args=(engine, harvests, stream),
            name=f"self-play-producer-{stream}",
            daemon=True,
        )
        t.start()
        return t

    def _fresh_stream_engine(self, stream: int, attempt: int):
        """A replacement engine for a crashed stream: fresh carry and
        PRNG stream (the crashed engine's donated buffers may be
        invalidated mid-dispatch), compiled programs shared with the
        primary — rollout programs depend only on configs, so the
        respawn never recompiles."""
        from ..rl.self_play import SelfPlayEngine

        primary = self.c.self_play
        return SelfPlayEngine(
            primary.env,
            primary.extractor,
            primary.net,
            primary.mcts_config,
            primary.config,
            # The primary may carry an explicit batch-size override
            # (engine batch ≠ config SELF_PLAY_BATCH_SIZE); defaulting
            # here would make share_compiled reject every respawn and
            # burn all PRODUCER_MAX_RESTARTS on a config error.
            batch_size=primary.batch_size,
            seed=self.cfg.RANDOM_SEED + 2000 + stream * 100 + attempt,
            share_compiled=primary,
            mesh=primary.mesh,
            data_axes=primary.data_axes,
        )

    def _supervise_producers(self, harvests: "queue.Queue") -> None:
        """Respawn crashed producer streams with exponential backoff;
        abort the run (original exception) once a stream exhausts
        PRODUCER_MAX_RESTARTS."""
        now = time.monotonic()
        while True:
            try:
                stream, exc = self._producer_failures.get_nowait()
            except queue.Empty:
                break
            rec = self._streams[stream]
            if rec["restarts"] >= self.cfg.PRODUCER_MAX_RESTARTS:
                logger.error(
                    "Producer stream %d crashed and exhausted its %d "
                    "restarts; aborting run.",
                    stream,
                    self.cfg.PRODUCER_MAX_RESTARTS,
                )
                self._producer_error = exc
                self.stop_event.set()
                return
            delay = self.cfg.PRODUCER_RESTART_BACKOFF_S * (
                2 ** rec["restarts"]
            )
            rec["restarts"] += 1
            rec["retry_at"] = now + delay
            logger.warning(
                "Producer stream %d crashed (%s: %s); respawning in "
                "%.2fs (restart %d/%d).",
                stream,
                type(exc).__name__,
                exc,
                delay,
                rec["restarts"],
                self.cfg.PRODUCER_MAX_RESTARTS,
            )
        for stream, rec in self._streams.items():
            if rec.get("retry_at") is not None and now >= rec["retry_at"]:
                rec["retry_at"] = None
                rec["engine"] = self._fresh_stream_engine(
                    stream, rec["restarts"]
                )
                rec["thread"] = self._spawn_producer_thread(
                    rec["engine"], harvests, stream
                )
                self.producer_restarts += 1
                self.c.stats.log_scalar(
                    "System/Producer_Restarts",
                    self.producer_restarts,
                    self.global_step,
                )

    def _learner_steps_allowed(self) -> int:
        """Replay-ratio gate: steps the learner may run this instant.

        REPLAY_RATIO = samples consumed per experience produced, i.e.
        allowed steps = produced * ratio / BATCH_SIZE. Counted within
        this run so a resumed `global_step` doesn't starve the gate.
        Dispatched-but-unfetched pipeline groups count as consumed.
        """
        target = (
            self.experiences_added * self.cfg.REPLAY_RATIO / self.cfg.BATCH_SIZE
        )
        return max(
            0, int(target) - self._steps_this_run - self._inflight_steps()
        )

    # --- pipelined learner (overlapped mode) ------------------------------

    def _inflight_steps(self) -> int:
        return sum(handle["k"] for handle, _ in self._inflight)

    def _dispatch_learner_group(self, allowed: int) -> bool:
        """Sample + dispatch ONE fused group without fetching results.

        Returns True when a group went out. The dispatch returns as
        soon as the transfer is enqueued, so the group's device
        execution overlaps the consumer's queue draining and the NEXT
        group's sampling — and, crucially, sits in the device FIFO
        behind at most one producer chunk instead of idling a full
        round trip per group.
        """
        c = self.c
        k = max(1, self.cfg.FUSED_LEARNER_STEPS)
        group = min(k, self._learner_budget(allowed))
        if group <= 0 or self.stop_event.is_set():
            return False
        samples = self._sample_group(group)
        if not samples:
            return False
        with self.profile.phase("dispatch"):
            if self._device_replay:
                if len(samples) == k and k > 1:
                    handle = c.trainer.train_steps_from_begin(
                        c.buffer, samples
                    )
                    groups = [(handle, samples)] if handle is not None else []
                else:
                    groups = []
                    for s in samples:
                        handle = c.trainer.train_steps_from_begin(
                            c.buffer, [s]
                        )
                        if handle is None:
                            break
                        groups.append((handle, [s]))
            elif len(samples) == k and k > 1:
                handle = c.trainer.train_steps_begin(
                    [s["batch"] for s in samples]
                )
                groups = [(handle, samples)] if handle is not None else []
            else:
                # Short groups ride the per-step program one batch per
                # handle: a fused program per distinct group size would
                # recompile (same guard as _run_training_steps).
                groups = []
                for s in samples:
                    handle = c.trainer.train_steps_begin([s["batch"]])
                    if handle is None:
                        break
                    groups.append((handle, [s]))
        if not groups:
            return False
        self._inflight.extend(groups)
        return True

    def _finish_oldest_group(self) -> int:
        """Blocking fetch + bookkeeping for the oldest inflight group.

        Weight sync after a finish installs the trainer's CURRENT state
        — possibly one group fresher than the step label when another
        group is already inflight; fresher-than-labeled is harmless
        (self-play only ever wants the newest weights).
        """
        handle, samples = self._inflight.popleft()
        with self.profile.phase("train"):
            outs = self.c.trainer.train_steps_finish(handle)
        prev_step = self.global_step
        for i, (s, (metrics, td_errors)) in enumerate(zip(samples, outs)):
            self._record_step(
                metrics, td_errors, s["indices"], prev_step + i + 1
            )
        self._maybe_sync_weights(prev_step)
        return len(outs)

    def _drain_learner(self) -> int:
        ran = 0
        while self._inflight:
            ran += self._finish_oldest_group()
        return ran

    def _pump_learner(self, allowed: int) -> int:
        """One pipelined learner beat: dispatch group N+1, then fetch
        group N. Keeps exactly one group executing and one queued in
        steady state; empties naturally when the gate or buffer starves
        the dispatch. Checkpoints drain the pipeline first so the saved
        params and the step label agree exactly.
        """
        dispatched = self._dispatch_learner_group(allowed)
        ran = 0
        while len(self._inflight) >= 2:
            ran += self._finish_oldest_group()
        if self._inflight and not dispatched:
            ran += self._finish_oldest_group()
        if ran and self._checkpoint_due():
            ran += self._drain_learner()
            with self.profile.phase("checkpoint"):
                self._maybe_checkpoint()
        return ran

    def _make_rollout_streams(self) -> list:
        """The primary engine plus NUM_SELF_PLAY_WORKERS-1 extra
        independent streams (own carry + seed, shared net/weights).
        The count is clamped to the host/device budget (reference
        clamps its actors to cores-2, `setup.py:106-151`)."""
        from ..rl.self_play import SelfPlayEngine
        from .setup import clamp_self_play_workers

        primary = self.c.self_play
        streams = [primary]
        for i in range(1, clamp_self_play_workers(self.cfg.NUM_SELF_PLAY_WORKERS)):
            streams.append(
                SelfPlayEngine(
                    primary.env,
                    primary.extractor,
                    primary.net,
                    primary.mcts_config,
                    primary.config,
                    seed=self.cfg.RANDOM_SEED + 1000 + i,
                    share_compiled=primary,
                    mesh=primary.mesh,
                    data_axes=primary.data_axes,
                )
            )
        return streams

    def _run_async(self) -> None:
        cfg = self.cfg
        harvests: "queue.Queue" = queue.Queue(maxsize=cfg.ROLLOUT_QUEUE_MAX)
        # Materialize the shared chunk program's jit wrapper before the
        # producer threads race the lru_cache: concurrent first misses
        # may each build (and compile) their own wrapper.
        self.c.self_play._chunk_fn(cfg.ROLLOUT_CHUNK_MOVES)
        if cfg.ASYNC_CHUNK_SECONDS is not None:
            # Auto-size async dispatches from an UNCONTENDED measurement
            # taken before any producer or learner work exists: with N
            # streams already running, a producer's own chunk wall time
            # includes the other streams' queued programs and would
            # over-shrink the tuned size N-fold. Chunk 1 compiles;
            # chunk 2 times clean seconds/move. Both harvests feed the
            # buffer — nothing is thrown away. The timed window covers
            # the PLAY only (the fold/ingest is deferred past `dt`): the
            # tuned size targets device seconds per move, and folding a
            # chunk is host/ingest work that would inflate it.
            self._process_rollout()
            t0 = time.perf_counter()
            result, payload = self._play_rollout(
                self.c.self_play, cfg.ROLLOUT_CHUNK_MOVES
            )
            dt = time.perf_counter() - t0
            self._fold_result(result, payload=payload)
            self._maybe_tune_chunk(
                cfg.ROLLOUT_CHUNK_MOVES, dt, warmed=True
            )
        self._streams = {
            i: {
                "engine": engine,
                "thread": self._spawn_producer_thread(engine, harvests, i),
                "restarts": 0,
                "retry_at": None,
            }
            for i, engine in enumerate(self._make_rollout_streams())
        }
        iteration = 0
        try:
            while not self.stop_event.is_set():
                if self._max_steps_reached():
                    logger.info(
                        "Reached MAX_TRAINING_STEPS=%d.",
                        cfg.MAX_TRAINING_STEPS,
                    )
                    break
                self.profile.on_iteration(iteration)
                iteration += 1
                self._supervise_producers(harvests)
                # Drain everything available; block briefly only when
                # there is no learner work to do either.
                folded = 0
                with self.profile.phase("fold"):
                    while True:
                        try:
                            self._fold_result(*harvests.get_nowait())
                            folded += 1
                        except queue.Empty:
                            break
                    if (
                        folded == 0
                        and not self.stop_event.is_set()
                        and (
                            self._learner_steps_allowed() == 0
                            or not self.c.buffer.is_ready()
                        )
                    ):
                        try:
                            self._fold_result(*harvests.get(timeout=0.5))
                            folded += 1
                        except queue.Empty:
                            pass
                if self.cfg.PIPELINE_LEARNER:
                    steps_ran = self._pump_learner(
                        self._learner_steps_allowed()
                    )
                else:
                    steps_ran = self._run_training_steps(
                        self._learner_steps_allowed()
                    )
                if folded == 0 and steps_ran == 0:
                    # Gate open but the buffer can't produce a batch yet
                    # (or the trainer rejected one): don't busy-spin.
                    time.sleep(0.05)
                self.c.stats.log_scalar(
                    "System/Rollout_Queue_Depth",
                    harvests.qsize(),
                    self.global_step,
                )
                if self.experiences_added:
                    self.c.stats.log_scalar(
                        "System/Replay_Ratio_Actual",
                        self._steps_this_run
                        * cfg.BATCH_SIZE
                        / self.experiences_added,
                        self.global_step,
                    )
                self._iteration_tail()
        finally:
            self.stop_event.set()
            # Land any dispatched-but-unfetched learner groups so their
            # steps are recorded before the final checkpoint.
            try:
                self._drain_learner()
            except Exception:
                logger.exception("Draining inflight learner groups failed.")
            for rec in self._streams.values():
                rec["thread"].join(timeout=30.0)
                if rec["thread"].is_alive():
                    logger.warning(
                        "%s did not join within 30s.", rec["thread"].name
                    )
            # Fold any harvests still queued so the final checkpoint /
            # buffer spill includes everything that was actually played.
            while True:
                try:
                    self._fold_result(*harvests.get_nowait())
                except queue.Empty:
                    break
            if self._producer_error is not None:
                raise self._producer_error

    def _transfer_seconds(self) -> tuple[float, float]:
        """Cumulative host<->device transfer seconds: (h2d, d2h).

        h2d = the trainer's batch staging uploads; d2h = the trainer's
        result fetches plus every rollout engine's harvest fetches
        (engines are deduped — async streams include the primary)."""
        c = self.c
        h2d = float(getattr(c.trainer, "transfer_h2d_seconds", 0.0))
        d2h = float(getattr(c.trainer, "transfer_d2h_seconds", 0.0))
        engines = {id(c.self_play): c.self_play}
        for rec in self._streams.values():
            engine = rec.get("engine")
            if engine is not None:
                engines[id(engine)] = engine
        d2h += sum(
            float(getattr(e, "transfer_d2h_seconds", 0.0))
            for e in engines.values()
        )
        if self._megastep_runner is not None:
            d2h += float(self._megastep_runner.transfer_d2h_seconds)
        return h2d, d2h

    def _total_dispatches(self) -> int:
        """Cumulative device-program dispatches across every component
        (rollout engines, learner, ring ingest, megastep) — the
        numerator of the dispatches-per-iteration gauge that makes the
        megastep's one-dispatch iteration visible in `cli perf`."""
        c = self.c
        total = int(getattr(c.trainer, "dispatch_count", 0))
        total += int(getattr(c.buffer, "dispatch_count", 0))
        engines = {id(c.self_play): c.self_play}
        for rec in self._streams.values():
            engine = rec.get("engine")
            if engine is not None:
                engines[id(engine)] = engine
        total += sum(
            int(getattr(e, "dispatch_count", 0)) for e in engines.values()
        )
        if self._megastep_runner is not None:
            total += int(self._megastep_runner.dispatch_count)
        return total

    def _drain_device_stats(self) -> "dict | None":
        """The freshest in-program stat-pack fold (device-stats plane,
        telemetry/device_stats.py): the megastep runner's when fused,
        else the self-play engine's. Consumed once — the producer slot
        is cleared so an idle iteration doesn't re-ledger stale stats."""
        sources = []
        if self._megastep_runner is not None:
            sources.append(self._megastep_runner)
        sources.append(self.c.self_play)
        for rec in self._streams.values():
            engine = rec.get("engine")
            if engine is not None and engine is not self.c.self_play:
                sources.append(engine)
        for src in sources:
            ds = getattr(src, "last_device_stats", None)
            if ds:
                src.last_device_stats = None
                return ds
        return None

    def _iteration_tail(self) -> None:
        if self.cfg.PROFILE_WORKERS:
            for name, val in self.profile.timers.metrics().items():
                self.c.stats.log_scalar(name, val, self.global_step)
        # Device-stats record + the gauge mirror for metrics.prom /
        # `cli watch` (None on legacy/off runs — zero new fields then).
        ds = self._drain_device_stats()
        extra = {}
        if ds:
            self.telemetry.record_device_stats(self.global_step, **ds)
            search = ds.get("search") or {}
            if search.get("root_entropy") is not None:
                extra["root_visit_entropy"] = search["root_entropy"]
            if search.get("occupancy") is not None:
                extra["tree_occupancy"] = search["occupancy"]
            from ..telemetry.device_stats import beacons_armed

            extra["beacons_armed"] = int(beacons_armed())
        # Utilization record first (ledger + heartbeat fields), then the
        # heartbeat write (health.json) — before the stats tick so any
        # Anomaly/* or Health/* events logged this iteration flush too.
        h2d, d2h = self._transfer_seconds()
        self.iterations += 1
        # Cumulative sealed-dispatch wall feeds the chip-idle gauge
        # (telemetry/roofline.py); None on legacy/flight-off runs, so
        # those util records carry zero new fields.
        flight = getattr(self.telemetry, "flight", None)
        dispatch_wall = getattr(flight, "sealed_wall_seconds", None)
        self.telemetry.on_util_tick(
            self.global_step,
            episodes=self.episodes_played,
            experiences=self.experiences_added,
            simulations=self.total_simulations,
            reused_visits=self.total_reused_visits,
            buffer_size=len(self.c.buffer),
            transfer_h2d_s=h2d,
            transfer_d2h_s=d2h,
            dispatches=self._total_dispatches(),
            iterations=self.iterations,
            dispatch_wall_s=dispatch_wall,
            extra=extra or None,
        )
        self.telemetry.on_tick(self.global_step, len(self.c.buffer))
        self.c.stats.process_and_log(self.global_step)
        self._log_progress()
