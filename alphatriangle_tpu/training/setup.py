"""Component construction (reference `training/setup.py:29-239`).

The reference's setup is dominated by Ray: `ray.init` fallbacks, CPU
detection and worker clamping, detached-actor discovery. None of that
exists here — setup is pure object construction plus config validation,
mesh building, and checkpoint-manager creation. Errors propagate; there
are no actors to tear down on failure.
"""

import logging
import os

from ..config.env_config import EnvConfig
from ..config.mcts_config import MCTSConfig
from ..config.mesh_config import (
    MeshConfig,
    lane_shard_count,
    rollout_lane_axes,
)
from ..config.model_config import ModelConfig
from ..config.persistence_config import PersistenceConfig
from ..config.telemetry_config import TelemetryConfig
from ..config.train_config import TrainConfig
from ..config.validation import print_config_info_and_validate
from ..env.engine import TriangleEnv
from ..features.core import get_feature_extractor
from ..nn.network import NeuralNetwork
from ..parallel.distributed import is_primary
from ..rl.buffer import ExperienceBuffer
from ..rl.self_play import SelfPlayEngine
from ..rl.trainer import Trainer
from ..stats.collector import StatsCollector
from ..stats.persistence import CheckpointManager
from ..telemetry import RunTelemetry
from .components import TrainingComponents

logger = logging.getLogger(__name__)

# Each rollout stream keeps roughly one multi-second chunk program in
# the device FIFO at all times; past a few streams per chip the learner
# and the streams only inflate each other's queue waits.
MAX_STREAMS_PER_DEVICE = 4


def clamp_self_play_workers(requested: int) -> int:
    """Clamp rollout-stream count to the host + device budget.

    The reference clamps its Ray self-play actors to cores-2
    (`alphatriangle/training/setup.py:106-151`) because its actors ARE
    CPU-bound searchers. Streams here are producer threads driving
    device-batched engines: on an accelerator host they spend their
    lives blocked on device transfers (harvest compaction is light),
    so the binding budget is device dispatch depth
    (MAX_STREAMS_PER_DEVICE per local chip), not host cores — a 1-core
    TPU VM frontend legitimately runs several streams. Only when the
    "device" IS the host CPU does the reference's cores-2 rule apply
    unchanged. Returns the effective count, warning when it clamps.
    """
    import jax

    cores = os.cpu_count() or 1
    device_cap = MAX_STREAMS_PER_DEVICE * jax.local_device_count()
    if jax.default_backend() == "cpu":
        # The "device" IS the host: reference rule, cores-2.
        cap = max(1, min(cores - 2 if cores > 2 else 1, device_cap))
    else:
        # Accelerator host: threads are dispatch-bound, cores don't
        # bind — the per-chip dispatch budget is the whole cap.
        cap = max(1, device_cap)
    if requested > cap:
        logger.warning(
            "NUM_SELF_PLAY_WORKERS=%d exceeds this host's budget "
            "(%d cores, %d local device(s)); clamping to %d streams.",
            requested,
            cores,
            jax.local_device_count(),
            cap,
        )
        return cap
    return requested


def _make_buffer(
    train_config: TrainConfig,
    env_config: EnvConfig,
    model_config: ModelConfig,
    extractor,
    mesh,
) -> ExperienceBuffer:
    """Pick the replay-ring home per `TrainConfig.DEVICE_REPLAY`.

    Three tiers:
    - single-device, single-process mesh -> `DeviceReplayBuffer`
      (rl/device_buffer.py): the ring lives on the one chip;
    - dp-ONLY multi-device mesh (mdl == sp == 1, single process, with
      capacity and batch dividing dp) -> `ShardedDeviceReplayBuffer`
      (rl/sharded_device_buffer.py): the ring shards over dp and
      composes with dp-sharded rollouts into a fully device-local
      experience path;
    - anything else -> host buffer.

    "auto" additionally requires an accelerator backend: on the CPU
    backend host NumPy and "device" memory are the same RAM, so the
    scatter program would add overhead for nothing ("on" still forces
    it there — tests do).
    """
    import jax

    grid_shape = (
        model_config.GRID_INPUT_CHANNELS,
        env_config.ROWS,
        env_config.COLS,
    )
    mode = train_config.DEVICE_REPLAY
    single = jax.process_count() == 1 and mesh.devices.size == 1
    # First axis is data-parallel by convention (MeshConfig.build_mesh).
    dp = mesh.shape[mesh.axis_names[0]]
    sharded_ok = (
        jax.process_count() == 1
        and mesh.devices.size > 1
        and mesh.devices.size == dp  # dp-only: no mdl/sp replication
        and train_config.BUFFER_CAPACITY % dp == 0
        and train_config.BATCH_SIZE % dp == 0
        # The ingest shard_map splits payload lanes dp-ways, so the
        # rollout engine must actually be lane-sharded the same way —
        # a single-device engine's payload would crash the scatter.
        and train_config.SELF_PLAY_BATCH_SIZE % dp == 0
    )
    if train_config.FUSED_MEGASTEP and not (single or sharded_ok):
        # The megastep program samples and trains against a device-
        # resident ring: either the single-device ring or the dp-
        # sharded one (per-device ring shards + in-program shard_map
        # sampling, rl/megastep.py) — anything else has no ring for
        # the fused program to live in.
        raise ValueError(
            "FUSED_MEGASTEP needs a single-process mesh that is "
            "single-device, or dp-only with BUFFER_CAPACITY, "
            "BATCH_SIZE and SELF_PLAY_BATCH_SIZE divisible by dp "
            f"(got {dict(mesh.shape)}, {jax.process_count()} "
            "processes)."
        )
    want = (
        mode == "on"
        or (mode == "auto" and jax.default_backend() != "cpu")
        # Megastep requires the device ring wherever it runs (the CPU
        # backend included — the smoke/parity tier), exactly like an
        # explicit "on".
        or train_config.FUSED_MEGASTEP
    )
    if mode == "on" and not (single or sharded_ok):
        # An explicit force that can't be honored must not silently
        # substitute the other code path.
        raise ValueError(
            "DEVICE_REPLAY='on' needs a single-device mesh or a "
            "single-process dp-only mesh with BUFFER_CAPACITY, "
            "BATCH_SIZE and SELF_PLAY_BATCH_SIZE divisible by dp "
            f"(got {dict(mesh.shape)}, {jax.process_count()} "
            "processes); use DEVICE_REPLAY='auto' to fall back to "
            "the host buffer."
        )
    if want and single:
        from ..rl.device_buffer import DeviceReplayBuffer

        logger.info(
            "Device-resident replay ring: capacity %d on %s.",
            train_config.BUFFER_CAPACITY,
            jax.devices()[0],
        )
        return DeviceReplayBuffer(
            train_config,
            grid_shape=grid_shape,
            other_dim=extractor.other_dim,
            action_dim=env_config.action_dim,
        )
    if want and sharded_ok:
        from ..rl.sharded_device_buffer import ShardedDeviceReplayBuffer

        logger.info(
            "dp-sharded device replay ring: capacity %d over %d shards.",
            train_config.BUFFER_CAPACITY,
            dp,
        )
        return ShardedDeviceReplayBuffer(
            train_config,
            grid_shape=grid_shape,
            other_dim=extractor.other_dim,
            action_dim=env_config.action_dim,
            mesh=mesh,
            dp_axis=mesh.axis_names[0],
        )
    if want:
        logger.info(
            "DEVICE_REPLAY=%s: mesh %s not eligible for a device ring "
            "-> host buffer.",
            mode,
            dict(mesh.shape),
        )
    return ExperienceBuffer(train_config, action_dim=env_config.action_dim)


def setup_training_components(
    train_config: TrainConfig | None = None,
    env_config: EnvConfig | None = None,
    model_config: ModelConfig | None = None,
    mcts_config: MCTSConfig | None = None,
    mesh_config: MeshConfig | None = None,
    persistence_config: PersistenceConfig | None = None,
    telemetry_config: TelemetryConfig | None = None,
    use_tensorboard: bool = True,
) -> TrainingComponents:
    """Validate configs and build every training component."""
    configs = print_config_info_and_validate(
        env=env_config,
        model=model_config,
        train=train_config,
        mcts=mcts_config,
        mesh=mesh_config,
        persistence=persistence_config,
    )
    env_config = configs["env"]
    model_config = configs["model"]
    train_config = configs["train"]
    mcts_config = configs["mcts"]
    mesh_config = configs["mesh"]
    persistence_config = configs["persistence"]
    # The run's artifacts live under its RUN_NAME.
    if persistence_config.RUN_NAME != train_config.RUN_NAME:
        persistence_config = persistence_config.model_copy(
            update={"RUN_NAME": train_config.RUN_NAME}
        )

    # Resolve the telemetry config FIRST and publish the device-stats
    # flag process-wide: engines snapshot it at CONSTRUCTION (it shapes
    # their compiled programs and joins the AOT cache digests), so the
    # flag must be settled before SelfPlayEngine/Trainer exist. Set on
    # every process unconditionally — a primary-only gate would compile
    # DIFFERENT programs per process and deadlock a multi-host mesh.
    telemetry_config = telemetry_config or TelemetryConfig()
    from ..telemetry.device_stats import set_device_stats

    set_device_stats(
        telemetry_config.ENABLED and telemetry_config.DEVICE_STATS
    )

    try:
        mesh = mesh_config.build_mesh()
    except ValueError as exc:
        logger.warning("Mesh build failed (%s); single-device fallback.", exc)
        mesh = MeshConfig.single_device_mesh()

    env = TriangleEnv(env_config)
    extractor = get_feature_extractor(env, model_config)
    # Sequence-parallel attention when the mesh has a real sp axis
    # (otherwise a configured SP_SIZE would silently shard nothing and
    # halve effective throughput with replicated work).
    attention_fn = None
    if mesh.shape.get(mesh_config.SP_AXIS, 1) > 1:
        from ..parallel import make_sp_attention

        attention_fn = make_sp_attention(
            mesh,
            kind=mesh_config.SP_ATTENTION,
            sp_axis=mesh_config.SP_AXIS,
            dp_axis=mesh_config.DP_AXIS,
        )
        logger.info(
            "Sequence-parallel attention: %s over sp=%d",
            mesh_config.SP_ATTENTION,
            mesh.shape[mesh_config.SP_AXIS],
        )
    net = NeuralNetwork(
        model_config,
        env_config,
        seed=train_config.RANDOM_SEED,
        attention_fn=attention_fn,
    )
    trainer = Trainer(
        net, train_config, mesh=mesh, mdl_axis=mesh_config.MDL_AXIS
    )
    buffer = _make_buffer(train_config, env_config, model_config, extractor, mesh)
    # Multi-device mesh: shard the lockstep lanes so rollouts occupy
    # every chip, not just one of the learner's (the reference fans
    # self-play actors across hardware, `worker_manager.py:39-75`).
    # Lanes ride the dp axis plus sp when present — sequence
    # parallelism never applies to the board-sized rollout net, so a
    # real sp axis would otherwise sit idle (or worse, duplicate
    # rollout work) during self-play.
    sp_mesh = None
    sp_axes: tuple = ()
    if mesh.devices.size > 1:
        sp_axes = rollout_lane_axes(
            mesh, mesh_config.DP_AXIS, mesh_config.SP_AXIS
        )
        lane_shards = lane_shard_count(mesh, sp_axes)
        if train_config.SELF_PLAY_BATCH_SIZE % lane_shards == 0:
            sp_mesh = mesh
            logger.info(
                "Self-play lanes sharded over mesh axes %s (%d-way).",
                sp_axes,
                lane_shards,
            )
        else:
            logger.warning(
                "SELF_PLAY_BATCH_SIZE=%d does not divide the mesh's "
                "%d lane shards %s; self-play stays on one device "
                "(pick a divisible batch to fan rollouts across the "
                "mesh).",
                train_config.SELF_PLAY_BATCH_SIZE,
                lane_shards,
                sp_axes,
            )
    self_play = SelfPlayEngine(
        env,
        extractor,
        net,
        mcts_config,
        train_config,
        seed=train_config.RANDOM_SEED + 1,
        mesh=sp_mesh,
        data_axes=sp_axes or ("dp",),
    )
    # Fused megastep (rl/megastep.py): one device program per iteration
    # runs rollout + ingest + on-device PER sampling + K learner steps;
    # the runner binds the engine/trainer/ring triple built above.
    megastep_runner = None
    if train_config.FUSED_MEGASTEP:
        from ..rl.megastep import MegastepRunner

        megastep_runner = MegastepRunner(
            self_play, trainer, buffer, train_config
        )
        logger.info(
            "Fused megastep mode: %d moves + %d learner steps per "
            "mesh dispatch (%d-way dp-sharded).",
            train_config.ROLLOUT_CHUNK_MOVES,
            train_config.LEARNER_STEPS_PER_ROLLOUT
            or max(1, train_config.FUSED_LEARNER_STEPS),
            megastep_runner.dp,
        )
    # TensorBoard and the live-console JSONL are singleton host-side
    # work: process 0 only (N processes appending one shared file would
    # interleave diverging step/episode lines and corrupt `cli watch`'s
    # windowed rates).
    stats = StatsCollector(
        persistence_config,
        use_tensorboard=use_tensorboard and is_primary(),
        use_live_file=is_primary(),
    )
    checkpoints = CheckpointManager(persistence_config)
    # Telemetry (spans + heartbeat + watchdog + anomaly screening) is a
    # primary-process concern like the live file: N hosts rewriting one
    # shared health.json would interleave diverging heartbeats.
    telemetry_config = telemetry_config or TelemetryConfig()
    if not is_primary():
        telemetry_config = telemetry_config.model_copy(
            update={"ENABLED": False}
        )
    # Live MFU/throughput accounting (telemetry/perf.py): analytic
    # FLOPs from the run's own model/env configs, peak from the device
    # kind table or the ALPHATRIANGLE_PEAK_TFLOPS override. Feeds the
    # metrics ledger, health.json and `cli watch`.
    import jax

    from ..telemetry.perf import UtilizationMeter
    from ..utils.flops import forward_flops, train_step_flops

    device = jax.devices()[0]
    perf_meter = UtilizationMeter(
        forward_flops=forward_flops(
            model_config, env_config, env_config.action_dim
        ),
        train_step_flops=train_step_flops(
            model_config,
            env_config,
            env_config.action_dim,
            train_config.BATCH_SIZE,
        ),
        device_kind=str(getattr(device, "device_kind", device.platform)),
        buffer_capacity=train_config.BUFFER_CAPACITY,
        # Gauge denominator contract: dispatch counters tally mesh-level
        # program launches (one per host dispatch, however many devices
        # the mesh spans), so the meter records the mesh width beside
        # them instead of scaling them by it.
        mesh_devices=mesh.devices.size,
    )
    telemetry = RunTelemetry(
        telemetry_config,
        run_dir=persistence_config.get_run_base_dir(),
        stats=stats,
        run_name=persistence_config.RUN_NAME,
        perf=perf_meter,
    )
    # Every processed metric batch is appended to the durable ledger —
    # including the loop's final force flush and the collector's own
    # close-time flush (docs/OBSERVABILITY.md "Ledger").
    stats.set_tick_sink(telemetry.record_metrics)
    # Compile costs become `compile/<program>` spans in trace.json: the
    # AOT executable cache (compile_cache.py) reports every hit
    # (deserialize), miss (fresh compile) and serialize through the
    # run's tracer, so cold-vs-warm start cost is visible next to the
    # rollout/learner spans it delays.
    from ..compile_cache import get_compile_cache

    get_compile_cache().set_tracer(telemetry.tracer)
    # Dispatch flight recorder (telemetry/flight.py): every hot-family
    # device dispatch writes an intent record before launch and a seal
    # after the fetch, so a SIGKILLed window still names the program it
    # died inside (`cli doctor`).
    self_play.flight = telemetry.flight
    trainer.flight = telemetry.flight
    if megastep_runner is not None:
        megastep_runner.flight = telemetry.flight
    # Static memory attribution -> metrics ledger (telemetry/memory.py):
    # train-state bytes from tree-size accounting, replay-ring bytes
    # from the buffers' own dtype/shape math. Program records join
    # lazily as each program compiles (compile_cache memory capture);
    # `cli mem <run>` renders the combined table from artifacts alone.
    try:
        from ..telemetry.memory import replay_ring_record, replay_ring_bytes, train_state_record

        telemetry.record_memory(train_state_record(trainer.state))
        if hasattr(buffer, "memory_record"):
            telemetry.record_memory(buffer.memory_record())
        else:
            telemetry.record_memory(
                replay_ring_record(
                    replay_ring_bytes(
                        train_config.BUFFER_CAPACITY,
                        (
                            model_config.GRID_INPUT_CHANNELS,
                            env_config.ROWS,
                            env_config.COLS,
                        ),
                        extractor.other_dim,
                        env_config.action_dim,
                    ),
                    train_config.BUFFER_CAPACITY,
                    location="host",
                )
            )
    except Exception:
        logger.exception("static memory attribution failed (continuing)")
    # Compiler cost ground truth for the learner-side family
    # (telemetry/roofline.py): on CPU those programs bypass the AOT
    # dispatch path (cpu_aot=False), so nothing would ever capture
    # their `cost_analysis()` — analyze once at setup. On accelerators
    # this doubles as a warm-up: the analyzed executable is the cached
    # one the first dispatch reuses. Best-effort like the block above;
    # ALPHATRIANGLE_COST_PRECAPTURE=0 skips it (the test suite — the
    # compile is pure overhead in seconds-long throwaway runs).
    from ..telemetry.roofline import cost_precapture_enabled

    if telemetry.enabled and cost_precapture_enabled():
        try:
            if megastep_runner is not None:
                megastep_runner.analyze_megastep()
            else:
                trainer.analyze_step()
        except Exception:
            logger.exception("cost pre-capture failed (continuing)")
    all_configs = {
        "env": env_config,
        "model": model_config,
        "train": train_config,
        "mcts": mcts_config,
        "mesh": mesh_config,
        "persistence": persistence_config,
        "telemetry": telemetry_config,
    }
    checkpoints.save_configs(all_configs)
    # Experiment-param channel (reference `logging_utils.py:13-35`).
    stats.log_params(all_configs)
    logger.info(
        "Components ready: mesh %s, self-play batch %d, run %s",
        dict(mesh.shape),
        self_play.batch_size,
        persistence_config.RUN_NAME,
    )
    return TrainingComponents(
        env=env,
        extractor=extractor,
        net=net,
        buffer=buffer,
        trainer=trainer,
        self_play=self_play,
        stats=stats,
        checkpoints=checkpoints,
        env_config=env_config,
        model_config=model_config,
        train_config=train_config,
        mcts_config=mcts_config,
        mesh_config=mesh_config,
        persistence_config=persistence_config,
        telemetry=telemetry,
        telemetry_config=telemetry_config,
        megastep=megastep_runner,
    )
