"""Component bundle (reference `training/components.py:22-36`)."""

from dataclasses import dataclass, field
from typing import Any

from ..config.env_config import EnvConfig
from ..config.mcts_config import MCTSConfig
from ..config.mesh_config import MeshConfig
from ..config.model_config import ModelConfig
from ..config.persistence_config import PersistenceConfig
from ..config.telemetry_config import TelemetryConfig
from ..config.train_config import TrainConfig
from ..env.engine import TriangleEnv
from ..features.core import FeatureExtractor
from ..nn.network import NeuralNetwork
from ..rl.buffer import ExperienceBuffer
from ..rl.self_play import SelfPlayEngine
from ..rl.trainer import Trainer
from ..stats.collector import StatsCollector
from ..stats.persistence import CheckpointManager
from ..telemetry import RunTelemetry


@dataclass
class TrainingComponents:
    """Everything a training run needs, built by `setup_training_components`."""

    env: TriangleEnv
    extractor: FeatureExtractor
    net: NeuralNetwork
    buffer: ExperienceBuffer
    trainer: Trainer
    self_play: SelfPlayEngine
    stats: StatsCollector
    checkpoints: CheckpointManager

    env_config: EnvConfig
    model_config: ModelConfig
    train_config: TrainConfig
    mcts_config: MCTSConfig
    mesh_config: MeshConfig
    persistence_config: PersistenceConfig

    # Built by setup; a None (manually assembled components) makes the
    # loop create a default-enabled RunTelemetry itself.
    telemetry: RunTelemetry | None = None
    telemetry_config: TelemetryConfig | None = None

    # Fused-megastep runner (rl/megastep.py), built by setup when
    # TrainConfig.FUSED_MEGASTEP; the loop constructs one lazily for
    # manually assembled components.
    megastep: Any = None

    extra: dict[str, Any] = field(default_factory=dict)
