"""Device-resident experience replay: the zero-copy training data path.

The host replay buffer (`rl/buffer.py`) mirrors the reference's
topology (`alphatriangle/rl/core/buffer.py:25-195`): experiences are
fetched from the rollout device program to host memory, stored in a
NumPy ring, and every sampled batch is re-uploaded for training. On a
chip whose host link is slow relative to compute — PCIe on a real TPU
VM, a network tunnel in this dev environment — that round trip IS the
learner bottleneck: at flagship scale one fused 16-step group stages
~8.5 MB of batches and the measured learner throughput pinned to the
link bandwidth, not the MXU (BENCH r4: 7.9 steps/s, 0.4% MFU).

`DeviceReplayBuffer` keeps the ring in device HBM instead:

- **Ingest** is one jitted scatter: the rollout chunk's dense masked
  experience outputs (still device arrays — `SelfPlayEngine.
  play_moves_device` never fetches them) are flattened, validated
  (finiteness + policy-distribution checks, absorbing the role of
  `SelfPlayResult`'s validator) and ring-written at positions derived
  from a running cursor via a prefix-sum over the validity mask.
  Invalid rows land in a trash slot at index `capacity`. Only the
  *count* of rows written returns to the host (one scalar), which is
  all the host-side PER SumTree needs: rows occupy slots
  `[cursor, cursor+count) % capacity` in order, and new rows get
  max-priority init exactly like the host buffer.
- **Sampling** stays host-side (the SumTree is cheap and sequential —
  SURVEY.md §7 "PER on host vs device") but returns only slot
  *indices* and IS weights; the trainer gathers the actual rows on
  device (`Trainer.train_steps_from`), so a fused K-step group uploads
  K*B int32 indices (~16 KB) instead of K batches (~8.5 MB).
- **Priorities** update from the TD errors the trainer already fetches
  (K*B float32 — small), identical to the host path.
- **Persistence** round-trips through the same snapshot dict as the
  host buffer (one bulk fetch per buffer spill — checkpoints are rare)
  so `.npz` spills are interchangeable between the two buffer kinds
  and a run can resume from either.

Storage dtypes match the host ring: grid int8 (cells are exactly
{-1,0,1}), everything else float32. The gather casts grid back to
float32, so a batch sampled from the device ring is bit-identical to
the same rows sampled from the host ring.

Single-device, single-process only (gated in `training/setup.py`):
this ring lives on one chip. The multi-chip variant — ring sharded
over the dp axis, each device ingesting its own lanes' rollouts via
`shard_map` and gathering its own batch rows — is
`rl/sharded_device_buffer.py`.

CPU-backend caveat (DEVICE_REPLAY="on" there is a test/dev mode):
XLA:CPU's *async dispatch* deadlocks when one thread blocks on an
in-flight program while another thread enqueues programs sharing its
buffers — reproduced with a producer rollout chunk + consumer ingest
of its payload from two threads, flagship-size programs only (both
fetches hang forever; tiny programs slip through). The fix is
`jax.config.update("jax_cpu_enable_async_dispatch", False)` BEFORE the
CPU client is created (the flag is latched at client construction —
setting it here in the constructor is provably too late). The runner
(`training/runner.py`) applies it at entry when DEVICE_REPLAY="on";
tests apply it in conftest. The TPU backend's device-FIFO dispatch
model is unaffected.
"""

import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..config.train_config import TrainConfig
from .buffer import ExperienceBuffer

logger = logging.getLogger(__name__)

# Canonical field order for experience row blocks (the key names the
# rollout program emits for its `mat`/`flush` outputs).
_BLOCK_FIELDS = ("grid", "other", "policy", "ret", "pw")


def ring_scatter(
    storage: dict[str, jax.Array],
    cursor: jax.Array,
    blocks: tuple[dict[str, jax.Array], ...],
    cap: int,
    with_positions: bool = False,
):
    """Flatten + validate + ring-scatter experience blocks (pure).

    The single source of the ingest math for BOTH device rings AND the
    fused megastep program (rl/megastep.py): the single-device buffer
    calls it whole-ring, the dp-sharded buffer calls it per shard
    inside `shard_map` — the validation predicate and keep/trash-slot
    rules must never diverge between them.

    Each block holds arrays with arbitrary leading dims (the chunk
    program's (T,B) matured and (T,B,n) flushed outputs) plus a boolean
    `mask` over those leading dims. Rows are written in block order,
    leading-dims-major — the same order the host path produces via
    boolean indexing, so the paths fill identical slots with identical
    rows. Returns (new_storage, new_cursor, rows_written); with
    `with_positions` it additionally returns the per-row scatter slots
    and keep mask, which the megastep needs to max-priority-init the
    fresh rows in its device-resident PER array."""

    def flat(block: dict[str, jax.Array], f: str) -> jax.Array:
        lead = block["mask"].shape
        v = block[f]
        return v.reshape(-1, *v.shape[len(lead):])

    rows = {
        f: jnp.concatenate([flat(b, f) for b in blocks])
        for f in _BLOCK_FIELDS
    }
    mask = jnp.concatenate([b["mask"].reshape(-1) for b in blocks])
    # Validation absorbed from SelfPlayResult's validator + the host
    # buffer's finite filter (rl/types.py:78-85, buffer.py:120-128).
    valid = (
        mask
        & jnp.isfinite(rows["grid"]).all(axis=(1, 2, 3))
        & jnp.isfinite(rows["other"]).all(axis=1)
        & jnp.isfinite(rows["policy"]).all(axis=1)
        & jnp.isfinite(rows["ret"])
        & (jnp.abs(rows["policy"].sum(axis=1) - 1.0) < 1e-3)
    )
    offsets = jnp.cumsum(valid.astype(jnp.int32)) - 1
    count = valid.sum(dtype=jnp.int32)
    # A single ingest larger than the ring keeps only the newest `cap`
    # rows — the older ones would be overwritten by the wrap anyway,
    # and dropping them guarantees distinct scatter slots, making
    # last-write-wins deterministic (`.at[pos].set` with duplicate
    # indices has an unspecified winner). The cursor still advances by
    # the full count, matching the host ring.
    keep = valid & (offsets >= count - cap)
    pos = jnp.where(keep, (cursor + offsets) % cap, cap)
    new_storage = {
        "grid": storage["grid"].at[pos].set(rows["grid"].astype(jnp.int8)),
        "other_features": storage["other_features"]
        .at[pos]
        .set(rows["other"].astype(jnp.float32)),
        "policy_target": storage["policy_target"]
        .at[pos]
        .set(rows["policy"].astype(jnp.float32)),
        "value_target": storage["value_target"]
        .at[pos]
        .set(rows["ret"].astype(jnp.float32)),
        "policy_weight": storage["policy_weight"]
        .at[pos]
        .set(rows["pw"].astype(jnp.float32)),
    }
    new_cursor = (cursor + count) % cap
    if with_positions:
        return new_storage, new_cursor, count, pos, keep
    return new_storage, new_cursor, count


class DeviceReplayBuffer(ExperienceBuffer):
    """Uniform/PER replay whose ring storage lives in device HBM.

    Subclasses the host buffer for everything link-independent
    (readiness, beta annealing, priority updates, SumTree sampling
    math); replaces storage reads/writes with jitted device ops.
    """

    is_device = True

    def __init__(
        self,
        config: TrainConfig,
        grid_shape: tuple[int, int, int],
        other_dim: int,
        action_dim: int,
        seed: int | None = None,
    ):
        super().__init__(config, seed=seed, action_dim=action_dim)
        cap = self.capacity
        # One trash row at index `cap` absorbs invalid-row scatters.
        self.storage: dict[str, jax.Array] = {
            "grid": jnp.zeros((cap + 1, *grid_shape), jnp.int8),
            "other_features": jnp.zeros((cap + 1, other_dim), jnp.float32),
            "policy_target": jnp.zeros((cap + 1, action_dim), jnp.float32),
            "value_target": jnp.zeros(cap + 1, jnp.float32),
            "policy_weight": jnp.ones(cap + 1, jnp.float32),
        }
        self._grid_shape = grid_shape
        self._other_dim = other_dim
        self._ingest_jit = jax.jit(self._ingest_impl, donate_argnums=(0,))
        # Device program dispatches this ring made (telemetry: the
        # loop's dispatches-per-iteration gauge sums these counters).
        self.dispatch_count = 0

    # --- device ingest ----------------------------------------------------

    def _ingest_impl(
        self,
        storage: dict[str, jax.Array],
        cursor: jax.Array,
        blocks: tuple[dict[str, jax.Array], ...],
    ):
        """Flatten + validate + ring-scatter experience blocks.

        The math lives in the module-level `ring_scatter` (shared with
        the dp-sharded ring's per-shard ingest).
        """
        return ring_scatter(storage, cursor, blocks, self.capacity)

    def _ingest_blocks(
        self, blocks: "tuple[dict[str, Any], ...]"
    ) -> tuple[int, np.ndarray]:
        """Run the jitted ingest; returns (rows written, their slots)."""
        self.storage, _, count_dev = self._ingest_jit(
            self.storage, jnp.int32(self._pos), blocks
        )
        self.dispatch_count += 1
        count = int(count_dev)  # the one blocking scalar fetch
        slots = (self._pos + np.arange(count)) % self.capacity
        if self.tree is not None and count:
            self.tree.update_batch(
                slots, np.full(count, self.tree.max_priority, dtype=np.float64)
            )
            self.tree.data_pointer = int((self._pos + count) % self.capacity)
            self.tree.n_entries = min(self._size + count, self.capacity)
        self._pos = int((self._pos + count) % self.capacity)
        self._size = min(self._size + count, self.capacity)
        return count, slots

    def ingest_payload(self, payload: dict[str, Any]) -> int:
        """Fold one rollout chunk's device-resident experience outputs
        (`SelfPlayEngine.play_moves_device`) into the ring. Returns the
        number of rows written — the only thing fetched."""
        return self._ingest_blocks((payload["mat"], payload["flush"]))[0]

    def add_dense(
        self,
        grid: np.ndarray,
        other_features: np.ndarray,
        policy_target: np.ndarray,
        value_target: np.ndarray,
        policy_weight: np.ndarray | None = None,
    ) -> np.ndarray:
        """Host-array insert (restore path, tests, host-side generators).

        Same contract as the host buffer's `add_dense`, via one upload
        + the shared ingest program. Note the device path additionally
        enforces the policy-distribution check (the validator layer the
        device path absorbs), which the host buffer leaves to
        `SelfPlayResult`.
        """
        grid = np.asarray(grid, dtype=np.float32)
        k = grid.shape[0]
        if k == 0:
            return np.zeros(0, dtype=np.int64)
        block = {
            "grid": jnp.asarray(grid),
            "other": jnp.asarray(other_features, dtype=jnp.float32),
            "policy": jnp.asarray(policy_target, dtype=jnp.float32),
            "ret": jnp.asarray(
                np.asarray(value_target, dtype=np.float32).reshape(-1)
            ),
            "pw": jnp.asarray(
                np.ones(k, np.float32)
                if policy_weight is None
                else np.asarray(policy_weight, dtype=np.float32).reshape(-1)
            ),
            "mask": jnp.ones(k, bool),
        }
        count, slots = self._ingest_blocks((block,))
        if count < k:
            logger.warning(
                "DeviceReplayBuffer: dropped %d invalid rows of %d on add.",
                k - count,
                k,
            )
        return slots.astype(np.int64)

    # --- memory attribution (telemetry/memory.py) -------------------------

    def storage_nbytes(self) -> int:
        """Exact bytes of the device-resident ring storage (dtype/shape
        math over the allocated arrays; equals
        `telemetry.memory.replay_ring_bytes` for this geometry)."""
        from ..telemetry.memory import tree_bytes

        return tree_bytes(self.storage)

    def memory_record(self) -> dict:
        """This ring's `kind: "memory"` ledger record (HBM-resident)."""
        from ..telemetry.memory import replay_ring_record

        return replay_ring_record(
            self.storage_nbytes(), self.capacity, shards=1, location="device"
        )

    # --- sampling ---------------------------------------------------------

    def sample(
        self, batch_size: int, current_train_step: int | None = None
    ) -> "dict[str, np.ndarray] | None":
        """Sample slot indices + IS weights (no data movement).

        Returns {"indices", "weights"} — the trainer gathers the rows
        on device (`Trainer.train_steps_from`). The sampling math is
        the parent's `_sample_indices` (shared, not duplicated).
        """
        sampled = self._sample_indices(batch_size, current_train_step)
        if sampled is None:
            return None
        slots, weights = sampled
        return {"indices": slots.astype(np.int64), "weights": weights}

    # --- persistence ------------------------------------------------------

    def get_state(self) -> dict[str, Any]:
        """Same snapshot dict as the host buffer (one bulk fetch)."""
        state: dict[str, Any] = {
            "pos": self._pos,
            "size": self._size,
            "storage": None,
            "priorities": None,
        }
        if self._size > 0:
            host = jax.device_get(self.storage)
            state["storage"] = {
                k: np.asarray(v[: self._size]).copy() for k, v in host.items()
            }
        if self.tree is not None and self._size > 0:
            leaves = np.arange(self._size) + self.tree._cap2
            state["priorities"] = self.tree.tree[leaves].copy()
        return state

    def set_state(self, state: dict[str, Any]) -> None:
        """Restore a snapshot (host- or device-buffer produced): let the
        parent rebuild its host ring + SumTree, then upload the ring."""
        super().set_state(state)
        if self._storage is None:
            return
        host = {
            k: np.zeros(
                (self.capacity + 1, *v.shape[1:]), dtype=self.storage[k].dtype
            )
            for k, v in self._storage.items()
        }
        for k, v in self._storage.items():
            host[k][: self.capacity] = v
        self.storage = jax.device_put(host)
        self._storage = None  # free the host copy; device ring is truth
