"""RL core: replay buffer, trainer, self-play (reference `alphatriangle/rl/`)."""

from .buffer import DenseSample, ExperienceBuffer
from .types import SelfPlayResult

__all__ = ["DenseSample", "ExperienceBuffer", "SelfPlayResult"]
