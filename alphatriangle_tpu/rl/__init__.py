"""RL core: replay buffer, trainer, self-play (reference `alphatriangle/rl/`)."""

from .buffer import DenseSample, ExperienceBuffer
from .megastep import MegastepRunner
from .self_play import SelfPlayEngine
from .trainer import Trainer, TrainState
from .types import SelfPlayResult

__all__ = [
    "DenseSample",
    "ExperienceBuffer",
    "MegastepRunner",
    "SelfPlayEngine",
    "SelfPlayResult",
    "TrainState",
    "Trainer",
]
