"""Batched self-play: lockstep rollouts + vectorized n-step pipeline.

Capability parity with the reference's `SelfPlayWorker.run_episode`
(`alphatriangle/rl/self_play/worker.py:166-513`): MCTS per move,
temperature-scheduled action selection, policy targets from visit
counts, n-step returns with value bootstrap, trailing flush of
unmatured experiences at episode end, staleness tagging.

TPU-native redesign (SURVEY.md §7 step 9):
- One `SelfPlayEngine` steps `B` games in lockstep; each move is a
  handful of batched device dispatches (feature extract, MCTS search —
  which itself batches every leaf eval across games onto the MXU —
  action select, env step). There are no per-game actors and no weight
  broadcast; the engine reads the `NeuralNetwork` wrapper's current
  variables each search, so a learner `sync_to_network()` is visible on
  the very next move (replaces `worker_manager.py:169-209`).
- The n-step machinery is a **vectorized sliding window**: (B, n)
  host arrays of pending experiences with incrementally-maintained
  discounted partial returns, instead of per-game Python deques
  (`worker.py:410-485`). An experience added at move t matures at move
  t+n and is bootstrapped with that search's root value — the
  MCTS-improved estimate of V(s_{t+n}), a strict upgrade over the
  reference's raw network bootstrap (`worker.py:418`).
- Games that finish flush their window without bootstrap (trailing
  flush, `worker.py:466-485`) and are reset in place, so the batch
  never shrinks and shapes stay static.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..config.mcts_config import MCTSConfig
from ..config.train_config import TrainConfig
from ..env.engine import TriangleEnv
from ..features.core import FeatureExtractor
from ..mcts.helpers import policy_target_from_visits, select_action_from_visits
from ..mcts.search import BatchedMCTS
from ..nn.network import NeuralNetwork
from .types import SelfPlayResult

logger = logging.getLogger(__name__)


class SelfPlayEngine:
    """B games played in lockstep, emitting n-step experiences."""

    def __init__(
        self,
        env: TriangleEnv,
        extractor: FeatureExtractor,
        net: NeuralNetwork,
        mcts_config: MCTSConfig,
        train_config: TrainConfig,
        batch_size: int | None = None,
        seed: int = 0,
    ):
        self.env = env
        self.extractor = extractor
        self.net = net
        self.mcts = BatchedMCTS(
            env, extractor, net.model, mcts_config, net.support
        )
        self.config = train_config
        self.mcts_config = mcts_config
        self.batch_size = batch_size or train_config.SELF_PLAY_BATCH_SIZE
        self.n_step = train_config.N_STEP_RETURNS
        self.gamma = train_config.GAMMA

        self._rng = jax.random.PRNGKey(seed)
        self._rng, reset_key = jax.random.split(self._rng)
        self.states = env.reset_batch(
            jax.random.split(reset_key, self.batch_size)
        )

        b, n = self.batch_size, self.n_step
        c = extractor.model_config.GRID_INPUT_CHANNELS
        f = extractor.other_dim
        a = env.action_dim
        self._grid_shape = (c, env.rows, env.cols)
        self._pend_grid = np.zeros((b, n, c, env.rows, env.cols), np.float32)
        self._pend_other = np.zeros((b, n, f), np.float32)
        self._pend_policy = np.zeros((b, n, a), np.float32)
        self._pend_return = np.zeros((b, n), np.float32)
        self._pend_discount = np.ones((b, n), np.float32)
        self._pend_active = np.zeros((b, n), bool)

        self._move_index = 0  # global move counter (window slot = t % n)
        # Oldest weights version contributing to the current harvest
        # window (conservative staleness tag; a mid-window sync must not
        # relabel earlier experiences as fresh). None = window not
        # started; resolved at the first move of each window.
        self._min_weights_version: int | None = None
        self._out: list[tuple[np.ndarray, ...]] = []
        self._episode_scores: list[float] = []
        self._episode_lengths: list[int] = []
        self._episodes_played = 0
        self._total_simulations = 0

    def _next_key(self) -> jax.Array:
        self._rng, key = jax.random.split(self._rng)
        return key

    def _temperatures(self, step_counts: np.ndarray) -> np.ndarray:
        """Per-game move-indexed temperature (reference `worker.py:311-332`)."""
        cfg = self.config
        frac = np.minimum(
            step_counts.astype(np.float32) / cfg.TEMPERATURE_ANNEAL_MOVES, 1.0
        )
        return cfg.TEMPERATURE_INITIAL + frac * (
            cfg.TEMPERATURE_FINAL - cfg.TEMPERATURE_INITIAL
        )

    def _emit(self, mask: np.ndarray, slot_returns: np.ndarray, slots: slice | int):
        """Queue pending experiences `[mask, slots]` with final returns."""
        if not mask.any():
            return
        self._out.append(
            (
                self._pend_grid[mask, slots].reshape(-1, *self._grid_shape),
                self._pend_other[mask, slots].reshape(
                    -1, self._pend_other.shape[-1]
                ),
                self._pend_policy[mask, slots].reshape(
                    -1, self._pend_policy.shape[-1]
                ),
                np.asarray(slot_returns[mask], np.float32).reshape(-1),
            )
        )

    def play_move(self) -> None:
        """Advance every game by one move."""
        t = self._move_index
        w = t % self.n_step
        states = self.states
        self._min_weights_version = (
            self.net.weights_version
            if self._min_weights_version is None
            else min(self._min_weights_version, self.net.weights_version)
        )

        # 1-2. Features for replay + batched search (one MXU leaf batch
        # per simulation across all B games).
        grids, others = self.extractor.extract_batch(states)
        out = self.mcts.search(self.net.variables, states, self._next_key())
        counts = np.asarray(out.visit_counts)
        root_value = np.asarray(out.root_value)
        self._total_simulations += int(out.total_simulations)

        valid = np.asarray(self.env.valid_mask_batch(states))
        policy = np.asarray(
            policy_target_from_visits(out.visit_counts, jnp.asarray(valid))
        )

        # 3. Mature the slot added n moves ago: bootstrap with this
        # search's root value (the MCTS estimate of V(s_{t}) = V(s_{t-n+n})).
        matured = self._pend_active[:, w].copy()
        if matured.any():
            boot = (
                self._pend_return[:, w]
                + self._pend_discount[:, w] * root_value
            )
            self._emit(matured, boot, w)
            self._pend_active[:, w] = False

        # 4. Select actions (temperature by each game's own move count)
        # and step all games in one dispatch.
        temps = self._temperatures(np.asarray(states.step_count))
        actions = select_action_from_visits(
            out.visit_counts, jnp.asarray(temps), self._next_key()
        )
        actions = jnp.maximum(actions, 0)  # sentinel guard (no-visit rows)
        new_states, rewards, dones = self.env.step_batch(states, actions)
        rewards_np = np.asarray(rewards)
        dones_np = np.asarray(dones)

        # 5. Add this move's experience into window slot w.
        self._pend_grid[:, w] = np.asarray(grids)
        self._pend_other[:, w] = np.asarray(others)
        self._pend_policy[:, w] = policy
        self._pend_return[:, w] = 0.0
        self._pend_discount[:, w] = 1.0
        self._pend_active[:, w] = True

        # 6. Fold this move's reward into every pending experience.
        self._pend_return += np.where(
            self._pend_active, self._pend_discount * rewards_np[:, None], 0.0
        )
        self._pend_discount = np.where(
            self._pend_active, self._pend_discount * self.gamma, 1.0
        )

        # 7. Trailing flush for finished (or move-capped) games: emit all
        # pending slots without bootstrap (`worker.py:466-485`).
        step_counts = np.asarray(new_states.step_count)
        truncated = (~dones_np) & (step_counts >= self.config.MAX_EPISODE_MOVES)
        ending = dones_np | truncated
        if ending.any():
            flush = self._pend_active & ending[:, None]
            self._emit(flush, self._pend_return.copy(), slice(None))
            self._pend_active[ending] = False
            scores = np.asarray(new_states.score)
            for b in np.flatnonzero(ending):
                self._episode_scores.append(float(scores[b]))
                self._episode_lengths.append(int(step_counts[b]))
            self._episodes_played += int(ending.sum())
            # Force-terminate truncated games so reset picks them up.
            if truncated.any():
                new_states = new_states.replace(
                    done=jnp.asarray(dones_np | truncated)
                )

        # 8. Reset finished games in place; batch shape never changes.
        self.states = self.env.reset_where_done_jit(
            new_states, self._next_key()
        )
        self._move_index += 1

    def play_moves(self, num_moves: int) -> SelfPlayResult:
        """Advance all games `num_moves` moves and harvest experiences."""
        for _ in range(num_moves):
            self.play_move()
        return self.harvest()

    def harvest(self) -> SelfPlayResult:
        """Collect emitted experiences + episode stats since last call."""
        if self._out:
            grids = np.concatenate([o[0] for o in self._out])
            others = np.concatenate([o[1] for o in self._out])
            policies = np.concatenate([o[2] for o in self._out])
            values = np.concatenate([o[3] for o in self._out])
        else:
            c, h, w = self._grid_shape
            grids = np.zeros((0, c, h, w), np.float32)
            others = np.zeros((0, self._pend_other.shape[-1]), np.float32)
            policies = np.zeros((0, self._pend_policy.shape[-1]), np.float32)
            values = np.zeros((0,), np.float32)
        result = SelfPlayResult(
            grid=grids,
            other_features=others,
            policy_target=policies,
            value_target=values,
            episode_scores=self._episode_scores,
            episode_lengths=self._episode_lengths,
            num_episodes=self._episodes_played,
            total_simulations=self._total_simulations,
            trainer_step_at_episode_start=(
                self._min_weights_version
                if self._min_weights_version is not None
                else self.net.weights_version
            ),
        )
        self._out = []
        self._episode_scores = []
        self._episode_lengths = []
        self._episodes_played = 0
        self._total_simulations = 0
        self._min_weights_version = None
        return result
