"""Batched self-play: fused lockstep rollouts + vectorized n-step pipeline.

Capability parity with the reference's `SelfPlayWorker.run_episode`
(`alphatriangle/rl/self_play/worker.py:166-513`): MCTS per move,
temperature-scheduled action selection, policy targets from visit
counts, n-step returns with value bootstrap, trailing flush of
unmatured experiences at episode end, staleness tagging.

TPU-native redesign (SURVEY.md §7 step 9):
- One `SelfPlayEngine` steps `B` games in lockstep. A whole rollout
  chunk (`play_chunk`) — search -> select -> env step -> n-step window
  update, times `num_moves` — is ONE jitted dispatch: a `lax.scan` over
  moves whose carry holds the env states *and* the n-step window as
  device arrays. The host sees exactly one transfer per chunk (the
  stacked, masked experience outputs), replacing the >=6 blocking
  transfers per move of the round-2 engine.
- There are no per-game actors and no weight broadcast; the engine
  reads the `NeuralNetwork` wrapper's current variables at each chunk,
  so a learner `sync_to_network()` is visible on the next chunk
  (replaces `worker_manager.py:169-209`).
- The n-step machinery is a **vectorized sliding window**: (B, n)
  device arrays of pending experiences with incrementally-maintained
  discounted partial returns, instead of per-game Python deques
  (`worker.py:410-485`). An experience added at move t matures at move
  t+n and is bootstrapped with that search's root value — the
  MCTS-improved estimate of V(s_{t+n}), a strict upgrade over the
  reference's raw network bootstrap (`worker.py:418`).
- Games that finish flush their window without bootstrap (trailing
  flush, `worker.py:466-485`) and are reset in place, so the batch
  never shrinks and shapes stay static. Emissions use fixed-shape
  (moves, B[, n]) buffers with boolean masks; the host compacts them
  after the single device_get.
- Staleness is tracked per episode: each game carries the weights
  version it started under; episode-end records it (finer than the
  reference's per-episode tag at `worker.py:136-139`, which tags with
  the version at *episode start* too — parity, but batched).
"""

import functools
import logging
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..compile_cache import config_digest, get_compile_cache
from ..config.mcts_config import MCTSConfig
from ..config.train_config import TrainConfig
from ..env.engine import EnvState, TriangleEnv
from ..features.core import FeatureExtractor
from ..mcts.gumbel import GumbelMCTS
from ..mcts.helpers import policy_target_from_visits, select_action_from_visits
from ..telemetry.device_stats import (
    beacon_signature,
    beacons_armed,
    device_stats_signature,
    fold_search_stats,
    note_dispatch,
    rollout_chunk_stats,
)
from ..telemetry.flight import flight_span
from ..mcts.search import BatchedMCTS
from ..nn.network import NeuralNetwork
from ..nn.precision import cast_params_for_inference, inference_dtype
from .types import SelfPlayResult

logger = logging.getLogger(__name__)


@struct.dataclass
class RolloutCarry:
    """Device-resident rollout state carried across chunks."""

    env: EnvState  # (B, ...) lockstep game states
    rng: jax.Array  # PRNG key
    pend_grid: jax.Array  # (B, n, C, H, W) float32 pending features
    pend_other: jax.Array  # (B, n, F) float32
    pend_policy: jax.Array  # (B, n, A) float32 pending policy targets
    pend_pweight: jax.Array  # (B, n) float32 policy-loss weight (PCR)
    pend_return: jax.Array  # (B, n) float32 discounted partial returns
    pend_discount: jax.Array  # (B, n) float32 next-reward discounts
    pend_active: jax.Array  # (B, n) bool slot occupancy
    episode_start_version: jax.Array  # (B,) int32 weights version at ep start
    move_index: jax.Array  # () int32 global move counter
    # Promoted search tree carried across moves (mcts/search.py
    # CarriedTree) when MCTSConfig.tree_reuse is on. None (the default)
    # is an EMPTY pytree node: the reuse-off carry flattens to exactly
    # the same leaves as before this field existed, so fresh-root
    # programs, shardings and donation layouts are bit-identical.
    tree: Any = None


class SelfPlayEngine:
    """B games played in lockstep, emitting n-step experiences."""

    def __init__(
        self,
        env: TriangleEnv,
        extractor: FeatureExtractor,
        net: NeuralNetwork,
        mcts_config: MCTSConfig,
        train_config: TrainConfig,
        batch_size: int | None = None,
        seed: int = 0,
        share_compiled: "SelfPlayEngine | None" = None,
        mesh: "jax.sharding.Mesh | None" = None,
        data_axes: tuple = ("dp",),
    ):
        """`share_compiled`: another engine whose jitted chunk programs
        this one reuses (multi-stream rollouts, training/loop.py). The
        rollout computation depends only on configs — carry, weights
        and version are arguments — so identically-configured streams
        must not compile the heaviest program in the codebase N times.

        `mesh`: shard the lockstep lanes over the mesh's `data_axes`
        (B games -> B/n per device, ONE jitted program spanning the
        mesh) so rollouts occupy every chip — the TPU counterpart of
        the reference fanning self-play actors across hardware
        (`alphatriangle/training/worker_manager.py:39-75`). Every lane
        is independent, so GSPMD partitions the chunk program with no
        cross-device collectives; network weights ride replicated (or
        tensor-sharded, if the caller hands mesh-sharded variables —
        the specs compose). None = single-device engine (unchanged).
        """
        self.env = env
        self.extractor = extractor
        self.net = net
        search_cls = (
            GumbelMCTS if mcts_config.root_selection == "gumbel" else BatchedMCTS
        )
        self.mcts = search_cls(
            env, extractor, net.model, mcts_config, net.support
        )
        # Playout cap randomization (KataGo, arXiv:1902.10565 §3.1):
        # a second, cheap search program for the non-policy-training
        # moves — fewer sims, no root noise (exploit, don't explore).
        self.mcts_fast: BatchedMCTS | None = None
        if mcts_config.fast_simulations is not None:
            fast_cfg = mcts_config.model_copy(
                update={
                    "max_simulations": mcts_config.fast_simulations,
                    "dirichlet_epsilon": 0.0,
                }
            )
            fast_kw = (
                # Fast Gumbel searches must exploit, not explore: the
                # PUCT path gets this via temperature 0 at selection,
                # the Gumbel path by zeroing the root Gumbel sample.
                {"exploit": True}
                if search_cls is GumbelMCTS
                else {}
            )
            self.mcts_fast = search_cls(
                env, extractor, net.model, fast_cfg, net.support, **fast_kw
            )
        self.config = train_config
        self.mcts_config = mcts_config
        self.batch_size = batch_size or train_config.SELF_PLAY_BATCH_SIZE
        self.n_step = train_config.N_STEP_RETURNS
        self.gamma = train_config.GAMMA

        b, n = self.batch_size, self.n_step
        c = extractor.model_config.GRID_INPUT_CHANNELS
        f = extractor.other_dim
        a = env.action_dim
        self._grid_shape = (c, env.rows, env.cols)
        self._other_dim = f
        self._action_dim = a

        self.mesh = mesh
        self.data_axes = tuple(data_axes)
        self._lane_sharding = None
        self._replicated = None
        # (weights_version, mesh-replicated variables) memo for
        # _place_variables — held on the PRIMARY engine so N rollout
        # streams sharing one net share one replicated copy instead of
        # uploading (and pinning in HBM) N of them.
        self._placed_variables: tuple | None = None
        # (weights_version, inference-cast variables) memo for
        # _inference_variables — same owner-chain sharing.
        self._cast_variables: tuple | None = None
        self._placed_owner: "SelfPlayEngine" = (
            # Follow the chain so every stream lands on one root owner.
            share_compiled._placed_owner
            if share_compiled is not None
            else self
        )
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..config.mesh_config import lane_shard_count

            shards = lane_shard_count(mesh, self.data_axes)
            if b % shards != 0:
                raise ValueError(
                    f"SELF_PLAY_BATCH_SIZE={b} must divide evenly over "
                    f"the mesh data axes {self.data_axes} "
                    f"({shards} shards)."
                )
            self._lane_sharding = NamedSharding(
                mesh, PartitionSpec(self.data_axes)
            )
            self._replicated = NamedSharding(mesh, PartitionSpec())

        rng = jax.random.PRNGKey(seed)
        rng, reset_key = jax.random.split(rng)
        version0 = self.net.weights_version
        self._carry = RolloutCarry(
            env=env.reset_batch(jax.random.split(reset_key, b)),
            rng=rng,
            pend_grid=jnp.zeros((b, n, c, env.rows, env.cols), jnp.float32),
            pend_other=jnp.zeros((b, n, f), jnp.float32),
            pend_policy=jnp.zeros((b, n, a), jnp.float32),
            pend_pweight=jnp.ones((b, n), jnp.float32),
            pend_return=jnp.zeros((b, n), jnp.float32),
            pend_discount=jnp.ones((b, n), jnp.float32),
            pend_active=jnp.zeros((b, n), bool),
            episode_start_version=jnp.full((b,), version0, jnp.int32),
            move_index=jnp.int32(0),
        )
        if mcts_config.tree_reuse:
            # Subtree reuse: the promoted tree rides the chunk carry
            # (zero extra dispatches). Starts all-invalid — move 1 of
            # every lane is a fresh-root search.
            self._carry = self._carry.replace(
                tree=self.mcts.zero_carried(self._carry.env)
            )
        if self._lane_sharding is not None:
            self._carry = jax.device_put(
                self._carry, self._carry_shardings()
            )

        # One compiled program per distinct chunk length, carry donated
        # so XLA reuses the window buffers in place.
        if share_compiled is not None:
            if (
                share_compiled.batch_size != self.batch_size
                or share_compiled.mcts_config != self.mcts_config
                or share_compiled.config != self.config
                or share_compiled.mesh is not self.mesh
                or share_compiled.data_axes != self.data_axes
            ):
                raise ValueError(
                    "share_compiled requires identically-configured "
                    "engines (batch size / MCTS / train configs / "
                    "mesh + data axes — jit specializes per input "
                    "sharding, so a mismatch would recompile anyway)."
                )
            self._chunk_fn = share_compiled._chunk_fn
        else:
            # Each distinct chunk length wraps its jitted program in
            # the AOT compile cache: a warm cache (cli warm, a prior
            # bench/run with these shapes) deserializes the serialized
            # executable instead of paying the full first-chunk compile
            # — the heaviest program in the codebase, and the one that
            # burned every short healthy chip window in rounds 1-5.
            # The config digest keys everything that shapes the program
            # but is invisible in its input avals (sim counts, n-step,
            # reward params, net architecture).
            chunk_extra = (
                config_digest(
                    self.mcts_config,
                    self.config,
                    extractor.model_config,
                    env.cfg,
                )
                + f"|lanes{self.data_axes if mesh is not None else ()}"
                # Device telemetry shapes the program: the stat-pack
                # adds output leaves, beacons embed host callbacks
                # (which also make the executable non-serializable).
                + device_stats_signature()
                + beacon_signature()
            )
            self._chunk_fn = functools.lru_cache(maxsize=None)(
                lambda num_moves: get_compile_cache().wrap(
                    f"self_play_chunk/t{num_moves}",
                    jax.jit(
                        functools.partial(self._chunk, num_moves),
                        donate_argnums=(1,),
                    ),
                    extra=chunk_extra,
                    serialize=not beacons_armed(),
                )
            )

        # Oldest weights version contributing to the current harvest
        # window (conservative chunk-level tag; per-episode tags ride in
        # episode_start_versions). None = window not started.
        self._min_weights_version: int | None = None
        self._out: list[tuple[np.ndarray, ...]] = []
        self._episode_scores: list[float] = []
        self._episode_lengths: list[int] = []
        self._episode_start_versions: list[int] = []
        self._episodes_played = 0
        self._episodes_truncated = 0
        self._total_simulations = 0
        # Root visits inherited from carried subtrees (tree_reuse);
        # summed with simulations this gives leaf-equivalent search
        # effort (leaf-evals/s in telemetry/perf.py).
        self._total_reused_visits = 0
        # Cumulative host-blocking harvest-fetch seconds (the chunk's
        # device_get — includes any wait for the chunk to finish, i.e.
        # the host-visible round-trip cost telemetry/perf.py reports).
        # Lock-guarded: producer threads fetch concurrently.
        self.transfer_d2h_seconds = 0.0
        self._transfer_lock = threading.Lock()
        # Rollout program dispatches (telemetry: the loop's dispatches-
        # per-iteration gauge; lock-guarded with the transfer time).
        self.dispatch_count = 0
        # Dispatch flight recorder (telemetry/flight.py), attached by
        # training/setup.py; None = no intent/seal records written.
        self.flight = None
        # (T, B) per-move diagnostics of the most recent chunk.
        self.last_trace: dict[str, np.ndarray] | None = None
        # Device telemetry (telemetry/device_stats.py): the searches'
        # stat-pack flag, snapshotted at construction like the MCTS
        # instances themselves. When on, `last_device_stats` holds the
        # most recent chunk's folded search + rollout legs.
        self.device_stats = self.mcts.device_stats
        self.last_device_stats: dict | None = None

    # --- multi-chip lane sharding -----------------------------------------

    def _carry_shardings(self) -> RolloutCarry:
        """Sharding pytree matching the carry: every (B, ...) leaf
        shards its lane dim over the mesh's data axes; the single PRNG
        key and the scalar move counter replicate."""
        lane, rep = self._lane_sharding, self._replicated
        return RolloutCarry(
            env=jax.tree_util.tree_map(lambda _: lane, self._carry.env),
            rng=rep,
            pend_grid=lane,
            pend_other=lane,
            pend_policy=lane,
            pend_pweight=lane,
            pend_return=lane,
            pend_discount=lane,
            pend_active=lane,
            episode_start_version=lane,
            move_index=rep,
            # Every CarriedTree leaf is (B, ...): lane-sharded like the
            # env states. None (reuse off) stays the empty pytree node.
            tree=(
                None
                if self._carry.tree is None
                else jax.tree_util.tree_map(lambda _: lane, self._carry.tree)
            ),
        )

    def _place_variables(self, variables, version: int):
        """Place net weights for a mesh-spanning chunk dispatch.

        Weights already sharded on THIS mesh (e.g. the trainer's
        tensor-parallel specs after a zero-copy sync) pass through —
        their specs compose with the lane sharding, giving TP network
        evals inside the search. Anything else (fresh init committed to
        one device, checkpoint restore) is replicated across the mesh;
        mixing single-device-committed and mesh-sharded args in one jit
        is an error JAX refuses at dispatch time. The replicated copy
        is cached per weights version — without it every chunk of a
        pre-first-sync run would re-upload the full network.
        """
        if self.mesh is None:
            return variables
        from jax.sharding import NamedSharding

        leaf = jax.tree_util.tree_leaves(variables)[0]
        sh = getattr(leaf, "sharding", None)
        owner = self._placed_owner
        if isinstance(sh, NamedSharding) and sh.mesh == self.mesh:
            # Trainer-sharded weights took over: drop any pre-sync
            # replicated copy so it doesn't pin a dead full-model
            # buffer in HBM for the rest of the run.
            owner._placed_variables = None
            return variables
        if owner._placed_variables is not None:
            cached_version, placed = owner._placed_variables
            if cached_version == version:
                return placed
        placed = jax.device_put(variables, self._replicated)
        owner._placed_variables = (version, placed)
        return placed

    def _inference_variables(self, variables, version: int):
        """Apply the inference precision policy (nn/precision.py) to
        the net variables before a chunk dispatch: a bf16 copy under
        INFERENCE_PRECISION="bfloat16", the original object under f32.
        Memoized per weights version on the primary engine (the
        `_place_variables` owner chain) so N rollout streams share one
        cast copy; `astype` preserves NamedShardings, so the cast
        composes with mesh placement."""
        if inference_dtype(self.extractor.model_config) == jnp.float32:
            return variables
        owner = self._placed_owner
        if owner._cast_variables is not None:
            cached_version, cast = owner._cast_variables
            if cached_version == version:
                return cast
        cast = cast_params_for_inference(
            variables, self.extractor.model_config
        )
        owner._cast_variables = (version, cast)
        return cast

    # --- device-side chunk ------------------------------------------------

    def _temperatures(self, step_counts: jax.Array) -> jax.Array:
        """Per-game move-indexed temperature (reference `worker.py:311-332`)."""
        cfg = self.config
        frac = jnp.minimum(
            step_counts.astype(jnp.float32) / cfg.TEMPERATURE_ANNEAL_MOVES, 1.0
        )
        return cfg.TEMPERATURE_INITIAL + frac * (
            cfg.TEMPERATURE_FINAL - cfg.TEMPERATURE_INITIAL
        )

    def _move_body(self, variables, version, carry: RolloutCarry, _):
        """One lockstep move of all B games (scan body)."""
        n = self.n_step
        w = carry.move_index % n
        states = carry.env
        rng, k_search, k_select, k_reset, k_mode = jax.random.split(
            carry.rng, 5
        )

        # 1-2. Features for replay + batched search (one MXU leaf batch
        # per simulation across all B games). Under playout cap
        # randomization the whole lockstep move is a full search with
        # prob `full_search_prob`, else the cheap fast search — a
        # per-move (not per-game) draw, which keeps the batch lanes in
        # lockstep while matching KataGo's per-move distribution.
        grids, others = jax.vmap(self.extractor.extract)(states)
        final_tree = None
        reused = None
        if self.mcts_config.tree_reuse:
            # Subtree reuse (incompatible with PCR/Gumbel — config-
            # validated): seed this move's search with the carried
            # promoted tree; lanes with an invalid carry run fresh.
            out, final_tree, reused = self.mcts._search_carried(
                variables, states, k_search, carry.tree
            )
            is_full = jnp.bool_(True)
            sims_this_move = jnp.int32(self.mcts_config.max_simulations)
        elif self.mcts_fast is None:
            out = self.mcts._search(variables, states, k_search)
            is_full = jnp.bool_(True)
            sims_this_move = jnp.int32(self.mcts_config.max_simulations)
        else:
            is_full = jax.random.bernoulli(
                k_mode, self.mcts_config.full_search_prob
            )
            out = jax.lax.cond(
                is_full,
                lambda: self.mcts._search(variables, states, k_search),
                lambda: self.mcts_fast._search(variables, states, k_search),
            )
            sims_this_move = jnp.where(
                is_full,
                self.mcts_config.max_simulations,
                self.mcts_config.fast_simulations,
            ).astype(jnp.int32)
        valid = jax.vmap(self.env.valid_action_mask)(states)
        if self.mcts_config.root_selection == "gumbel":
            # Completed-Q improved policy (mcts/gumbel.py) — a policy-
            # improvement operator, not a visit histogram.
            policy = out.improved_policy
        else:
            policy = policy_target_from_visits(out.visit_counts, valid)
        pweight = jnp.where(is_full, 1.0, 0.0)

        # 3. Mature the slot added n moves ago: bootstrap with this
        # search's root value (the MCTS estimate of V(s_t) = V(s_{t-n+n})).
        mat_mask = carry.pend_active[:, w]
        if (
            self.mcts_fast is not None
            and not self.mcts_config.pcr_record_fast_rows
        ):
            # KataGo-faithful playout cap randomization: positions
            # searched cheaply never become training rows (their
            # targets — noisy fast-search policy AND the n-step value
            # whose bootstrap is a fast root — are below training
            # quality; measured in docs/MCTS_DESIGN.md §e).
            mat_mask = mat_mask & (carry.pend_pweight[:, w] > 0.5)
        mat = {
            "grid": carry.pend_grid[:, w],
            "other": carry.pend_other[:, w],
            "policy": carry.pend_policy[:, w],
            "pw": carry.pend_pweight[:, w],
            "ret": carry.pend_return[:, w]
            + carry.pend_discount[:, w] * out.root_value,
            "mask": mat_mask,
        }
        pend_active = carry.pend_active.at[:, w].set(False)

        # 4. Select actions and step all games in one vmapped
        # transition. PUCT: temperature-scheduled sampling from visit
        # counts; Gumbel: the search already resolved the argmax of
        # g + logits + sigma(q) (exploration IS the Gumbel sample).
        if self.mcts_config.root_selection == "gumbel":
            actions = out.selected_action
        else:
            temps = self._temperatures(states.step_count)
            if self.mcts_fast is not None:
                # Playout-cap fast moves play GREEDILY (KataGo §3.1):
                # they exist to advance the game with the best cheap
                # decision, not to explore — temperature on a handful
                # of visits is near-uniform noise, and training on the
                # resulting near-random trajectories degrades the value
                # head (measured: greedy eval 7.53 -> 6.82 before this
                # guard). Exploration stays on full-search moves.
                temps = jnp.where(is_full, temps, 0.0)
            actions = select_action_from_visits(
                out.visit_counts, temps, k_select
            )
        # Sentinel guard: -1 (zero root visits) only happens for finished
        # games, where step() is a no-op; count live-game sentinels so the
        # host can surface the anomaly instead of silently clamping.
        sentinel_live = ((actions < 0) & ~states.done).sum(dtype=jnp.int32)
        actions = jnp.maximum(actions, 0)
        new_states, rewards, dones = jax.vmap(self.env.step)(states, actions)

        # 5. Add this move's experience into window slot w.
        pend_grid = carry.pend_grid.at[:, w].set(grids)
        pend_other = carry.pend_other.at[:, w].set(others)
        pend_policy = carry.pend_policy.at[:, w].set(policy)
        pend_pweight = carry.pend_pweight.at[:, w].set(pweight)
        pend_return = carry.pend_return.at[:, w].set(0.0)
        pend_discount = carry.pend_discount.at[:, w].set(1.0)
        pend_active = pend_active.at[:, w].set(True)

        # 6. Fold this move's reward into every pending experience.
        pend_return = pend_return + jnp.where(
            pend_active, pend_discount * rewards[:, None], 0.0
        )
        pend_discount = jnp.where(
            pend_active, pend_discount * self.gamma, 1.0
        )

        # 7. Trailing flush for finished (or move-capped) games: emit all
        # pending slots without bootstrap (`worker.py:466-485`).
        step_counts = new_states.step_count
        truncated = (~dones) & (step_counts >= self.config.MAX_EPISODE_MOVES)
        ending = dones | truncated
        flush_mask = pend_active & ending[:, None]
        if (
            self.mcts_fast is not None
            and not self.mcts_config.pcr_record_fast_rows
        ):
            flush_mask = flush_mask & (pend_pweight > 0.5)
        flush = {
            "grid": pend_grid,
            "other": pend_other,
            "policy": pend_policy,
            "pw": pend_pweight,
            "ret": pend_return,
            "mask": flush_mask,
        }
        pend_active = pend_active & ~ending[:, None]

        episode = {
            "ending": ending,
            # Truncated = hit MAX_EPISODE_MOVES rather than a natural
            # game over; a high fraction means the cap is biting (the
            # health signal the reference's get_game_over_reason
            # served, `worker.py:196`).
            "truncated": truncated,
            "score": new_states.score,
            "length": step_counts,
            "start_version": carry.episode_start_version,
        }

        # 8. Reset finished games in place; batch shape never changes.
        new_states = new_states.replace(done=ending)
        reset_states = self.env.reset_where_done(new_states, k_reset)
        episode_start_version = jnp.where(
            ending, version, carry.episode_start_version
        )

        # 9. Root promotion for the next move (subtree reuse): compact
        # the played action's subtree into the leading rows; ending
        # lanes reset to a fresh search (their next root is a new game).
        new_tree = carry.tree
        if final_tree is not None:
            new_tree = self.mcts.promote(final_tree, actions)
            new_tree = new_tree.replace(valid=new_tree.valid & ~ending)

        new_carry = RolloutCarry(
            env=reset_states,
            rng=rng,
            pend_grid=pend_grid,
            pend_other=pend_other,
            pend_policy=pend_policy,
            pend_pweight=pend_pweight,
            pend_return=pend_return,
            pend_discount=pend_discount,
            pend_active=pend_active,
            episode_start_version=episode_start_version,
            move_index=carry.move_index + 1,
            tree=new_tree,
        )
        outputs = {
            "mat": mat,
            "flush": flush,
            "episode": episode,
            "sentinel_live": sentinel_live,
            # Per-move diagnostics (tiny (B,) rows): lets tests validate
            # the windowed n-step math against an independent reference
            # without reaching inside the traced computation.
            "trace": {
                "root_value": out.root_value,
                "reward": rewards,
                "ending": ending,
                # Orphan node slots this search (duplicate/revisited
                # edges) — the waste the no-tree-reuse design accepts.
                "wasted_slots": out.wasted_slots,
                # Playout-cap accounting: sims actually run this move
                # and whether it was a full (policy-training) search.
                "sims": sims_this_move,
                "is_full": is_full,
                # Root visits inherited from the carried subtree this
                # move (0 with reuse off) — the leaf evaluations the
                # search did not have to spend; feeds leaf-evals/s.
                "reused": (
                    reused
                    if reused is not None
                    else jnp.zeros_like(out.root_value)
                ),
            },
            # Search stat-pack (None when DEVICE_STATS is off — an
            # empty pytree node, so the off-path program is unchanged).
            # (T,·)-stacked by the scan; rides the chunk's one fetch.
            "device_stats": out.stats,
        }
        return new_carry, outputs

    def _chunk(self, num_moves: int, variables, carry: RolloutCarry, version):
        """`num_moves` lockstep moves as one scanned computation."""
        body = functools.partial(self._move_body, variables, version)
        return jax.lax.scan(body, carry, None, length=num_moves)

    # --- host API ---------------------------------------------------------

    @property
    def states(self) -> EnvState:
        """Current (device-resident) batched game states."""
        return self._carry.env

    def play_chunk(
        self, num_moves: int | None = None, fetch_experiences: bool = True
    ) -> "dict | None":
        """Advance every game `num_moves` moves in ONE jitted dispatch.

        `fetch_experiences=False` is the device-replay path: the dense
        masked experience outputs (the overwhelming bulk of a chunk's
        payload) are NOT transferred — they return as device arrays for
        `DeviceReplayBuffer.ingest_payload` to scatter into the
        on-device ring; only episode stats + diagnostics (KBs) are
        fetched. Returns that device payload, or None in fetch mode.
        """
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        version = self.net.weights_version
        self._min_weights_version = (
            version
            if self._min_weights_version is None
            else min(self._min_weights_version, version)
        )
        with flight_span(
            self.flight,
            "rollout",
            f"self_play_chunk/t{t}",
            avals=f"B{self.batch_size}xT{t}",
        ):
            note_dispatch(f"self_play_chunk/t{t}")
            self._carry, outputs = self._chunk_fn(t)(
                self._place_variables(
                    self._inference_variables(self.net.variables, version),
                    version,
                ),
                self._carry,
                jnp.int32(version),
            )
            payload: dict | None = None
            t0 = time.perf_counter()
            if fetch_experiences:
                host = jax.device_get(outputs)  # graftlint: allow(host-sync-in-hot-path) the one transfer per chunk
            else:
                payload = {
                    "mat": outputs.pop("mat"),
                    "flush": outputs.pop("flush"),
                }
                host = jax.device_get(outputs)  # graftlint: allow(host-sync-in-hot-path) stats + trace only (small)
        dt = time.perf_counter() - t0
        with self._transfer_lock:
            self.transfer_d2h_seconds += dt
            self.dispatch_count += 1
        # Under playout cap randomization the per-move sim count varies;
        # the trace records what actually ran.
        self._total_simulations += (
            int(host["trace"]["sims"].sum()) * self.batch_size
        )
        self._total_reused_visits += int(host["trace"]["reused"].sum())

        self.last_trace = host["trace"]
        if self.device_stats:
            # Search leg folded from the fetched stat-pack; rollout leg
            # is a pure host fold over arrays the fetch ALREADY carried
            # (per-step-of-T terminations, reward extremes).
            self.last_device_stats = {
                "search": fold_search_stats(host.get("device_stats")),
                "rollout": rollout_chunk_stats(
                    host["episode"]["ending"], host["trace"]["reward"]
                ),
            }
        episode = host["episode"]
        self._fold_episode_stats(episode)
        sentinels = int(host["sentinel_live"].sum())
        if sentinels:
            logger.warning(
                "SelfPlay: %d zero-visit sentinel actions on LIVE games "
                "(clamped to action 0) — root search produced no visits.",
                sentinels,
            )
        if not fetch_experiences:
            return payload
        mat, flush = host["mat"], host["flush"]
        mmask = mat["mask"]  # (T, B)
        if mmask.any():
            self._out.append(
                (
                    mat["grid"][mmask],
                    mat["other"][mmask],
                    mat["policy"][mmask],
                    mat["ret"][mmask].astype(np.float32),
                    mat["pw"][mmask].astype(np.float32),
                )
            )
        fmask = flush["mask"]  # (T, B, n)
        if fmask.any():
            self._out.append(
                (
                    flush["grid"][fmask],
                    flush["other"][fmask],
                    flush["policy"][fmask],
                    flush["ret"][fmask].astype(np.float32),
                    flush["pw"][fmask].astype(np.float32),
                )
            )
        return None

    def _fold_episode_stats(self, episode: dict) -> None:
        """Accumulate finished-episode stats from one chunk's outputs."""
        ending = episode["ending"]  # (T, B)
        if ending.any():
            self._episode_scores.extend(
                episode["score"][ending].astype(float).tolist()
            )
            self._episode_lengths.extend(
                episode["length"][ending].astype(int).tolist()
            )
            self._episode_start_versions.extend(
                episode["start_version"][ending].astype(int).tolist()
            )
            self._episodes_played += int(ending.sum())
            self._episodes_truncated += int(episode["truncated"][ending].sum())

    def warm_chunk(self, num_moves: int | None = None) -> bool:
        """AOT-precompile the rollout chunk program WITHOUT running it.

        Lowers with the engine's real (variables, carry, version)
        arguments — so the cache signature matches what `play_chunk`
        will dispatch — and either deserializes a cached executable or
        compiles + serializes one. Lowering never executes or donates;
        the carry is untouched. Returns True when an AOT executable is
        ready (`cli warm`, benchmarks/tpu_watch.sh)."""
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        version = self.net.weights_version
        return self._chunk_fn(t).warm(
            self._place_variables(
                self._inference_variables(self.net.variables, version),
                version,
            ),
            self._carry,
            jnp.int32(version),
        )

    def analyze_chunk(self, num_moves: int | None = None) -> "dict | None":
        """Memory record of the rollout chunk program at this engine's
        real dispatch avals (telemetry/memory.py) — AOT analysis only,
        nothing executes and the carry is untouched (`cli fit`). The
        rollout family's `cost_analysis()` record rides the same
        compile (telemetry/roofline.py)."""
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        version = self.net.weights_version
        return self._chunk_fn(t).analyze(
            self._place_variables(
                self._inference_variables(self.net.variables, version),
                version,
            ),
            self._carry,
            jnp.int32(version),
        )

    def play_move(self) -> None:
        """Advance every game by one move (single-move chunk)."""
        self.play_chunk(1)

    def play_moves(self, num_moves: int) -> SelfPlayResult:
        """Advance all games `num_moves` moves and harvest experiences."""
        self.play_chunk(num_moves)
        return self.harvest()

    def play_moves_device(
        self, num_moves: int
    ) -> tuple[SelfPlayResult, dict]:
        """Device-replay variant of `play_moves`: experiences never
        leave the device. Returns (stats-only harvest, device payload
        for `DeviceReplayBuffer.ingest_payload`)."""
        payload = self.play_chunk(num_moves, fetch_experiences=False)
        assert payload is not None
        return self.harvest(), payload

    def harvest(self) -> SelfPlayResult:
        """Collect emitted experiences + episode stats since last call."""
        if self._out:
            grids = np.concatenate([o[0] for o in self._out])
            others = np.concatenate([o[1] for o in self._out])
            policies = np.concatenate([o[2] for o in self._out])
            values = np.concatenate([o[3] for o in self._out])
            pweights = np.concatenate([o[4] for o in self._out])
        else:
            c, h, w = self._grid_shape
            grids = np.zeros((0, c, h, w), np.float32)
            others = np.zeros((0, self._other_dim), np.float32)
            policies = np.zeros((0, self._action_dim), np.float32)
            values = np.zeros((0,), np.float32)
            pweights = np.zeros((0,), np.float32)
        result = SelfPlayResult(
            grid=grids,
            other_features=others,
            policy_target=policies,
            value_target=values,
            policy_weight=pweights,
            episode_scores=self._episode_scores,
            episode_lengths=self._episode_lengths,
            episode_start_versions=self._episode_start_versions,
            num_episodes=self._episodes_played,
            num_truncated=self._episodes_truncated,
            total_simulations=self._total_simulations,
            total_reused_visits=self._total_reused_visits,
            trainer_step_at_episode_start=(
                self._min_weights_version
                if self._min_weights_version is not None
                else self.net.weights_version
            ),
        )
        self._out = []
        self._episode_scores = []
        self._episode_lengths = []
        self._episode_start_versions = []
        self._episodes_played = 0
        self._episodes_truncated = 0
        self._total_simulations = 0
        self._total_reused_visits = 0
        self._min_weights_version = None
        return result
