"""RL result types (reference: `alphatriangle/rl/types.py:14-89`).

`SelfPlayResult` carries a *dense* block of experiences — fixed-shape
arrays straight out of the batched rollout — instead of the reference's
list of tuples. Its validator performs the same role as the reference's
(`rl/types.py:32-86`): structurally broken or non-finite rows are
dropped, not propagated into the buffer.
"""

import logging
from typing import Any

import numpy as np
from pydantic import BaseModel, ConfigDict, model_validator

logger = logging.getLogger(__name__)


class SelfPlayResult(BaseModel):
    """One harvest of finished self-play episodes, dense-form."""

    model_config = ConfigDict(arbitrary_types_allowed=True)

    grid: np.ndarray  # (N, C, H, W) float32
    other_features: np.ndarray  # (N, F) float32
    policy_target: np.ndarray  # (N, A) float32
    value_target: np.ndarray  # (N,) float32 n-step returns
    # Per-row policy-loss weight: 0.0 for experiences from fast
    # (playout-cap-randomized) searches whose visit counts are too
    # noisy to train the policy on; 1.0 otherwise. None -> all ones.
    policy_weight: np.ndarray | None = None

    episode_scores: list[float] = []
    episode_lengths: list[int] = []
    # Weights version each finished episode *started* under — the
    # per-episode staleness tag (reference `worker.py:136-139`), finer
    # than the window-level `trainer_step_at_episode_start` below.
    episode_start_versions: list[int] = []
    num_episodes: int = 0
    # Episodes that hit MAX_EPISODE_MOVES instead of a natural game
    # over (a persistently high fraction means the cap is biting).
    num_truncated: int = 0
    total_simulations: int = 0
    # Root visits inherited from carried subtrees (MCTS tree_reuse);
    # 0 with reuse off. simulations + reused = leaf-equivalent search
    # effort per harvest (telemetry leaf-evals/s).
    total_reused_visits: int = 0
    # Weight version the producing rollout ran with (staleness tag,
    # reference `rl/types.py:22` / `worker.py:136-139`).
    trainer_step_at_episode_start: int = 0
    context: dict[str, Any] = {}

    @property
    def num_experiences(self) -> int:
        return int(self.grid.shape[0])

    @model_validator(mode="after")
    def _drop_invalid_rows(self) -> "SelfPlayResult":
        n = self.grid.shape[0]
        if self.policy_weight is None:
            object.__setattr__(
                self, "policy_weight", np.ones(n, dtype=np.float32)
            )
        assert self.policy_weight is not None
        if self.policy_weight.shape[0] != n:
            raise ValueError(
                f"policy_weight rows {self.policy_weight.shape[0]} != {n}"
            )
        if not (
            self.other_features.shape[0]
            == self.policy_target.shape[0]
            == self.value_target.shape[0]
            == n
        ):
            raise ValueError(
                "Experience arrays disagree on row count: "
                f"{self.grid.shape[0]}/{self.other_features.shape[0]}/"
                f"{self.policy_target.shape[0]}/{self.value_target.shape[0]}"
            )
        if n == 0:
            return self
        keep = (
            np.isfinite(self.grid).all(axis=tuple(range(1, self.grid.ndim)))
            & np.isfinite(self.other_features).all(axis=1)
            & np.isfinite(self.policy_target).all(axis=1)
            & np.isfinite(self.value_target)
            # A policy target must be a distribution (rows sum to ~1).
            & (np.abs(self.policy_target.sum(axis=1) - 1.0) < 1e-3)
        )
        if not keep.all():
            logger.warning(
                "SelfPlayResult: dropping %d invalid experiences of %d.",
                int(n - keep.sum()),
                n,
            )
            object.__setattr__(self, "grid", self.grid[keep])
            object.__setattr__(self, "other_features", self.other_features[keep])
            object.__setattr__(self, "policy_target", self.policy_target[keep])
            object.__setattr__(self, "value_target", self.value_target[keep])
            object.__setattr__(self, "policy_weight", self.policy_weight[keep])
        return self
