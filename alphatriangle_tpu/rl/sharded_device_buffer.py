"""dp-sharded device-resident replay: the multi-chip zero-copy data path.

`DeviceReplayBuffer` (rl/device_buffer.py) keeps the replay ring in one
chip's HBM so the steady-state learner uploads indices, not batches.
This module extends the idea to a data-parallel mesh: the ring shards
over the dp axis, and the whole experience path becomes device-local —

- **rollouts** shard their lockstep lanes over dp (rl/self_play.py), so
  each device produces experience rows for exactly the games it played;
- **ingest** is a `shard_map` scatter: every device ring-writes ITS OWN
  lanes' rows into ITS OWN ring shard (per-shard cursors), so no
  experience bytes cross devices or the host link — the counts (dp
  int32s) are the only fetch;
- **sampling** stays host-side but stratifies per shard: B/dp rows from
  each shard's own SumTree, because the learner batch is dp-sharded and
  each device can only gather its local rows without collectives. (The
  reference's PER is a single global tree; equal-rows-per-shard
  proportional sampling is the standard distributed-PER relaxation —
  shard contents are i.i.d. games, so per-shard totals concentrate.)
- **gather** is a `shard_map` on the learner side: each device gathers
  its B/dp batch rows from its local shard (`Trainer`'s sharded `from`
  path), feeding the dp-sharded fused train step directly.

Indices are globally encoded as `shard * (cap_local + 1) + slot` — the
actual row index in the sharded storage array — so priority updates
route by arithmetic and the trash row (one per shard, at local index
`cap_local`) absorbs invalid scatters exactly like the single-device
ring.

Scope (gated in training/setup.py): single-process, dp-only meshes
(mdl == sp == 1) — with a wider sp the sp-replicas of the learner batch
would need identical rows, which per-device ingest cannot provide
without the collectives this design exists to avoid. The reference has
no counterpart: its buffer is one host object fed by actor RPC
(`alphatriangle/rl/core/buffer.py:25-195`).
"""

import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config.train_config import TrainConfig
from ..ops import per_sample
from ..utils.sumtree import SumTree
from .buffer import ExperienceBuffer
from .device_buffer import ring_scatter

logger = logging.getLogger(__name__)


class ShardedDeviceReplayBuffer(ExperienceBuffer):
    """PER/uniform replay whose ring shards over the mesh's dp axis."""

    is_device = True
    is_sharded = True

    def __init__(
        self,
        config: TrainConfig,
        grid_shape: tuple[int, int, int],
        other_dim: int,
        action_dim: int,
        mesh: Mesh,
        dp_axis: str = "dp",
        seed: int | None = None,
    ):
        super().__init__(config, seed=seed, action_dim=action_dim)
        dp = int(mesh.shape.get(dp_axis, 1))
        if mesh.devices.size != dp:
            raise ValueError(
                "ShardedDeviceReplayBuffer needs a dp-only mesh "
                f"(got {dict(mesh.shape)}): wider mdl/sp axes would "
                "need cross-device row movement at ingest or gather."
            )
        if self.capacity % dp != 0:
            raise ValueError(
                f"BUFFER_CAPACITY={self.capacity} must divide over "
                f"dp={dp} ring shards."
            )
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.dp = dp
        self.cap_local = self.capacity // dp
        self.stride = self.cap_local + 1  # + per-shard trash row
        self.per_sample_backend = config.PER_SAMPLE_BACKEND
        self._grid_shape = grid_shape
        self._other_dim = other_dim

        shard = NamedSharding(mesh, P(dp_axis))
        n = dp * self.stride
        self.storage: dict[str, jax.Array] = {
            "grid": jnp.zeros((n, *grid_shape), jnp.int8),
            "other_features": jnp.zeros((n, other_dim), jnp.float32),
            "policy_target": jnp.zeros((n, action_dim), jnp.float32),
            "value_target": jnp.zeros(n, jnp.float32),
            "policy_weight": jnp.ones(n, jnp.float32),
        }
        self.storage = jax.device_put(self.storage, shard)

        # Per-shard host bookkeeping. The parent's single global tree
        # is unused — sampling is stratified per shard.
        self.tree = None
        self.trees: "list[SumTree] | None" = (
            [SumTree(self.cap_local) for _ in range(dp)]
            if self.use_per
            else None
        )
        self._cursors = np.zeros(dp, dtype=np.int64)
        self._sizes = np.zeros(dp, dtype=np.int64)
        # Device program dispatches this ring made (telemetry gauge).
        self.dispatch_count = 0

        from ..parallel.sharding import shard_map_compat

        self._ingest_jit = jax.jit(
            shard_map_compat(
                self._ingest_local,
                mesh=mesh,
                in_specs=(P(dp_axis), P(dp_axis), P(None, dp_axis)),
                out_specs=(P(dp_axis), P(dp_axis)),
            ),
            donate_argnums=(0,),
        )

    # --- device ingest ----------------------------------------------------

    def _ingest_local(
        self,
        storage_local: dict[str, jax.Array],
        cursor_local: jax.Array,
        blocks_local: tuple[dict[str, jax.Array], ...],
    ):
        """One shard's ring-scatter: the SAME `ring_scatter` math as the
        single-device ring, over the LOCAL lanes and the LOCAL ring
        shard (cap = cap_local). Runs under shard_map with no
        collectives — the partitioning IS the distribution."""
        new_storage, _, count = ring_scatter(
            storage_local, cursor_local[0], blocks_local, self.cap_local
        )
        return new_storage, count.reshape(1)

    def _ingest_blocks(
        self, blocks: "tuple[dict[str, Any], ...]"
    ) -> tuple[int, np.ndarray]:
        """Run the sharded ingest. Returns (total rows written, their
        globally-encoded slots in per-shard write order)."""
        self.storage, counts_dev = self._ingest_jit(
            self.storage, jnp.asarray(self._cursors, jnp.int32), blocks
        )
        self.dispatch_count += 1
        counts = np.asarray(counts_dev)  # (dp,) — the one fetch
        return self.reconcile_ingest(counts)

    def reconcile_ingest(
        self,
        counts: np.ndarray,
        max_priority: "float | None" = None,
    ) -> tuple[int, np.ndarray]:
        """Host bookkeeping for rows a device program ALREADY scattered
        into the shards (per-shard write order: cursor, cursor+1, ...):
        SumTree max-priority init, per-shard cursors/sizes, the global
        size. Callers are the dispatching ingest above and the sharded
        megastep (rl/megastep.py), which scatters INSIDE its fused
        program and reconciles from the returned per-shard counts.

        `max_priority` pins the watermark fresh rows enter at — the
        sharded megastep passes the single pre-dispatch watermark its
        device program sampled against, so mirror and device priorities
        stay row-for-row equal; None uses each tree's own current
        watermark (the plain ingest path's per-shard semantics).

        Returns (total rows written, their globally-encoded slots in
        per-shard write order)."""
        counts = np.asarray(counts).reshape(-1)
        # Host-side slot reconstruction below assumes each shard wrote
        # at most cap_local rows this ingest (slot uniqueness): a count
        # above cap_local would mean the ring lapped itself WITHIN one
        # scatter, making `cursor + arange(c) % cap_local` repeat slots
        # — later writes would silently win and the SumTree priorities
        # would attach to overwritten rows. The engine cannot produce
        # it (a chunk's lanes-per-shard x (T + n) rows are sized well
        # under capacity), so a trip here means a config/payload bug.
        assert int(counts.max(initial=0)) <= self.cap_local, (
            f"sharded ingest wrote {counts.max()} rows into a "
            f"{self.cap_local}-slot shard in one scatter; per-shard "
            "slot uniqueness is violated (shrink the chunk or grow "
            "BUFFER_CAPACITY)"
        )
        all_slots = []
        for k in range(self.dp):
            c = int(counts[k])
            if c == 0:
                continue
            local = (self._cursors[k] + np.arange(c)) % self.cap_local
            all_slots.append(k * self.stride + local)
            if self.trees is not None:
                tree = self.trees[k]
                watermark = (
                    tree.max_priority
                    if max_priority is None
                    else max_priority
                )
                tree.update_batch(
                    local,
                    np.full(c, watermark, dtype=np.float64),
                )
                tree.data_pointer = int(
                    (self._cursors[k] + c) % self.cap_local
                )
                tree.n_entries = int(
                    min(self._sizes[k] + c, self.cap_local)
                )
            self._cursors[k] = (self._cursors[k] + c) % self.cap_local
            self._sizes[k] = min(self._sizes[k] + c, self.cap_local)
        self._size = int(self._sizes.sum())
        slots = (
            np.concatenate(all_slots)
            if all_slots
            else np.zeros(0, dtype=np.int64)
        )
        return int(counts.sum()), slots

    def ingest_payload(self, payload: dict[str, Any]) -> int:
        """Fold one dp-sharded rollout chunk's device-resident outputs
        into the sharded ring. Each device's lanes scatter into its own
        shard; only the per-shard counts come back."""
        return self._ingest_blocks((payload["mat"], payload["flush"]))[0]

    # --- in-program entry points (the sharded megastep's shard_map) -------

    @property
    def max_priority(self) -> float:
        """Global max-priority watermark across the shard trees. The
        sharded megastep passes ONE watermark into its device program
        (fresh rows on every shard enter at it before sampling) and
        `reconcile_ingest` re-applies the same one to the mirror."""
        if self.trees is None:
            return 1.0
        return float(max(t.max_priority for t in self.trees))

    def scatter_local(
        self,
        storage_local: dict[str, jax.Array],
        priorities_local: "jax.Array | None",
        cursor: jax.Array,
        blocks_local: tuple,
        max_priority: jax.Array,
    ):
        """One shard's ring scatter + PER priority init, for use INSIDE
        an enclosing `shard_map` body (the sharded megastep's fused
        program). Same `ring_scatter` math as `_ingest_local`, plus the
        priority bookkeeping the fused program needs before it samples:
        fresh rows enter at the caller's max-priority watermark and the
        trash row (local index cap_local) pins to 0 so sampling can
        never return it. `priorities_local` is the shard's (stride,)
        slice, or None for uniform replay.

        Returns (new_storage, new_priorities, rows written)."""
        new_storage, _, count, pos, keep = ring_scatter(
            storage_local,
            cursor,
            blocks_local,
            self.cap_local,
            with_positions=True,
        )
        if priorities_local is not None:
            priorities_local = priorities_local.at[pos].set(
                jnp.where(keep, max_priority, 0.0)
            )
            priorities_local = priorities_local.at[self.cap_local].set(0.0)
        return new_storage, priorities_local, count

    def sample_local(
        self,
        priorities_local: jax.Array,
        size: jax.Array,
        k: int,
        b_local: int,
        key: jax.Array,
        beta: jax.Array,
    ):
        """One shard's stratified (K, b_local) slot sampling inside an
        enclosing `shard_map` body. PER: the shared stratified draw over
        the shard's own priority slice (ops/per_sample.py;
        `TrainConfig.PER_SAMPLE_BACKEND` picks the searchsorted or
        Pallas compare-count lowering) — the vectorized equivalent of
        this shard's SumTree descent (utils/sumtree.py); zero-priority
        (empty/trash) slots have empty cumsum segments and are never
        selected. IS weights come back UNNORMALIZED — the caller
        max-normalizes across the GLOBAL batch (a pmax over dp),
        matching `sample`'s single batch-wide normalization. Uniform:
        floor(u * size), unit weights.

        Returns (local slot indices (K, b_local) int32, weights)."""
        size_f = size.astype(jnp.float32)
        if self.use_per:
            idx, probs = per_sample(
                priorities_local,
                self.cap_local,
                k,
                b_local,
                key,
                mode=self.per_sample_backend,
            )
            weights = (size_f * probs) ** (-beta)
        else:
            u = jax.random.uniform(key, (k, b_local))
            idx = jnp.clip(
                jnp.floor(u * size_f).astype(jnp.int32),
                0,
                jnp.maximum(size - 1, 0),
            )
            weights = jnp.ones((k, b_local), jnp.float32)
        return idx, weights

    # --- memory attribution (telemetry/memory.py) -------------------------

    def storage_nbytes(self) -> int:
        """Exact bytes of the sharded ring storage across all dp shards
        (dtype/shape math; `storage_nbytes() // dp` is the per-device
        HBM the ring occupies)."""
        from ..telemetry.memory import tree_bytes

        return tree_bytes(self.storage)

    def memory_record(self) -> dict:
        """This ring's `kind: "memory"` ledger record (dp-sharded)."""
        from ..telemetry.memory import replay_ring_record

        return replay_ring_record(
            self.storage_nbytes(),
            self.capacity,
            shards=self.dp,
            location="device",
        )

    def add_dense(
        self,
        grid: np.ndarray,
        other_features: np.ndarray,
        policy_target: np.ndarray,
        value_target: np.ndarray,
        policy_weight: np.ndarray | None = None,
    ) -> np.ndarray:
        """Host-array insert (restore path, tests). Rows stripe across
        the dp shards (contiguous N/dp runs per shard — slot layout
        differs from the host ring, which replay semantics permit);
        ragged counts are padded with masked rows."""
        grid = np.asarray(grid, dtype=np.float32)
        k = grid.shape[0]
        if k == 0:
            return np.zeros(0, dtype=np.int64)
        pad = (-k) % self.dp
        n = k + pad

        def padded(a: np.ndarray, dtype) -> jnp.ndarray:
            a = np.asarray(a, dtype=dtype)
            if pad:
                a = np.concatenate(
                    [a, np.zeros((pad, *a.shape[1:]), dtype=dtype)]
                )
            return jnp.asarray(a[None])  # (1, N, ...) lane dim on axis 1

        mask = np.ones(n, bool)
        mask[k:] = False
        block = {
            "grid": padded(grid, np.float32),
            "other": padded(other_features, np.float32),
            "policy": padded(policy_target, np.float32),
            "ret": padded(
                np.asarray(value_target, np.float32).reshape(-1), np.float32
            ),
            "pw": padded(
                np.ones(k, np.float32)
                if policy_weight is None
                else np.asarray(policy_weight, np.float32).reshape(-1),
                np.float32,
            ),
            "mask": jnp.asarray(mask[None]),
        }
        count, slots = self._ingest_blocks((block,))
        if count < k:
            logger.warning(
                "ShardedDeviceReplayBuffer: dropped %d invalid rows "
                "of %d on add.",
                k - count,
                k,
            )
        return slots.astype(np.int64)

    # --- sampling ---------------------------------------------------------

    def sample(
        self, batch_size: int, current_train_step: int | None = None
    ) -> "dict[str, np.ndarray] | None":
        """Stratified per-shard sampling: B/dp rows from each shard's
        own tree, returned shard-major so the (K, B) index upload's
        axis-1 sharding lands each shard's slice on its device.
        Returns {"indices" (globally encoded), "weights"} or None."""
        if batch_size % self.dp != 0:
            raise ValueError(
                f"BATCH_SIZE={batch_size} must divide over dp={self.dp} "
                "for the sharded ring (each device gathers B/dp rows)."
            )
        b_local = batch_size // self.dp
        if not self.is_ready() or any(
            self._sizes[k] < b_local for k in range(self.dp)
        ):
            return None
        indices = np.empty(batch_size, dtype=np.int64)
        weights = np.empty(batch_size, dtype=np.float32)
        for k in range(self.dp):
            lo, hi = k * b_local, (k + 1) * b_local
            if self.use_per:
                if current_train_step is None:
                    raise ValueError(
                        "current_train_step is required for PER sampling."
                    )
                assert self.trees is not None
                tree = self.trees[k]
                slots, priorities = tree.sample_batch(b_local, self._rng)
                probs = np.maximum(priorities, 1e-12) / max(
                    tree.total_priority, 1e-12
                )
                beta = self.beta(current_train_step)
                weights[lo:hi] = (self._sizes[k] * probs) ** (-beta)
            else:
                slots = self._rng.integers(
                    0, self._sizes[k], size=b_local
                )
                weights[lo:hi] = 1.0
            indices[lo:hi] = k * self.stride + slots
        # Max-normalize across the WHOLE batch (matches the host path's
        # single normalization; per-shard maxima would skew shards).
        weights = (weights / weights.max()).astype(np.float32)
        return {"indices": indices, "weights": weights}

    def update_priorities(
        self, indices: np.ndarray, td_errors: np.ndarray
    ) -> None:
        """Route the parent's `p = (|δ| + ε)^α` update to each shard's
        tree via the global index encoding."""
        if not self.use_per or self.trees is None:
            return
        indices = np.asarray(indices, dtype=np.int64).reshape(-1)
        td = np.asarray(td_errors, dtype=np.float64).reshape(-1)
        if indices.shape != td.shape:
            raise ValueError(
                f"indices {indices.shape} and td_errors {td.shape} "
                "must match."
            )
        if len(indices) == 0:
            return
        td = np.where(np.isfinite(td), td, 0.0)
        priorities = (np.abs(td) + self.per_epsilon) ** self.alpha
        shard = indices // self.stride
        slot = indices % self.stride
        for k in range(self.dp):
            m = shard == k
            if m.any():
                self.trees[k].update_batch(slot[m], priorities[m])

    # --- persistence ------------------------------------------------------

    def get_state(self) -> dict[str, Any]:
        """Snapshot interchangeable with the host/device buffers: valid
        rows concatenated shard by shard (each shard's rows in
        chronological order; cross-shard interleaving is not recorded —
        replay sampling is order-free, so only row+priority content
        matters)."""
        state: dict[str, Any] = {
            "pos": 0,
            "size": self._size,
            "storage": None,
            "priorities": None,
        }
        if self._size == 0:
            return state
        host = jax.device_get(self.storage)
        parts: dict[str, list] = {k: [] for k in host}
        pri_parts: list[np.ndarray] = []
        for k in range(self.dp):
            sz = int(self._sizes[k])
            if sz == 0:
                continue
            # Chronological within the shard: oldest at the cursor when
            # the shard ring has wrapped.
            order = np.arange(sz)
            if sz == self.cap_local:
                order = np.roll(order, -int(self._cursors[k]))
            rows = k * self.stride + order
            for name, arr in host.items():
                parts[name].append(np.asarray(arr[rows]).copy())
            if self.trees is not None:
                leaves = order + self.trees[k]._cap2
                pri_parts.append(self.trees[k].tree[leaves].copy())
        state["storage"] = {
            name: np.concatenate(chunks) for name, chunks in parts.items()
        }
        if pri_parts:
            state["priorities"] = np.concatenate(pri_parts)
        # Rows are already chronological per shard; mark unwrapped so a
        # restorer's slot->chronology roll is a no-op.
        state["pos"] = min(self._size, self.capacity)
        return state

    def set_state(self, state: dict[str, Any]) -> None:
        """Restore a snapshot from ANY buffer kind by re-ingesting its
        rows (striped across shards) and re-attaching priorities."""
        storage = state.get("storage")
        if storage is None:
            return
        old_size = int(state["size"])
        old_pos = int(state["pos"])
        order = np.roll(
            np.arange(old_size), -(old_pos % max(old_size, 1))
        )
        n = min(old_size, self.capacity)
        order = order[-n:]  # keep newest on shrink

        # Reset shards.
        self._cursors[:] = 0
        self._sizes[:] = 0
        self._size = 0
        if self.use_per:
            self.trees = [SumTree(self.cap_local) for _ in range(self.dp)]

        slots = self.add_dense(
            np.asarray(storage["grid"])[order].astype(np.float32),
            np.asarray(storage["other_features"])[order],
            np.asarray(storage["policy_target"])[order],
            np.asarray(storage["value_target"])[order],
            policy_weight=np.asarray(
                storage.get(
                    "policy_weight", np.ones(old_size, np.float32)
                )
            )[order],
        )
        pri = state.get("priorities")
        if pri is not None and self.trees is not None:
            pri = np.asarray(pri, dtype=np.float64)[order]
            if len(pri) == len(slots):
                # update_priorities would re-apply the (|δ|+ε)^α
                # transform; these are already priorities.
                shard = slots // self.stride
                slot = slots % self.stride
                for k in range(self.dp):
                    m = shard == k
                    if m.any():
                        self.trees[k].update_batch(slot[m], pri[m])
            else:
                logger.warning(
                    "Priority snapshot length %d != restored rows %d; "
                    "keeping max-priority init.",
                    len(pri),
                    len(slots),
                )
