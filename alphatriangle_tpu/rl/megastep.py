"""Anakin-style fused megastep: rollout chunk + ring ingest + K learner
steps as ONE device program (Podracer, arXiv:2104.06272 §2 "Anakin").

The round-5 bench showed the cost of host-orchestrated phases: the
overlapped loop ran at 0.774x of serialized self-play and fused learner
steps gained nothing (0.44 -> 0.45 steps/s), because every iteration
pays per-phase host round trips — dispatch chunk, fetch, fold, sample,
dispatch learner — and the phases contend in the device FIFO instead of
composing. Anakin's answer is to keep acting, replay and learning
inside one XLA program so the only host work per iteration is fetching
metrics. This module composes the three seams the codebase already has
into that program:

- `SelfPlayEngine._chunk` (rl/self_play.py): `ROLLOUT_CHUNK_MOVES`
  lockstep moves of all B games, driven by the learner's *current
  on-device params* (`TrainState.params`), so weight sync is free and
  ZERO-staleness — there is no `sync_to_network` copy on the hot path,
  and every move of every megastep searches with the newest weights.
- `ring_scatter` (rl/device_buffer.py): the chunk's masked experience
  outputs scatter straight into the device-resident replay ring —
  nothing is fetched, nothing is re-uploaded.
- `Trainer._train_steps_from_impl` (rl/trainer.py): K training batches
  are sampled ON DEVICE from the ring (stratified proportional PER over
  a device-resident priority array, or uniform), gathered, and run as K
  fused SGD steps.

Only stats/metrics/TD summaries return to the host: ONE dispatch and
ONE `device_get` per iteration, counter-asserted in the tests.

PER semantics (host mirror reconciliation):

The priority array lives on device and is the sampling truth inside the
program: freshly ingested rows get max-priority init before sampling,
and the group's TD errors update priorities in step order after the
fused steps ((|δ|+ε)^α — the same formula as the host SumTree). The
host SumTree stays alive as a *mirror*, reconciled at megastep
boundaries from the returned (slots, TD errors): it serves beta
annealing, readiness gating, the max-priority watermark, metrics and —
critically — buffer persistence, so checkpoints and resume are
interchangeable with the other loop modes. `sync_priorities_from_host`
(re)seeds the device array from the mirror after restores/warmup.

dp-sharded megastep (multi-device meshes):

On a single-process dp-only mesh the SAME fused program spans every
device (program family `megastep/dp<D>_t<T>_k<K>`), composing the three
sharded seams the codebase already has:

- the rollout chunk runs lane-sharded under GSPMD (each device plays
  its B/dp games — lanes are independent, so no collectives appear);
- ONE `shard_map` region (parallel/sharding.py::shard_map_compat) does
  the per-shard replay work with no collectives except a weight-norm
  `pmax`: every shard ring-scatters ITS lanes' rows into ITS ring shard
  (`ShardedDeviceReplayBuffer.scatter_local`, cap_local slots + a trash
  row), max-priority-inits them in its slice of the dp-sharded priority
  array, samples its B/dp stratum of each of the K batches from that
  device-local slice (`sample_local`, per-shard rng via
  `fold_in(key, axis_index)`), IS-normalizes against the global batch
  max (`pmax` over dp), and gathers its sampled rows locally — indices
  come back globally encoded as `shard * stride + slot`;
- the K learner steps run on the dp-sharded stacked batch under GSPMD
  with replicated params: the gradient `psum` over dp is inserted by
  XLA from the shardings (the repo-wide idiom — rl/trainer.py spells no
  collective by hand), so params stay bit-identical on every shard;
- a second small `shard_map` writes the K steps' TD-error priorities
  back into each shard's priority slice, in step order.

Host reconciliation generalizes per shard: the program returns (dp,)
per-shard counts + globally-encoded (K, B) sampled indices + TD errors,
and the host replays them into the per-shard SumTree mirrors
(`ShardedDeviceReplayBuffer.reconcile_ingest` at the SAME pre-dispatch
max-priority watermark the device sampled against, then
`update_priorities` routed by the global index encoding). Checkpoints
keep flowing through the buffer's snapshot contract, so resume is
interchangeable with sync/overlapped/single-device-megastep runs.

Scope: single-process; single-device mesh, or a dp-only mesh whose
capacity/batch/lanes divide dp (the `ShardedDeviceReplayBuffer` gate in
training/setup.py). Sharded sampling draws per-shard strata with
per-shard keys, so sampled BATCHES differ from a single-device run at
the same seed — the pinned invariants are params bit-identical across
shards and device/host priority agreement per shard
(tests/test_megastep_sharded.py).

CPU note: the program contains learner steps, so it rides
`cpu_aot=False` like the rest of the learner family (an XLA:CPU
deserialized executable of a donating learner program returns the train
state UNCHANGED — see rl/trainer.py). The donation/reload regression
guard (params actually update across megasteps) is pinned in
tests/test_megastep.py.
"""

import functools
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..compile_cache import config_digest, get_compile_cache
from ..config.train_config import TrainConfig
from ..nn.precision import cast_params_for_inference
from ..ops import per_sample
from ..telemetry.device_stats import (
    beacon_signature,
    beacons_armed,
    device_stats_signature,
    emit_beacon,
    fold_search_stats,
    note_dispatch,
    rollout_chunk_stats,
)
from ..telemetry.flight import flight_span
from .device_buffer import DeviceReplayBuffer, ring_scatter

logger = logging.getLogger(__name__)


class MegastepRunner:
    """Owns the fused megastep program binding one (engine, trainer,
    device ring) triple; the training loop's third mode
    (`TrainConfig.FUSED_MEGASTEP`) drives it one call per iteration."""

    def __init__(
        self,
        engine,
        trainer,
        buffer: DeviceReplayBuffer,
        train_config: TrainConfig,
    ):
        if not getattr(buffer, "is_device", False):
            raise ValueError(
                "MegastepRunner needs a device-resident replay ring "
                "(rl/device_buffer.DeviceReplayBuffer, or the dp-sharded "
                "rl/sharded_device_buffer.ShardedDeviceReplayBuffer)."
            )
        if jax.process_count() > 1:
            raise ValueError("MegastepRunner is single-process only.")
        self.sharded = bool(getattr(buffer, "is_sharded", False))
        if self.sharded:
            # The fused program's shard_map region pairs each device's
            # rollout lanes with its own ring shard: the engine must
            # shard its lanes over exactly the ring's mesh + dp axis.
            if engine.mesh is None or engine.mesh != buffer.mesh:
                raise ValueError(
                    "Sharded megastep: the self-play engine must shard "
                    "its lanes over the replay ring's mesh (got engine "
                    f"mesh {engine.mesh}, ring mesh {buffer.mesh})."
                )
            if tuple(engine.data_axes) != (buffer.dp_axis,):
                raise ValueError(
                    "Sharded megastep: engine lanes must ride exactly "
                    f"the ring's dp axis ({buffer.dp_axis!r}); got "
                    f"{tuple(engine.data_axes)}."
                )
            if trainer.mesh != buffer.mesh:
                raise ValueError(
                    "Sharded megastep: trainer and replay ring must "
                    "share one mesh."
                )
            if train_config.BATCH_SIZE % buffer.dp != 0:
                raise ValueError(
                    f"BATCH_SIZE={train_config.BATCH_SIZE} must divide "
                    f"over dp={buffer.dp} (each shard samples its B/dp "
                    "stratum in-program)."
                )
        elif engine.mesh is not None:
            raise ValueError(
                "MegastepRunner with the single-device ring needs a "
                "single-device engine; mesh-sharded lanes pair with the "
                "dp-sharded ring (ShardedDeviceReplayBuffer)."
            )
        self.engine = engine
        self.trainer = trainer
        self.buffer = buffer
        self.config = train_config
        self.batch_size = train_config.BATCH_SIZE
        self.cap = buffer.capacity
        self.dp = buffer.dp if self.sharded else 1
        self.use_per = train_config.USE_PER
        self.per_alpha = float(train_config.PER_ALPHA)
        self.per_epsilon = float(train_config.PER_EPSILON)
        self.beta_initial = float(train_config.PER_BETA_INITIAL)
        self.beta_final = float(train_config.PER_BETA_FINAL)
        self.beta_anneal = float(train_config.PER_BETA_ANNEAL_STEPS or 1)
        self.per_sample_backend = train_config.PER_SAMPLE_BACKEND
        # Device-resident priority array — the sampling truth inside
        # the program. Single-device: (cap + 1,) float32, the +1 the
        # trash slot pinned at priority 0 so it is never sampled.
        # Sharded: (dp * stride,) float32 sharded over dp, one trash
        # slot per shard at local index cap_local. None until
        # `sync_priorities_from_host` seeds it (lazily on the first
        # megastep, or explicitly after a checkpoint restore).
        self._priorities: jax.Array | None = None
        # One compiled program per distinct (chunk moves, K) pair, AOT
        # cached. cpu_aot=False: the program donates + updates the train
        # state, the exact family whose XLA:CPU deserialization silently
        # returns donated state unchanged (rl/trainer.py).
        # Device telemetry plane (telemetry/device_stats.py): the
        # stat-pack flag rides the engine's searches (snapshotted at
        # engine construction) and adds output leaves; beacons embed
        # host callbacks. Both shape the program, so both join the
        # cache extra, and beacon-armed executables skip serialization.
        self.device_stats = bool(getattr(engine, "device_stats", False))
        self.last_device_stats: "dict | None" = None
        extra = (
            config_digest(
                engine.mcts_config,
                train_config,
                trainer.nn.model_config,
                engine.env.cfg,
            )
            + (
                f"|att{int(getattr(trainer.nn.model, 'attention_fn', None) is not None)}"
            )
            + device_stats_signature()
            + beacon_signature()
        )
        impl = self._sharded_impl if self.sharded else self._impl
        name = (
            (lambda t, k: f"megastep/dp{self.dp}_t{t}_k{k}")
            if self.sharded
            else (lambda t, k: f"megastep/t{t}_k{k}")
        )
        self._name_fn = name
        self._megastep_fn = functools.lru_cache(maxsize=None)(
            lambda t, k: get_compile_cache().wrap(
                name(t, k),
                jax.jit(
                    functools.partial(impl, t, k),
                    donate_argnums=(0, 1, 2, 3),
                ),
                extra=extra,
                cpu_aot=False,
                serialize=not beacons_armed(),
            )
        )
        # Observability: program dispatches (the loop's one-dispatch-
        # per-iteration assertion reads this) and blocking fetch time
        # (telemetry/perf.py transfer accounting).
        self.dispatch_count = 0
        self.transfer_d2h_seconds = 0.0
        # Flight recorder (telemetry/flight.py); training/setup.py and
        # the loop's lazy construction path attach the run's recorder.
        self.flight = None

    # --- device program ---------------------------------------------------

    def _sample_indices(self, priorities, size, state, k: int):
        """On-device (K, B) slot sampling + IS weights.

        PER: stratified proportional sampling over the priority array
        (ops/per_sample.py; `TrainConfig.PER_SAMPLE_BACKEND` picks the
        searchsorted or Pallas compare-count lowering) — the vectorized
        equivalent of the host SumTree's stratified descent
        (utils/sumtree.py). Zero-priority (empty/trash) slots are never
        selected: their cumsum segments are empty. Uniform:
        floor(u * size).
        """
        b = self.batch_size
        rng, k_sample = jax.random.split(state.rng)
        state = state.replace(rng=rng)
        if self.use_per:
            idx, probs = per_sample(
                priorities,
                self.cap,
                k,
                b,
                k_sample,
                mode=self.per_sample_backend,
            )
            # Beta annealed on the learner-step clock, exactly as the
            # host mirror's `ExperienceBuffer.beta` computes it.
            frac = jnp.clip(
                state.step.astype(jnp.float32) / self.beta_anneal, 0.0, 1.0
            )
            beta = self.beta_initial + frac * (
                self.beta_final - self.beta_initial
            )
            w = (size.astype(jnp.float32) * probs) ** (-beta)
            weights = (
                w / jnp.max(w, axis=1, keepdims=True)
            ).astype(jnp.float32)
        else:
            u = jax.random.uniform(k_sample, (k, b))
            idx = jnp.clip(
                jnp.floor(u * size.astype(jnp.float32)).astype(jnp.int32),
                0,
                jnp.maximum(size - 1, 0),
            )
            weights = jnp.ones((k, b), jnp.float32)
        return state, idx, weights

    def _per_stat_pack(self, priorities, weights) -> dict:
        """Ingest/PER stat leg of the device stat-pack: priority-mass
        skew (max over mean of the live slots — empty and trash slots
        sit at exactly 0 so the mask is free) and the IS-weight
        extremes of the K sampled batches. Pure reductions over arrays
        already in the program; rides the one fetch."""
        live = priorities
        count = jnp.maximum((live > 0).sum(), 1).astype(jnp.float32)
        mean_live = jnp.maximum(live.sum() / count, 1e-9)
        return {
            "priority_skew": live.max() / mean_live,
            "is_weight_min": weights.min(),
            "is_weight_max": weights.max(),
        }

    def _impl(
        self,
        num_moves: int,
        k: int,
        state,
        carry,
        storage,
        priorities,
        cursor,
        size,
        max_priority,
    ):
        """The fused megastep (pure; donated: state, carry, storage,
        priorities). Returns (state', carry', storage', priorities',
        host outputs) — the host outputs are the ONLY fetch."""
        # 1. Rollout chunk with the learner's live params: weight sync
        # is the absence of a copy. The version tag for staleness
        # accounting is the learner step itself (zero staleness by
        # construction: every episode starts under the current step).
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        # Inference precision policy (nn/precision.py): the rollout
        # phase reads a cast copy; the learner steps below keep
        # consuming the f32 originals in `state`.
        variables = cast_params_for_inference(
            variables, self.trainer.nn.model_config
        )
        new_carry, outs = self.engine._chunk(
            num_moves, variables, carry, state.step.astype(jnp.int32)
        )
        emit_beacon("rollout_chunk", state.step)
        mat, flush = outs.pop("mat"), outs.pop("flush")
        ds_search = outs.pop("device_stats", None)

        # 2. Scatter the harvest into the device ring (same math as
        # DeviceReplayBuffer._ingest_impl, positions kept for PER).
        new_storage, new_cursor, count, pos, keep = ring_scatter(
            storage, cursor, (mat, flush), self.cap, with_positions=True
        )
        new_size = jnp.minimum(size + count, self.cap)
        emit_beacon("ring_scatter", state.step)

        # 3. Max-priority init for the fresh rows (host-ring parity),
        # trash slot pinned to 0 so sampling can never return it.
        if self.use_per:
            priorities = priorities.at[pos].set(
                jnp.where(keep, max_priority, 0.0)
            )
            priorities = priorities.at[self.cap].set(0.0)

        # 4. Sample K batches on device (post-ingest: fresh rows are
        # immediately eligible, as in the sync loop's fold-then-sample).
        state, idx, weights = self._sample_indices(
            priorities, new_size, state, k
        )
        ds_per = (
            self._per_stat_pack(priorities, weights)
            if self.device_stats
            else None
        )

        # 5. K fused learner steps gathered from the ring (the exact
        # program body Trainer.train_steps_from dispatches).
        new_state, metrics_k, td_k = self.trainer._train_steps_from_impl(
            state, new_storage, idx, weights
        )

        # 6. Priority updates from the group's TD errors, in step order
        # (deterministic last-write-wins for rows sampled by several
        # steps — the same net effect as the host path's sequential
        # per-step update_batch calls).
        if self.use_per:
            for j in range(k):
                prio_j = (
                    jnp.abs(td_k[j]) + self.per_epsilon
                ) ** self.per_alpha
                priorities = priorities.at[idx[j]].set(
                    prio_j.astype(jnp.float32)
                )

        out = {
            "rows_added": count,
            "episode": outs["episode"],
            "trace": outs["trace"],
            "sentinel_live": outs["sentinel_live"],
            "metrics": metrics_k,
            "td": td_k,
            "idx": idx,
            # Stat-pack legs (None = empty pytree nodes when off):
            # search leg from the chunk's scanned waves, PER leg from
            # the sampling phase. They ride this one fetch.
            "device_stats": {"search": ds_search, "per": ds_per},
        }
        return new_state, new_carry, new_storage, priorities, out

    def _sharded_impl(
        self,
        num_moves: int,
        k: int,
        state,
        carry,
        storage,
        priorities,
        cursors,
        sizes,
        max_priority,
    ):
        """The dp-sharded fused megastep (pure; donated: state, carry,
        storage, priorities). Same five phases as `_impl`, with the
        replay phases per shard under ONE shard_map region and the
        rollout/learner phases under GSPMD — the learner's gradient
        psum over dp comes from the shardings (replicated params,
        dp-sharded batch), not from hand-written collectives, so params
        stay bit-identical on every shard.

        `cursors`/`sizes` are (dp,) int32 per-shard ring state (from
        the host mirror, like `_impl`'s scalar cursor/size); indices
        return globally encoded (`shard * stride + slot`)."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.sharding import shard_map_compat

        buf = self.buffer
        dp_axis = buf.dp_axis
        b_local = self.batch_size // buf.dp

        # 1. Rollout chunk with the learner's live params, lane-sharded
        # over dp under GSPMD (the engine's own mesh-mode program body).
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        # Inference precision policy (nn/precision.py): the rollout
        # phase reads a cast copy; the learner steps below keep
        # consuming the f32 originals in `state`.
        variables = cast_params_for_inference(
            variables, self.trainer.nn.model_config
        )
        new_carry, outs = self.engine._chunk(
            num_moves, variables, carry, state.step.astype(jnp.int32)
        )
        emit_beacon("rollout_chunk", state.step)
        mat, flush = outs.pop("mat"), outs.pop("flush")
        ds_search = outs.pop("device_stats", None)

        # Per-call scalars for the shard_map region, computed OUTSIDE
        # it: one sampling key split off the train state (each shard
        # folds in its axis index for an independent stratum draw) and
        # beta on the learner-step clock, exactly as `_sample_indices`.
        rng, k_sample = jax.random.split(state.rng)
        state = state.replace(rng=rng)
        if self.use_per:
            frac = jnp.clip(
                state.step.astype(jnp.float32) / self.beta_anneal, 0.0, 1.0
            )
            beta = self.beta_initial + frac * (
                self.beta_final - self.beta_initial
            )
        else:
            beta = jnp.float32(0.0)

        def shard_body(
            storage_local,
            priorities_local,
            cursor_local,
            size_local,
            mat_local,
            flush_local,
            max_p,
            key,
            beta_,
        ):
            # 2+3. Scatter this shard's lanes into this shard's ring
            # slice, fresh rows max-priority-inited, trash row pinned.
            new_storage, new_prios, count = buf.scatter_local(
                storage_local,
                priorities_local if self.use_per else None,
                cursor_local[0],
                (mat_local, flush_local),
                max_p,
            )
            if new_prios is None:
                new_prios = priorities_local
            new_size = jnp.minimum(size_local[0] + count, buf.cap_local)
            # 4. Sample this shard's B/dp stratum of each of the K
            # batches from the device-local priority slice.
            shard = jax.lax.axis_index(dp_axis)
            idx_local, w = buf.sample_local(
                new_prios,
                new_size,
                k,
                b_local,
                jax.random.fold_in(key, shard),
                beta_,
            )
            if self.use_per:
                # One max-normalization across the GLOBAL batch per
                # step row (the host path's single batch-wide
                # normalization) — the region's only collective.
                wmax = jax.lax.pmax(
                    jnp.max(w, axis=1, keepdims=True), dp_axis
                )
                w = w / wmax
            w = w.astype(jnp.float32)
            # Local row gather: each device reads only its own shard.
            rows = {name: v[idx_local] for name, v in new_storage.items()}
            idx_global = (shard * buf.stride + idx_local).astype(jnp.int32)
            return (
                new_storage,
                new_prios,
                count.reshape(1),
                idx_global,
                w,
                rows,
            )

        shd, stk, rep = P(dp_axis), P(None, dp_axis), P()
        (
            new_storage,
            priorities,
            counts,
            idx,
            weights,
            rows,
        ) = shard_map_compat(
            shard_body,
            mesh=buf.mesh,
            in_specs=(shd, shd, shd, shd, stk, stk, rep, rep, rep),
            out_specs=(shd, shd, shd, stk, stk, stk),
        )(storage, priorities, cursors, sizes, mat, flush,
          max_priority, k_sample, beta)
        emit_beacon("ring_scatter", state.step)
        # PER stat leg over the dp-sharded priority array + stacked
        # weights: plain jnp reductions — GSPMD inserts the cross-shard
        # collectives from the shardings, same idiom as the learner's
        # gradient psum below.
        ds_per = (
            self._per_stat_pack(priorities, weights)
            if self.device_stats
            else None
        )

        # 5. K fused learner steps on the (K, B) stacked batch, dp-
        # sharded on axis 1 (the shard_map's out_specs): GSPMD inserts
        # the gradient all-reduce over dp, params remain replicated.
        stacked = {
            "grid": rows["grid"].astype(jnp.float32),
            "other_features": rows["other_features"],
            "policy_target": rows["policy_target"],
            "value_target": rows["value_target"],
            "policy_weight": rows["policy_weight"],
            "weights": weights,
        }
        new_state, metrics_k, td_k = self.trainer._train_steps_impl(
            state, stacked
        )

        # 6. TD-error priority write-back, per shard in step order
        # (each shard owns exactly the indices it sampled — the global
        # encoding routes by arithmetic, no cross-shard traffic).
        if self.use_per:
            stride = buf.stride

            def write_prios(priorities_local, idx_local, td_local):
                base = jax.lax.axis_index(dp_axis) * stride
                p = priorities_local
                for j in range(k):
                    prio_j = (
                        jnp.abs(td_local[j]) + self.per_epsilon
                    ) ** self.per_alpha
                    p = p.at[idx_local[j] - base].set(
                        prio_j.astype(jnp.float32)
                    )
                return p

            priorities = shard_map_compat(
                write_prios,
                mesh=buf.mesh,
                in_specs=(shd, stk, stk),
                out_specs=shd,
            )(priorities, idx, td_k)

        out = {
            "counts": counts,  # (dp,) per-shard rows written
            "episode": outs["episode"],
            "trace": outs["trace"],
            "sentinel_live": outs["sentinel_live"],
            "metrics": metrics_k,
            "td": td_k,
            "idx": idx,
            "device_stats": {"search": ds_search, "per": ds_per},
        }
        return new_state, new_carry, new_storage, priorities, out

    # --- host API ---------------------------------------------------------

    def _max_priority_watermark(self) -> float:
        """The pre-dispatch max-priority watermark fresh rows enter at
        — the host mirror reconciliation reuses the SAME value."""
        if self.sharded:
            return self.buffer.max_priority
        tree = self.buffer.tree
        return float(tree.max_priority) if tree is not None else 1.0

    def sync_priorities_from_host(self) -> None:
        """(Re)seed the device priority array from the host SumTree
        mirror(s) — after warmup ingests, a checkpoint restore, or any
        other host-side write. Device becomes the sampling truth from
        the next megastep on."""
        buf = self.buffer
        if self.sharded:
            # (dp * stride,) laid out shard-major, matching the global
            # encoding; per-shard trash rows stay 0.
            p = np.zeros(buf.dp * buf.stride, np.float32)
            if buf.trees is not None:
                for s, tree in enumerate(buf.trees):
                    leaves = np.arange(buf.cap_local) + tree._cap2
                    lo = s * buf.stride
                    p[lo : lo + buf.cap_local] = tree.tree[leaves]
            self._priorities = jnp.asarray(p)
            return
        p = np.zeros(self.cap + 1, np.float32)
        tree = buf.tree
        if tree is not None:
            leaves = np.arange(self.cap) + tree._cap2
            p[: self.cap] = tree.tree[leaves]
        self._priorities = jnp.asarray(p)

    def _dispatch_args(self, t: int, k: int) -> tuple:
        if self._priorities is None:
            self.sync_priorities_from_host()
        buf = self.buffer
        max_p = self._max_priority_watermark()
        if self.sharded:
            from jax.sharding import NamedSharding, PartitionSpec as P

            args = (
                self.trainer.state,
                self.engine._carry,
                buf.storage,
                self._priorities,
                jnp.asarray(buf._cursors, jnp.int32),
                jnp.asarray(buf._sizes, jnp.int32),
                jnp.float32(max_p),
            )
            shard = NamedSharding(buf.mesh, P(buf.dp_axis))
            rep = NamedSharding(buf.mesh, P())
            # Commit every argument AT ITS PROGRAM SHARDING before
            # dispatch — the same recompile trap as the single-device
            # path below, with shardings instead of a device: the first
            # call's host-built arrays (seeded priorities, cursors, the
            # scalars) would otherwise key a second compile once the
            # previous megastep's committed outputs flow back in.
            return jax.device_put(
                args,
                (
                    self.trainer._state_shard,
                    self.engine._carry_shardings(),
                    shard,
                    shard,
                    shard,
                    shard,
                    rep,
                ),
            )
        args = (
            self.trainer.state,
            self.engine._carry,
            buf.storage,
            self._priorities,
            jnp.int32(buf._pos),
            jnp.int32(buf._size),
            jnp.float32(max_p),
        )
        # Commit EVERY argument to the device before dispatch. The first
        # call's arguments are a mix of uncommitted host-built arrays
        # (initial carry window zeros, the seeded priority array, the
        # per-call scalars) and committed jit outputs, while every later
        # call sees all-committed outputs of the previous megastep — and
        # jit keys compiled executables on that placement mapping, so
        # without this the SECOND megastep silently recompiles the whole
        # program (measured: a 48s duplicate compile at bench smoke
        # scale). device_put is a no-op for anything already resident.
        return jax.device_put(args, jax.devices()[0])

    def run_megastep(
        self, num_moves: int | None = None, k: int | None = None
    ) -> tuple[list, int]:
        """One fused megastep: ONE device dispatch, ONE blocking fetch.

        Returns (per-step (metrics, TD errors) list — the
        `train_steps_finish` contract — and the number of experience
        rows ingested). Side effects: engine carry + episode stats,
        buffer storage/counters + reconciled host PER mirror, trainer
        state + host step mirror all advance.
        """
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        k = int(k or max(1, self.config.FUSED_LEARNER_STEPS))
        buf, engine, trainer = self.buffer, self.engine, self.trainer
        max_p = self._max_priority_watermark()
        args = self._dispatch_args(t, k)
        start_step = trainer._host_step
        with flight_span(
            self.flight,
            "megastep",
            self._name_fn(t, k),
            avals=f"B{self.batch_size}xT{t}xK{k}",
        ):
            note_dispatch(self._name_fn(t, k))
            (
                trainer.state,
                engine._carry,
                buf.storage,
                self._priorities,
                out,
            ) = self._megastep_fn(t, k)(*args)
            self.dispatch_count += 1
            t0 = time.perf_counter()
            host = jax.device_get(out)  # graftlint: allow(host-sync-in-hot-path) the one transfer per megastep
            self.transfer_d2h_seconds += time.perf_counter() - t0

        # --- host mirror reconciliation (megastep boundary) ----------
        if self.sharded:
            counts = np.asarray(host["counts"]).reshape(-1)
            count = int(counts.sum())
            # Per-shard SumTree mirrors, cursors and sizes replay the
            # device's scatter at the SAME pre-dispatch watermark it
            # sampled against; then the TD updates route by the global
            # index encoding, in the device's step order.
            buf.reconcile_ingest(
                counts,
                max_priority=max_p if buf.trees is not None else None,
            )
            if buf.trees is not None:
                for j in range(k):
                    buf.update_priorities(host["idx"][j], host["td"][j])
        else:
            tree = buf.tree
            count = int(host["rows_added"])
            # One chunk's rows (B * (T + n) worst case) must fit the
            # ring for the mirror's slot arithmetic to stay 1:1 with
            # surviving rows — same assumption as the sharded ring's
            # ingest assert.
            assert count <= self.cap, (
                f"megastep ingested {count} rows into a {self.cap}-slot "
                "ring in one scatter (shrink ROLLOUT_CHUNK_MOVES or grow "
                "BUFFER_CAPACITY)"
            )
            slots = (buf._pos + np.arange(count)) % self.cap
            if tree is not None and count:
                # Fresh rows at the same pre-group watermark the device
                # used.
                tree.update_batch(slots, np.full(count, max_p))
                tree.data_pointer = int((buf._pos + count) % self.cap)
                tree.n_entries = min(buf._size + count, self.cap)
            buf._pos = int((buf._pos + count) % self.cap)
            buf._size = min(buf._size + count, self.cap)
            # TD-error priority updates, in the same step order the
            # device applied them.
            if tree is not None:
                for j in range(k):
                    buf.update_priorities(host["idx"][j], host["td"][j])

        # --- engine-side stats (play_chunk's host tail) --------------
        engine.last_trace = host["trace"]
        if self.device_stats:
            ds = host.get("device_stats") or {}
            metrics = host["metrics"]
            learner = {}
            for src, dst in (
                ("grad_norm", "grad_norm_max"),
                ("update_norm", "update_norm_max"),
            ):
                if src in metrics:
                    learner[dst] = round(float(np.max(metrics[src])), 6)
            per = {
                key: round(float(val), 6)
                for key, val in (ds.get("per") or {}).items()
            }
            self.last_device_stats = {
                "search": fold_search_stats(ds.get("search")),
                "rollout": rollout_chunk_stats(
                    host["episode"]["ending"], host["trace"]["reward"]
                ),
                "per": per or None,
                "learner": learner or None,
            }
            engine.last_device_stats = self.last_device_stats
        engine._fold_episode_stats(host["episode"])
        engine._total_simulations += (
            int(host["trace"]["sims"].sum()) * engine.batch_size
        )
        engine._total_reused_visits += int(host["trace"]["reused"].sum())
        # The megastep's version clock is the learner step (zero
        # staleness); seed the harvest window tag with the group start.
        engine._min_weights_version = (
            start_step
            if engine._min_weights_version is None
            else min(engine._min_weights_version, start_step)
        )
        sentinels = int(host["sentinel_live"].sum())
        if sentinels:
            logger.warning(
                "Megastep: %d zero-visit sentinel actions on LIVE games "
                "(clamped to action 0).",
                sentinels,
            )

        # --- trainer-side results (train_steps_finish contract) ------
        trainer._host_step += k
        results = []
        for i in range(k):
            m = {key: float(v[i]) for key, v in host["metrics"].items()}
            m["learning_rate"] = float(trainer.schedule(start_step + i + 1))
            results.append((m, np.asarray(host["td"][i])))
        return results, count

    # --- AOT warming / memory analysis (cli warm / cli fit) ---------------

    def warm_megastep(
        self, num_moves: int | None = None, k: int | None = None
    ) -> bool:
        """AOT-precompile the megastep program WITHOUT executing it (no
        donation happens at lowering). True when an AOT executable is
        ready; always False on CPU (cpu_aot bypass, reported as
        skipped-cpu by `cli warm`)."""
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        k = int(k or max(1, self.config.FUSED_LEARNER_STEPS))
        return self._megastep_fn(t, k).warm(*self._dispatch_args(t, k))

    def analyze_megastep(
        self, num_moves: int | None = None, k: int | None = None
    ) -> "dict | None":
        """Memory record of the megastep program at real dispatch avals
        (telemetry/memory.py; `cli fit`) — AOT analysis only, nothing
        executes. The record persists as a `.mem.json` sidecar in the
        compile cache even on CPU, where the executable itself is
        never serialized (cpu_aot bypass); the megastep family's
        `cost_analysis()` record + `.cost.json` sidecar ride the same
        compile (telemetry/roofline.py)."""
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        k = int(k or max(1, self.config.FUSED_LEARNER_STEPS))
        return self._megastep_fn(t, k).analyze(
            *self._dispatch_args(t, k), persist=True
        )
