"""Anakin-style fused megastep: rollout chunk + ring ingest + K learner
steps as ONE device program (Podracer, arXiv:2104.06272 §2 "Anakin").

The round-5 bench showed the cost of host-orchestrated phases: the
overlapped loop ran at 0.774x of serialized self-play and fused learner
steps gained nothing (0.44 -> 0.45 steps/s), because every iteration
pays per-phase host round trips — dispatch chunk, fetch, fold, sample,
dispatch learner — and the phases contend in the device FIFO instead of
composing. Anakin's answer is to keep acting, replay and learning
inside one XLA program so the only host work per iteration is fetching
metrics. This module composes the three seams the codebase already has
into that program:

- `SelfPlayEngine._chunk` (rl/self_play.py): `ROLLOUT_CHUNK_MOVES`
  lockstep moves of all B games, driven by the learner's *current
  on-device params* (`TrainState.params`), so weight sync is free and
  ZERO-staleness — there is no `sync_to_network` copy on the hot path,
  and every move of every megastep searches with the newest weights.
- `ring_scatter` (rl/device_buffer.py): the chunk's masked experience
  outputs scatter straight into the device-resident replay ring —
  nothing is fetched, nothing is re-uploaded.
- `Trainer._train_steps_from_impl` (rl/trainer.py): K training batches
  are sampled ON DEVICE from the ring (stratified proportional PER over
  a device-resident priority array, or uniform), gathered, and run as K
  fused SGD steps.

Only stats/metrics/TD summaries return to the host: ONE dispatch and
ONE `device_get` per iteration, counter-asserted in the tests.

PER semantics (host mirror reconciliation):

The priority array lives on device and is the sampling truth inside the
program: freshly ingested rows get max-priority init before sampling,
and the group's TD errors update priorities in step order after the
fused steps ((|δ|+ε)^α — the same formula as the host SumTree). The
host SumTree stays alive as a *mirror*, reconciled at megastep
boundaries from the returned (slots, TD errors): it serves beta
annealing, readiness gating, the max-priority watermark, metrics and —
critically — buffer persistence, so checkpoints and resume are
interchangeable with the other loop modes. `sync_priorities_from_host`
(re)seeds the device array from the mirror after restores/warmup.

Scope: single-process, single-device mesh (the same gate as
`DeviceReplayBuffer`). The dp-sharded megastep — per-device rings +
`shard_map` sampling — is future work (docs/PARALLELISM.md).

CPU note: the program contains learner steps, so it rides
`cpu_aot=False` like the rest of the learner family (an XLA:CPU
deserialized executable of a donating learner program returns the train
state UNCHANGED — see rl/trainer.py). The donation/reload regression
guard (params actually update across megasteps) is pinned in
tests/test_megastep.py.
"""

import functools
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..compile_cache import config_digest, get_compile_cache
from ..config.train_config import TrainConfig
from .device_buffer import DeviceReplayBuffer, ring_scatter

logger = logging.getLogger(__name__)


class MegastepRunner:
    """Owns the fused megastep program binding one (engine, trainer,
    device ring) triple; the training loop's third mode
    (`TrainConfig.FUSED_MEGASTEP`) drives it one call per iteration."""

    def __init__(
        self,
        engine,
        trainer,
        buffer: DeviceReplayBuffer,
        train_config: TrainConfig,
    ):
        if not getattr(buffer, "is_device", False) or getattr(
            buffer, "is_sharded", False
        ):
            raise ValueError(
                "MegastepRunner needs the single-device replay ring "
                "(rl/device_buffer.DeviceReplayBuffer); the dp-sharded "
                "megastep is not implemented yet."
            )
        if engine.mesh is not None:
            raise ValueError(
                "MegastepRunner is single-device: the self-play engine "
                "must not be mesh-sharded (megastep over a dp mesh is "
                "future work)."
            )
        if jax.process_count() > 1:
            raise ValueError("MegastepRunner is single-process only.")
        self.engine = engine
        self.trainer = trainer
        self.buffer = buffer
        self.config = train_config
        self.batch_size = train_config.BATCH_SIZE
        self.cap = buffer.capacity
        self.use_per = train_config.USE_PER
        self.per_alpha = float(train_config.PER_ALPHA)
        self.per_epsilon = float(train_config.PER_EPSILON)
        self.beta_initial = float(train_config.PER_BETA_INITIAL)
        self.beta_final = float(train_config.PER_BETA_FINAL)
        self.beta_anneal = float(train_config.PER_BETA_ANNEAL_STEPS or 1)
        # Device-resident priority array, (cap + 1,) float32 — the +1 is
        # the trash slot, pinned at priority 0 so it is never sampled.
        # None until `sync_priorities_from_host` seeds it (lazily on the
        # first megastep, or explicitly after a checkpoint restore).
        self._priorities: jax.Array | None = None
        # One compiled program per distinct (chunk moves, K) pair, AOT
        # cached. cpu_aot=False: the program donates + updates the train
        # state, the exact family whose XLA:CPU deserialization silently
        # returns donated state unchanged (rl/trainer.py).
        extra = config_digest(
            engine.mcts_config,
            train_config,
            trainer.nn.model_config,
            engine.env.cfg,
        ) + (
            f"|att{int(getattr(trainer.nn.model, 'attention_fn', None) is not None)}"
        )
        self._megastep_fn = functools.lru_cache(maxsize=None)(
            lambda t, k: get_compile_cache().wrap(
                f"megastep/t{t}_k{k}",
                jax.jit(
                    functools.partial(self._impl, t, k),
                    donate_argnums=(0, 1, 2, 3),
                ),
                extra=extra,
                cpu_aot=False,
            )
        )
        # Observability: program dispatches (the loop's one-dispatch-
        # per-iteration assertion reads this) and blocking fetch time
        # (telemetry/perf.py transfer accounting).
        self.dispatch_count = 0
        self.transfer_d2h_seconds = 0.0

    # --- device program ---------------------------------------------------

    def _sample_indices(self, priorities, size, state, k: int):
        """On-device (K, B) slot sampling + IS weights.

        PER: stratified proportional sampling over the priority array
        via inclusive-cumsum + searchsorted — the vectorized equivalent
        of the host SumTree's stratified descent (utils/sumtree.py).
        Zero-priority (empty/trash) slots are never selected: their
        cumsum segments are empty. Uniform: floor(u * size).
        """
        b = self.batch_size
        rng, k_sample = jax.random.split(state.rng)
        state = state.replace(rng=rng)
        if self.use_per:
            cum = jnp.cumsum(priorities[: self.cap])
            total = cum[-1]
            u = (
                (jnp.arange(b, dtype=jnp.float32)[None, :]
                 + jax.random.uniform(k_sample, (k, b)))
                / b
                * total
            )
            idx = jnp.clip(
                jnp.searchsorted(cum, u), 0, self.cap - 1
            ).astype(jnp.int32)
            probs = jnp.maximum(priorities[idx], 1e-12) / jnp.maximum(
                total, 1e-12
            )
            # Beta annealed on the learner-step clock, exactly as the
            # host mirror's `ExperienceBuffer.beta` computes it.
            frac = jnp.clip(
                state.step.astype(jnp.float32) / self.beta_anneal, 0.0, 1.0
            )
            beta = self.beta_initial + frac * (
                self.beta_final - self.beta_initial
            )
            w = (size.astype(jnp.float32) * probs) ** (-beta)
            weights = (
                w / jnp.max(w, axis=1, keepdims=True)
            ).astype(jnp.float32)
        else:
            u = jax.random.uniform(k_sample, (k, b))
            idx = jnp.clip(
                jnp.floor(u * size.astype(jnp.float32)).astype(jnp.int32),
                0,
                jnp.maximum(size - 1, 0),
            )
            weights = jnp.ones((k, b), jnp.float32)
        return state, idx, weights

    def _impl(
        self,
        num_moves: int,
        k: int,
        state,
        carry,
        storage,
        priorities,
        cursor,
        size,
        max_priority,
    ):
        """The fused megastep (pure; donated: state, carry, storage,
        priorities). Returns (state', carry', storage', priorities',
        host outputs) — the host outputs are the ONLY fetch."""
        # 1. Rollout chunk with the learner's live params: weight sync
        # is the absence of a copy. The version tag for staleness
        # accounting is the learner step itself (zero staleness by
        # construction: every episode starts under the current step).
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        new_carry, outs = self.engine._chunk(
            num_moves, variables, carry, state.step.astype(jnp.int32)
        )
        mat, flush = outs.pop("mat"), outs.pop("flush")

        # 2. Scatter the harvest into the device ring (same math as
        # DeviceReplayBuffer._ingest_impl, positions kept for PER).
        new_storage, new_cursor, count, pos, keep = ring_scatter(
            storage, cursor, (mat, flush), self.cap, with_positions=True
        )
        new_size = jnp.minimum(size + count, self.cap)

        # 3. Max-priority init for the fresh rows (host-ring parity),
        # trash slot pinned to 0 so sampling can never return it.
        if self.use_per:
            priorities = priorities.at[pos].set(
                jnp.where(keep, max_priority, 0.0)
            )
            priorities = priorities.at[self.cap].set(0.0)

        # 4. Sample K batches on device (post-ingest: fresh rows are
        # immediately eligible, as in the sync loop's fold-then-sample).
        state, idx, weights = self._sample_indices(
            priorities, new_size, state, k
        )

        # 5. K fused learner steps gathered from the ring (the exact
        # program body Trainer.train_steps_from dispatches).
        new_state, metrics_k, td_k = self.trainer._train_steps_from_impl(
            state, new_storage, idx, weights
        )

        # 6. Priority updates from the group's TD errors, in step order
        # (deterministic last-write-wins for rows sampled by several
        # steps — the same net effect as the host path's sequential
        # per-step update_batch calls).
        if self.use_per:
            for j in range(k):
                prio_j = (
                    jnp.abs(td_k[j]) + self.per_epsilon
                ) ** self.per_alpha
                priorities = priorities.at[idx[j]].set(
                    prio_j.astype(jnp.float32)
                )

        out = {
            "rows_added": count,
            "episode": outs["episode"],
            "trace": outs["trace"],
            "sentinel_live": outs["sentinel_live"],
            "metrics": metrics_k,
            "td": td_k,
            "idx": idx,
        }
        return new_state, new_carry, new_storage, priorities, out

    # --- host API ---------------------------------------------------------

    def sync_priorities_from_host(self) -> None:
        """(Re)seed the device priority array from the host SumTree
        mirror — after warmup ingests, a checkpoint restore, or any
        other host-side write. Device becomes the sampling truth from
        the next megastep on."""
        p = np.zeros(self.cap + 1, np.float32)
        tree = self.buffer.tree
        if tree is not None:
            leaves = np.arange(self.cap) + tree._cap2
            p[: self.cap] = tree.tree[leaves]
        self._priorities = jnp.asarray(p)

    def _dispatch_args(self, t: int, k: int) -> tuple:
        if self._priorities is None:
            self.sync_priorities_from_host()
        buf = self.buffer
        tree = buf.tree
        max_p = float(tree.max_priority) if tree is not None else 1.0
        args = (
            self.trainer.state,
            self.engine._carry,
            buf.storage,
            self._priorities,
            jnp.int32(buf._pos),
            jnp.int32(buf._size),
            jnp.float32(max_p),
        )
        # Commit EVERY argument to the device before dispatch. The first
        # call's arguments are a mix of uncommitted host-built arrays
        # (initial carry window zeros, the seeded priority array, the
        # per-call scalars) and committed jit outputs, while every later
        # call sees all-committed outputs of the previous megastep — and
        # jit keys compiled executables on that placement mapping, so
        # without this the SECOND megastep silently recompiles the whole
        # program (measured: a 48s duplicate compile at bench smoke
        # scale). device_put is a no-op for anything already resident.
        return jax.device_put(args, jax.devices()[0])

    def run_megastep(
        self, num_moves: int | None = None, k: int | None = None
    ) -> tuple[list, int]:
        """One fused megastep: ONE device dispatch, ONE blocking fetch.

        Returns (per-step (metrics, TD errors) list — the
        `train_steps_finish` contract — and the number of experience
        rows ingested). Side effects: engine carry + episode stats,
        buffer storage/counters + reconciled host PER mirror, trainer
        state + host step mirror all advance.
        """
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        k = int(k or max(1, self.config.FUSED_LEARNER_STEPS))
        buf, engine, trainer = self.buffer, self.engine, self.trainer
        tree = buf.tree
        max_p = float(tree.max_priority) if tree is not None else 1.0
        args = self._dispatch_args(t, k)
        start_step = trainer._host_step
        (
            trainer.state,
            engine._carry,
            buf.storage,
            self._priorities,
            out,
        ) = self._megastep_fn(t, k)(*args)
        self.dispatch_count += 1
        t0 = time.perf_counter()
        host = jax.device_get(out)  # the one transfer per megastep
        self.transfer_d2h_seconds += time.perf_counter() - t0

        # --- host mirror reconciliation (megastep boundary) ----------
        count = int(host["rows_added"])
        # One chunk's rows (B * (T + n) worst case) must fit the ring
        # for the mirror's slot arithmetic to stay 1:1 with surviving
        # rows — same assumption as the sharded ring's ingest assert.
        assert count <= self.cap, (
            f"megastep ingested {count} rows into a {self.cap}-slot "
            "ring in one scatter (shrink ROLLOUT_CHUNK_MOVES or grow "
            "BUFFER_CAPACITY)"
        )
        slots = (buf._pos + np.arange(count)) % self.cap
        if tree is not None and count:
            # Fresh rows at the same pre-group watermark the device used.
            tree.update_batch(slots, np.full(count, max_p))
            tree.data_pointer = int((buf._pos + count) % self.cap)
            tree.n_entries = min(buf._size + count, self.cap)
        buf._pos = int((buf._pos + count) % self.cap)
        buf._size = min(buf._size + count, self.cap)
        # TD-error priority updates, in the same step order the device
        # applied them.
        if tree is not None:
            for j in range(k):
                buf.update_priorities(host["idx"][j], host["td"][j])

        # --- engine-side stats (play_chunk's host tail) --------------
        engine.last_trace = host["trace"]
        engine._fold_episode_stats(host["episode"])
        engine._total_simulations += (
            int(host["trace"]["sims"].sum()) * engine.batch_size
        )
        # The megastep's version clock is the learner step (zero
        # staleness); seed the harvest window tag with the group start.
        engine._min_weights_version = (
            start_step
            if engine._min_weights_version is None
            else min(engine._min_weights_version, start_step)
        )
        sentinels = int(host["sentinel_live"].sum())
        if sentinels:
            logger.warning(
                "Megastep: %d zero-visit sentinel actions on LIVE games "
                "(clamped to action 0).",
                sentinels,
            )

        # --- trainer-side results (train_steps_finish contract) ------
        trainer._host_step += k
        results = []
        for i in range(k):
            m = {key: float(v[i]) for key, v in host["metrics"].items()}
            m["learning_rate"] = float(trainer.schedule(start_step + i + 1))
            results.append((m, np.asarray(host["td"][i])))
        return results, count

    # --- AOT warming / memory analysis (cli warm / cli fit) ---------------

    def warm_megastep(
        self, num_moves: int | None = None, k: int | None = None
    ) -> bool:
        """AOT-precompile the megastep program WITHOUT executing it (no
        donation happens at lowering). True when an AOT executable is
        ready; always False on CPU (cpu_aot bypass, reported as
        skipped-cpu by `cli warm`)."""
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        k = int(k or max(1, self.config.FUSED_LEARNER_STEPS))
        return self._megastep_fn(t, k).warm(*self._dispatch_args(t, k))

    def analyze_megastep(
        self, num_moves: int | None = None, k: int | None = None
    ) -> "dict | None":
        """Memory record of the megastep program at real dispatch avals
        (telemetry/memory.py; `cli fit`) — AOT analysis only, nothing
        executes. The record persists as a `.mem.json` sidecar in the
        compile cache even on CPU, where the executable itself is
        never serialized (cpu_aot bypass)."""
        t = int(num_moves or self.config.ROLLOUT_CHUNK_MOVES)
        k = int(k or max(1, self.config.FUSED_LEARNER_STEPS))
        return self._megastep_fn(t, k).analyze(
            *self._dispatch_args(t, k), persist=True
        )
