"""Learner: optimizer factories, C51 target projection, pjit train step.

Capability parity with the reference `Trainer`
(`alphatriangle/rl/core/trainer.py:48-310`): Adam/AdamW/SGD + Step/
Cosine LR schedules, dense policy targets, C51 two-hot projection of
scalar n-step returns, IS-weighted policy CE + value CE + entropy bonus,
global-norm gradient clipping, per-sample TD errors for PER.

TPU-native redesign:
- The train step is one **pure jitted function** over a named device
  mesh: model/optimizer state replicated, the batch sharded on the `dp`
  axis. Gradient all-reduce is not written anywhere — XLA inserts the
  ICI collectives because the loss reduces over a sharded axis (GSPMD).
  The reference's single-device `backward()` (`trainer.py:274-286`)
  becomes multi-chip for free.
- Optimizer/schedule are optax transforms; LR is recomputed from the
  schedule, not read from mutable optimizer state.
- The C51 projection of a *scalar* return is a two-hot scatter
  (`trainer.py:159-202` does the same dance with torch index math).
"""

import logging
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compile_cache import config_digest, get_compile_cache
from ..config.mesh_config import MeshConfig
from ..config.train_config import TrainConfig
from ..nn.network import NeuralNetwork
from ..telemetry.device_stats import emit_beacon
from ..telemetry.flight import flight_span
from ..parallel.sharding import (
    batch_sharding,
    local_rows,
    replicated,
    shard_batch,
    shard_map_compat,
    state_shardings,
)
from ..utils.types import DenseBatch

logger = logging.getLogger(__name__)


# --- optimizer / schedule factories --------------------------------------


def make_lr_schedule(cfg: TrainConfig) -> optax.Schedule:
    """LR schedule per `TrainConfig` (reference `trainer.py:66-102`)."""
    if cfg.LR_SCHEDULER_TYPE == "CosineAnnealingLR":
        t_max = cfg.LR_SCHEDULER_T_MAX or (cfg.MAX_TRAINING_STEPS or 100_000)
        return optax.cosine_decay_schedule(
            init_value=cfg.LEARNING_RATE,
            decay_steps=t_max,
            alpha=cfg.LR_SCHEDULER_ETA_MIN / cfg.LEARNING_RATE,
        )
    if cfg.LR_SCHEDULER_TYPE == "StepLR":
        return optax.exponential_decay(
            init_value=cfg.LEARNING_RATE,
            transition_steps=cfg.LR_SCHEDULER_STEP_SIZE,
            decay_rate=cfg.LR_SCHEDULER_GAMMA,
            staircase=True,
        )
    return optax.constant_schedule(cfg.LEARNING_RATE)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """Clip + optimizer + schedule chain (reference `trainer.py:48-64`)."""
    schedule = make_lr_schedule(cfg)
    if cfg.OPTIMIZER_TYPE == "AdamW":
        opt = optax.adamw(schedule, weight_decay=cfg.WEIGHT_DECAY)
    elif cfg.OPTIMIZER_TYPE == "Adam":
        # torch-style coupled L2: decay folds into the gradient before
        # the moment estimates (vs AdamW's decoupled decay).
        opt = optax.chain(
            optax.add_decayed_weights(cfg.WEIGHT_DECAY), optax.adam(schedule)
        )
    elif cfg.OPTIMIZER_TYPE == "SGD":
        opt = optax.chain(
            optax.add_decayed_weights(cfg.WEIGHT_DECAY), optax.sgd(schedule)
        )
    else:  # pragma: no cover - pydantic Literal prevents this
        raise ValueError(f"Unknown optimizer {cfg.OPTIMIZER_TYPE}")
    if cfg.GRADIENT_CLIP_VALUE is not None:
        return optax.chain(
            optax.clip_by_global_norm(cfg.GRADIENT_CLIP_VALUE), opt
        )
    return opt


# --- C51 projection -------------------------------------------------------


def project_to_support(
    returns: jax.Array, num_atoms: int, v_min: float, v_max: float
) -> jax.Array:
    """(B,) scalar returns -> (B, num_atoms) two-hot target distribution.

    Categorical projection of a delta distribution onto the fixed atom
    support (reference `trainer.py:159-202`).
    """
    delta_z = (v_max - v_min) / (num_atoms - 1)
    b = (jnp.clip(returns, v_min, v_max) - v_min) / delta_z  # (B,) in [0, A-1]
    lower = jnp.floor(b).astype(jnp.int32)
    upper = jnp.ceil(b).astype(jnp.int32)
    exact = lower == upper
    w_lower = jnp.where(exact, 1.0, upper.astype(jnp.float32) - b)
    w_upper = jnp.where(exact, 0.0, b - lower.astype(jnp.float32))
    onehot_l = jax.nn.one_hot(lower, num_atoms, dtype=jnp.float32)
    onehot_u = jax.nn.one_hot(upper, num_atoms, dtype=jnp.float32)
    return onehot_l * w_lower[:, None] + onehot_u * w_upper[:, None]


# --- train state ----------------------------------------------------------


@struct.dataclass
class TrainState:
    """Replicated learner state (a pure pytree; checkpoints directly)."""

    params: Any
    batch_stats: Any  # {} unless NORM_TYPE == "batch"
    opt_state: Any
    step: jax.Array  # () int32
    rng: jax.Array  # dropout PRNG key


class Trainer:
    """Owns the jitted sharded train step bound to one network + mesh."""

    def __init__(
        self,
        nn: NeuralNetwork,
        train_config: TrainConfig,
        mesh: Mesh | None = None,
        mdl_axis: str | None = None,
    ):
        self.nn = nn
        self.config = train_config
        self.mesh = mesh or MeshConfig.single_device_mesh()
        # Data-parallel axis: the conventional name "dp" wins if present
        # (meshes may order axes arbitrarily); otherwise the first axis
        # (MeshConfig.DP_AXIS is configurable and always comes first).
        self.dp_axis = (
            "dp" if "dp" in self.mesh.axis_names else self.mesh.axis_names[0]
        )
        self.dp_size = self.mesh.shape[self.dp_axis]
        # Model (tensor-parallel) axis: transformer params shard over
        # it when it is wider than 1 (parallel/sharding.py Megatron
        # layout); 1-wide or absent means fully-replicated state (the
        # default — the flagship net is ~3M params). Only an axis
        # DISTINCT from dp qualifies: guessing (e.g. taking the second
        # axis of a custom-named mesh) could silently tensor-shard
        # params over a data or sequence axis. Callers with custom
        # axis names pass `mdl_axis` explicitly (setup.py forwards
        # MeshConfig.MDL_AXIS).
        if mdl_axis is None:
            mdl_axis = "mdl" if "mdl" in self.mesh.axis_names else None
        if (
            mdl_axis is not None
            and mdl_axis != self.dp_axis
            and mdl_axis in self.mesh.axis_names
        ):
            self.mdl_axis: str | None = mdl_axis
            self.tp_size = self.mesh.shape[mdl_axis]
        else:
            self.mdl_axis = None
            self.tp_size = 1
        self.model = nn.model
        # Host<->device transfer accounting (telemetry/perf.py reads
        # deltas per tick): h2d = batch staging uploads; d2h = blocking
        # result fetches (includes any wait for the step to finish —
        # the host-visible cost of the round trip, which is what the
        # utilization record is after). Single-writer (main/consumer
        # thread), so bare float accumulation is safe.
        self.transfer_h2d_seconds = 0.0
        self.transfer_d2h_seconds = 0.0
        # Learner program dispatches (telemetry: the loop's dispatches-
        # per-iteration gauge; one per step/group dispatch).
        self.dispatch_count = 0
        # Dispatch flight recorder (telemetry/flight.py), attached by
        # training/setup.py; None = no intent/seal records written.
        self.flight = None
        mc = nn.model_config
        self.num_atoms = mc.NUM_VALUE_ATOMS
        self.v_min, self.v_max = mc.VALUE_MIN, mc.VALUE_MAX
        self.schedule = make_lr_schedule(train_config)
        self.optimizer = make_optimizer(train_config)

        # Deep-copy the wrapper's variables: the jitted step donates its
        # input state, and a donated buffer aliased by `nn.variables`
        # would leave the eval wrapper holding deleted arrays.
        variables = jax.tree_util.tree_map(jnp.array, nn.variables)
        self.state = TrainState(
            params=variables["params"],
            batch_stats=variables.get("batch_stats", {}),
            opt_state=self.optimizer.init(variables["params"]),
            step=jnp.int32(0),
            rng=jax.random.PRNGKey(train_config.RANDOM_SEED),
        )

        rep = replicated(self.mesh)
        state_shard = state_shardings(
            self.mesh, self.state, mdl_axis=self.mdl_axis
        )
        self._state_shard = state_shard
        bshard = batch_sharding(self.mesh, self.dp_axis)
        batch_shards: dict[str, Any] = {
            "grid": bshard,
            "other_features": bshard,
            "policy_target": bshard,
            "value_target": bshard,
            "weights": bshard,
            "policy_weight": bshard,
        }
        # Learner programs ride the AOT compile cache (compile_cache.py):
        # a warm cache (cli warm / a prior same-shape run) deserializes
        # the serialized executable instead of recompiling. The digest
        # keys the program shapers invisible in input avals: optimizer/
        # schedule/loss config, net architecture, board geometry.
        cache = get_compile_cache()
        from ..telemetry.device_stats import beacon_signature, beacons_armed

        self._cache_extra = (
            config_digest(train_config, nn.model_config, nn.env_config)
            + f"|att{int(getattr(nn.model, 'attention_fn', None) is not None)}"
            # Beacon-armed learner programs embed host callbacks in the
            # scan body — distinct executables, never serialized (the
            # wrap sites pass serialize=False under arming).
            + beacon_signature()
        )
        # cpu_aot=False on every learner program: XLA:CPU DESERIALIZED
        # executables of this program family run without error but
        # return the donated train state UNCHANGED — params silently
        # stop updating. Reproduced deterministically (fresh compile
        # updates params; the same process or a later one reloading the
        # serialized artifact does not), while the rollout-chunk
        # programs reload correctly. On CPU these programs therefore
        # always compile fresh; accelerator backends keep the full AOT
        # cache behavior.
        self._step_fn = cache.wrap(
            "learner_step",
            jax.jit(
                self._train_step_impl,
                in_shardings=(state_shard, batch_shards),
                out_shardings=(state_shard, rep, bshard),
                donate_argnums=(0,),
            ),
            extra=self._cache_extra,
            cpu_aot=False,
            serialize=not beacons_armed(),
        )
        # Fused multi-step: batches stacked on a new leading K axis, dp
        # sharding on axis 1; one compiled program per distinct K.
        stacked_shard = NamedSharding(
            self.mesh, P(None, self.dp_axis)
        )
        stacked_shards = {k: stacked_shard for k in batch_shards}
        self._multi_step_fn = cache.wrap(
            "learner_fused_steps",
            jax.jit(
                self._train_steps_impl,
                in_shardings=(state_shard, stacked_shards),
                out_shardings=(state_shard, rep, stacked_shard),
                donate_argnums=(0,),
            ),
            extra=self._cache_extra,
            cpu_aot=False,
            serialize=not beacons_armed(),
        )
        self._stacked_shard = stacked_shard
        # Device-buffer path (rl/device_buffer.py): batches are gathered
        # ON DEVICE from the replay ring by sampled indices — the fused
        # group's host->device traffic shrinks from K full batches to
        # K*B int32 indices. One compiled program per distinct K (the
        # cache wrapper keys executables per input signature, so the
        # distinct-K programs each get their own AOT cache entry).
        self._from_fn = cache.wrap(
            "learner_fused_from_ring",
            jax.jit(self._train_steps_from_impl, donate_argnums=(0,)),
            extra=self._cache_extra,
            cpu_aot=False,
            serialize=not beacons_armed(),
        )
        # dp-sharded ring variant (rl/sharded_device_buffer.py): built
        # lazily on first use, cached per shard geometry — the program
        # closes over (stride, dp_axis), and a geometry change with a
        # stale program would gather silently-wrong rows (JAX clamps
        # out-of-range gather indices rather than erroring).
        self._from_sharded_fns: dict[tuple, Any] = {}
        # Keep state resident on the mesh (replicated, or TP-sharded
        # over the mdl axis when it is wider than 1).
        self.state = jax.device_put(self.state, state_shard)
        # Host mirror of state.step: global_step / LR lookups must not
        # block on a device fetch (each fetch is a full round trip —
        # painful when the chip sits behind a network tunnel).
        self._host_step = 0

    # --- pure core --------------------------------------------------------

    def _loss_fn(self, params, batch_stats, rng, batch: DenseBatch):
        cfg = self.config
        variables = {"params": params}
        mutable: list[str] | bool = False
        if batch_stats:
            variables["batch_stats"] = batch_stats
            mutable = ["batch_stats"]
        out = self.model.apply(
            variables,
            batch["grid"],
            batch["other_features"],
            train=True,
            rngs={"dropout": rng},
            mutable=mutable,
        )
        if mutable:
            (policy_logits, value_logits), updates = out
            new_batch_stats = updates.get("batch_stats", {})
        else:
            policy_logits, value_logits = out
            new_batch_stats = batch_stats

        log_policy = jax.nn.log_softmax(policy_logits, axis=-1)
        policy_ce = -(batch["policy_target"] * log_policy).sum(axis=-1)  # (B,)
        # Playout-cap randomization: rows from fast searches carry
        # policy_weight 0 — their visit counts are too noisy to train
        # the policy on; they still train the value head below.
        pw = batch["policy_weight"]
        policy_ce = pw * policy_ce

        target_dist = project_to_support(
            batch["value_target"], self.num_atoms, self.v_min, self.v_max
        )
        log_value = jax.nn.log_softmax(value_logits, axis=-1)
        value_ce = -(target_dist * log_value).sum(axis=-1)  # (B,)

        probs = jnp.exp(log_policy)
        # Entropy regularizes the policy, so it follows the policy mask.
        # The LOSS term averages over all B rows — the same denominator
        # as the masked policy CE — so the entropy-to-policy-gradient
        # ratio is invariant to the PCR full-search fraction. The
        # REPORTED entropy averages over policy-trainable rows only
        # (interpretable as nats/decision regardless of masking).
        entropy_rows = -(probs * log_policy).sum(axis=-1)  # (B,)
        entropy_term = (pw * entropy_rows).mean()
        entropy_metric = (pw * entropy_rows).sum() / jnp.maximum(
            pw.sum(), 1.0
        )

        w = batch["weights"]
        per_sample = (
            cfg.POLICY_LOSS_WEIGHT * policy_ce
            + cfg.VALUE_LOSS_WEIGHT * value_ce
        )
        # Entropy regularization uses the UNWEIGHTED (by IS weight) mean
        # — the reference is explicit about this ("Use mean entropy, not
        # weighted", `trainer.py:253-256`); IS weights must not modulate
        # the regularizer's strength per sample.
        total = (w * per_sample).mean() - cfg.ENTROPY_BONUS_WEIGHT * entropy_term
        aux = {
            "total_loss": total,
            "policy_loss": (w * policy_ce).mean(),
            "value_loss": (w * value_ce).mean(),
            "entropy": entropy_metric,
            "td_errors": value_ce,
            "batch_stats": new_batch_stats,
        }
        return total, aux

    def _train_step_impl(self, state: TrainState, batch: DenseBatch):
        rng, step_rng = jax.random.split(state.rng)
        grads, aux = jax.grad(
            lambda p: self._loss_fn(p, state.batch_stats, step_rng, batch),
            has_aux=True,
        )(state.params)
        updates, opt_state = self.optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params,
            batch_stats=aux["batch_stats"],
            opt_state=opt_state,
            step=state.step + 1,
            rng=rng,
        )
        metrics = {
            "total_loss": aux["total_loss"],
            "policy_loss": aux["policy_loss"],
            "value_loss": aux["value_loss"],
            "entropy": aux["entropy"],
            "grad_norm": optax.global_norm(grads),
            # Post-transform step size: grad_norm tells you what the
            # loss surface did, update_norm what the optimizer actually
            # applied — the pair separates "gradient explosion" from
            # "adaptive-moment blowup" per fused step.
            "update_norm": optax.global_norm(updates),
        }
        return new_state, metrics, aux["td_errors"]

    def _train_steps_impl(self, state: TrainState, stacked: DenseBatch):
        """K fused SGD steps: a lax.scan over the leading batch axis.

        Produces bit-identical results to K sequential `_train_step_impl`
        calls on the same batches (same state threading, same RNG split
        sequence) — only the host round trips collapse to one.

        This body is also the learner phase of the megastep program
        families (rl/megastep.py). Under a dp-sharded stacked batch
        (axis 1) with replicated params, XLA inserts the gradient
        all-reduce over dp from the shardings — the "psum axis" of the
        sharded megastep, with nothing spelled by hand (module
        docstring) — so the updated params stay bit-identical on every
        shard.

        The scan is fully unrolled on the CPU backend: XLA-CPU runs ops
        inside a While loop single-threaded, which makes a rolled scan
        ~15x slower per step than the identical unrolled program
        (measured; TPU has no such penalty, and rolled keeps compile
        time flat in K there).
        """

        def body(st, batch):
            emit_beacon("learner_step", st.step)
            new_st, metrics, td = self._train_step_impl(st, batch)
            return new_st, (metrics, td)

        state, (metrics_k, td_k) = jax.lax.scan(
            body,
            state,
            stacked,
            unroll=True if jax.default_backend() == "cpu" else 1,
        )
        return state, metrics_k, td_k

    @staticmethod
    def _stacked_rows_batch(rows, weights) -> DenseBatch:
        """(K, B, ...) ring rows -> the stacked DenseBatch the fused
        steps consume. The grid int8->float32 cast reproduces the host
        ring's storage round trip exactly. Shared by every gathered-
        from-ring program: `_train_steps_from_impl`, the sharded-ring
        gather below, and the megastep program families that embed the
        fused steps (rl/megastep.py)."""
        return {
            "grid": rows["grid"].astype(jnp.float32),
            "other_features": rows["other_features"],
            "policy_target": rows["policy_target"],
            "value_target": rows["value_target"],
            "policy_weight": rows["policy_weight"],
            "weights": weights,
        }

    def _get_from_sharded_fn(self, buffer):
        """Jitted fused-steps program for the dp-SHARDED replay ring:
        each device gathers its B/dp batch rows from its LOCAL ring
        shard (shard_map, no collectives), then runs the dp-sharded
        fused train step. Index upload stays K*B int32 — the sharded
        ring keeps the index-only-upload property per device."""
        key = (buffer.stride, buffer.dp_axis)
        if key not in self._from_sharded_fns:
            stride = buffer.stride
            dp_axis = buffer.dp_axis

            def gather_local(storage_local, idx_local):
                base = jax.lax.axis_index(dp_axis) * stride
                local = idx_local - base  # global encoding -> local slot
                return {k: v[local] for k, v in storage_local.items()}

            gather = shard_map_compat(
                gather_local,
                mesh=self.mesh,
                in_specs=(P(dp_axis), P(None, dp_axis)),
                out_specs=P(None, dp_axis),
            )

            def impl(state, storage, idx, weights):
                g = gather(storage, idx)
                stacked = self._stacked_rows_batch(
                    g,
                    jax.lax.with_sharding_constraint(
                        weights, self._stacked_shard
                    ),
                )
                return self._train_steps_impl(state, stacked)

            from ..telemetry.device_stats import beacons_armed

            self._from_sharded_fns[key] = get_compile_cache().wrap(
                f"learner_fused_from_sharded_ring/s{stride}_{dp_axis}",
                jax.jit(impl, donate_argnums=(0,)),
                extra=self._cache_extra,
                cpu_aot=False,
                serialize=not beacons_armed(),
            )
        return self._from_sharded_fns[key]

    def _train_steps_from_impl(self, state: TrainState, storage, idx, weights):
        """K fused steps whose batches are gathered from the device
        replay ring: `idx` is (K, B) int32 slot indices, `weights` the
        matching (K, B) IS weights. Bit-identical to `_train_steps_impl`
        on the same rows."""
        rows = {name: v[idx] for name, v in storage.items()}
        return self._train_steps_impl(
            state, self._stacked_rows_batch(rows, weights)
        )

    # --- host API ---------------------------------------------------------

    @staticmethod
    def _with_policy_weight(batch: dict, n: int) -> dict:
        """Default the PCR policy-loss mask to ones when absent, so
        callers that predate playout-cap randomization stay valid."""
        if "policy_weight" not in batch:
            batch["policy_weight"] = np.ones(n, dtype=np.float32)
        return batch

    def _check_local_batch(self, n: int) -> None:
        # Multi-process: `batch` is this host's share; it must tile this
        # host's slice of the dp axis (shard_batch assembles the global
        # array in process order).
        local_dp = max(1, self.dp_size // jax.process_count())
        if n % local_dp != 0:
            raise ValueError(
                f"Local batch size {n} not divisible by the local dp "
                f"extent {local_dp} (global dp={self.dp_size})."
            )

    def train_step(
        self, batch: DenseBatch
    ) -> tuple[dict[str, float], np.ndarray] | None:
        """One SGD step. Returns (metrics, per-sample TD errors) or None
        on an empty batch (reference `trainer.py:204-310` contract)."""
        # Static shape read — np.asarray here would fetch the whole
        # array from the device just to look at its metadata.
        n = int(batch["value_target"].shape[0])
        if n == 0:
            return None
        self._check_local_batch(n)
        batch = self._with_policy_weight(dict(batch), n)
        t0 = time.perf_counter()
        device_batch = shard_batch(self.mesh, batch, self.dp_axis)
        self.transfer_h2d_seconds += time.perf_counter() - t0
        with flight_span(
            self.flight, "learner", "learner_step", avals=f"B{n}"
        ):
            self.state, metrics, td = self._step_fn(self.state, device_batch)
            self.dispatch_count += 1
            # ONE blocking transfer for everything this step produced
            # (fetching each metric separately costs a round trip apiece).
            t0 = time.perf_counter()
            host_metrics, td_host = jax.device_get(  # graftlint: allow(host-sync-in-hot-path) the one blocking fetch per step
                (metrics, td if jax.process_count() == 1 else None)
            )
            self.transfer_d2h_seconds += time.perf_counter() - t0
        if td_host is None:
            td_host = local_rows(td)
        self._host_step += 1
        host_metrics = {k: float(v) for k, v in host_metrics.items()}
        host_metrics["learning_rate"] = self.get_current_lr()
        # PER bookkeeping is host-local: return only this host's rows.
        return host_metrics, np.asarray(td_host)

    def train_steps(
        self, batches: "list[DenseBatch]"
    ) -> list[tuple[dict[str, float], np.ndarray]]:
        """K SGD steps in ONE device dispatch (`FUSED_LEARNER_STEPS`).

        Equivalent to K sequential `train_step` calls on the same
        batches, but with a single host→device transfer and a single
        device→host fetch for the whole group. Returns the per-step
        (metrics, local TD errors) list, in execution order.
        """
        handle = self.train_steps_begin(batches)
        if handle is None:
            return []
        return self.train_steps_finish(handle)

    def train_steps_begin(
        self, batches: "list[DenseBatch]"
    ) -> dict | None:
        """Stage + dispatch a fused group WITHOUT fetching results.

        The dispatch is asynchronous: this returns as soon as the
        host→device transfer is enqueued, so a caller can overlap the
        group's device execution with host work (PER sampling, harvest
        folding) and with *staging the next group* — the double-buffered
        pipeline the overlapped training loop runs. Fetch the results
        later with `train_steps_finish`; `self.state` is already the
        group-end state (as a device future), so `sync_to_network` and
        checkpointing may run before the fetch.

        Returns an opaque handle, or None when `batches` is empty or
        the batch is degenerate (same skip contract as `train_step`).
        """
        if not batches:
            return None
        n = int(batches[0]["value_target"].shape[0])
        if n == 0:  # same skip contract as train_step
            return None
        self._check_local_batch(n)
        batches = [self._with_policy_weight(dict(b), n) for b in batches]
        if len(batches) == 1:
            # Single-step groups reuse the per-step program (a fused
            # K=1 program would recompile for nothing).
            t0 = time.perf_counter()
            device_batch = shard_batch(self.mesh, batches[0], self.dp_axis)
            self.transfer_h2d_seconds += time.perf_counter() - t0
            span = (
                self.flight.begin("learner", "learner_step", avals=f"B{n}")
                if self.flight is not None
                else None
            )
            self.state, metrics, td = self._step_fn(self.state, device_batch)
            self.dispatch_count += 1
            handle: dict = {"k": 1, "metrics": metrics, "td": td}
        else:
            t0 = time.perf_counter()
            stacked_host = {
                key: np.stack([np.asarray(b[key]) for b in batches])
                for key in batches[0]
            }
            if jax.process_count() > 1:
                stacked = jax.tree_util.tree_map(
                    lambda x: jax.make_array_from_process_local_data(
                        self._stacked_shard, x
                    ),
                    stacked_host,
                )
            else:
                stacked = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, self._stacked_shard),
                    stacked_host,
                )
            self.transfer_h2d_seconds += time.perf_counter() - t0
            span = (
                self.flight.begin(
                    "learner",
                    "learner_fused_steps",
                    avals=f"K{len(batches)}xB{n}",
                )
                if self.flight is not None
                else None
            )
            self.state, metrics_k, td_k = self._multi_step_fn(
                self.state, stacked
            )
            self.dispatch_count += 1
            handle = {"k": len(batches), "metrics": metrics_k, "td": td_k}
        # The group stays in flight until train_steps_finish fetches;
        # the seal there gives the dispatch->fetch wall for the record.
        handle["flight"] = span
        # The dispatch semantically runs the steps; advance the host
        # mirror now so LR lookups / buffer sampling for the NEXT group
        # see the post-group step while this group still executes.
        handle["start_step"] = self._host_step
        self._host_step += handle["k"]
        return handle

    def train_steps_from(
        self, buffer, samples: "list[dict]"
    ) -> list[tuple[dict[str, float], np.ndarray]]:
        """K fused steps sampled from a `DeviceReplayBuffer`: upload
        only indices + IS weights; rows are gathered on device."""
        handle = self.train_steps_from_begin(buffer, samples)
        if handle is None:
            return []
        return self.train_steps_finish(handle)

    def train_steps_from_begin(
        self, buffer, samples: "list[dict]"
    ) -> dict | None:
        """Pipelined dispatch of a device-gathered fused group.

        `samples` are `DeviceReplayBuffer.sample` /
        `ShardedDeviceReplayBuffer.sample` outputs ({"indices",
        "weights"}); the sharded ring routes through a per-device
        local gather. Single-process only (gated in training/setup.py).
        Same handle/fetch contract as `train_steps_begin`/
        `train_steps_finish`.
        """
        if not samples:
            return None
        idx = np.stack(
            [np.asarray(s["indices"], dtype=np.int32) for s in samples]
        )
        weights = np.stack(
            [np.asarray(s["weights"], dtype=np.float32) for s in samples]
        )
        sharded = getattr(buffer, "is_sharded", False)
        from_fn = (
            self._get_from_sharded_fn(buffer) if sharded else self._from_fn
        )
        program = (
            "learner_fused_from_sharded_ring"
            if sharded
            else "learner_fused_from_ring"
        )
        span = (
            self.flight.begin(
                "learner", program, avals=f"K{len(samples)}"
            )
            if self.flight is not None
            else None
        )
        self.state, metrics_k, td_k = from_fn(
            self.state, buffer.storage, idx, weights
        )
        self.dispatch_count += 1
        handle = {
            "k": len(samples),
            "metrics": metrics_k,
            "td": td_k,
            # The scan stacks outputs even at K=1; tells finish so.
            "stacked": True,
            "flight": span,
            "start_step": self._host_step,
        }
        self._host_step += len(samples)
        return handle

    def train_steps_finish(
        self, handle: dict
    ) -> list[tuple[dict[str, float], np.ndarray]]:
        """Blocking fetch of a `train_steps_begin` group's results.

        ONE device→host transfer for the whole group. Returns the
        per-step (metrics, local TD errors) list, in execution order.
        """
        k = handle["k"]
        metrics_k, td_k = handle["metrics"], handle["td"]
        t0 = time.perf_counter()
        host_metrics_k, td_host = jax.device_get(  # graftlint: allow(host-sync-in-hot-path) the one blocking fetch per fused group
            (metrics_k, td_k if jax.process_count() == 1 else None)
        )
        self.transfer_d2h_seconds += time.perf_counter() - t0
        span = handle.pop("flight", None)
        if span is not None:
            span.seal()
        if td_host is None:
            td_host = local_rows(
                td_k, axis=1 if (k > 1 or handle.get("stacked")) else 0
            )
        td_host = np.asarray(td_host)
        if k == 1 and not handle.get("stacked"):
            host_metrics_k = {
                key: np.asarray(v)[None] for key, v in host_metrics_k.items()
            }
            td_host = td_host[None]
        results = []
        for i in range(k):
            m = {key: float(v[i]) for key, v in host_metrics_k.items()}
            m["learning_rate"] = float(
                self.schedule(handle["start_step"] + i + 1)
            )
            results.append((m, td_host[i]))
        return results

    # --- AOT warming (compile_cache.py; cli warm) -------------------------

    @property
    def aot_enabled(self) -> bool:
        """Whether the learner programs use the AOT artifact path on
        this backend (False on CPU — see the cpu_aot note at the wrap
        sites; `warm.py` reports those programs as skipped)."""
        return self._step_fn.aot_active

    def _zero_batch(self, n: int) -> DenseBatch:
        """A dense batch of the training shapes, all zeros — enough to
        lower the learner programs without touching a replay buffer."""
        mc, ec = self.nn.model_config, self.nn.env_config
        return {
            "grid": np.zeros(
                (n, mc.GRID_INPUT_CHANNELS, ec.ROWS, ec.COLS), np.float32
            ),
            "other_features": np.zeros(
                (n, mc.OTHER_NN_INPUT_FEATURES_DIM), np.float32
            ),
            "policy_target": np.full(
                (n, ec.action_dim), 1.0 / ec.action_dim, np.float32
            ),
            "value_target": np.zeros(n, np.float32),
            "weights": np.ones(n, np.float32),
            "policy_weight": np.ones(n, np.float32),
        }

    def warm_step(self, batch_size: int | None = None) -> bool:
        """AOT-precompile the per-step learner program (no execution,
        no state donation). True when an AOT executable is ready."""
        b = batch_size or self.config.BATCH_SIZE
        device_batch = shard_batch(
            self.mesh, self._zero_batch(b), self.dp_axis
        )
        return self._step_fn.warm(self.state, device_batch)

    def warm_steps(self, k: int, batch_size: int | None = None) -> bool:
        """AOT-precompile the K-fused learner program (one entry per
        distinct K, matching `train_steps`' per-K jit specialization)."""
        b = batch_size or self.config.BATCH_SIZE
        batch = self._zero_batch(b)
        stacked_host = {key: np.stack([batch[key]] * k) for key in batch}
        stacked = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._stacked_shard), stacked_host
        )
        return self._multi_step_fn.warm(self.state, stacked)

    def warm_steps_from(
        self, buffer, k: int, batch_size: int | None = None
    ) -> bool:
        """AOT-precompile the device-replay fused program against a
        real ring's storage (shapes + shardings must match dispatch)."""
        b = batch_size or self.config.BATCH_SIZE
        idx = np.zeros((k, b), np.int32)
        weights = np.ones((k, b), np.float32)
        from_fn = (
            self._get_from_sharded_fn(buffer)
            if getattr(buffer, "is_sharded", False)
            else self._from_fn
        )
        return from_fn.warm(self.state, buffer.storage, idx, weights)

    # --- memory attribution (telemetry/memory.py; cli fit) ----------------

    def analyze_step(self, batch_size: int | None = None) -> "dict | None":
        """Memory record of the per-step learner program (AOT-lowered,
        never executed — works on CPU despite the cpu_aot bypass).
        The learner family's `cost_analysis()` record + `.cost.json`
        sidecar ride the same compile (telemetry/roofline.py), which
        is what gives `cli roofline` FLOP coverage of a family whose
        executable never enters the AOT artifact path on CPU."""
        b = batch_size or self.config.BATCH_SIZE
        device_batch = shard_batch(
            self.mesh, self._zero_batch(b), self.dp_axis
        )
        return self._step_fn.analyze(self.state, device_batch)

    def analyze_steps(
        self, k: int, batch_size: int | None = None
    ) -> "dict | None":
        """Memory record of the K-fused learner program."""
        b = batch_size or self.config.BATCH_SIZE
        batch = self._zero_batch(b)
        stacked_host = {key: np.stack([batch[key]] * k) for key in batch}
        stacked = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._stacked_shard), stacked_host
        )
        return self._multi_step_fn.analyze(self.state, stacked)

    def analyze_steps_from(
        self, buffer, k: int, batch_size: int | None = None
    ) -> "dict | None":
        """Memory record of the device-replay fused gather program
        (needs a real ring — its storage IS an argument)."""
        b = batch_size or self.config.BATCH_SIZE
        idx = np.zeros((k, b), np.int32)
        weights = np.ones((k, b), np.float32)
        from_fn = (
            self._get_from_sharded_fn(buffer)
            if getattr(buffer, "is_sharded", False)
            else self._from_fn
        )
        return from_fn.analyze(self.state, buffer.storage, idx, weights)

    @property
    def global_step(self) -> int:
        return self._host_step

    def get_current_lr(self) -> float:
        """LR at the current step (reference `trainer.py:312-323`)."""
        return float(self.schedule(self.global_step))

    def get_variables(self) -> dict:
        """Current model variables (for pushing into the eval wrapper)."""
        variables = {"params": self.state.params}
        if self.state.batch_stats:
            variables["batch_stats"] = self.state.batch_stats
        return variables

    def sync_to_network(self) -> int:
        """Install learner params into the `NeuralNetwork`; returns the
        bumped weights version (the TPU replacement for the reference's
        Ray weight broadcast, `worker_manager.py:169-209`).

        Hands the wrapper a device-side copy: the live state buffers get
        donated by the next train step. Tensor-sharded params are
        gathered first — the eval wrapper serves the single-device
        self-play path, which wants whole tensors."""
        variables = self.get_variables()
        if self.tp_size > 1:
            # On-device all-gather (ICI) first: after it every host
            # holds full replicas with no host round trip. Then hand
            # the eval wrapper each tensor's LOCAL replica (a
            # single-device array) — a multi-host replicated array
            # cannot be device_put to one device directly (it spans
            # non-addressable devices), but its first addressable
            # shard IS the whole tensor, already resident locally.
            variables = jax.device_put(variables, replicated(self.mesh))
            # jnp.array COPIES the local replica: for leaves that were
            # already replicated the device_put above is a no-op, and
            # handing the wrapper the raw shard would alias live state
            # buffers that the next train step donates.
            variables = jax.tree_util.tree_map(
                lambda x: jnp.array(x.addressable_shards[0].data), variables
            )
        else:
            variables = jax.tree_util.tree_map(jnp.array, variables)
        self.nn.set_weights(variables)
        return self.nn.weights_version

    def set_state(self, state: TrainState) -> None:
        """Install a restored TrainState (checkpoint resume path).

        Deep-copies: device_put is a no-op for already-replicated
        arrays, and an aliased caller pytree would be deleted by the
        next step's donation."""
        state = jax.tree_util.tree_map(jnp.array, state)
        self.state = jax.device_put(state, self._state_shard)
        self._host_step = int(self.state.step)  # one fetch, resume-only
