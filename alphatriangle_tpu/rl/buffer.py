"""Experience replay buffer: uniform + prioritized (PER).

Capability parity with the reference `ExperienceBuffer`
(`alphatriangle/rl/core/buffer.py:25-195`): ring storage, max-priority
insert, stratified proportional sampling with β-annealed importance
weights, `(|δ|+ε)^α` priority updates, readiness gating.

TPU-native redesign: experiences are stored as **fixed-shape
struct-of-arrays** (grid int8, features/policy/value float32) instead of
Python tuples, so a sampled batch is already a dense, device-ready
`DenseBatch` — no per-sample tensor stacking on the hot path, and the
whole PER sample is two vectorized SumTree sweeps instead of the
reference's 256 sequential Python descents per train step
(`buffer.py:104-150`).
"""

import logging
from typing import Any, TypedDict

import numpy as np

from ..config.train_config import TrainConfig
from ..utils.sumtree import SumTree
from ..utils.types import DenseBatch, Experience, dense_policy_from_mapping

logger = logging.getLogger(__name__)


class DenseSample(TypedDict):
    """One sampled training batch plus PER bookkeeping."""

    batch: DenseBatch
    indices: np.ndarray  # (B,) int64 buffer slot indices
    weights: np.ndarray  # (B,) float32 IS weights (ones when uniform)


class ExperienceBuffer:
    """Uniform or prioritized replay over dense SoA ring storage.

    Storage is allocated lazily on the first add (shapes inferred from
    the data), so the buffer needs no env/model config.
    """

    def __init__(
        self,
        config: TrainConfig,
        seed: int | None = None,
        action_dim: int | None = None,
    ):
        self.config = config
        self.capacity = config.BUFFER_CAPACITY
        self.min_size_to_train = config.MIN_BUFFER_SIZE_TO_TRAIN
        self.use_per = config.USE_PER
        self.alpha = config.PER_ALPHA
        self.beta_initial = config.PER_BETA_INITIAL
        self.beta_final = config.PER_BETA_FINAL
        # TrainConfig's validator guarantees this is set when USE_PER.
        self.beta_anneal_steps = config.PER_BETA_ANNEAL_STEPS or 1
        self.per_epsilon = config.PER_EPSILON
        self._action_dim = action_dim

        self.tree = SumTree(self.capacity) if self.use_per else None
        self._rng = np.random.default_rng(
            config.RANDOM_SEED if seed is None else seed
        )
        self._storage: dict[str, np.ndarray] | None = None
        self._pos = 0
        self._size = 0

    # --- storage ----------------------------------------------------------

    def _ensure_storage(
        self, grid: np.ndarray, other: np.ndarray, policy: np.ndarray
    ) -> None:
        if self._storage is not None:
            return
        # Grid cells are exactly {-1, 0, 1}; int8 storage is lossless and
        # quarters the ring's HBM-host footprint at 250k capacity.
        self._storage = {
            "grid": np.zeros((self.capacity, *grid.shape[1:]), dtype=np.int8),
            "other_features": np.zeros(
                (self.capacity, *other.shape[1:]), dtype=np.float32
            ),
            "policy_target": np.zeros(
                (self.capacity, *policy.shape[1:]), dtype=np.float32
            ),
            "value_target": np.zeros(self.capacity, dtype=np.float32),
            # Per-row policy-loss mask (0 for fast playout-cap moves).
            "policy_weight": np.ones(self.capacity, dtype=np.float32),
        }

    # --- writes -----------------------------------------------------------

    def add_dense(
        self,
        grid: np.ndarray,
        other_features: np.ndarray,
        policy_target: np.ndarray,
        value_target: np.ndarray,
        policy_weight: np.ndarray | None = None,
    ) -> np.ndarray:
        """Ring-insert a batch of experiences from dense arrays.

        Returns the slot indices used. New items get max-priority init
        under PER (`buffer.py:55-70` semantics). `policy_weight` rows
        mask the policy loss per sample (None -> ones).
        """
        grid = np.asarray(grid)
        other_features = np.asarray(other_features, dtype=np.float32)
        policy_target = np.asarray(policy_target, dtype=np.float32)
        value_target = np.asarray(value_target, dtype=np.float32).reshape(-1)
        k = grid.shape[0]
        policy_weight = (
            np.ones(k, dtype=np.float32)
            if policy_weight is None
            else np.asarray(policy_weight, dtype=np.float32).reshape(-1)
        )
        if k == 0:
            return np.zeros(0, dtype=np.int64)
        finite = (
            np.isfinite(grid).all(axis=tuple(range(1, grid.ndim)))
            & np.isfinite(other_features).all(axis=tuple(range(1, other_features.ndim)))
            & np.isfinite(policy_target).all(axis=tuple(range(1, policy_target.ndim)))
            & np.isfinite(value_target)
        )
        if not finite.all():
            dropped = int(k - finite.sum())
            logger.warning("Dropping %d non-finite experiences on add.", dropped)
            grid = grid[finite]
            other_features = other_features[finite]
            policy_target = policy_target[finite]
            value_target = value_target[finite]
            policy_weight = policy_weight[finite]
            k = grid.shape[0]
            if k == 0:
                return np.zeros(0, dtype=np.int64)
        self._ensure_storage(grid, other_features, policy_target)
        assert self._storage is not None
        idxs = (self._pos + np.arange(k)) % self.capacity
        self._storage["grid"][idxs] = grid.astype(np.int8)
        self._storage["other_features"][idxs] = other_features
        self._storage["policy_target"][idxs] = policy_target
        self._storage["value_target"][idxs] = value_target
        self._storage["policy_weight"][idxs] = policy_weight
        if self.tree is not None:
            self.tree.update_batch(
                idxs, np.full(k, self.tree.max_priority, dtype=np.float64)
            )
            self.tree.data_pointer = int((self._pos + k) % self.capacity)
            self.tree.n_entries = min(self._size + k, self.capacity)
        self._pos = int((self._pos + k) % self.capacity)
        self._size = min(self._size + k, self.capacity)
        return idxs

    def add(self, experience: Experience) -> None:
        """Parity path: insert one `(StateType, mapping, return)` tuple."""
        self.add_batch([experience])

    def add_batch(self, experiences: list[Experience]) -> None:
        """Parity path: insert reference-style experience tuples."""
        if not experiences:
            return
        action_dim = self._infer_action_dim(experiences)
        grids = np.stack([e[0]["grid"] for e in experiences])
        others = np.stack([e[0]["other_features"] for e in experiences])
        policies = np.stack(
            [dense_policy_from_mapping(e[1], action_dim) for e in experiences]
        )
        values = np.asarray([e[2] for e in experiences], dtype=np.float32)
        self.add_dense(grids, others, policies, values)

    def _infer_action_dim(self, experiences: list[Experience]) -> int:
        if self._action_dim is not None:
            return self._action_dim
        if self._storage is not None:
            return int(self._storage["policy_target"].shape[1])
        raise ValueError(
            "Tuple-form adds need the action space width before dense "
            "storage exists; construct ExperienceBuffer(..., action_dim=N)."
        )

    # --- reads ------------------------------------------------------------

    def __len__(self) -> int:
        return self._size

    def is_ready(self) -> bool:
        return self._size >= self.min_size_to_train

    def beta(self, train_step: int) -> float:
        """Annealed PER importance-sampling exponent at `train_step`."""
        frac = min(1.0, max(0.0, train_step / self.beta_anneal_steps))
        return self.beta_initial + frac * (self.beta_final - self.beta_initial)

    def _sample_indices(
        self, batch_size: int, current_train_step: int | None
    ) -> tuple[np.ndarray, np.ndarray] | None:
        """Shared slot-sampling math: (slots, IS weights) or None until
        ready. Stratified proportional PER with β-annealed, max-
        normalized importance weights (reference `buffer.py:96-150`)."""
        if not self.is_ready() or batch_size > self._size:
            return None
        if self.use_per:
            if current_train_step is None:
                raise ValueError(
                    "current_train_step is required for PER sampling."
                )
            assert self.tree is not None
            slots, priorities = self.tree.sample_batch(batch_size, self._rng)
            total = self.tree.total_priority
            probs = np.maximum(priorities, 1e-12) / max(total, 1e-12)
            beta = self.beta(current_train_step)
            weights = (self._size * probs) ** (-beta)
            weights = (weights / weights.max()).astype(np.float32)
        else:
            slots = self._rng.integers(0, self._size, size=batch_size)
            weights = np.ones(batch_size, dtype=np.float32)
        return slots, weights

    def sample(
        self, batch_size: int, current_train_step: int | None = None
    ) -> DenseSample | None:
        """Sample a dense training batch.

        Returns None until `is_ready()` (reference `buffer.py:85-92`).
        Under PER, `current_train_step` is required for β annealing
        (reference `buffer.py:96-101`).
        """
        sampled = self._sample_indices(batch_size, current_train_step)
        if sampled is None:
            return None
        slots, weights = sampled
        assert self._storage is not None
        batch: DenseBatch = {
            "grid": self._storage["grid"][slots].astype(np.float32),
            "other_features": self._storage["other_features"][slots],
            "policy_target": self._storage["policy_target"][slots],
            "value_target": self._storage["value_target"][slots],
            "weights": weights,
            "policy_weight": self._storage["policy_weight"][slots],
        }
        return {"batch": batch, "indices": slots.astype(np.int64), "weights": weights}

    def update_priorities(self, indices: np.ndarray, td_errors: np.ndarray) -> None:
        """PER priority update: `p = (|δ| + ε)^α` (reference `buffer.py:162-195`)."""
        if not self.use_per or self.tree is None:
            return
        indices = np.asarray(indices, dtype=np.int64).reshape(-1)
        td = np.asarray(td_errors, dtype=np.float64).reshape(-1)
        if indices.shape != td.shape:
            raise ValueError(
                f"indices {indices.shape} and td_errors {td.shape} must match."
            )
        if len(indices) == 0:
            return
        td = np.where(np.isfinite(td), td, 0.0)
        priorities = (np.abs(td) + self.per_epsilon) ** self.alpha
        self.tree.update_batch(indices, priorities)

    # --- persistence ------------------------------------------------------

    def get_state(self) -> dict[str, Any]:
        """Snapshot for checkpointing (improves on the reference, which
        drops priorities on resume — `training/runner.py:87-91`)."""
        state: dict[str, Any] = {
            "pos": self._pos,
            "size": self._size,
            "storage": None,
            "priorities": None,
        }
        if self._storage is not None:
            state["storage"] = {
                k: v[: self._size].copy() if self._size < self.capacity else v.copy()
                for k, v in self._storage.items()
            }
        if self.tree is not None and self._size > 0:
            leaves = np.arange(self._size) + self.tree._cap2
            state["priorities"] = self.tree.tree[leaves].copy()
        return state

    def set_state(self, state: dict[str, Any]) -> None:
        """Restore a `get_state` snapshot (shapes may differ from current
        capacity; contents are clipped to fit).

        Snapshot rows are in *slot* order; for a wrapped ring the oldest
        row sits at the old write position, not slot 0. Restore in
        chronological order (oldest at slot 0, `_pos` after the newest)
        so later ring writes overwrite oldest-first regardless of any
        capacity change, and clipping keeps the NEWEST rows."""
        storage = state.get("storage")
        if storage is None:
            return
        old_size = int(state["size"])
        old_pos = int(state["pos"])
        # Slot -> chronological order: a wrapped ring's oldest entry is
        # at old_pos (an unwrapped one's pos == size, making this a no-op).
        order = np.roll(np.arange(old_size), -(old_pos % max(old_size, 1)))
        n = min(old_size, self.capacity)
        order = order[-n:]  # keep newest on shrink
        self._ensure_storage(
            storage["grid"][:1],
            storage["other_features"][:1],
            storage["policy_target"][:1],
        )
        assert self._storage is not None
        # Columns added after a snapshot was written restore to an
        # explicit default; anything else missing is loud corruption.
        restore_defaults = {"policy_weight": 1.0}  # pre-PCR: trainable
        for k in self._storage:
            if k in storage:
                self._storage[k][:n] = storage[k][order]
            elif k in restore_defaults:
                self._storage[k][:n] = restore_defaults[k]
            else:
                raise KeyError(
                    f"Buffer snapshot is missing column {k!r} and no "
                    "restore default is defined for it."
                )
        self._size = n
        self._pos = n % self.capacity
        if self.tree is not None:
            prios = state.get("priorities")
            if prios is None:
                prios = np.ones(n, dtype=np.float64)
            else:
                prios = np.asarray(prios, dtype=np.float64)[order]
            # Write the full leaf range: slots >= n must be zeroed, or a
            # smaller snapshot restored over a fuller tree leaves stale
            # priorities inflating total_priority and hijacking sampling.
            full = np.zeros(self.capacity, dtype=np.float64)
            full[:n] = np.asarray(prios[:n], dtype=np.float64)
            self.tree.update_batch(np.arange(self.capacity), full)
            self.tree.data_pointer = self._pos
            self.tree.n_entries = n
            # Reset the max-priority watermark too: update_batch only
            # ratchets it up, and the pre-restore buffer's (possibly
            # huge) max would otherwise dominate every post-restore add.
            self.tree._max_priority_seen = float(max(1.0, full[:n].max(initial=0.0)))
