"""AOT executable cache: serialize compiled XLA programs across processes.

Five rounds of benchmarking produced zero driver-captured TPU numbers
because the first rollout-chunk compile (34.7s CPU / 58.8s on the
tunneled TPU, BENCH_r05.json) burned every short healthy chip window
before the first metric landed. The XLA persistent compilation cache
(utils/helpers.py:enable_persistent_compilation_cache) already removes
*re*-compiles on accelerator backends, but (a) it is disabled on CPU
(AOT reload SIGILL risk at the XLA layer), (b) it still pays tracing +
lowering + cache lookup inside the measurement window, and (c) nothing
fills it ahead of a window. This module closes all three gaps,
Podracer-style (arXiv:2104.06272 treats program build/launch latency as
a first-class amortized cost):

- `CompileCache.wrap(name, jit_fn)` returns a `CachedProgram` that, on
  first dispatch of each distinct input signature, either DESERIALIZES
  a previously saved executable (hit: milliseconds instead of a full
  compile) or compiles fresh and serializes the result for the next
  process (miss). Executables ride `jax.experimental.
  serialize_executable` and live beside the XLA persistent cache.
- Keys are (jax version, backend, device kinds + topology, a source
  digest of this package, program name + config digest, input
  avals/shardings) — see `docs/COMPILE_CACHE.md` for the invalidation
  rules. A key mismatch is never an error: it just falls back to a
  fresh `lower().compile()`.
- `warm.py` + `cli warm` enumerate the hot bench/training programs for
  a preset and push them through this cache ahead of time, so the chip
  watcher can make any future healthy window start measuring in
  seconds.

Every load/compile/serialize is recorded as a `compile/<name>` span on
the attached `SpanTracer` (telemetry/tracer.py), so compile cost shows
up in trace.json next to rollout/learner spans; `stats()` feeds the
bench JSON's `compile_cache: {hits, misses}` block.

Degradation contract: any failure (unpicklable executable, corrupt
file, host feature mismatch on reload, an exotic backend without
serialization support) logs once and falls back to the plain jitted
call — the cache can only ever add speed, never break a run.
"""

import hashlib
import logging
import os
import pickle
import threading
import time
from contextlib import nullcontext
from pathlib import Path

import jax

logger = logging.getLogger(__name__)


def _exc_brief(exc: BaseException, limit: int = 160) -> str:
    """Exception text bounded for logs (XLA reload errors embed the
    full missing-symbol list — thousands of characters of noise)."""
    text = f"{type(exc).__name__}: {exc}"
    return text if len(text) <= limit else text[: limit - 3] + "..."

# Sentinel stored per signature when AOT execution is not viable for
# those inputs; the program permanently delegates to the jitted fall
# back for that signature (never retries a failing executable).
_FALLBACK = object()


def default_cache_dir() -> str:
    """AOT executables live in an `aot/` subdir beside the XLA
    persistent cache so one directory knob (JAX_COMPILATION_CACHE_DIR)
    moves both."""
    root = (
        os.environ.get("ALPHATRIANGLE_AOT_CACHE_DIR")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or "/tmp/alphatriangle_tpu_jax_cache"
    )
    return os.path.join(root, "aot")


def _package_source_digest() -> str:
    """Digest of every .py file in this package: executables are only
    reused by the exact code that produced them. The shape signature
    alone cannot see a changed scan body or loss function — reusing a
    stale executable would silently compute the wrong thing, the one
    failure mode a cache must not have."""
    pkg = Path(__file__).parent
    h = hashlib.sha256()
    for path in sorted(pkg.rglob("*.py")):
        h.update(str(path.relative_to(pkg)).encode())
        try:
            h.update(path.read_bytes())
        except OSError:
            h.update(b"?")
    return h.hexdigest()[:16]


_source_digest_cache: str | None = None


def _source_digest() -> str:
    global _source_digest_cache
    if _source_digest_cache is None:
        _source_digest_cache = _package_source_digest()
    return _source_digest_cache


def config_digest(*configs) -> str:
    """Fingerprint config objects that shape a program but are invisible
    in its input avals (MCTS sim counts, loss weights, optimizer type).
    Pydantic models dump to canonical JSON; anything else reprs.
    RUN_NAME is excluded — it can never affect a compiled program, and
    keeping it would make every differently-named run a cache miss."""
    h = hashlib.sha256()
    for cfg in configs:
        if cfg is None:
            h.update(b"none")
            continue
        dump = getattr(cfg, "model_dump", None)
        if callable(dump):
            d = dump()
            d.pop("RUN_NAME", None)
            h.update(repr(sorted(d.items())).encode())
        else:
            h.update(repr(cfg).encode())
    return h.hexdigest()[:12]


def _describe_leaf(x) -> str:
    """Stable aval + sharding description of one input leaf.

    Mesh (Named) shardings genuinely change the lowered program (GSPMD
    partitioning) and are part of the key; single-device placement vs
    an uncommitted host array does not (both lower to the same
    default-device program), so everything else canonicalizes to "-"
    — this is what lets `cli warm`'s lowering match the bench process's
    real dispatch arguments.
    """
    shape = tuple(getattr(x, "shape", ()))
    dtype = getattr(getattr(x, "dtype", None), "name", str(getattr(x, "dtype", type(x).__name__)))
    sh = getattr(x, "sharding", None)
    if sh is not None and type(sh).__name__ == "NamedSharding":
        mesh_desc = tuple((str(k), int(v)) for k, v in sh.mesh.shape.items())
        sh_desc = f"NS{mesh_desc}{sh.spec}"
    else:
        sh_desc = "-"
    return f"{dtype}{list(shape)}@{sh_desc}"


class CompileCache:
    """Process-wide registry of AOT-cached programs (see module doc)."""

    def __init__(
        self, cache_dir: str | None = None, enabled: bool | None = None
    ) -> None:
        if enabled is None:
            enabled = os.environ.get("ALPHATRIANGLE_NO_COMPILE_CACHE") != "1"
        self.cache_dir = Path(cache_dir or default_cache_dir())
        self.enabled = enabled
        self.tracer = None  # optional telemetry SpanTracer
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.deserialize_errors = 0
        self.serialize_errors = 0
        self.exec_errors = 0
        # name -> {"event": hit|miss|..., "seconds": float}
        self.events: list[dict] = []
        # "name:key" -> memory record (telemetry/memory.py): every
        # program's AOT memory_analysis, captured at compile/reload
        # time and persisted beside the executable artifact. Runs pull
        # from this registry (RunTelemetry keeps its own seen-set, so
        # several runs in one process each ledger every record once).
        self.memory_records: dict[str, dict] = {}
        # "name:key" -> cost record (telemetry/roofline.py): the same
        # flow for `cost_analysis()` — compiler-reported FLOPs / bytes
        # accessed / transcendentals, persisted as `.cost.json`
        # sidecars and drained into run ledgers for `cli roofline`.
        self.cost_records: dict[str, dict] = {}

    # --- wiring -----------------------------------------------------------

    def set_tracer(self, tracer) -> None:
        """Attach a telemetry SpanTracer: every load/compile/serialize
        becomes a `compile/<program>` span in the run's trace.json."""
        self.tracer = tracer

    def _span(self, name: str, **args):
        if self.tracer is None:
            return nullcontext()
        return self.tracer.span(name, **args)

    def wrap(
        self,
        name: str,
        jit_fn,
        extra: str = "",
        cpu_aot: bool = True,
        serialize: bool = True,
    ) -> "CachedProgram":
        """Wrap a jitted function in an AOT-caching dispatcher.

        `extra` carries a digest of everything that shapes the program
        but is invisible in its input avals (use `config_digest`).
        `cpu_aot=False` bypasses the AOT path entirely on the CPU
        backend (plain jit, no artifacts read or written): XLA:CPU
        deserialization of the learner-step program family is broken in
        this image — the reloaded executable runs without error and
        returns the donated train state UNCHANGED (params silently stop
        updating; reproduced deterministically, see rl/trainer.py).
        Accelerator backends are unaffected by the flag.
        `serialize=False` keeps the in-memory AOT path (lower+compile
        once per signature) but never reads or writes executable
        artifacts on ANY backend — for programs whose executables are
        not round-trippable, e.g. beacon-armed programs embedding
        `jax.debug.callback` closures (telemetry/device_stats.py)."""
        return CachedProgram(
            self, name, jit_fn, extra=extra, cpu_aot=cpu_aot,
            serialize=serialize,
        )

    # --- keying -----------------------------------------------------------

    def signature(self, name: str, args: tuple, extra: str = "") -> str:
        """Cross-process cache key for one (program, inputs) pair."""
        backend = jax.default_backend()
        devices = jax.devices()
        parts = [
            jax.__version__,
            backend,
            ",".join(
                sorted({str(getattr(d, "device_kind", d.platform)) for d in devices})
            ),
            f"d{len(devices)}p{jax.process_count()}",
            _source_digest(),
            name,
            extra,
            str(jax.tree_util.tree_structure(args)),
        ]
        parts.extend(
            _describe_leaf(leaf) for leaf in jax.tree_util.tree_leaves(args)
        )
        return hashlib.sha256("|".join(parts).encode()).hexdigest()[:20]

    def _path(self, name: str, key: str) -> Path:
        safe = name.replace("/", "_").replace(" ", "_")
        return self.cache_dir / f"{safe}-{key}.jaxexe"

    # --- memory attribution (telemetry/memory.py; docs/OBSERVABILITY.md) --

    def memory_record_for(self, name: str, key: str) -> "dict | None":
        with self._lock:
            return self.memory_records.get(f"{name}:{key}")

    def _register_memory(self, name: str, key: str, record: dict) -> None:
        with self._lock:
            self.memory_records.setdefault(f"{name}:{key}", record)

    def capture_memory(
        self, name: str, key: str, compiled, persist: bool = True
    ) -> "dict | None":
        """Record `compiled.memory_analysis()` for one program and (by
        default) persist it as a `.mem.json` sidecar beside the
        executable artifact, so `cli mem` can attribute a run's HBM
        without recompiling anything. Never raises — attribution can
        only ever add visibility, never break a compile."""
        existing = self.memory_record_for(name, key)
        if existing is not None:
            return existing
        try:
            from .telemetry.memory import program_memory_record

            record = program_memory_record(
                name,
                compiled,
                backend=jax.default_backend(),
                key=key,
            )
        except Exception:
            return None
        if record is None:
            return None
        self._register_memory(name, key, record)
        if persist:
            try:
                import json

                sidecar = self._path(name, key).with_suffix(".mem.json")
                sidecar.parent.mkdir(parents=True, exist_ok=True)
                tmp = sidecar.with_suffix(f".tmp{os.getpid()}")
                tmp.write_text(json.dumps(record))
                tmp.replace(sidecar)
            except OSError:
                logger.debug(
                    "compile_cache: %s memory sidecar write failed", name
                )
        return record

    def _load_memory_sidecar(self, name: str, key: str) -> "dict | None":
        """Reload a previously persisted memory record on an AOT hit
        (the analysis also works on deserialized executables — the
        sidecar just makes the record survive artifact sharing)."""
        try:
            import json

            sidecar = self._path(name, key).with_suffix(".mem.json")
            record = json.loads(sidecar.read_text())
        except (OSError, ValueError):
            return None
        if not isinstance(record, dict) or record.get("kind") != "memory":
            return None
        record["origin"] = "sidecar"
        self._register_memory(name, key, record)
        return record

    def memory_summary(self) -> list[dict]:
        """Every program memory record this process captured (the bench
        JSON's `extra.memory.programs` block)."""
        with self._lock:
            return list(self.memory_records.values())

    # --- cost attribution (telemetry/roofline.py) -------------------------

    def cost_record_for(self, name: str, key: str) -> "dict | None":
        with self._lock:
            return self.cost_records.get(f"{name}:{key}")

    def _register_cost(self, name: str, key: str, record: dict) -> None:
        with self._lock:
            self.cost_records.setdefault(f"{name}:{key}", record)

    def capture_cost(
        self, name: str, key: str, compiled, persist: bool = True
    ) -> "dict | None":
        """Record `compiled.cost_analysis()` for one program and (by
        default) persist it as a `.cost.json` sidecar beside the
        executable artifact — the exact twin of `capture_memory`, so
        `cli roofline` can attribute a run without recompiling
        anything. Never raises."""
        existing = self.cost_record_for(name, key)
        if existing is not None:
            return existing
        try:
            from .telemetry.roofline import program_cost_record

            record = program_cost_record(
                name,
                compiled,
                backend=jax.default_backend(),
                key=key,
            )
        except Exception:
            return None
        if record is None:
            return None
        self._register_cost(name, key, record)
        if persist:
            try:
                import json

                sidecar = self._path(name, key).with_suffix(".cost.json")
                sidecar.parent.mkdir(parents=True, exist_ok=True)
                tmp = sidecar.with_suffix(f".tmp{os.getpid()}")
                tmp.write_text(json.dumps(record))
                tmp.replace(sidecar)
            except OSError:
                logger.debug(
                    "compile_cache: %s cost sidecar write failed", name
                )
        return record

    def _load_cost_sidecar(self, name: str, key: str) -> "dict | None":
        """Reload a previously persisted cost record on an AOT hit.
        Missing, corrupt or wrong-kind sidecars return None (the caller
        re-analyzes the reloaded executable) — torn files degrade,
        never raise."""
        try:
            import json

            sidecar = self._path(name, key).with_suffix(".cost.json")
            record = json.loads(sidecar.read_text())
        except (OSError, ValueError):
            return None
        if not isinstance(record, dict) or record.get("kind") != "cost":
            return None
        record["origin"] = "sidecar"
        self._register_cost(name, key, record)
        return record

    def cost_summary(self) -> list[dict]:
        """Every program cost record this process captured (the bench
        JSON's `extra.roofline.programs` block)."""
        with self._lock:
            return list(self.cost_records.values())

    # --- load / compile / serialize ---------------------------------------

    def load_or_compile(
        self, name: str, key: str, jit_fn, args, serialize: bool = True
    ):
        """Deserialize a cached executable for `key`, or compile fresh
        (serializing the result). Returns a `jax.stages.Compiled`, or
        `_FALLBACK` when neither path is viable. `serialize=False`
        skips BOTH artifact directions (no deserialize, no serialize):
        the executable lives only in this process."""
        path = self._path(name, key)
        if serialize and path.exists():
            t0 = time.time()
            try:
                with self._span(f"compile/{name}", event="deserialize"):
                    from jax.experimental.serialize_executable import (
                        deserialize_and_load,
                    )

                    with path.open("rb") as fh:
                        record = pickle.load(fh)
                    compiled = deserialize_and_load(
                        record["payload"], record["in_tree"], record["out_tree"]
                    )
                dt = time.time() - t0
                self._note("hit", name, dt)
                # Attribution rides the hit too: prefer the persisted
                # sidecars, fall back to analyzing the reloaded program.
                if self._load_memory_sidecar(name, key) is None:
                    self.capture_memory(name, key, compiled)
                if self._load_cost_sidecar(name, key) is None:
                    self.capture_cost(name, key, compiled)
                logger.info(
                    "compile_cache: %s HIT (%s, deserialized in %.2fs)",
                    name,
                    path.name,
                    dt,
                )
                return compiled
            except Exception as exc:
                # Corrupt file, jaxlib mismatch, host feature check
                # failure on reload — treat as a miss and recompile.
                self.deserialize_errors += 1
                logger.warning(
                    "compile_cache: %s deserialize failed (%s); "
                    "recompiling fresh.",
                    name,
                    _exc_brief(exc),
                )
        t0 = time.time()
        try:
            with self._span(f"compile/{name}", event="compile"):
                compiled = jit_fn.lower(*args).compile()
        except Exception as exc:
            # e.g. a transform jit cannot lower for these args; the
            # plain call path may still work — let it own the error.
            logger.warning(
                "compile_cache: %s AOT lower/compile failed (%s); "
                "falling back to the jitted call.",
                name,
                _exc_brief(exc),
            )
            self.exec_errors += 1
            return _FALLBACK
        dt = time.time() - t0
        self._note("miss", name, dt)
        logger.info("compile_cache: %s MISS (compiled in %.2fs)", name, dt)
        self.capture_memory(name, key, compiled)
        self.capture_cost(name, key, compiled)
        if serialize:
            self._serialize(name, path, compiled)
        return compiled

    def _serialize(self, name: str, path: Path, compiled) -> None:
        tmp = path.with_suffix(f".tmp{os.getpid()}")
        try:
            with self._span(f"compile/{name}", event="serialize"):
                from jax.experimental.serialize_executable import (
                    deserialize_and_load,
                    serialize,
                )

                payload, in_tree, out_tree = serialize(compiled)
                record = {
                    "payload": payload,
                    "in_tree": in_tree,
                    "out_tree": out_tree,
                    "meta": {
                        "name": name,
                        "jax": jax.__version__,
                        "backend": jax.default_backend(),
                        "created": time.time(),
                    },
                }
                path.parent.mkdir(parents=True, exist_ok=True)
                with tmp.open("wb") as fh:
                    pickle.dump(record, fh)
                # VALIDATE before publishing: an executable that
                # compile() itself loaded from the XLA persistent cache
                # serializes to a truncated payload on XLA:CPU (the
                # object code is absent; reload dies with "Symbols not
                # found"). A broken artifact would turn every future
                # warm start into a deserialize-error + recompile — so
                # prove the round trip here, where the cost is off any
                # measurement window, and publish only what reloads.
                with tmp.open("rb") as fh:
                    check = pickle.load(fh)
                deserialize_and_load(
                    check["payload"], check["in_tree"], check["out_tree"]
                )
                tmp.replace(path)  # atomic: readers never see a torn file
        except Exception as exc:
            self.serialize_errors += 1
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            logger.warning(
                "compile_cache: %s not serialized (%s) — this process "
                "keeps its in-memory executable; the next cold process "
                "recompiles (or reuses the XLA persistent cache).",
                name,
                _exc_brief(exc),
            )

    def _note(self, event: str, name: str, seconds: float) -> None:
        with self._lock:
            if event == "hit":
                self.hits += 1
            else:
                self.misses += 1
            self.events.append(
                {"event": event, "program": name, "seconds": round(seconds, 3)}
            )

    # --- reporting --------------------------------------------------------

    def stats(self) -> dict:
        """The bench JSON `compile_cache` block."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "dir": str(self.cache_dir),
                "hits": self.hits,
                "misses": self.misses,
                "deserialize_errors": self.deserialize_errors,
                "serialize_errors": self.serialize_errors,
                "exec_errors": self.exec_errors,
                "events": list(self.events),
            }


class CachedProgram:
    """Callable wrapper over one jitted function: per-input-signature
    AOT executables with a jitted fallback.

    Drop-in for the jitted function it wraps (bit-identical outputs —
    it runs the same lowered program), plus:
    - `warm(*args)`: populate (deserialize or compile+serialize) the
      executable for these argument avals WITHOUT executing — the AOT
      precompilation entry point (`cli warm`).
    - multi-signature: a program called with several distinct shapes
      (e.g. the trainer's fused-from program across K values) caches an
      executable per signature, exactly like jit's own cache.
    """

    def __init__(
        self,
        cache: CompileCache,
        name: str,
        jit_fn,
        extra: str = "",
        cpu_aot: bool = True,
        serialize: bool = True,
    ) -> None:
        self._cache = cache
        self.name = name
        self._jit_fn = jit_fn
        self._extra = extra
        self._cpu_aot = cpu_aot
        self._serialize_artifacts = serialize
        self._execs: dict[str, object] = {}
        self._lock = threading.Lock()

    @property
    def aot_active(self) -> bool:
        """Whether this program uses the AOT artifact path here: the
        cache is enabled AND the program is not CPU-bypassed (see
        CompileCache.wrap's cpu_aot)."""
        return self._cache.enabled and (
            self._cpu_aot or jax.default_backend() != "cpu"
        )

    def _executable_for(self, args):
        key = self._cache.signature(self.name, args, self._extra)
        exe = self._execs.get(key)
        if exe is None:
            with self._lock:
                exe = self._execs.get(key)
                if exe is None:
                    exe = self._cache.load_or_compile(
                        self.name,
                        key,
                        self._jit_fn,
                        args,
                        serialize=self._serialize_artifacts,
                    )
                    self._execs[key] = exe
        return key, exe

    def warm(self, *args) -> bool:
        """Ensure an executable exists for these argument avals (no
        execution, no donation). True when an AOT executable is ready,
        False when this program fell back to plain jit (or is
        CPU-bypassed)."""
        if not self.aot_active:
            return False
        _, exe = self._executable_for(args)
        return exe is not _FALLBACK

    def analyze(self, *args, persist: bool = False) -> "dict | None":
        """Memory record for this program at these argument avals
        (telemetry/memory.py), compiling AOT if needed — WITHOUT
        executing anything. Works even for CPU-bypassed programs
        (cpu_aot=False guards *deserialization*; a fresh lower+compile
        purely for `memory_analysis()` is safe and is not serialized).
        `persist=True` additionally writes the `.mem.json` sidecar on
        the fresh-compile path (the megastep uses this so its record
        survives into the cache dir even where the executable itself
        is CPU-bypassed); the default keeps analysis artifact-free.
        None when the program can't lower or the backend reports no
        analysis. This is `cli fit`'s estimator entry point.

        The cost leg (telemetry/roofline.py) rides every branch: each
        compiled object analyzed here also captures its
        `cost_analysis()` record, so `cli roofline` covers programs
        whose executables never touch the AOT artifact path
        (cpu_aot=False families included). Cost sidecars persist
        unconditionally — a `.cost.json` is a few hundred bytes of
        compiler ground truth (autotune's `--calibrate` cost_flops
        source reads them across processes), unlike the executable
        artifact whose serialization `persist` actually guards."""
        key = self._cache.signature(self.name, args, self._extra)
        record = self._cache.memory_record_for(self.name, key)
        if record is not None and (
            self._cache.cost_record_for(self.name, key) is not None
        ):
            return record
        if self.aot_active:
            _, exe = self._executable_for(args)
            if exe is not _FALLBACK:
                # Cost rides every analysis leg (telemetry/roofline.py):
                # the same compiled object answers both questions.
                self._cache.capture_cost(self.name, key, exe)
                record = self._cache.memory_record_for(self.name, key)
                if record is not None:
                    return record
                return self._cache.capture_memory(self.name, key, exe)
        try:
            compiled = self._jit_fn.lower(*args).compile()
        except Exception as exc:
            logger.warning(
                "compile_cache: %s memory analysis lower/compile failed "
                "(%s)",
                self.name,
                _exc_brief(exc),
            )
            return record
        self._cache.capture_cost(self.name, key, compiled)
        mem = self._cache.capture_memory(
            self.name, key, compiled, persist=persist
        )
        return mem if mem is not None else record

    def __call__(self, *args):
        if not self.aot_active:
            return self._jit_fn(*args)
        key, exe = self._executable_for(args)
        if exe is _FALLBACK:
            return self._jit_fn(*args)
        try:
            return exe(*args)
        except (TypeError, ValueError) as exc:
            # Input validation rejected the call BEFORE execution (so
            # no buffer was donated): e.g. a weak-typed scalar the jit
            # path would have accepted. Never retry this signature.
            self._cache.exec_errors += 1
            logger.warning(
                "compile_cache: %s AOT call rejected (%s: %s); using "
                "the jitted path for this signature.",
                self.name,
                type(exc).__name__,
                exc,
            )
            self._execs[key] = _FALLBACK
            return self._jit_fn(*args)


# --- process-wide cache ----------------------------------------------------

_global_cache: CompileCache | None = None
_global_lock = threading.Lock()


def get_compile_cache() -> CompileCache:
    """The process-wide cache every engine/trainer wraps through.

    Multi-process runs disable AOT caching (deserializing an executable
    that spans non-addressable devices is not supported); the XLA
    persistent cache still covers those.
    """
    global _global_cache
    with _global_lock:
        if _global_cache is None:
            cache = CompileCache()
            if jax.process_count() > 1:
                cache.enabled = False
            _global_cache = cache
        return _global_cache


def reset_compile_cache(
    cache_dir: str | None = None, enabled: bool | None = None
) -> CompileCache:
    """Replace the process-wide cache (tests; fresh stats windows)."""
    global _global_cache
    with _global_lock:
        _global_cache = CompileCache(cache_dir=cache_dir, enabled=enabled)
        return _global_cache
