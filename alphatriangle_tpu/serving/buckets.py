"""The serve-shape bucket ladder: ONE definition of the `serve/b<B>`
shapes a deployment compiles, shared by every consumer that picks a
serve batch shape.

Why one module: the fleet supervisor's quarantine policy
(supervise/policy.py `SERVE_SLOTS__scale: 0.5`) halves a wedging
replica's bucket, the policy service's micro-batcher walks its
compiled shape up under sustained load and back down on drain, `cli
warm` precompiles the shapes a serve process may dispatch, and
`estimate_fit --serve` budgets them. If each of those owned its own
rung list they would drift — a quarantined replica could respawn onto
a shape nobody warmed. `BucketLadder` is the single source of truth:
quarantine IS a forced walk-down on this ladder, the micro-batcher's
walk-up is the inverse move, and warm/fit enumerate `ladder.rungs`.

Stdlib-only on purpose: the fleet supervisor never imports JAX
(serving/fleet.py), so the ladder it routes `_effective_slots` through
cannot either.
"""

from dataclasses import dataclass


def default_rungs(base: int, *, floor: int = 1) -> tuple[int, ...]:
    """The implicit ladder under a single `--slots` knob: geometric
    halving from `base` down to `floor` — exactly the shapes the
    legacy quarantine multiplier (0.5 per strike) could land on, so
    routing it through the ladder changes no deployed behavior."""
    base = int(base)
    if base < 1:
        raise ValueError(f"ladder base must be >= 1, got {base}")
    rungs = []
    r = base
    while r > max(1, int(floor)):
        rungs.append(r)
        r = max(1, r // 2)
    rungs.append(max(1, int(floor)) if base >= floor else base)
    return tuple(sorted(set(rungs)))


@dataclass(frozen=True)
class BucketLadder:
    """Sorted, deduplicated serve batch shapes (e.g. (64, 256, 1024)).

    `rungs[i]` is a compiled `serve/b<rungs[i]>` shape; walking up or
    down moves one index. All lookups clamp — the ladder never
    invents a shape it doesn't own.
    """

    rungs: tuple[int, ...]

    def __post_init__(self):
        rungs = tuple(sorted({int(r) for r in self.rungs}))
        if not rungs:
            raise ValueError("BucketLadder needs at least one rung")
        if rungs[0] < 1:
            raise ValueError(f"rungs must be >= 1, got {rungs}")
        object.__setattr__(self, "rungs", rungs)

    # --- construction -------------------------------------------------

    @classmethod
    def from_spec(
        cls, spec, base: "int | None" = None
    ) -> "BucketLadder":
        """Parse a ladder from a config knob: an iterable of ints, a
        CSV string ("64,256,1024"), or None/"" (the implicit halving
        ladder under `base`, or the single-rung ladder when no base)."""
        if isinstance(spec, BucketLadder):
            return spec
        if spec is None or spec == "":
            if base is None:
                raise ValueError("from_spec needs a spec or a base")
            return cls(default_rungs(base))
        if isinstance(spec, str):
            spec = [p for p in spec.replace(";", ",").split(",") if p.strip()]
        rungs = tuple(int(p) for p in spec)
        if base is not None and int(base) not in rungs:
            rungs = rungs + (int(base),)
        return cls(rungs)

    @classmethod
    def single(cls, slots: int) -> "BucketLadder":
        """The degenerate one-rung ladder: fixed-shape serving."""
        return cls((int(slots),))

    # --- lookups ------------------------------------------------------

    @property
    def min_rung(self) -> int:
        return self.rungs[0]

    @property
    def max_rung(self) -> int:
        return self.rungs[-1]

    def __contains__(self, rung) -> bool:
        return int(rung) in self.rungs

    def index(self, rung: int) -> int:
        return self.rungs.index(int(rung))

    def rung_for(self, demand: int) -> int:
        """Smallest rung holding `demand` sessions (clamped to the top
        rung when demand exceeds every shape)."""
        for r in self.rungs:
            if r >= demand:
                return r
        return self.max_rung

    def rung_at_or_below(self, target: float) -> int:
        """Largest rung <= target (clamped to the bottom rung): how a
        fractional quarantine multiplier lands on a real shape."""
        best = self.rungs[0]
        for r in self.rungs:
            if r <= target:
                best = r
        return best

    def up(self, rung: int) -> int:
        """One rung up (clamped at the top)."""
        i = self.index(rung)
        return self.rungs[min(i + 1, len(self.rungs) - 1)]

    def down(self, rung: int) -> int:
        """One rung down (clamped at the bottom)."""
        i = self.index(rung)
        return self.rungs[max(i - 1, 0)]

    def walk_down(self, rung: int, strikes: int = 1) -> int:
        """`strikes` forced steps down — the quarantine move. One
        strike from rung R equals the legacy `SERVE_SLOTS__scale: 0.5`
        halving on the implicit ladder (test_fleet pins this)."""
        r = int(rung)
        for _ in range(max(0, int(strikes))):
            r = self.down(r)
        return r
