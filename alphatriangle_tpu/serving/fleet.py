"""Serve-fleet control plane: N replicas, one router, self-healing.

The JAX-FREE parent process behind `cli fleet` (docs/SERVING.md
"Fleet"): spawns N `serving/replica.py` subprocesses (each hosting one
PolicyService with its own compiled `serve/b<B>`, heartbeat, flight
ring and metrics ledger), keeps a `ReplicaRouter` admission view fresh
via the shared `telemetry.health.probe_run` probe, and reuses PR 14's
supervision machinery verbatim for replica lifecycle:

- a death is classified with `supervise.supervisor.diagnose` over the
  replica's OWN run dir, evidence since spawn (a SIGKILL reads clean,
  a hang-serve wedge reads dispatch-hung naming `serve/b<B>`);
- `supervise.policy.RecoveryPolicy` maps verdicts to backoff restarts
  under a restart budget — the serve quarantine arm's
  `SERVE_SLOTS__scale` override is interpreted HERE, respawning the
  replica onto a smaller compiled bucket (the degraded fallback);
- the replica's served-move count is the progress signal that resets
  the backoff streak (forward motion = traffic served since last
  death, the serving analogue of a new committed checkpoint).

Every lifecycle and routing decision lands crash-safe in
`fleet.jsonl` through the same append-only MetricsLedger writer the
supervisor and training ledgers use — the death -> verdict -> respawn
-> re-admission chain `make fleet-smoke` asserts is read back from
this file. The parent also writes plain `kind:"util"` ticks to its
own metrics.jsonl so `cli perf` / `cli compare` summarize a fleet run
like any other.

JAX never loads here: replica handles speak JSON lines over pipes,
and the probe/doctor/policy/ledger stack is stdlib-only (the same
contract `benchmarks/fleet_smoke.py` pins with an import guard).
"""

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

from ..supervise.policy import RecoveryPolicy
from ..supervise.supervisor import diagnose
from ..telemetry import tracectx
from ..telemetry.flight import (
    DOCTOR_EXIT_CODES,
    FLIGHT_FILENAME,
    FlightRecorder,
    read_flight,
    unsealed_intents,
)
from ..telemetry.health import PROBE_LIVE, probe_run
from ..telemetry.ledger import MetricsLedger, iter_jsonl_records, ledger_paths
from .router import ReplicaError, ReplicaRouter

logger = logging.getLogger(__name__)

FLEET_FILENAME = "fleet.jsonl"


class _Pending:
    """Minimal future for one in-flight replica request."""

    __slots__ = ("rid", "_handle", "_ev", "value", "error", "cancelled")

    def __init__(self, rid: int, handle=None):
        self.rid = rid
        self._handle = handle
        self._ev = threading.Event()
        self.value: "dict | None" = None
        self.error: "Exception | None" = None
        self.cancelled = False

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: "float | None" = None) -> bool:
        return self._ev.wait(timeout)

    def resolve(self, value: dict) -> None:
        self.value = value
        self._ev.set()

    def fail(self, error: Exception) -> None:
        if not self._ev.is_set():
            self.error = error
            self._ev.set()

    def cancel(self) -> None:
        """Cancel-on-first-win: drop the request from its handle's
        queue-depth accounting and resolve the waiter; the replica may
        still answer (idempotent episodes), the reply is ignored."""
        self.cancelled = True
        if self._handle is not None:
            self._handle._discard(self.rid)
        self.fail(ReplicaError("cancelled"))


class ProcessReplicaHandle:
    """Persistent identity for one replica slot across incarnations.

    Satisfies the router's handle protocol (`name`/`routable`/
    `queue_depth`/`bucket`/`submit`). `attach` binds a fresh
    subprocess (spawn or respawn); a reader thread resolves pending
    futures from stdout and fails them all on EOF so a SIGKILLed
    replica turns into immediate retries instead of timeouts."""

    def __init__(self, name: str, run_dir: Path):
        self.name = name
        self.run_dir = Path(run_dir)
        self.proc = None
        self.generation = 0
        self.bucket: "int | None" = None
        # Inference precision self-reported on the ready line (None
        # until ready, and for legacy replicas that don't report it).
        self.precision: "str | None" = None
        self.admit = True  # rolling-reload drain gate
        self.probe_ok = False
        self.ready = threading.Event()
        self.ready_info: "dict | None" = None
        # Fired (handle, ready_msg) when an incarnation's ready line
        # lands — the fleet supervisor ledgers the replica's
        # (monotonic, wall) clock pair for trace merge calibration.
        self.on_ready = None
        self.served_moves = 0  # progress signal for the recovery policy
        self.episodes_ok = 0
        self._lock = threading.Lock()
        self._pending: dict[int, _Pending] = {}
        self._rid = 0

    # --- router protocol -------------------------------------------------

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def routable(self) -> bool:
        return (
            self.alive and self.admit and self.probe_ok and self.ready.is_set()
        )

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def submit(self, payload: dict) -> _Pending:
        with self._lock:
            proc = self.proc
            if proc is None or proc.poll() is not None:
                raise ReplicaError(f"replica {self.name} is not running")
            self._rid += 1
            pending = _Pending(self._rid, self)
            self._pending[self._rid] = pending
            line = json.dumps({**payload, "id": self._rid}) + "\n"
            try:
                proc.stdin.write(line)
                proc.stdin.flush()
            except Exception as exc:
                del self._pending[self._rid]
                raise ReplicaError(
                    f"replica {self.name} pipe write failed: {exc}"
                ) from exc
        return pending

    def request(self, payload: dict, timeout_s: float = 30.0) -> dict:
        """Synchronous control-plane request (ping/stats/reload)."""
        pending = self.submit(payload)
        if not pending.wait(timeout_s):
            pending.cancel()
            raise ReplicaError(
                f"replica {self.name} {payload.get('kind')} timed out "
                f"after {timeout_s:g}s"
            )
        if pending.error is not None:
            raise pending.error
        return pending.value or {}

    # --- incarnation lifecycle -------------------------------------------

    def attach(self, proc, bucket: int) -> None:
        self.proc = proc
        self.bucket = bucket
        self.generation += 1
        self.ready.clear()
        self.ready_info = None
        self.probe_ok = False
        reader = threading.Thread(
            target=self._read_loop,
            args=(proc,),
            name=f"fleet-read-{self.name}",
            daemon=True,
        )
        reader.start()

    def _read_loop(self, proc) -> None:
        try:
            for line in proc.stdout:
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    logger.warning(
                        "%s: unparseable reply line %r", self.name, line[:200]
                    )
                    continue
                if msg.get("kind") == "ready" and "id" not in msg:
                    self.ready_info = msg
                    self.ready.set()
                    if self.on_ready is not None:
                        try:
                            self.on_ready(self, msg)
                        except Exception:
                            logger.exception(
                                "%s on_ready hook failed", self.name
                            )
                    continue
                with self._lock:
                    pending = self._pending.pop(msg.get("id"), None)
                if pending is None:
                    continue  # cancelled (hedge loser) or stale
                if msg.get("ok"):
                    if msg.get("kind") == "episode":
                        self.served_moves += int(msg.get("moves") or 0)
                        self.episodes_ok += 1
                    pending.resolve(msg)
                else:
                    pending.fail(
                        ReplicaError(
                            f"{self.name}: {msg.get('error') or 'replica error'}"
                        )
                    )
        except Exception:
            logger.exception("%s reader failed", self.name)
        finally:
            # EOF: only fail pendings if this is still the live
            # incarnation (a respawn may already have replaced us).
            if self.proc is proc:
                self.fail_all(ReplicaError(f"replica {self.name} died"))

    def fail_all(self, error: Exception) -> None:
        with self._lock:
            pending, self._pending = dict(self._pending), {}
        for p in pending.values():
            p.fail(error)

    def _discard(self, rid: int) -> None:
        with self._lock:
            self._pending.pop(rid, None)


class FleetSupervisor:
    """Spawn/probe/classify/respawn loop around N serve replicas.

    `popen`/`now`/`sleep` are injectable (tests/test_supervise.py
    style); `policy_factory` builds one RecoveryPolicy PER replica so
    each has its own backoff streak and restart budget."""

    def __init__(
        self,
        run_dir: "Path | str",
        *,
        replicas: int = 2,
        slots: int = 8,
        sims: int = 4,
        ladder=None,
        seed: int = 0,
        configs_dir: "Path | str | None" = None,
        replica_extra_argv: "list | None" = None,
        policy_factory=None,
        probe_deadline_s: float = 10.0,
        poll_s: float = 0.25,
        spawn_timeout_s: float = 300.0,
        popen=subprocess.Popen,
        now=time.time,
        sleep=time.sleep,
    ) -> None:
        from .buckets import BucketLadder

        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.slots = slots
        # The serve-shape ladder quarantine walks replicas down
        # (serving/buckets.py — the SAME rung set the micro-batcher
        # and `cli warm` use; None = the implicit halving ladder under
        # `slots`, which reproduces the legacy 0.5-multiplier buckets
        # exactly).
        self.ladder = BucketLadder.from_spec(ladder, base=slots)
        self.sims = sims
        self.seed = seed
        self.configs_dir = str(configs_dir) if configs_dir else ""
        self.replica_extra_argv = list(replica_extra_argv or [])
        self.probe_deadline_s = probe_deadline_s
        self.poll_s = poll_s
        self.spawn_timeout_s = spawn_timeout_s
        self._popen = popen
        self._now = now
        self._sleep = sleep
        policy_factory = policy_factory or RecoveryPolicy
        self._ledger = MetricsLedger(self.run_dir / FLEET_FILENAME)
        self._metrics = MetricsLedger(self.run_dir / "metrics.jsonl")
        # The fleet's own flight ring: routed requests bracket as
        # `fleet/route` so a dead parent names its in-flight requests.
        self.flight = FlightRecorder(self.run_dir / "flight.jsonl")
        self.handles = [
            ProcessReplicaHandle(f"r{i}", self.run_dir / f"replica_r{i}")
            for i in range(replicas)
        ]
        for h in self.handles:
            h.on_ready = self._on_replica_ready
        # Fleet-lifetime root trace (telemetry/tracectx.py); each
        # replica incarnation spawns under a child of it, handed to the
        # replica process via the traceparent env seam so its own
        # telemetry links back to the spawn event.
        self.trace_ctx = tracectx.mint(parent=tracectx.from_env())
        self._spawn_ctx: dict[str, tracectx.TraceContext] = {}
        self._policies = {h.name: policy_factory() for h in self.handles}
        self._spawn_t: dict[str, float] = {}
        self._attempts: dict[str, int] = {h.name: 0 for h in self.handles}
        self._overrides: dict[str, dict] = {h.name: {} for h in self.handles}
        self._restart_at: dict[str, float] = {}
        self.gaveup: set = set()
        self.deaths = 0
        self.respawns = 0
        self.evictions = 0
        self.readmissions = 0
        self.reload_rounds = 0
        self.reload_recompiles = 0
        self._stop = threading.Event()
        self._monitor: "threading.Thread | None" = None

    # --- ledger -----------------------------------------------------------

    def _event(self, event: str, **fields) -> None:
        self._ledger.append(
            {
                "kind": "fleet",
                "event": event,
                "time": self._now(),
                "pid": os.getpid(),
                **fields,
            }
        )

    def util_tick(
        self, step: int, moves: int, requests: int, window_s: float
    ) -> None:
        """One `kind:"util"` record on the fleet parent's metrics
        ledger — the minimal utilization signature `cli perf` /
        `load_comparable` need to treat a fleet run like any run."""
        dt = max(1e-9, window_s)
        self._metrics.append(
            {
                "kind": "util",
                "time": self._now(),
                "step": step,
                "window_s": round(window_s, 3),
                "moves_per_sec": round(moves / dt, 3),
                "serve_requests_per_sec": round(requests / dt, 3),
            }
        )

    def router_event(self, fields: dict) -> None:
        """ReplicaRouter.on_event sink: shed/retry/hedge/exhausted
        decisions land beside the lifecycle events."""
        fields = dict(fields)
        # The router annotates sheds with the REQUEST's kind
        # ("episode"); rename it or it would override the ledger's
        # `kind: "fleet"` and hide the event from summarize_fleet.
        if "kind" in fields:
            fields["request_kind"] = fields.pop("kind")
        self._event(fields.pop("event", "route"), **fields)

    def build_router(self, **router_kw) -> ReplicaRouter:
        router_kw.setdefault("flight", self.flight)
        router_kw.setdefault("on_event", self.router_event)
        return ReplicaRouter(self.handles, **router_kw)

    # --- spawning ---------------------------------------------------------

    def _effective_slots(self, name: str) -> int:
        """The `serve/b<B>` rung this replica's next incarnation
        compiles: the base bucket scaled by any quarantine multiplier
        (supervise/policy.py `SERVE_SLOTS__scale`), then snapped DOWN
        onto the bucket ladder — quarantine is a forced walk-down on
        the same ladder the micro-batcher climbs, so a degraded
        replica always lands on a shape `cli warm` precompiled
        (test_fleet pins ladder/scale agreement)."""
        scale = float(
            self._overrides.get(name, {}).get("SERVE_SLOTS__scale", 1.0)
        )
        return self.ladder.rung_at_or_below(
            max(1.0, round(self.slots * scale))
        )

    def _spawn(self, handle: ProcessReplicaHandle, event: str) -> None:
        self._attempts[handle.name] += 1
        attempt = self._attempts[handle.name]
        bucket = self._effective_slots(handle.name)
        handle.run_dir.mkdir(parents=True, exist_ok=True)
        argv = [
            sys.executable,
            "-m",
            "alphatriangle_tpu.serving.replica",
            "--run-dir",
            str(handle.run_dir),
            "--configs-dir",
            self.configs_dir,
            "--name",
            handle.name,
            "--slots",
            str(bucket),
            "--sims",
            str(self.sims),
            "--seed",
            str(self.seed + int(handle.name[1:] or 0)),
            *self.replica_extra_argv,
        ]
        stderr_log = open(  # noqa: SIM115 — lives as long as the child
            handle.run_dir / "replica.stderr.log", "ab"
        )
        # Each incarnation gets a child trace context, handed down via
        # the env seam (the replica's RunTelemetry adopts it as the
        # base trace on its flight ring) and stamped on the spawn and
        # death events so one trace_id follows the incarnation.
        ctx = self.trace_ctx.child()
        self._spawn_ctx[handle.name] = ctx
        proc = self._popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=stderr_log,
            text=True,
            env=tracectx.child_env(ctx),
        )
        stderr_log.close()
        self._spawn_t[handle.name] = self._now()
        handle.attach(proc, bucket)
        self._event(
            event,
            replica=handle.name,
            pid=proc.pid,
            slots=bucket,
            attempt=attempt,
            overrides=self._overrides.get(handle.name) or {},
            **ctx.fields(),
        )

    def _on_replica_ready(self, handle: ProcessReplicaHandle, msg: dict) -> None:
        """Ledger a replica's ready line — most importantly its
        `(t_mono, time)` clock pair, the calibration sample
        telemetry/merge.py uses to place that process's monotonic
        timestamps on the shared wall-clock timeline."""
        ctx = self._spawn_ctx.get(handle.name)
        # The replica self-reports its compiled rung + inference
        # precision (legacy replicas omit them; every reader treats
        # the fields as optional) — `cli watch`'s fleet line renders
        # both, so a quarantine-halved or ladder-walked replica is
        # visible at a glance.
        handle.precision = msg.get("precision")
        self._event(
            "replica-ready",
            replica=handle.name,
            generation=handle.generation,
            replica_pid=msg.get("pid"),
            slots=msg.get("slots"),
            precision=msg.get("precision"),
            warm_aot=msg.get("warm_aot"),
            t_mono=msg.get("t_mono"),
            replica_time=msg.get("time"),
            **(ctx.fields() if ctx is not None else {}),
        )

    def start(self, wait_ready: bool = True) -> None:
        self._event(
            "fleet-start",
            replicas=len(self.handles),
            slots=self.slots,
            rungs=list(self.ladder.rungs),
            sims=self.sims,
        )
        for h in self.handles:
            self._spawn(h, "spawn")
        if wait_ready:
            deadline = time.monotonic() + self.spawn_timeout_s
            for h in self.handles:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not h.ready.wait(remaining):
                    raise RuntimeError(
                        f"replica {h.name} not ready within "
                        f"{self.spawn_timeout_s:g}s (see "
                        f"{h.run_dir / 'replica.stderr.log'})"
                    )
            for h in self.handles:
                self._probe(h)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True
        )
        self._monitor.start()

    # --- monitoring -------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:
                logger.exception("fleet monitor iteration failed")

    def poll_once(self) -> None:
        now = self._now()
        for h in self.handles:
            if h.name in self.gaveup:
                continue
            if h.name in self._restart_at:
                if now >= self._restart_at[h.name]:
                    del self._restart_at[h.name]
                    self.respawns += 1
                    self._spawn(h, "respawn")
                continue
            if h.proc is not None and h.proc.poll() is not None:
                self._on_death(h)
                continue
            if h.alive and h.ready.is_set():
                self._probe(h)

    def _on_death(self, handle: ProcessReplicaHandle) -> None:
        rc = handle.proc.returncode
        handle.fail_all(
            ReplicaError(f"replica {handle.name} died (rc={rc})")
        )
        handle.probe_ok = False
        verdict = diagnose(
            handle.run_dir, since=self._spawn_t.get(handle.name, 0.0)
        )
        policy = self._policies[handle.name]
        action = policy.decide(
            verdict=verdict["verdict"],
            exit_code=rc if rc is not None else -1,
            family=verdict.get("family"),
            progress_step=handle.served_moves,
        )
        self.deaths += 1
        ctx = self._spawn_ctx.get(handle.name)
        self._event(
            "death",
            replica=handle.name,
            rc=rc,
            generation=handle.generation,
            verdict=verdict["verdict"],
            program=verdict.get("program"),
            family=verdict.get("family"),
            progress_moves=handle.served_moves,
            action=action.kind,
            delay_s=action.delay_s,
            overrides=action.overrides,
            reason=action.reason,
            **(ctx.fields() if ctx is not None else {}),
        )
        logger.warning(
            "replica %s died (rc=%s, verdict=%s) -> %s: %s",
            handle.name,
            rc,
            verdict["verdict"],
            action.kind,
            action.reason,
        )
        if action.kind != "restart":
            self.gaveup.add(handle.name)
            self._event("give-up", replica=handle.name, reason=action.reason)
            return
        self._overrides[handle.name] = dict(action.overrides)
        self._restart_at[handle.name] = self._now() + action.delay_s

    def _probe(self, handle: ProcessReplicaHandle) -> None:
        result = probe_run(
            handle.run_dir,
            now=self._now(),
            deadline_s=self.probe_deadline_s,
        )
        ok = result["code"] == PROBE_LIVE
        if ok and not handle.probe_ok:
            handle.probe_ok = True
            self.readmissions += 1
            self._event(
                "readmit",
                replica=handle.name,
                generation=handle.generation,
                slots=handle.bucket,
            )
        elif not ok and handle.probe_ok:
            handle.probe_ok = False
            self.evictions += 1
            self._event(
                "evict",
                replica=handle.name,
                code=result["code"],
                verdict=result["verdict"],
                reason=result["reason"],
            )

    # --- rolling weight swap ---------------------------------------------

    def rolling_reload(
        self,
        drain_timeout_s: float = 30.0,
        request_timeout_s: float = 120.0,
    ) -> dict:
        """Drain one replica at a time out of admission, hot-reload its
        weights, verify zero recompiles from the reply, re-admit. The
        rest of the fleet keeps serving throughout."""
        self._event("reload-start")
        reloaded, recompiles = 0, 0
        for h in self.handles:
            if not (h.alive and h.ready.is_set()):
                continue
            h.admit = False
            t0 = time.monotonic()
            while h.queue_depth > 0 and time.monotonic() - t0 < drain_timeout_s:
                self._sleep(0.05)
            try:
                reply = h.request(
                    {"kind": "reload"}, timeout_s=request_timeout_s
                )
                rec = int(reply.get("recompiles") or 0)
                reloaded += 1
                recompiles += rec
                self._event(
                    "replica-reloaded",
                    replica=h.name,
                    reloads=reply.get("reloads"),
                    recompiles=rec,
                    drained_s=round(time.monotonic() - t0, 3),
                )
            except Exception as exc:
                self._event(
                    "reload-failed", replica=h.name, error=str(exc)
                )
            finally:
                h.admit = True
        self.reload_rounds += 1
        self.reload_recompiles += recompiles
        self._event("reload-done", replicas=reloaded, recompiles=recompiles)
        return {"replicas": reloaded, "recompiles": recompiles}

    # --- chaos + shutdown --------------------------------------------------

    def kill_replica(self, name: "str | None" = None) -> "str | None":
        """SIGKILL one live replica (the storm's chaos hook). Returns
        the victim's name (None when nothing is killable)."""
        for h in self.handles:
            if (name is None or h.name == name) and h.alive:
                self._event("chaos-kill", replica=h.name, pid=h.proc.pid)
                try:
                    os.kill(h.proc.pid, signal.SIGKILL)
                except OSError:
                    return None
                return h.name
        return None

    def stop(self, timeout_s: float = 15.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        for h in self.handles:
            if not h.alive:
                continue
            try:
                h.request({"kind": "shutdown"}, timeout_s=timeout_s)
            except Exception:
                pass
            try:
                h.proc.stdin.close()
            except Exception:
                pass
            try:
                h.proc.wait(timeout=timeout_s)
            except Exception:
                try:
                    h.proc.kill()
                    h.proc.wait(timeout=5.0)
                except Exception:
                    pass
        self.flight.close()
        self._event(
            "fleet-stop",
            deaths=self.deaths,
            respawns=self.respawns,
            gaveup=sorted(self.gaveup),
        )

    def summary(self) -> dict:
        return {
            "replicas": len(self.handles),
            "deaths": self.deaths,
            "respawns": self.respawns,
            "evictions": self.evictions,
            "readmissions": self.readmissions,
            "gaveup": sorted(self.gaveup),
            "reload_rounds": self.reload_rounds,
            "reload_recompiles": self.reload_recompiles,
            "buckets": {h.name: h.bucket for h in self.handles},
            "precisions": {h.name: h.precision for h in self.handles},
            "rungs": list(self.ladder.rungs),
        }


def run_fleet_load(
    router: ReplicaRouter,
    fleet: "FleetSupervisor | None" = None,
    *,
    requests: int = 32,
    concurrency: int = 8,
    max_moves: int = 12,
    seed: int = 0,
    timeout_s: "float | None" = None,
    tick_every_s: float = 1.0,
    on_complete=None,
) -> dict:
    """The loadgen storm: `requests` episode requests pushed through
    the router from `concurrency` worker threads. `on_complete(n)`
    fires after the n-th terminal outcome (the smoke's chaos-kill and
    rolling-reload triggers). Returns the accounting the zero-lost
    invariant is asserted on."""
    from ..telemetry.perf import _percentile

    jobs: list[int] = list(range(requests))
    jobs.reverse()
    results: list = []
    lock = threading.Lock()
    moves_window = [0]
    t_start = time.monotonic()
    last_tick = [t_start]
    last_n = [0]  # terminal outcomes already reported in a prior tick

    def worker() -> None:
        while True:
            with lock:
                if not jobs:
                    return
                i = jobs.pop()
            res = router.route(
                {"kind": "episode", "seed": seed + i, "max_moves": max_moves},
                timeout_s=timeout_s,
            )
            with lock:
                results.append(res)
                n = len(results)
                if res.ok and res.value:
                    moves_window[0] += int(res.value.get("moves") or 0)
                now = time.monotonic()
                tick_due = (
                    fleet is not None
                    and now - last_tick[0] >= tick_every_s
                )
                if tick_due:
                    window = now - last_tick[0]
                    moves, moves_window[0] = moves_window[0], 0
                    # Windowed, not cumulative: the SLO engine
                    # (telemetry/slo.py) integrates rate * window_s per
                    # tick, so each request must be counted once.
                    win_requests = n - last_n[0]
                    last_n[0] = n
                    last_tick[0] = now
            if tick_due:
                fleet.util_tick(
                    step=n,
                    moves=moves,
                    requests=win_requests,
                    window_s=window,
                )
            if on_complete is not None:
                try:
                    on_complete(n)
                except Exception:
                    logger.exception("storm on_complete hook failed")

    threads = [
        threading.Thread(target=worker, name=f"storm-{i}", daemon=True)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = max(1e-9, time.monotonic() - t_start)
    if fleet is not None:
        # Final tick covers only the tail window since the last mid-
        # storm tick (same once-per-request accounting as above).
        fleet.util_tick(
            step=len(results),
            moves=moves_window[0],
            requests=len(results) - last_n[0],
            window_s=max(1e-9, time.monotonic() - last_tick[0]),
        )

    completed = [r for r in results if r.ok]
    shed = [r for r in results if not r.ok and r.rejection is not None]
    lost = len(results) - len(completed) - len(shed)
    lat_ms = [
        float(v)
        for r in completed
        if r.value
        for v in (r.value.get("lat_ms") or [])
    ]
    request_s = [r.wait_s for r in completed]
    summary = {
        "requests": requests,
        "terminal": len(results),
        "completed": len(completed),
        "shed": len(shed),
        "shed_by_code": {
            code: sum(1 for r in shed if r.rejection == code)
            for code in sorted({r.rejection for r in shed})
        },
        "lost": lost,
        "retried_requests": sum(1 for r in results if r.attempts > 1),
        "hedged_requests": sum(1 for r in results if r.hedged),
        "moves": sum(
            int(r.value.get("moves") or 0)
            for r in completed
            if r.value
        ),
        "elapsed_s": round(elapsed, 3),
        "requests_per_sec": round(len(completed) / elapsed, 3),
        "move_latency_ms_p50": _percentile(lat_ms, 0.50),
        "move_latency_ms_p95": _percentile(lat_ms, 0.95),
        "request_s_p95": _percentile(request_s, 0.95),
        "router": router.stats.as_dict(),
    }
    if fleet is not None:
        fleet._event("storm-summary", **summary)
    return summary


# --- postmortem readers (no JAX import anywhere on this path) -----------


def read_fleet_events(run_dir: "Path | str") -> list[dict]:
    """All parseable `kind:"fleet"` events across ledger rotations,
    oldest first — the same tolerant-reader contract as read_flight
    (torn tails and legacy id-less records parse fine)."""
    out: list[dict] = []
    for p in ledger_paths(Path(run_dir) / FLEET_FILENAME):
        out.extend(iter_jsonl_records(p, kinds={"fleet"}))
    return out


def classify_fleet(run_dir: "Path | str") -> dict:
    """Postmortem classifier for a FLEET-PARENT run dir (the `cli
    doctor` branch for dirs holding a fleet.jsonl — a fleet parent has
    no learner heartbeat, so `classify_run` would misread it as
    never-started).

    Verdicts reuse the DOCTOR_EXIT_CODES vocabulary, strongest
    evidence first:

    - `dispatch-hung`: the parent died holding routed requests — an
      unsealed `fleet/route` intent in the parent's own flight ring
      with no `fleet-stop` event.
    - a replica verdict: the parent died mid-run (no `fleet-stop`)
      right after a replica death, or gave a replica up — the fleet's
      verdict is that replica's ledgered death verdict (SIGKILL-style
      clean crash-loops surface as `host-stall` with the loop named).
    - `host-stall`: the parent died between routed requests (no
      `fleet-stop`, no death to blame).
    - `never-started`: a fleet.jsonl exists but holds no events.
    - `clean`: `fleet-stop` was written — the fleet ran to completion;
      deaths/respawns along the way were healed (the self-healing
      contract) and ride in the evidence.

    Returns the classify_run result shape:
    {verdict, exit_code, program, family, detail, evidence}.
    """
    run_dir = Path(run_dir)
    events = read_fleet_events(run_dir)
    by_event: dict[str, list[dict]] = {}
    for e in events:
        by_event.setdefault(str(e.get("event")), []).append(e)
    deaths = by_event.get("death", [])
    gaveup = sorted(
        {str(e.get("replica")) for e in by_event.get("give-up", [])}
    )
    stopped = bool(by_event.get("fleet-stop"))
    torn_route = [
        r
        for r in unsealed_intents(read_flight(run_dir / FLIGHT_FILENAME))
        if r.get("family") == "fleet"
    ]
    evidence = {
        "fleet_events": len(events),
        "deaths": len(deaths),
        "respawns": len(by_event.get("respawn", [])),
        "evictions": len(by_event.get("evict", [])),
        "gaveup": gaveup,
        "fleet_stop": stopped,
        "storm_summary": bool(by_event.get("storm-summary")),
        "unsealed_route_intents": len(torn_route),
    }

    def result(verdict, program=None, family=None, detail=""):
        return {
            "verdict": verdict,
            "exit_code": DOCTOR_EXIT_CODES[verdict],
            "program": program,
            "family": family,
            "detail": detail,
            "evidence": evidence,
        }

    def replica_verdict(death: dict, why: str) -> dict:
        verdict = str(death.get("verdict"))
        replica = death.get("replica")
        if verdict in DOCTOR_EXIT_CODES and verdict not in (
            "clean",
            "never-started",
        ):
            return result(
                verdict,
                program=death.get("program"),
                family=death.get("family"),
                detail=f"{why}: replica {replica} died with verdict "
                f"{verdict} (rc={death.get('rc')})",
            )
        return result(
            "host-stall",
            detail=f"{why}: replica {replica} crash-looped "
            f"(last death rc={death.get('rc')}, verdict "
            f"{verdict or 'unknown'})",
        )

    if not events:
        return result(
            "never-started",
            detail="fleet.jsonl exists but holds no events: the parent "
            "died before spawning its first replica",
        )
    if torn_route and not stopped:
        intent = torn_route[-1]
        return result(
            "dispatch-hung",
            program=str(intent.get("program")),
            family="fleet",
            detail="fleet parent died holding "
            f"{len(torn_route)} routed request(s) in flight "
            f"(last seq {intent.get('seq')}, "
            f"trace {intent.get('trace_id') or 'untraced'})",
        )
    if not stopped:
        if deaths:
            return replica_verdict(
                deaths[-1], "fleet parent died mid-run (no fleet-stop)"
            )
        return result(
            "host-stall",
            detail="fleet parent died between routed requests: no "
            "fleet-stop event and no replica death to blame",
        )
    if gaveup:
        for death in reversed(deaths):
            if str(death.get("replica")) in gaveup:
                return replica_verdict(
                    death,
                    "fleet completed degraded (gave up on "
                    f"{', '.join(gaveup)})",
                )
        return result(
            "host-stall",
            detail="fleet completed degraded: gave up on "
            f"{', '.join(gaveup)} with no ledgered death verdict",
        )
    stop = by_event["fleet-stop"][-1]
    return result(
        "clean",
        detail="fleet ran to completion: "
        f"{stop.get('deaths', 0)} death(s), "
        f"{stop.get('respawns', 0)} respawn(s), all healed",
    )
