"""Serve-fleet control plane: N replicas, one router, self-healing.

The JAX-FREE parent process behind `cli fleet` (docs/SERVING.md
"Fleet"): spawns N `serving/replica.py` subprocesses (each hosting one
PolicyService with its own compiled `serve/b<B>`, heartbeat, flight
ring and metrics ledger), keeps a `ReplicaRouter` admission view fresh
via the shared `telemetry.health.probe_run` probe, and reuses PR 14's
supervision machinery verbatim for replica lifecycle:

- a death is classified with `supervise.supervisor.diagnose` over the
  replica's OWN run dir, evidence since spawn (a SIGKILL reads clean,
  a hang-serve wedge reads dispatch-hung naming `serve/b<B>`);
- `supervise.policy.RecoveryPolicy` maps verdicts to backoff restarts
  under a restart budget — the serve quarantine arm's
  `SERVE_SLOTS__scale` override is interpreted HERE, respawning the
  replica onto a smaller compiled bucket (the degraded fallback);
- the replica's served-move count is the progress signal that resets
  the backoff streak (forward motion = traffic served since last
  death, the serving analogue of a new committed checkpoint).

Every lifecycle and routing decision lands crash-safe in
`fleet.jsonl` through the same append-only MetricsLedger writer the
supervisor and training ledgers use — the death -> verdict -> respawn
-> re-admission chain `make fleet-smoke` asserts is read back from
this file. The parent also writes plain `kind:"util"` ticks to its
own metrics.jsonl so `cli perf` / `cli compare` summarize a fleet run
like any other.

JAX never loads here: replica handles speak JSON lines over pipes,
and the probe/doctor/policy/ledger stack is stdlib-only (the same
contract `benchmarks/fleet_smoke.py` pins with an import guard).
"""

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

from ..supervise.policy import RecoveryPolicy
from ..supervise.supervisor import diagnose
from ..telemetry.flight import FlightRecorder
from ..telemetry.health import PROBE_LIVE, probe_run
from ..telemetry.ledger import MetricsLedger
from .router import ReplicaError, ReplicaRouter

logger = logging.getLogger(__name__)

FLEET_FILENAME = "fleet.jsonl"


class _Pending:
    """Minimal future for one in-flight replica request."""

    __slots__ = ("rid", "_handle", "_ev", "value", "error", "cancelled")

    def __init__(self, rid: int, handle=None):
        self.rid = rid
        self._handle = handle
        self._ev = threading.Event()
        self.value: "dict | None" = None
        self.error: "Exception | None" = None
        self.cancelled = False

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: "float | None" = None) -> bool:
        return self._ev.wait(timeout)

    def resolve(self, value: dict) -> None:
        self.value = value
        self._ev.set()

    def fail(self, error: Exception) -> None:
        if not self._ev.is_set():
            self.error = error
            self._ev.set()

    def cancel(self) -> None:
        """Cancel-on-first-win: drop the request from its handle's
        queue-depth accounting and resolve the waiter; the replica may
        still answer (idempotent episodes), the reply is ignored."""
        self.cancelled = True
        if self._handle is not None:
            self._handle._discard(self.rid)
        self.fail(ReplicaError("cancelled"))


class ProcessReplicaHandle:
    """Persistent identity for one replica slot across incarnations.

    Satisfies the router's handle protocol (`name`/`routable`/
    `queue_depth`/`bucket`/`submit`). `attach` binds a fresh
    subprocess (spawn or respawn); a reader thread resolves pending
    futures from stdout and fails them all on EOF so a SIGKILLed
    replica turns into immediate retries instead of timeouts."""

    def __init__(self, name: str, run_dir: Path):
        self.name = name
        self.run_dir = Path(run_dir)
        self.proc = None
        self.generation = 0
        self.bucket: "int | None" = None
        self.admit = True  # rolling-reload drain gate
        self.probe_ok = False
        self.ready = threading.Event()
        self.ready_info: "dict | None" = None
        self.served_moves = 0  # progress signal for the recovery policy
        self.episodes_ok = 0
        self._lock = threading.Lock()
        self._pending: dict[int, _Pending] = {}
        self._rid = 0

    # --- router protocol -------------------------------------------------

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def routable(self) -> bool:
        return (
            self.alive and self.admit and self.probe_ok and self.ready.is_set()
        )

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def submit(self, payload: dict) -> _Pending:
        with self._lock:
            proc = self.proc
            if proc is None or proc.poll() is not None:
                raise ReplicaError(f"replica {self.name} is not running")
            self._rid += 1
            pending = _Pending(self._rid, self)
            self._pending[self._rid] = pending
            line = json.dumps({**payload, "id": self._rid}) + "\n"
            try:
                proc.stdin.write(line)
                proc.stdin.flush()
            except Exception as exc:
                del self._pending[self._rid]
                raise ReplicaError(
                    f"replica {self.name} pipe write failed: {exc}"
                ) from exc
        return pending

    def request(self, payload: dict, timeout_s: float = 30.0) -> dict:
        """Synchronous control-plane request (ping/stats/reload)."""
        pending = self.submit(payload)
        if not pending.wait(timeout_s):
            pending.cancel()
            raise ReplicaError(
                f"replica {self.name} {payload.get('kind')} timed out "
                f"after {timeout_s:g}s"
            )
        if pending.error is not None:
            raise pending.error
        return pending.value or {}

    # --- incarnation lifecycle -------------------------------------------

    def attach(self, proc, bucket: int) -> None:
        self.proc = proc
        self.bucket = bucket
        self.generation += 1
        self.ready.clear()
        self.ready_info = None
        self.probe_ok = False
        reader = threading.Thread(
            target=self._read_loop,
            args=(proc,),
            name=f"fleet-read-{self.name}",
            daemon=True,
        )
        reader.start()

    def _read_loop(self, proc) -> None:
        try:
            for line in proc.stdout:
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    logger.warning(
                        "%s: unparseable reply line %r", self.name, line[:200]
                    )
                    continue
                if msg.get("kind") == "ready" and "id" not in msg:
                    self.ready_info = msg
                    self.ready.set()
                    continue
                with self._lock:
                    pending = self._pending.pop(msg.get("id"), None)
                if pending is None:
                    continue  # cancelled (hedge loser) or stale
                if msg.get("ok"):
                    if msg.get("kind") == "episode":
                        self.served_moves += int(msg.get("moves") or 0)
                        self.episodes_ok += 1
                    pending.resolve(msg)
                else:
                    pending.fail(
                        ReplicaError(
                            f"{self.name}: {msg.get('error') or 'replica error'}"
                        )
                    )
        except Exception:
            logger.exception("%s reader failed", self.name)
        finally:
            # EOF: only fail pendings if this is still the live
            # incarnation (a respawn may already have replaced us).
            if self.proc is proc:
                self.fail_all(ReplicaError(f"replica {self.name} died"))

    def fail_all(self, error: Exception) -> None:
        with self._lock:
            pending, self._pending = dict(self._pending), {}
        for p in pending.values():
            p.fail(error)

    def _discard(self, rid: int) -> None:
        with self._lock:
            self._pending.pop(rid, None)


class FleetSupervisor:
    """Spawn/probe/classify/respawn loop around N serve replicas.

    `popen`/`now`/`sleep` are injectable (tests/test_supervise.py
    style); `policy_factory` builds one RecoveryPolicy PER replica so
    each has its own backoff streak and restart budget."""

    def __init__(
        self,
        run_dir: "Path | str",
        *,
        replicas: int = 2,
        slots: int = 8,
        sims: int = 4,
        seed: int = 0,
        configs_dir: "Path | str | None" = None,
        replica_extra_argv: "list | None" = None,
        policy_factory=None,
        probe_deadline_s: float = 10.0,
        poll_s: float = 0.25,
        spawn_timeout_s: float = 300.0,
        popen=subprocess.Popen,
        now=time.time,
        sleep=time.sleep,
    ) -> None:
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.slots = slots
        self.sims = sims
        self.seed = seed
        self.configs_dir = str(configs_dir) if configs_dir else ""
        self.replica_extra_argv = list(replica_extra_argv or [])
        self.probe_deadline_s = probe_deadline_s
        self.poll_s = poll_s
        self.spawn_timeout_s = spawn_timeout_s
        self._popen = popen
        self._now = now
        self._sleep = sleep
        policy_factory = policy_factory or RecoveryPolicy
        self._ledger = MetricsLedger(self.run_dir / FLEET_FILENAME)
        self._metrics = MetricsLedger(self.run_dir / "metrics.jsonl")
        # The fleet's own flight ring: routed requests bracket as
        # `fleet/route` so a dead parent names its in-flight requests.
        self.flight = FlightRecorder(self.run_dir / "flight.jsonl")
        self.handles = [
            ProcessReplicaHandle(f"r{i}", self.run_dir / f"replica_r{i}")
            for i in range(replicas)
        ]
        self._policies = {h.name: policy_factory() for h in self.handles}
        self._spawn_t: dict[str, float] = {}
        self._attempts: dict[str, int] = {h.name: 0 for h in self.handles}
        self._overrides: dict[str, dict] = {h.name: {} for h in self.handles}
        self._restart_at: dict[str, float] = {}
        self.gaveup: set = set()
        self.deaths = 0
        self.respawns = 0
        self.evictions = 0
        self.readmissions = 0
        self.reload_rounds = 0
        self.reload_recompiles = 0
        self._stop = threading.Event()
        self._monitor: "threading.Thread | None" = None

    # --- ledger -----------------------------------------------------------

    def _event(self, event: str, **fields) -> None:
        self._ledger.append(
            {
                "kind": "fleet",
                "event": event,
                "time": self._now(),
                "pid": os.getpid(),
                **fields,
            }
        )

    def util_tick(
        self, step: int, moves: int, requests: int, window_s: float
    ) -> None:
        """One `kind:"util"` record on the fleet parent's metrics
        ledger — the minimal utilization signature `cli perf` /
        `load_comparable` need to treat a fleet run like any run."""
        dt = max(1e-9, window_s)
        self._metrics.append(
            {
                "kind": "util",
                "time": self._now(),
                "step": step,
                "window_s": round(window_s, 3),
                "moves_per_sec": round(moves / dt, 3),
                "serve_requests_per_sec": round(requests / dt, 3),
            }
        )

    def router_event(self, fields: dict) -> None:
        """ReplicaRouter.on_event sink: shed/retry/hedge/exhausted
        decisions land beside the lifecycle events."""
        fields = dict(fields)
        # The router annotates sheds with the REQUEST's kind
        # ("episode"); rename it or it would override the ledger's
        # `kind: "fleet"` and hide the event from summarize_fleet.
        if "kind" in fields:
            fields["request_kind"] = fields.pop("kind")
        self._event(fields.pop("event", "route"), **fields)

    def build_router(self, **router_kw) -> ReplicaRouter:
        router_kw.setdefault("flight", self.flight)
        router_kw.setdefault("on_event", self.router_event)
        return ReplicaRouter(self.handles, **router_kw)

    # --- spawning ---------------------------------------------------------

    def _effective_slots(self, name: str) -> int:
        scale = float(
            self._overrides.get(name, {}).get("SERVE_SLOTS__scale", 1.0)
        )
        return max(1, int(round(self.slots * scale)))

    def _spawn(self, handle: ProcessReplicaHandle, event: str) -> None:
        self._attempts[handle.name] += 1
        attempt = self._attempts[handle.name]
        bucket = self._effective_slots(handle.name)
        handle.run_dir.mkdir(parents=True, exist_ok=True)
        argv = [
            sys.executable,
            "-m",
            "alphatriangle_tpu.serving.replica",
            "--run-dir",
            str(handle.run_dir),
            "--configs-dir",
            self.configs_dir,
            "--name",
            handle.name,
            "--slots",
            str(bucket),
            "--sims",
            str(self.sims),
            "--seed",
            str(self.seed + int(handle.name[1:] or 0)),
            *self.replica_extra_argv,
        ]
        stderr_log = open(  # noqa: SIM115 — lives as long as the child
            handle.run_dir / "replica.stderr.log", "ab"
        )
        proc = self._popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=stderr_log,
            text=True,
        )
        stderr_log.close()
        self._spawn_t[handle.name] = self._now()
        handle.attach(proc, bucket)
        self._event(
            event,
            replica=handle.name,
            pid=proc.pid,
            slots=bucket,
            attempt=attempt,
            overrides=self._overrides.get(handle.name) or {},
        )

    def start(self, wait_ready: bool = True) -> None:
        self._event(
            "fleet-start",
            replicas=len(self.handles),
            slots=self.slots,
            sims=self.sims,
        )
        for h in self.handles:
            self._spawn(h, "spawn")
        if wait_ready:
            deadline = time.monotonic() + self.spawn_timeout_s
            for h in self.handles:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not h.ready.wait(remaining):
                    raise RuntimeError(
                        f"replica {h.name} not ready within "
                        f"{self.spawn_timeout_s:g}s (see "
                        f"{h.run_dir / 'replica.stderr.log'})"
                    )
            for h in self.handles:
                self._probe(h)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True
        )
        self._monitor.start()

    # --- monitoring -------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:
                logger.exception("fleet monitor iteration failed")

    def poll_once(self) -> None:
        now = self._now()
        for h in self.handles:
            if h.name in self.gaveup:
                continue
            if h.name in self._restart_at:
                if now >= self._restart_at[h.name]:
                    del self._restart_at[h.name]
                    self.respawns += 1
                    self._spawn(h, "respawn")
                continue
            if h.proc is not None and h.proc.poll() is not None:
                self._on_death(h)
                continue
            if h.alive and h.ready.is_set():
                self._probe(h)

    def _on_death(self, handle: ProcessReplicaHandle) -> None:
        rc = handle.proc.returncode
        handle.fail_all(
            ReplicaError(f"replica {handle.name} died (rc={rc})")
        )
        handle.probe_ok = False
        verdict = diagnose(
            handle.run_dir, since=self._spawn_t.get(handle.name, 0.0)
        )
        policy = self._policies[handle.name]
        action = policy.decide(
            verdict=verdict["verdict"],
            exit_code=rc if rc is not None else -1,
            family=verdict.get("family"),
            progress_step=handle.served_moves,
        )
        self.deaths += 1
        self._event(
            "death",
            replica=handle.name,
            rc=rc,
            generation=handle.generation,
            verdict=verdict["verdict"],
            program=verdict.get("program"),
            family=verdict.get("family"),
            progress_moves=handle.served_moves,
            action=action.kind,
            delay_s=action.delay_s,
            overrides=action.overrides,
            reason=action.reason,
        )
        logger.warning(
            "replica %s died (rc=%s, verdict=%s) -> %s: %s",
            handle.name,
            rc,
            verdict["verdict"],
            action.kind,
            action.reason,
        )
        if action.kind != "restart":
            self.gaveup.add(handle.name)
            self._event("give-up", replica=handle.name, reason=action.reason)
            return
        self._overrides[handle.name] = dict(action.overrides)
        self._restart_at[handle.name] = self._now() + action.delay_s

    def _probe(self, handle: ProcessReplicaHandle) -> None:
        result = probe_run(
            handle.run_dir,
            now=self._now(),
            deadline_s=self.probe_deadline_s,
        )
        ok = result["code"] == PROBE_LIVE
        if ok and not handle.probe_ok:
            handle.probe_ok = True
            self.readmissions += 1
            self._event(
                "readmit",
                replica=handle.name,
                generation=handle.generation,
                slots=handle.bucket,
            )
        elif not ok and handle.probe_ok:
            handle.probe_ok = False
            self.evictions += 1
            self._event(
                "evict",
                replica=handle.name,
                code=result["code"],
                verdict=result["verdict"],
                reason=result["reason"],
            )

    # --- rolling weight swap ---------------------------------------------

    def rolling_reload(
        self,
        drain_timeout_s: float = 30.0,
        request_timeout_s: float = 120.0,
    ) -> dict:
        """Drain one replica at a time out of admission, hot-reload its
        weights, verify zero recompiles from the reply, re-admit. The
        rest of the fleet keeps serving throughout."""
        self._event("reload-start")
        reloaded, recompiles = 0, 0
        for h in self.handles:
            if not (h.alive and h.ready.is_set()):
                continue
            h.admit = False
            t0 = time.monotonic()
            while h.queue_depth > 0 and time.monotonic() - t0 < drain_timeout_s:
                self._sleep(0.05)
            try:
                reply = h.request(
                    {"kind": "reload"}, timeout_s=request_timeout_s
                )
                rec = int(reply.get("recompiles") or 0)
                reloaded += 1
                recompiles += rec
                self._event(
                    "replica-reloaded",
                    replica=h.name,
                    reloads=reply.get("reloads"),
                    recompiles=rec,
                    drained_s=round(time.monotonic() - t0, 3),
                )
            except Exception as exc:
                self._event(
                    "reload-failed", replica=h.name, error=str(exc)
                )
            finally:
                h.admit = True
        self.reload_rounds += 1
        self.reload_recompiles += recompiles
        self._event("reload-done", replicas=reloaded, recompiles=recompiles)
        return {"replicas": reloaded, "recompiles": recompiles}

    # --- chaos + shutdown --------------------------------------------------

    def kill_replica(self, name: "str | None" = None) -> "str | None":
        """SIGKILL one live replica (the storm's chaos hook). Returns
        the victim's name (None when nothing is killable)."""
        for h in self.handles:
            if (name is None or h.name == name) and h.alive:
                self._event("chaos-kill", replica=h.name, pid=h.proc.pid)
                try:
                    os.kill(h.proc.pid, signal.SIGKILL)
                except OSError:
                    return None
                return h.name
        return None

    def stop(self, timeout_s: float = 15.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        for h in self.handles:
            if not h.alive:
                continue
            try:
                h.request({"kind": "shutdown"}, timeout_s=timeout_s)
            except Exception:
                pass
            try:
                h.proc.stdin.close()
            except Exception:
                pass
            try:
                h.proc.wait(timeout=timeout_s)
            except Exception:
                try:
                    h.proc.kill()
                    h.proc.wait(timeout=5.0)
                except Exception:
                    pass
        self.flight.close()
        self._event(
            "fleet-stop",
            deaths=self.deaths,
            respawns=self.respawns,
            gaveup=sorted(self.gaveup),
        )

    def summary(self) -> dict:
        return {
            "replicas": len(self.handles),
            "deaths": self.deaths,
            "respawns": self.respawns,
            "evictions": self.evictions,
            "readmissions": self.readmissions,
            "gaveup": sorted(self.gaveup),
            "reload_rounds": self.reload_rounds,
            "reload_recompiles": self.reload_recompiles,
            "buckets": {h.name: h.bucket for h in self.handles},
        }


def run_fleet_load(
    router: ReplicaRouter,
    fleet: "FleetSupervisor | None" = None,
    *,
    requests: int = 32,
    concurrency: int = 8,
    max_moves: int = 12,
    seed: int = 0,
    timeout_s: "float | None" = None,
    tick_every_s: float = 1.0,
    on_complete=None,
) -> dict:
    """The loadgen storm: `requests` episode requests pushed through
    the router from `concurrency` worker threads. `on_complete(n)`
    fires after the n-th terminal outcome (the smoke's chaos-kill and
    rolling-reload triggers). Returns the accounting the zero-lost
    invariant is asserted on."""
    from ..telemetry.perf import _percentile

    jobs: list[int] = list(range(requests))
    jobs.reverse()
    results: list = []
    lock = threading.Lock()
    moves_window = [0]
    t_start = time.monotonic()
    last_tick = [t_start]

    def worker() -> None:
        while True:
            with lock:
                if not jobs:
                    return
                i = jobs.pop()
            res = router.route(
                {"kind": "episode", "seed": seed + i, "max_moves": max_moves},
                timeout_s=timeout_s,
            )
            with lock:
                results.append(res)
                n = len(results)
                if res.ok and res.value:
                    moves_window[0] += int(res.value.get("moves") or 0)
                now = time.monotonic()
                tick_due = (
                    fleet is not None
                    and now - last_tick[0] >= tick_every_s
                )
                if tick_due:
                    window = now - last_tick[0]
                    moves, moves_window[0] = moves_window[0], 0
                    last_tick[0] = now
            if tick_due:
                fleet.util_tick(
                    step=n, moves=moves, requests=n, window_s=window
                )
            if on_complete is not None:
                try:
                    on_complete(n)
                except Exception:
                    logger.exception("storm on_complete hook failed")

    threads = [
        threading.Thread(target=worker, name=f"storm-{i}", daemon=True)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = max(1e-9, time.monotonic() - t_start)
    if fleet is not None:
        fleet.util_tick(
            step=len(results),
            moves=sum(
                int(r.value.get("moves") or 0)
                for r in results
                if r.ok and r.value
            ),
            requests=len(results),
            window_s=elapsed,
        )

    completed = [r for r in results if r.ok]
    shed = [r for r in results if not r.ok and r.rejection is not None]
    lost = len(results) - len(completed) - len(shed)
    lat_ms = [
        float(v)
        for r in completed
        if r.value
        for v in (r.value.get("lat_ms") or [])
    ]
    request_s = [r.wait_s for r in completed]
    summary = {
        "requests": requests,
        "terminal": len(results),
        "completed": len(completed),
        "shed": len(shed),
        "shed_by_code": {
            code: sum(1 for r in shed if r.rejection == code)
            for code in sorted({r.rejection for r in shed})
        },
        "lost": lost,
        "retried_requests": sum(1 for r in results if r.attempts > 1),
        "hedged_requests": sum(1 for r in results if r.hedged),
        "moves": sum(
            int(r.value.get("moves") or 0)
            for r in completed
            if r.value
        ),
        "elapsed_s": round(elapsed, 3),
        "requests_per_sec": round(len(completed) / elapsed, 3),
        "move_latency_ms_p50": _percentile(lat_ms, 0.50),
        "move_latency_ms_p95": _percentile(lat_ms, 0.95),
        "request_s_p95": _percentile(request_s, 0.95),
        "router": router.stats.as_dict(),
    }
    if fleet is not None:
        fleet._event("storm-summary", **summary)
    return summary
