"""Policy-serving front end (docs/SERVING.md).

Continuous-batching inference over the lockstep wave search: a fixed
slot array of concurrent game sessions (`session.SessionSlots`), a
request queue + micro-batch dispatcher with per-request latency SLOs
(`service.PolicyService`), and a deterministic churn load generator
(`loadgen.run_simulated_load`). `cli serve` is the front end;
`arena.play` / `cli eval` / `benchmarks/elo_ladder.py` are the first
in-repo clients of the same session API.
"""

from .loadgen import run_simulated_load
from .service import (
    PolicyService,
    build_serve_telemetry,
    serve_program_name,
)
from .session import Session, SessionSlots

__all__ = [
    "PolicyService",
    "Session",
    "SessionSlots",
    "build_serve_telemetry",
    "run_simulated_load",
    "serve_program_name",
]
