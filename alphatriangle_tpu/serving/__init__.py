"""Policy-serving front end (docs/SERVING.md).

Continuous-batching inference over the lockstep wave search: a fixed
slot array of concurrent game sessions (`session.SessionSlots`), a
request queue + micro-batch dispatcher with per-request latency SLOs
(`service.PolicyService`), and a deterministic churn load generator
(`loadgen.run_simulated_load`). `cli serve` is the front end;
`arena.play` / `cli eval` / `benchmarks/elo_ladder.py` are the first
in-repo clients of the same session API.

The fleet layer (docs/SERVING.md "Fleet") splits the package in two:
`replica.py` hosts one PolicyService per subprocess (imports JAX),
while `router.py` + `fleet.py` are the JAX-FREE control plane the
`cli fleet` parent runs — the same contract as the training
supervisor (supervise/, "the parent must survive anything the device
runtime does"). Exports are therefore lazy (PEP 562): importing
`alphatriangle_tpu.serving.fleet` must not drag `service` -> mcts ->
jax into the parent process.
"""

_LAZY = {
    "BucketLadder": ".buckets",
    "default_rungs": ".buckets",
    "PolicyService": ".service",
    "build_serve_telemetry": ".service",
    "serve_program_name": ".service",
    "Session": ".session",
    "SessionSlots": ".session",
    "run_simulated_load": ".loadgen",
    "ReplicaRouter": ".router",
    "RouteResult": ".router",
    "FleetSupervisor": ".fleet",
    "run_fleet_load": ".fleet",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module

    return getattr(import_module(target, __name__), name)


def __dir__() -> list:
    return sorted(set(globals()) | set(_LAZY))
