"""One serve-fleet replica: a PolicyService behind a pipe protocol.

The JAX side of the fleet split (docs/SERVING.md "Fleet"): each
replica is a subprocess hosting one `PolicyService` (its own compiled
`serve/b<B>` program, its own run dir with heartbeat + flight ring +
metrics ledger), spoken to over a JSON-lines stdin/stdout protocol by
the JAX-free fleet parent (`serving/fleet.py`). On a TPU pod this
becomes one replica per device slice; on CPU tier-1 it is N processes
— the process boundary is the point: a wedged or SIGKILLed replica
takes down exactly one compiled program, and the router re-routes.

Protocol (one JSON object per line, `id` echoes back):

    {"id": N, "kind": "episode", "seed": S, "max_moves": M}
        -> {"id": N, "ok": true, "moves": m, "done": d, "score": s,
            "lat_ms": [per-move latency]}
        Plays one full game through the service (idempotent given the
        seed — safe to retry/hedge on another replica).
    {"id": N, "kind": "ping"}     -> liveness + queue depth
    {"id": N, "kind": "stats"}    -> serve_stats + compile-cache stats
    {"id": N, "kind": "reload"}   -> hot weight reload; the reply's
        `cache_misses` lets the fleet assert zero recompiles
    {"id": N, "kind": "shutdown"} -> ack, then clean exit

Threads: the main thread reads stdin and answers control requests
(responsive even when dispatch is busy); a dispatcher thread batches
every active episode's pending move into one `dispatch()` wave (the
micro-batching contract); a heartbeat thread keeps `health.json`
fresh while idle so the parent's probe gates admission on liveness,
not traffic. A `hang-serve` fault wedges the dispatcher inside its
flight bracket — the in-process DispatchWatchdog exits 113 and the
unsealed `serve/b<B>` intent is the evidence `cli doctor` and the
fleet probe both read.
"""

import argparse
import json
import logging
import os
import sys
import threading
import time

from ..telemetry import tracectx

logger = logging.getLogger(__name__)

READY_KIND = "ready"


def _clock_pair() -> dict:
    """This process's `(monotonic, wall)` clock sample — stamped on
    ready/ping replies so the fleet merge (telemetry/merge.py) can
    calibrate each replica's monotonic clock against shared wall time."""
    return {"t_mono": time.monotonic(), "time": time.time()}


class _Episode:
    __slots__ = (
        "req_id", "sid", "seed", "max_moves", "moves", "lat_ms",
        "trace", "t0_ns",
    )

    def __init__(self, req_id, sid, seed, max_moves, trace=None):
        self.req_id = req_id
        self.sid = sid
        self.seed = seed
        self.max_moves = max_moves
        self.moves = 0
        self.lat_ms: list = []
        # Trace-context fields of the routed request driving this
        # episode (telemetry/tracectx.py); empty for legacy callers.
        self.trace: dict = trace or {}
        self.t0_ns = time.time_ns()


class ReplicaServer:
    """Protocol loop around one PolicyService (built by `main`)."""

    def __init__(self, service, telemetry, tick_every: int = 8, out=None):
        self.service = service
        self.telemetry = telemetry
        self.tick_every = tick_every
        self.out = out or sys.stdout
        self._out_lock = threading.Lock()
        self._active: dict[int, _Episode] = {}  # sid -> episode
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._dispatches_since_tick = 0

    # --- wire -----------------------------------------------------------

    def reply(self, payload: dict) -> None:
        with self._out_lock:
            self.out.write(json.dumps(payload) + "\n")
            self.out.flush()

    # --- dispatcher thread ----------------------------------------------

    def _finish(self, ep: _Episode, ok: bool, error: str | None = None):
        try:
            summary = self.service.close_session(ep.sid)
        except Exception:
            summary = {}
        done = bool(summary.get("done"))
        # The episode's lane in this replica's trace.json: one complete
        # span from request arrival to reply, carrying the routed
        # request's trace ids so the fleet merge can draw the
        # router -> replica flow arrow.
        tracer = getattr(self.telemetry, "tracer", None)
        if tracer is not None:
            tracer.complete(
                "replica/episode",
                ep.t0_ns,
                time.time_ns(),
                moves=ep.moves,
                ok=ok,
                **ep.trace,
            )
        self.reply(
            {
                "id": ep.req_id,
                "ok": ok,
                "kind": "episode",
                "seed": ep.seed,
                "moves": ep.moves,
                "done": done,
                "score": summary.get("score"),
                "lat_ms": [round(v, 3) for v in ep.lat_ms],
                **ep.trace,
                **({"error": error} if error else {}),
            }
        )

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                while not self._active and not self._stop.is_set():
                    self._cond.wait(timeout=0.2)
                if self._stop.is_set():
                    return
            try:
                results = self.service.dispatch()
            except Exception as exc:
                # A dispatch that raises (e.g. the crash-serve fault)
                # sealed its flight bracket ok:false; the sessions it
                # was serving are in an undefined mid-wave state, so
                # fail them back to the router (which retries them on
                # another replica) and keep serving.
                logger.exception("dispatch failed; failing active episodes")
                with self._cond:
                    failed, self._active = dict(self._active), {}
                for ep in failed.values():
                    self._finish(ep, ok=False, error=f"dispatch: {exc}")
                continue
            finished: list = []
            with self._cond:
                for r in results:
                    ep = self._active.get(r["sid"])
                    if ep is None:
                        continue
                    ep.moves += 1
                    ep.lat_ms.append(float(r["latency_ms"]))
                    if r["done"] or ep.moves >= ep.max_moves:
                        finished.append(ep)
                        del self._active[ep.sid]
                    else:
                        self.service.request_move(ep.sid)
            for ep in finished:
                self._finish(ep, ok=True)
            if results:
                self._dispatches_since_tick += 1
                if self._dispatches_since_tick >= self.tick_every:
                    self._dispatches_since_tick = 0
                    try:
                        self.service.tick()
                    except Exception:
                        logger.exception("serve tick failed (continuing)")

    # --- heartbeat thread -----------------------------------------------

    def _heartbeat_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.telemetry.health.write()
            except Exception:
                logger.exception("heartbeat write failed (continuing)")

    # --- control-plane handlers ------------------------------------------

    def _handle(self, req: dict) -> bool:
        """Process one request; returns False on shutdown."""
        kind = req.get("kind")
        rid = req.get("id")
        if kind == "episode":
            trace = tracectx.trace_fields(req)
            try:
                s = self.service.open_session(seed=int(req.get("seed", 0)))
            except Exception as exc:
                self.reply(
                    {
                        "id": rid,
                        "ok": False,
                        "kind": kind,
                        "error": str(exc),
                        **trace,
                    }
                )
                return True
            set_trace = getattr(self.service, "set_session_trace", None)
            if set_trace is not None and trace:
                set_trace(s.sid, trace)
            # Register BEFORE request_move: the dispatcher may serve
            # the very next wave, and a result for an unregistered sid
            # would be dropped (wedging the episode forever).
            with self._cond:
                self._active[s.sid] = _Episode(
                    rid,
                    s.sid,
                    req.get("seed"),
                    int(req.get("max_moves", 64)),
                    trace=trace,
                )
            try:
                self.service.request_move(s.sid)
            except Exception as exc:
                with self._cond:
                    self._active.pop(s.sid, None)
                try:
                    self.service.close_session(s.sid)
                except Exception:
                    pass
                self.reply(
                    {"id": rid, "ok": False, "kind": kind, "error": str(exc)}
                )
                return True
            with self._cond:
                self._cond.notify()
            return True
        if kind == "ping":
            self.reply(
                {
                    "id": rid,
                    "ok": True,
                    "kind": kind,
                    "pid": os.getpid(),
                    "queue_depth": self.service.queue_depth,
                    "dispatches": self.service.dispatch_count,
                    **_clock_pair(),
                }
            )
            return True
        if kind == "stats":
            from ..compile_cache import get_compile_cache

            cache = get_compile_cache().stats()
            self.reply(
                {
                    "id": rid,
                    "ok": True,
                    "kind": kind,
                    "cache_misses": cache.get("misses"),
                    "cache_events": len(cache.get("events") or []),
                    **self.service.serve_stats(drain=False),
                }
            )
            return True
        if kind == "reload":
            from ..compile_cache import get_compile_cache

            before = get_compile_cache().stats().get("misses")
            reloads = self.service.reload_weights()
            after = get_compile_cache().stats().get("misses")
            self.reply(
                {
                    "id": rid,
                    "ok": True,
                    "kind": kind,
                    "reloads": reloads,
                    "cache_misses": after,
                    "recompiles": (after or 0) - (before or 0),
                }
            )
            return True
        if kind == "shutdown":
            self.reply({"id": rid, "ok": True, "kind": kind})
            return False
        self.reply(
            {"id": rid, "ok": False, "error": f"unknown kind {kind!r}"}
        )
        return True

    # --- lifecycle --------------------------------------------------------

    def serve_forever(self, heartbeat_s: float, stdin=None) -> int:
        stdin = stdin or sys.stdin
        threads = [
            threading.Thread(
                target=self._dispatch_loop, name="replica-dispatch", daemon=True
            ),
            threading.Thread(
                target=self._heartbeat_loop,
                args=(heartbeat_s,),
                name="replica-heartbeat",
                daemon=True,
            ),
        ]
        for t in threads:
            t.start()
        try:
            for line in stdin:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("unparseable request line: %r", line[:200])
                    continue
                try:
                    if not self._handle(req):
                        break
                except Exception as exc:
                    logger.exception("request handler failed")
                    self.reply(
                        {"id": req.get("id"), "ok": False, "error": str(exc)}
                    )
        finally:
            self._stop.set()
            with self._cond:
                self._cond.notify_all()
            for t in threads:
                t.join(timeout=5.0)
        return 0


def main(argv: "list | None" = None) -> int:
    p = argparse.ArgumentParser(description="serve-fleet replica worker")
    p.add_argument("--run-dir", required=True, help="this replica's run dir")
    p.add_argument(
        "--configs-dir",
        default="",
        help="dir holding configs.json (board/net); flagship defaults "
        "when missing",
    )
    p.add_argument("--name", default="replica")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument(
        "--buckets",
        default=None,
        help="CSV serve-shape ladder (serving/buckets.py); the service "
        "micro-batches across these rungs instead of the fixed --slots "
        "shape (--slots stays the starting rung).",
    )
    p.add_argument("--sims", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tick-every", type=int, default=8)
    p.add_argument("--gumbel", action="store_true")
    p.add_argument("--health-interval", type=float, default=1.0)
    p.add_argument("--watchdog-deadline", type=float, default=300.0)
    p.add_argument("--dispatch-min-deadline", type=float, default=60.0)
    p.add_argument("--dispatch-first-deadline", type=float, default=900.0)
    p.add_argument("--dispatch-watchdog-poll", type=float, default=5.0)
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stderr,
        format=f"%(asctime)s {args.name} %(levelname)s %(message)s",
    )

    from pathlib import Path

    from ..config import AlphaTriangleMCTSConfig, TelemetryConfig
    from ..config.run_configs import load_run_configs_or_default
    from ..env.engine import TriangleEnv
    from ..features.core import get_feature_extractor
    from ..mcts import BatchedMCTS, GumbelMCTS
    from ..nn.network import NeuralNetwork
    from .service import PolicyService, build_serve_telemetry

    run_dir = Path(args.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    cfg_dir = Path(args.configs_dir) if args.configs_dir else Path("/nonexistent")
    env_cfg, model_cfg = load_run_configs_or_default(cfg_dir)
    mcts_cfg = AlphaTriangleMCTSConfig(max_simulations=args.sims)
    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    mcts_cls = GumbelMCTS if args.gumbel else BatchedMCTS
    mcts_kw = {"exploit": True} if args.gumbel else {}
    mcts = mcts_cls(
        env, extractor, net.model, mcts_cfg, net.support, **mcts_kw
    )

    tele_cfg = TelemetryConfig(
        HEALTH_WRITE_INTERVAL_S=args.health_interval,
        WATCHDOG_DEADLINE_S=args.watchdog_deadline,
        DISPATCH_MIN_DEADLINE_S=args.dispatch_min_deadline,
        DISPATCH_FIRST_DEADLINE_S=args.dispatch_first_deadline,
        DISPATCH_WATCHDOG_POLL_S=args.dispatch_watchdog_poll,
    )
    telemetry = build_serve_telemetry(
        run_dir, args.name, env_cfg, model_cfg, telemetry_config=tele_cfg
    )
    from ..compile_cache import get_compile_cache

    get_compile_cache().set_tracer(telemetry.tracer)
    service = PolicyService(
        env,
        extractor,
        net,
        mcts,
        slots=args.slots,
        use_gumbel=args.gumbel,
        telemetry=telemetry,
        rng_seed=args.seed,
        ladder=args.buckets,
    )
    # AOT warm BEFORE the ready line: episode requests never pay the
    # search compile, so the storm's move latencies measure serving.
    t0 = time.time()
    aot = service.warm()
    logger.info(
        "warm %s in %.1fs (slots=%d sims=%d)",
        "aot" if aot else "jit-fallback",
        time.time() - t0,
        args.slots,
        args.sims,
    )
    telemetry.start()
    # First heartbeat BEFORE the ready line: the fleet parent's probe
    # gates admission on a fresh health.json, so a just-ready replica
    # must already have one on disk.
    telemetry.health.write()
    server = ReplicaServer(service, telemetry, tick_every=args.tick_every)
    server.reply(
        {
            "kind": READY_KIND,
            "name": args.name,
            "pid": os.getpid(),
            "slots": args.slots,
            # Rung + precision ride the ready line so the fleet ledger
            # (and `cli watch`'s fleet line) can show what shape and
            # dtype each replica actually serves at.
            "rungs": list(service.ladder.rungs),
            "precision": model_cfg.INFERENCE_PRECISION,
            "warm_aot": bool(aot),
            **_clock_pair(),
        }
    )
    try:
        return server.serve_forever(heartbeat_s=args.health_interval)
    finally:
        try:
            service.tick()
        except Exception:
            pass
        telemetry.close(step=service.dispatch_count)


if __name__ == "__main__":
    raise SystemExit(main())
