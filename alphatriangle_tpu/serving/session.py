"""Game-session slot array: the state tier under the policy service.

The serving design problem: `BatchedMCTS.search` (mcts/search.py) is
ONE compiled program over a fixed batch shape `(B, ...)`, but serving
traffic is many independent games starting and ending at uncorrelated
times (the Podracer acting/learning split, arXiv:2104.06272; RLAX-style
many-actors-one-policy streaming, PAPERS.md). `SessionSlots` bridges
the two: a fixed array of B device-resident game slots, sessions
admitted into free slots and retired out of them BETWEEN dispatches, so
one compiled search shape serves fluctuating load.

Key properties the serving tests pin:

- **Lane isolation.** Every per-lane quantity in the search (priors,
  Dirichlet/Gumbel noise, descents, backups) depends only on the lane's
  own state, its lane index, and the dispatch key — never on what other
  lanes hold. A session pinned to slot `i` therefore plays the exact
  same game whether the other B-1 slots hold live sessions, retired
  leftovers, or padding. Churn cannot leak between sessions.
- **Frozen padding.** Free slots hold `done=True` states: the engine
  steps them as no-ops and the search evaluates them as terminal
  (value 0), so padded lanes cost compute but never produce state.
- **Lockstep clients stay exact.** Admitting G sessions into slots
  0..G-1 of a G-slot array reproduces `env.reset_batch` bit for bit,
  and a full-mask step equals `env.step_batch` — which is why
  `arena.play` (and through it `cli eval` / `benchmarks/elo_ladder.py`)
  runs on this API with unchanged paired-hands results.

Everything here is host-side orchestration around jitted env programs;
the per-dispatch device work is one fused scatter/step/select program
(`_admit_rows`, `_masked_step`), not per-session Python.
"""

import itertools
import time
from dataclasses import dataclass, field
from types import SimpleNamespace

import numpy as np


def _slot_programs(env):
    """The jitted slot-array programs for one env, built once per env
    instance and shared by every SessionSlots over it (a per-instance
    jit would recompile the step/scatter programs for every arena play
    or service construction; jit's own cache handles distinct batch
    shapes)."""
    progs = getattr(env, "_session_slot_programs", None)
    if progs is not None:
        return progs
    import jax
    import jax.numpy as jnp

    def bcast(mask, leaf):
        return mask.reshape((-1,) + (1,) * (leaf.ndim - 1))

    def admit_rows(states, fresh, idx):
        return jax.tree_util.tree_map(
            lambda buf, rows: buf.at[idx].set(rows), states, fresh
        )

    def masked_step(states, actions, mask):
        stepped, rewards, dones = jax.vmap(env.step)(states, actions)
        selected = jax.tree_util.tree_map(
            lambda new, old: jnp.where(bcast(mask, new), new, old),
            stepped,
            states,
        )
        return selected, rewards, dones

    def freeze_slot(states, slot):
        return states.replace(done=states.done.at[slot].set(True))

    progs = SimpleNamespace(
        admit_rows=jax.jit(admit_rows),
        masked_step=jax.jit(masked_step),
        freeze_slot=jax.jit(freeze_slot),
    )
    env._session_slot_programs = progs
    return progs


@dataclass
class Session:
    """Host bookkeeping for one live (or just-retired) game session."""

    sid: int
    slot: int
    admitted_at: float
    moves: int = 0
    done: bool = False
    score: float = 0.0
    pending_since: "float | None" = None  # enqueue time of the open request
    meta: dict = field(default_factory=dict)

    def summary(self) -> dict:
        return {
            "sid": self.sid,
            "slot": self.slot,
            "moves": self.moves,
            "score": self.score,
            "done": self.done,
        }


class SessionSlots:
    """Fixed-shape slot array of concurrent game sessions.

    `slots` is the compiled batch shape: every search/step dispatch is
    over all `slots` lanes regardless of how many are occupied. Slots
    are assigned lowest-free-first, so a deterministic admit order
    yields deterministic lane placement (what makes serving results
    reproducible and the arena client's pairing exact).
    """

    def __init__(self, env, slots: int, pad_seed: int = 0):
        import jax
        import jax.numpy as jnp

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.env = env
        self.slots = int(slots)
        self._jnp = jnp
        self._free: list[int] = list(range(self.slots))
        self._by_slot: dict[int, Session] = {}
        self._sessions: dict[int, Session] = {}
        self._sid_counter = itertools.count(1)
        self.admitted_total = 0
        self.retired_total = 0

        # Padding base: reset states frozen with done=True (inert for
        # both the engine and the search).
        keys = jax.random.split(jax.random.PRNGKey(pad_seed), self.slots)
        base = env.reset_batch(keys)
        self.states = base.replace(
            done=jnp.ones((self.slots,), dtype=base.done.dtype)
        )
        progs = _slot_programs(env)
        self._admit_rows = progs.admit_rows
        self._masked_step = progs.masked_step
        self._freeze_slot = progs.freeze_slot

    # --- occupancy ----------------------------------------------------

    @property
    def live_count(self) -> int:
        return len(self._sessions)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def live_sessions(self) -> list[Session]:
        return list(self._sessions.values())

    def session(self, sid: int) -> Session:
        return self._sessions[sid]

    def live_mask(self) -> np.ndarray:
        mask = np.zeros(self.slots, dtype=bool)
        for s in self._sessions.values():
            mask[s.slot] = True
        return mask

    # --- admit / retire (between dispatches only) ---------------------

    def admit_many(self, reset_keys) -> list[Session]:
        """Admit len(reset_keys) sessions into the lowest free slots
        (ONE row-scatter dispatch). Raises when the array is full —
        back-pressure is the caller's queue, not silent eviction."""
        import jax.numpy as jnp

        keys = jnp.asarray(reset_keys)
        n = int(keys.shape[0])
        if n == 0:
            return []
        if n > len(self._free):
            raise RuntimeError(
                f"admit_many({n}): only {len(self._free)} of "
                f"{self.slots} slots free"
            )
        self._free.sort()
        taken, self._free = self._free[:n], self._free[n:]
        fresh = self.env.reset_batch(keys)
        self.states = self._admit_rows(
            self.states, fresh, jnp.asarray(taken, dtype=jnp.int32)
        )
        now = time.monotonic()
        out = []
        for slot in taken:
            s = Session(
                sid=next(self._sid_counter), slot=slot, admitted_at=now
            )
            self._sessions[s.sid] = s
            self._by_slot[slot] = s
            out.append(s)
        self.admitted_total += n
        return out

    def admit(self, reset_key) -> Session:
        return self.admit_many(reset_key[None])[0]

    def retire(self, sid: int) -> dict:
        """Release a session's slot (re-frozen so the lane stays inert)
        and return its final summary. Reads the slot's score/step_count
        from the device — retirement is a host sync by definition."""
        import jax

        s = self._sessions.pop(sid)
        self._by_slot.pop(s.slot, None)
        score, moves, done = jax.device_get(  # graftlint: allow(host-sync-in-hot-path) retirement IS the fetch; one transfer for all three scalars
            (
                self.states.score[s.slot],
                self.states.step_count[s.slot],
                self.states.done[s.slot],
            )
        )
        s.score = float(score)
        s.moves = int(moves)
        s.done = bool(done)
        self.states = self._freeze_slot(self.states, s.slot)
        self._free.append(s.slot)
        self.retired_total += 1
        return s.summary()

    # --- rung migration (serving/buckets.py micro-batcher) -------------

    def migrate(self, new_slots: int, pad_seed: int = 0) -> "SessionSlots":
        """A new slot array of `new_slots` lanes carrying every live
        session over — the micro-batcher's rung switch
        (serving/service.py). Sessions keep their identity (same
        Session objects, sids, pending requests, admitted/retired
        totals and sid counter), and are re-packed lowest-old-slot-first
        into slots 0..live-1: relative lane order is preserved, so a
        deterministic admit order still yields deterministic placement
        after any sequence of switches (the lane-isolation property
        then makes trajectories independent of the rung the crowd
        rides in). Device state moves in ONE gather+scatter per leaf.
        Raises when the live sessions don't fit the new shape."""
        import jax
        import jax.numpy as jnp

        new_slots = int(new_slots)
        live = sorted(self._sessions.values(), key=lambda s: s.slot)
        if len(live) > new_slots:
            raise RuntimeError(
                f"migrate({new_slots}): {len(live)} live sessions do "
                f"not fit"
            )
        target = SessionSlots(self.env, new_slots, pad_seed=pad_seed)
        if live:
            old_idx = jnp.asarray(
                [s.slot for s in live], dtype=jnp.int32
            )
            new_idx = jnp.asarray(
                list(range(len(live))), dtype=jnp.int32
            )
            rows = jax.tree_util.tree_map(
                lambda leaf: leaf[old_idx], self.states
            )
            target.states = target._admit_rows(target.states, rows, new_idx)
        # Host bookkeeping: the target adopts this array's session
        # identity wholesale (counters included — a migration is not
        # an admission).
        target._sessions = self._sessions
        target._by_slot = {}
        target._free = list(range(len(live), new_slots))
        for i, s in enumerate(live):
            s.slot = i
            target._by_slot[i] = s
        target._sid_counter = self._sid_counter
        target.admitted_total = self.admitted_total
        target.retired_total = self.retired_total
        return target

    # --- the lockstep step --------------------------------------------

    def step(self, actions, mask):
        """Step lanes where `mask` is True; the rest keep their state
        bit for bit. Returns device (rewards, dones) for ALL lanes
        (callers sync only what they need). `actions`/`mask` are (B,)
        host or device arrays."""
        import jax.numpy as jnp

        mask_np = np.asarray(mask, dtype=bool)
        actions = jnp.asarray(actions, dtype=jnp.int32)
        self.states, rewards, dones = self._masked_step(
            self.states, actions, jnp.asarray(mask_np)
        )
        # Advisory move counter (retire() reads the authoritative
        # step_count from the device).
        for s in self._sessions.values():
            if mask_np[s.slot]:
                s.moves += 1
        return rewards, dones

    # --- host views ----------------------------------------------------

    def snapshot(self) -> dict:
        """Occupancy facts for heartbeats/ticks (no device sync)."""
        return {
            "slots": self.slots,
            "live": self.live_count,
            "free": self.free_count,
            "admitted_total": self.admitted_total,
            "retired_total": self.retired_total,
        }

    def host_results(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(scores, step_counts, done) for the whole slot array as
        NumPy — ONE host sync; the arena client calls this once at the
        end of a run instead of per move."""
        import jax

        scores, steps, done = jax.device_get(  # graftlint: allow(host-sync-in-hot-path) the one end-of-run fetch the docstring promises
            (self.states.score, self.states.step_count, self.states.done)
        )
        return np.asarray(scores), np.asarray(steps), np.asarray(done)
