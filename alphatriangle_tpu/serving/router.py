"""Least-queue-depth replica router (docs/SERVING.md "Fleet").

The routing half of the serve fleet: a pool of interchangeable
replica handles (the Sebulba/RLAX fleet shape — arXiv:2104.06272,
arXiv:2512.06392) behind one `route()` call that carries the full
robustness toolkit:

- **health-gated admission** — only replicas whose `routable` flag is
  up (fresh heartbeat probe + no unsealed flight intent past deadline,
  maintained by `fleet.FleetSupervisor`) receive traffic; among those
  the least queue depth wins.
- **timeout + retry** — each attempt gets `timeout_s`; a failed or
  timed-out attempt retries with capped exponential backoff
  (`base * 2^(k-1)`, capped) onto a *different* replica (falling back
  to a tried one only when nothing else is healthy — retrying the
  failed replica still beats shedding).
- **hedged dispatch** — optionally, a straggling attempt launches a
  second copy on another replica after `hedge_after_s`; first result
  wins and the loser is cancelled (idempotent episode requests make
  the duplicate harmless).
- **load shedding** — admission is bounded (`max_inflight`); overflow
  and no-healthy-replica requests are REJECTED with distinct codes
  rather than queued forever. Every shed is an accounted outcome:
  `completed + shed + exhausted == requests` is the storm's
  zero-lost-requests invariant.

JAX-free and subprocess-free: handles are duck-typed (`name`,
`routable`, `queue_depth`, `bucket`, `submit(payload) -> pending`
where pending has `done()/wait(t)/cancel()/value/error`), so
tests/test_fleet.py drives every edge case with fakes and an
injectable clock/sleep — mirror of tests/test_supervise.py.
"""

import logging
import threading
import time
from dataclasses import dataclass, field

from ..telemetry import tracectx
from ..telemetry.flight import flight_span

logger = logging.getLogger(__name__)

#: Distinct rejection codes (docs/SERVING.md "Fleet" contract). A shed
#: request was REFUSED before dispatch; an exhausted one failed every
#: allowed attempt and surfaces the last error.
REJECT_QUEUE_FULL = "queue-full"
REJECT_NO_HEALTHY = "no-healthy-replica"
REJECT_RETRIES_EXHAUSTED = "retries-exhausted"

#: Program name the router's flight bracket dispatches under (family
#: "fleet" — analysis/rules.py FLIGHT_FAMILIES).
ROUTE_PROGRAM = "fleet/route"


class ReplicaError(RuntimeError):
    """A replica failed a request (died mid-flight, protocol error,
    or an in-replica exception surfaced in the reply)."""


@dataclass
class RouteResult:
    """Terminal outcome of one routed request: exactly one of
    `ok` (served), `rejection` set (shed/exhausted)."""

    ok: bool
    value: dict | None = None
    replica: str | None = None
    replica_bucket: int | None = None
    rejection: str | None = None
    error: Exception | None = None
    attempts: int = 0
    hedged: bool = False
    hedge_won: bool = False
    wait_s: float = 0.0
    trace_id: str | None = None


@dataclass
class RouterStats:
    requests: int = 0
    completed: int = 0
    shed_queue_full: int = 0
    shed_unhealthy: int = 0
    exhausted: int = 0
    retries: int = 0
    timeouts: int = 0
    hedges: int = 0
    hedge_wins: int = 0
    backoff_sleeps: list = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "requests": self.requests,
            "completed": self.completed,
            "shed_queue_full": self.shed_queue_full,
            "shed_unhealthy": self.shed_unhealthy,
            "exhausted": self.exhausted,
            "retries": self.retries,
            "timeouts": self.timeouts,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
        }


class ReplicaRouter:
    """Thread-safe router over a (mutable) list of replica handles.

    `clock`/`sleep` are injectable so tests freeze the backoff math;
    `poll_s` is the straggler-watch granularity while an attempt is in
    flight. `on_event` receives one dict per routing decision (shed /
    retry / hedge / exhausted) — the fleet supervisor ledgers them
    into fleet.jsonl; `flight` (optional FlightRecorder) brackets each
    routed request as `fleet/route` so a parent death names the
    requests it was holding."""

    def __init__(
        self,
        replicas: list,
        *,
        timeout_s: float = 30.0,
        retries: int = 2,
        backoff_base_s: float = 0.1,
        backoff_max_s: float = 2.0,
        hedge_after_s: "float | None" = None,
        max_inflight: int = 64,
        poll_s: float = 0.002,
        clock=time.monotonic,
        sleep=time.sleep,
        flight=None,
        on_event=None,
    ) -> None:
        self.replicas = replicas
        self.timeout_s = timeout_s
        self.retries = retries
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.hedge_after_s = hedge_after_s
        self.max_inflight = max_inflight
        self.poll_s = poll_s
        self._clock = clock
        self._sleep = sleep
        self.flight = flight
        self.on_event = on_event
        self.stats = RouterStats()
        self._lock = threading.Lock()
        self._inflight = 0

    # --- introspection ---------------------------------------------------

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def healthy(self) -> list:
        return [r for r in self.replicas if r.routable]

    def _emit(self, event: str, **fields) -> None:
        if self.on_event is None:
            return
        try:
            # Every decision carries the admission level so a tailer
            # (`cli watch`) can show queue pressure without polling.
            self.on_event(
                {"event": event, "inflight": self._inflight, **fields}
            )
        except Exception:
            logger.exception("router on_event hook failed for %r", event)

    def _pick(self, exclude: "tuple | list" = ()):
        """Healthiest target: least queue depth among routable replicas
        not yet tried this request; falls back to a tried replica when
        nothing else is routable (better than shedding), None when no
        replica is routable at all."""
        healthy = self.healthy()
        if not healthy:
            return None
        fresh = [r for r in healthy if r.name not in exclude]
        pool = fresh or healthy
        return min(pool, key=lambda r: (r.queue_depth, r.name))

    def backoff_delay(self, attempt: int) -> float:
        """Delay before retry `attempt` (1-based): base * 2^(k-1),
        capped — the same curve supervise.RecoveryPolicy uses."""
        return min(
            self.backoff_max_s, self.backoff_base_s * 2 ** (attempt - 1)
        )

    # --- the routed request ----------------------------------------------

    def route(self, payload: dict, timeout_s: "float | None" = None) -> RouteResult:
        """Route one request to a terminal outcome (never raises for
        replica-side failures — shed/exhausted outcomes carry their
        rejection code and last error instead).

        Every request is minted a trace context (telemetry/tracectx.py)
        — a child of any context already on the payload (a caller
        propagating its own trace), else a fresh root trace. The triple
        rides the payload to the replica, every router event, and the
        `fleet/route` flight bracket, so the merged fleet timeline can
        follow this exact request across the process boundary."""
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        ctx = tracectx.mint(parent=tracectx.TraceContext.from_fields(payload))
        trace = ctx.fields()
        payload = {**payload, **trace}
        with self._lock:
            self.stats.requests += 1
            if self._inflight >= self.max_inflight:
                self.stats.shed_queue_full += 1
                result = RouteResult(
                    ok=False,
                    rejection=REJECT_QUEUE_FULL,
                    trace_id=ctx.trace_id,
                )
                self._emit(
                    "shed",
                    rejection=REJECT_QUEUE_FULL,
                    kind=payload.get("kind"),
                    **trace,
                )
                return result
            self._inflight += 1
        t0 = self._clock()
        try:
            with flight_span(
                self.flight,
                "fleet",
                ROUTE_PROGRAM,
                avals=str(payload.get("kind", "request")),
                trace=trace,
            ):
                result = self._attempt_loop(payload, timeout_s, trace)
        finally:
            with self._lock:
                self._inflight -= 1
        result.wait_s = self._clock() - t0
        result.trace_id = ctx.trace_id
        if result.ok:
            with self._lock:
                self.stats.completed += 1
        return result

    def _attempt_loop(
        self, payload: dict, timeout_s: float, trace: "dict | None" = None
    ) -> RouteResult:
        tried: list = []
        trace = trace or {}
        last_error: "Exception | None" = None
        attempt = 0
        while attempt <= self.retries:
            target = self._pick(exclude=tried)
            if target is None:
                with self._lock:
                    self.stats.shed_unhealthy += 1
                self._emit(
                    "shed",
                    rejection=REJECT_NO_HEALTHY,
                    attempts=attempt,
                    error=str(last_error) if last_error else None,
                    kind=payload.get("kind"),
                    **trace,
                )
                return RouteResult(
                    ok=False,
                    rejection=REJECT_NO_HEALTHY,
                    attempts=attempt,
                    error=last_error,
                )
            if attempt > 0:
                delay = self.backoff_delay(attempt)
                with self._lock:
                    self.stats.retries += 1
                    self.stats.backoff_sleeps.append(delay)
                self._emit(
                    "retry",
                    replica=target.name,
                    attempt=attempt,
                    delay_s=delay,
                    error=str(last_error) if last_error else None,
                    **trace,
                )
                self._sleep(delay)
            tried.append(target.name)
            result = self._dispatch_one(
                target, payload, timeout_s, tried, trace
            )
            if result.ok:
                result.attempts = attempt + 1
                return result
            last_error = result.error
            attempt += 1
        with self._lock:
            self.stats.exhausted += 1
        self._emit(
            "exhausted",
            attempts=attempt,
            error=str(last_error) if last_error else None,
            kind=payload.get("kind"),
            **trace,
        )
        return RouteResult(
            ok=False,
            rejection=REJECT_RETRIES_EXHAUSTED,
            attempts=attempt,
            error=last_error,
        )

    def _dispatch_one(
        self,
        primary,
        payload: dict,
        timeout_s: float,
        tried: list,
        trace: "dict | None" = None,
    ) -> RouteResult:
        """One attempt on `primary`, optionally hedged onto a second
        replica after `hedge_after_s`. First finished copy wins; the
        loser is cancelled (cancel-on-first-win)."""
        deadline = self._clock() + timeout_s
        hedge_at = (
            None
            if self.hedge_after_s is None
            else self._clock() + self.hedge_after_s
        )
        try:
            pending = primary.submit(payload)
        except Exception as exc:  # dead pipe etc. — a failed attempt
            return RouteResult(ok=False, error=exc, replica=primary.name)
        hedge = None
        hedge_target = None
        while True:
            if pending is not None and pending.done():
                if hedge is not None:
                    hedge.cancel()
                if pending.error is None:
                    return RouteResult(
                        ok=True,
                        value=pending.value,
                        replica=primary.name,
                        replica_bucket=getattr(primary, "bucket", None),
                        hedged=hedge is not None,
                    )
                if hedge is None:
                    return RouteResult(
                        ok=False, error=pending.error, replica=primary.name
                    )
                # Primary failed but a hedge is still in flight: let it
                # race the remaining deadline before calling the
                # attempt failed.
                pending = None
            if hedge is not None and hedge.done():
                if pending is not None:
                    pending.cancel()
                if hedge.error is None:
                    with self._lock:
                        self.stats.hedge_wins += 1
                    self._emit(
                        "hedge-win",
                        replica=hedge_target.name,
                        primary=primary.name,
                        **(trace or {}),
                    )
                    return RouteResult(
                        ok=True,
                        value=hedge.value,
                        replica=hedge_target.name,
                        replica_bucket=getattr(hedge_target, "bucket", None),
                        hedged=True,
                        hedge_won=True,
                    )
                if pending is None:
                    return RouteResult(
                        ok=False, error=hedge.error, replica=hedge_target.name
                    )
                hedge = None  # hedge failed first; primary still racing
            now = self._clock()
            if now >= deadline:
                if pending is not None:
                    pending.cancel()
                if hedge is not None:
                    hedge.cancel()
                with self._lock:
                    self.stats.timeouts += 1
                return RouteResult(
                    ok=False,
                    error=TimeoutError(
                        f"request timed out after {timeout_s:g}s on "
                        f"{primary.name}"
                    ),
                    replica=primary.name,
                )
            if (
                hedge is None
                and hedge_at is not None
                and now >= hedge_at
                and pending is not None
            ):
                hedge_at = None  # at most one hedge per attempt
                hedge_target = self._pick(exclude=[*tried, primary.name])
                if hedge_target is not None and hedge_target is not primary:
                    try:
                        hedge = hedge_target.submit(payload)
                        with self._lock:
                            self.stats.hedges += 1
                        self._emit(
                            "hedge",
                            primary=primary.name,
                            backup=hedge_target.name,
                            **(trace or {}),
                        )
                    except Exception:
                        hedge = None
            self._sleep(self.poll_s)
