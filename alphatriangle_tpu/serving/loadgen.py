"""Simulated concurrent-session load for the policy service.

The serving smoke story (`cli serve --smoke`, `make serve-smoke`,
bench's serve section): drive N concurrent simulated game sessions
through the continuous batcher with real churn — sessions retire as
their games end and replacements are admitted mid-run, exactly the
fluctuating-load shape the slot-array + padding design exists for.
Deterministic given (seed, slot count, traffic shape): reset keys come
from a counted PRNG chain and admission is lowest-free-slot, so smoke
runs are reproducible.
"""

import logging
import time

logger = logging.getLogger(__name__)


def run_simulated_load(
    service,
    total_sessions: int,
    concurrency: "int | None" = None,
    max_moves: int = 200,
    seed: int = 0,
    tick_every: int = 8,
    max_dispatches: "int | None" = None,
    reload_hook=None,
    progress=None,
    clock=time.monotonic,
) -> dict:
    """Serve `total_sessions` games end to end, keeping up to
    `concurrency` live at once (default: every slot).

    `reload_hook(service, dispatch_count)`: optional between-dispatch
    callback — `cli serve` uses it to poll checkpoints for hot weight
    reloads; tests use it to swap weights mid-stream.
    `max_dispatches` is a runaway bound (a session that never finishes
    is truncated by `max_moves` per session anyway).
    Returns the run's summary stats.
    """
    import jax

    # Concurrency is bounded by the most sessions the service can EVER
    # hold — the ladder's top rung under a micro-batching service
    # (serving/buckets.py), the fixed slot count otherwise. Asking for
    # more than the current shape is exactly the sustained-demand
    # signal that drives the ladder walk-up.
    limit = int(getattr(service, "max_slots", service.sessions.slots))
    concurrency = min(concurrency or service.sessions.slots, limit)
    if concurrency < 1:
        raise ValueError("concurrency must be >= 1")
    t_start = clock()
    key_counter = 0

    def next_keys(n: int):
        nonlocal key_counter
        keys = [
            jax.random.fold_in(jax.random.PRNGKey(seed), key_counter + i)
            for i in range(n)
        ]
        key_counter += n
        import jax.numpy as jnp

        return jnp.stack(keys)

    def admit_up_to_target() -> int:
        want = min(
            concurrency - service.sessions.live_count,
            total_sessions - service.sessions.admitted_total,
            service.sessions.free_count,
        )
        if want > 0:
            for s in service.open_sessions(next_keys(want)):
                service.request_move(s.sid)
        return max(0, want)

    admit_up_to_target()
    dispatches = 0
    served_moves = 0
    retired = []
    while service.sessions.live_count > 0:
        results = service.dispatch()
        dispatches += 1
        served_moves += len(results)
        for r in results:
            finished = r["done"] or r["move"] >= max_moves
            if finished:
                retired.append(service.close_session(r["sid"]))
            else:
                service.request_move(r["sid"])
        admit_up_to_target()
        if reload_hook is not None:
            reload_hook(service, dispatches)
        if dispatches % tick_every == 0:
            service.tick()
            if progress is not None:
                progress(
                    f"serve: {len(retired)}/{total_sessions} sessions "
                    f"done, {served_moves} moves, "
                    f"{service.sessions.live_count} live, "
                    f"dispatch {dispatches}"
                )
        if max_dispatches is not None and dispatches >= max_dispatches:
            logger.warning(
                "loadgen: max_dispatches=%d reached with %d live "
                "session(s); truncating",
                max_dispatches,
                service.sessions.live_count,
            )
            for s in list(service.sessions.live_sessions()):
                retired.append(service.close_session(s.sid))
            break
    service.tick()
    elapsed = clock() - t_start
    scores = [r["score"] for r in retired]
    return {
        "sessions_served": len(retired),
        "sessions_finished": sum(1 for r in retired if r["done"]),
        "moves_served": served_moves,
        "dispatches": dispatches,
        "seconds": round(elapsed, 2),
        "moves_per_sec": round(served_moves / max(elapsed, 1e-9), 1),
        "mean_score": (
            round(float(sum(scores)) / len(scores), 2) if scores else None
        ),
        "max_concurrency": concurrency,
        "weight_reloads": service.weight_reloads,
    }
