"""Continuous-batching policy service over the lockstep wave search.

`PolicyService` is the inference front end the ROADMAP's
millions-of-users scenario needs: many concurrent game sessions
(humans playing the current net, arena/eval traffic, a league)
multiplexed onto ONE compiled `BatchedMCTS.search` dispatch shape.
Requests queue between dispatches; each dispatch serves every pending
session in one device program over the full slot array (idle/free
lanes ride along as frozen padding — see serving/session.py for the
lane-isolation argument), the Podracer acting-path pattern
(arXiv:2104.06272) applied to serving.

Composition of existing training plumbing, per the ROADMAP item:

- **AOT warm start** — the search program is wrapped in the compile
  cache as `serve/b<B>` (`cli warm` precompiles it alongside the bench
  plan; a warmed `cli serve` starts answering in ~0.5 s instead of
  after a flagship-scale search compile).
- **OOM pre-flight** — `analyze()` AOT-analyzes the serve program's
  HBM footprint without executing it (`estimate_fit(serve=True)`,
  `cli fit --serve`), and persists the `.mem.json` sidecar.
- **Latency SLOs** — per-request queue-wait and move latency land in
  the run's metrics ledger every tick (`serve_*` fields on the
  `kind: "util"` records), so `cli perf` summarizes p50/p95 per-move
  latency and `cli compare` gates regressions.
- **Liveness** — `cli serve` runs a `health.json` heartbeat + stall
  watchdog through the same `RunTelemetry` facade training uses.
- **Hot weight reload** — `reload_weights` swaps `net.variables`
  between dispatches; the compiled search reads variables as an input,
  so a reload never recompiles (the property `greedy_mcts_policy`
  established and test_serving counter-pins).
"""

import logging
import os
import threading
import time
from collections import deque

import numpy as np

from ..mcts.helpers import select_root_actions
from ..telemetry.device_stats import (
    beacon_signature,
    beacons_armed,
    device_stats_signature,
    fold_search_stats,
    merge_search_folds,
    note_dispatch,
)
from ..telemetry.flight import flight_span
from .session import SessionSlots

logger = logging.getLogger(__name__)


def serve_program_name(slots: int) -> str:
    """The compile-cache name of the serve search program for one slot
    shape — `serve/b<B>`, the spelling `cli warm` reports."""
    return f"serve/b{int(slots)}"


def _pct(values: list, q: float) -> "float | None":
    vals = sorted(v for v in values if isinstance(v, (int, float)))
    if not vals:
        return None
    idx = min(len(vals) - 1, max(0, round(q * (len(vals) - 1))))
    return float(vals[idx])


class PolicyService:
    """Request queue + micro-batcher over one `SessionSlots` array.

    Single-dispatcher model: any thread may open/close sessions and
    enqueue move requests (lock-guarded, O(1)); one caller drives
    `dispatch()` in a loop. Admission beyond the slot count raises —
    back-pressure belongs to the caller (the load generator queues,
    an HTTP front end would 503).

    With a `ladder` (serving/buckets.py), the service becomes a
    RUNG-SWITCHING micro-batcher: the compiled dispatch shape walks UP
    one rung when windowed batch fill sustains at/above `high_water`
    (or immediately when an admission would not fit the current shape
    — zero lost requests), and DOWN when fill sustains at/below
    `low_water` and the live sessions fit the smaller shape — the
    inverse of the fleet quarantine's forced walk-down on the same
    ladder. Every rung is AOT-warmed by `warm()` up front, so a switch
    between dispatches never compiles (test_serving pins the
    compile-cache event count across a storm). A switch migrates the
    live sessions lowest-old-slot-first (SessionSlots.migrate), clears
    every carried subtree (`_carry_ok`; reuse never crosses bucket
    shapes), and keeps the one-dispatch-per-wave contract untouched."""

    def __init__(
        self,
        env,
        extractor,
        net,
        mcts,
        slots: int,
        use_gumbel: bool = False,
        telemetry=None,
        rng_seed: int = 0,
        pad_seed: int = 0,
        clock=time.monotonic,
        ladder=None,
        high_water: float = 0.85,
        low_water: float = 0.25,
        sustain: int = 3,
    ):
        import jax

        from ..compile_cache import config_digest, get_compile_cache
        from .buckets import BucketLadder

        self.env = env
        self.extractor = extractor
        self.net = net
        self.mcts = mcts
        self.use_gumbel = bool(use_gumbel)
        self.telemetry = telemetry
        # Flight recorder rides the run telemetry (telemetry/flight.py);
        # None when serving without telemetry (tests, warm-only paths).
        self.flight = getattr(telemetry, "flight", None)
        # Optional trajectory sink (league/emitter.py): when set, every
        # dispatch hands it the pre-step states + search output so
        # served games become training data. None = serve-only.
        self.emitter = None
        self._clock = clock
        # The serve-shape ladder: None = the degenerate single-rung
        # ladder (fixed-shape serving, the historical behavior, bit
        # for bit). `slots` is the starting rung and is always a rung.
        if ladder is None:
            self.ladder = BucketLadder.single(slots)
        else:
            self.ladder = BucketLadder.from_spec(ladder, base=slots)
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.sustain = max(1, int(sustain))
        self.rung_switches = 0
        # Recent per-dispatch fills driving walk decisions (distinct
        # from the tick-drained `_win_fill` SLO window).
        self._ladder_fill: deque[float] = deque(maxlen=self.sustain)
        self._pad_seed = int(pad_seed)
        self.sessions = SessionSlots(env, slots, pad_seed=pad_seed)
        # The serve program: the search jit wrapped for AOT executable
        # caching. The digest covers everything that shapes the program
        # but is invisible in its avals (sim budget, net architecture,
        # board, and the search-class/exploit mode, which swap _search
        # bodies entirely).
        extra = (
            config_digest(mcts.config, extractor.model_config, env.cfg)
            + (
                f"|{type(mcts).__name__}"
                f"|exploit{int(getattr(mcts, 'exploit', False))}"
            )
            + device_stats_signature()
            + beacon_signature()
        )
        # Serve-wave stat-packs (telemetry/device_stats.py): snapshot
        # the process-global here — it must match what `mcts` captured
        # at construction, since `out.stats` exists iff the search was
        # built with stats on.
        self._device_stats = bool(getattr(mcts, "device_stats", False))
        self._win_device_stats: list[dict] = []
        self._last_serve_ds: "dict | None" = None
        # Subtree reuse (MCTSConfig.tree_reuse): each lane carries its
        # promoted search tree across dispatches, device-resident. The
        # serve program then fuses search + in-program action argmax +
        # root promotion into the same single dispatch; the host keeps
        # a per-lane validity mask (`_carry_ok`) and clears lanes on
        # churn (admit/retire), episode end, weight reload (carried
        # priors/visits came from the old net) and any lane the wave's
        # promotion advanced but the masked step did not (unserved
        # lanes must not inherit a tree for a move they never played).
        self._tree_reuse = bool(getattr(mcts.config, "tree_reuse", False))
        self._carry_ok = np.zeros(slots, dtype=bool)
        self._carried = None
        if self._tree_reuse:
            import jax.numpy as jnp

            def _serve_search_reuse(variables, states, rng, carried, ok):
                eff = carried.replace(valid=carried.valid & ok)
                out, tree, reused = mcts._search_carried(
                    variables, states, rng, eff
                )
                counts = out.visit_counts
                # Device replica of select_root_actions' PUCT rule
                # (helpers.py: argmax of visits, 0 on zero-visit rows)
                # — same values the host selects, so the promotion
                # follows exactly the action the masked step plays.
                actions = jnp.where(
                    counts.sum(axis=-1) > 0,
                    jnp.argmax(counts, axis=-1).astype(jnp.int32),
                    0,
                )
                return out, mcts.promote(tree, actions), reused

            self._carried = mcts.zero_carried(self.sessions.states)
            self._search_fn = jax.jit(_serve_search_reuse)
        else:
            self._search_fn = mcts.search
        # One CachedProgram per ladder rung, all over the SAME jitted
        # function (batch shape is an aval, not a closure): the cache
        # names them serve/b<rung> so flight spans / warm rows / memory
        # sidecars attribute per shape, and a rung switch just swaps
        # which program the dispatch calls — zero tracing, zero
        # recompiles once warmed.
        self._cache = get_compile_cache()
        self._extra = extra
        self._serialize_artifacts = not beacons_armed()
        self._programs: dict[int, object] = {}
        for rung in self.ladder.rungs:
            self._programs[rung] = self._cache.wrap(
                serve_program_name(rung),
                self._search_fn,
                extra=extra,
                serialize=self._serialize_artifacts,
            )
        self._base_rng = jax.random.PRNGKey(rng_seed)
        self._lock = threading.RLock()
        self._queue: deque[int] = deque()  # sids with a pending request
        # sid -> trace-context fields of the request currently driving
        # that session (telemetry/tracectx.py): the replica front end
        # registers them so the serve/b<B> flight bracket can name the
        # exact trace_ids each device wave served.
        self._session_trace: dict[int, dict] = {}
        # Cumulative counters (UtilizationMeter folds deltas).
        self.dispatch_count = 0
        self.requests_total = 0
        self.episodes_done_total = 0
        self.simulations_total = 0
        # Root visits inherited from carried subtrees across all waves
        # (0 unless tree_reuse): simulations + reused = leaf-equivalent
        # search effort (leaf-evals/s in telemetry/perf.py).
        self.reused_visits_total = 0
        self.weight_reloads = 0
        # Per-tick windows (drained by tick()).
        self._win_wait_ms: list[float] = []
        self._win_lat_ms: list[float] = []
        self._win_batch_ms: list[float] = []
        self._win_fill: list[float] = []
        self._win_requests = 0
        self._last_tick_t = clock()
        # (weights_version, reload count, inference-cast variables)
        # memo for _serve_variables (nn/precision.py).
        self._cast_variables: "tuple | None" = None

    # --- warm start / pre-flight --------------------------------------

    @property
    def _search(self):
        """The compiled program for the CURRENT rung (dispatch shape)."""
        return self._programs[self.sessions.slots]

    @property
    def max_slots(self) -> int:
        """The most sessions this service can ever hold (the ladder's
        top rung) — admission planners size against this, not the
        current shape (loadgen)."""
        return self.ladder.max_rung

    def _serve_variables(self):
        """The variables the serve dispatch reads: the net's, cast to
        the inference precision policy (nn/precision.py). Identity
        under f32; under bf16 the cast copy is memoized per
        (weights version, reload count) so steady-state dispatches
        reuse one device-resident copy and a hot reload re-casts."""
        from ..nn.precision import cast_params_for_inference, inference_dtype

        import jax.numpy as jnp

        cfg = self.extractor.model_config
        if inference_dtype(cfg) == jnp.float32:
            return self.net.variables
        key = (self.net.weights_version, self.weight_reloads)
        if self._cast_variables is not None:
            cached_key, cast = self._cast_variables
            if cached_key == key:
                return cast
        cast = cast_params_for_inference(self.net.variables, cfg)
        self._cast_variables = (key, cast)
        return cast

    def _sample_args_for(self, rung: int):
        """Dispatch-identical argument avals at one rung's shape: the
        current slot array when `rung` is the live shape, a frozen
        padding array otherwise (shapes/dtypes are all that matter —
        warm/analyze never execute)."""
        import jax
        import jax.numpy as jnp

        rung = int(rung)
        if rung == self.sessions.slots:
            states = self.sessions.states
            carried = self._carried
        else:
            keys = jax.random.split(
                jax.random.PRNGKey(self._pad_seed), rung
            )
            base = self.env.reset_batch(keys)
            states = base.replace(
                done=jnp.ones((rung,), dtype=base.done.dtype)
            )
            carried = (
                self.mcts.zero_carried(states) if self._tree_reuse else None
            )
        args = (self._serve_variables(), states, jax.random.PRNGKey(0))
        if self._tree_reuse:
            args += (carried, jnp.zeros(rung, dtype=bool))
        return args

    def _sample_args(self):
        return self._sample_args_for(self.sessions.slots)

    def warm(self) -> bool:
        """AOT-ready the serve program for EVERY ladder rung
        (deserialize or compile+serialize, never execute) — `cli
        warm`'s serve rows and `cli serve`'s startup both come through
        here. Warming every rung up front is what makes a mid-stream
        rung switch zero-recompile. True iff every rung is AOT-ready."""
        ok = True
        for rung in self.ladder.rungs:
            ok = self.warm_rung(rung) and ok
        return ok

    def warm_rung(self, rung: int) -> bool:
        """AOT-ready one rung's serve program (warm.py's per-rung
        target rows)."""
        return self._programs[int(rung)].warm(*self._sample_args_for(rung))

    def analyze(
        self, persist: bool = False, rung: "int | None" = None
    ) -> "dict | None":
        """Memory record for the serve program at one rung (default:
        the current shape; AOT analysis, never executed;
        telemetry/memory.py). `persist=True` writes the `.mem.json`
        sidecar beside the executable artifact."""
        r = self.sessions.slots if rung is None else int(rung)
        return self._programs[r].analyze(
            *self._sample_args_for(r), persist=persist
        )

    # --- the bucket ladder (serving/buckets.py) -----------------------

    def _switch_rung(self, new_rung: int, reason: str) -> None:
        """Swap the compiled dispatch shape between dispatches: migrate
        live sessions into a `new_rung`-lane slot array (identity-
        preserving, lowest-old-slot-first), invalidate every carried
        subtree (a promoted tree's static shape belongs to its bucket;
        reuse never crosses shapes), and reset the walk window. Caller
        holds the lock."""
        old = self.sessions.slots
        if new_rung == old:
            return
        self.sessions = self.sessions.migrate(
            new_rung, pad_seed=self._pad_seed
        )
        self._carry_ok = np.zeros(new_rung, dtype=bool)
        if self._tree_reuse:
            self._carried = self.mcts.zero_carried(self.sessions.states)
        self._ladder_fill.clear()
        self.rung_switches += 1
        logger.info(
            "serve: rung switch b%d -> b%d (%s; live=%d queue=%d)",
            old,
            new_rung,
            reason,
            self.sessions.live_count,
            self.queue_depth,
        )

    def _maybe_walk(self) -> None:
        """The windowed walk decision, taken between dispatches (caller
        holds the lock): up when fill sustains at/above the high-water
        mark, down when it sustains at/below the low-water mark AND the
        live sessions fit the smaller shape. Mirrors the fleet
        quarantine's walk-down on the same ladder — quarantine is this
        move, forced."""
        if len(self._ladder_fill) < self.sustain:
            return
        fill = sum(self._ladder_fill) / len(self._ladder_fill)
        rung = self.sessions.slots
        if fill >= self.high_water and rung < self.ladder.max_rung:
            self._switch_rung(
                self.ladder.up(rung), f"fill {fill:.2f} >= high-water"
            )
        elif fill <= self.low_water and rung > self.ladder.min_rung:
            lower = self.ladder.down(rung)
            if self.sessions.live_count <= lower:
                self._switch_rung(
                    lower, f"fill {fill:.2f} <= low-water"
                )

    # --- session lifecycle --------------------------------------------

    def _grow_for(self, needed: int) -> None:
        """Demand-driven walk-up: when an admission would overflow the
        current shape but fits a higher rung, switch BEFORE admitting —
        a burst is never shed while the ladder has headroom (caller
        holds the lock)."""
        demand = self.sessions.live_count + int(needed)
        if self.sessions.free_count >= needed or demand > self.ladder.max_rung:
            return
        target = self.ladder.rung_for(demand)
        if target > self.sessions.slots:
            self._switch_rung(target, f"admission demand {demand}")

    def open_session(self, reset_key=None, seed: "int | None" = None):
        """Admit one session (fresh game). Returns the Session handle.
        Walks the ladder up when the current shape is full but a
        higher rung exists; raises RuntimeError when every slot of the
        TOP rung is occupied."""
        import jax

        if reset_key is None:
            reset_key = jax.random.PRNGKey(0 if seed is None else seed)
        with self._lock:
            self._grow_for(1)
            s = self.sessions.admit(reset_key)
            self._carry_ok[s.slot] = False
            return s

    def open_sessions(self, reset_keys) -> list:
        with self._lock:
            self._grow_for(len(reset_keys))
            admitted = self.sessions.admit_many(reset_keys)
            for s in admitted:
                self._carry_ok[s.slot] = False
            return admitted

    def set_session_trace(self, sid: int, fields: "dict | None") -> None:
        """Attach (or clear) the trace-context fields of the request
        currently driving session `sid` — stamped onto the serve
        dispatch bracket and the session's result dicts."""
        with self._lock:
            if fields:
                self._session_trace[sid] = dict(fields)
            else:
                self._session_trace.pop(sid, None)

    def close_session(self, sid: int) -> dict:
        with self._lock:
            s = self.sessions.session(sid)
            s.pending_since = None
            self._session_trace.pop(sid, None)
            self._carry_ok[s.slot] = False
            summary = self.sessions.retire(sid)
            if sid in self._queue:
                self._queue.remove(sid)
            if self.emitter is not None:
                try:
                    self.emitter.on_session_close(sid, summary)
                except Exception:
                    logger.exception(
                        "trajectory emitter failed closing session %d", sid
                    )
            return summary

    def request_move(self, sid: int) -> None:
        """Enqueue one move request; a session holds at most one
        outstanding request (it is a lockstep game, not a stream)."""
        with self._lock:
            s = self.sessions.session(sid)
            if s.pending_since is not None:
                raise RuntimeError(f"session {sid} already has a pending move")
            s.pending_since = self._clock()
            self._queue.append(sid)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    # --- weights --------------------------------------------------------

    def reload_weights(self, variables=None) -> int:
        """Hot-swap the served net between dispatches (no recompile:
        variables are a program input). `variables=None` records a
        reload performed externally — `Trainer.sync_to_network()`
        already installs a donation-safe copy into the net, and the
        service reads `net.variables` live. Returns the reload count."""
        with self._lock:
            if variables is not None:
                self.net.set_weights(variables)
            self.weight_reloads += 1
            # Carried subtrees were searched under the old net: their
            # interior priors/values no longer match what a fresh
            # search would compute. Reload churn resets every lane to
            # fresh-root (the documented cost of reuse under high
            # reload rates, docs/KERNELS.md).
            self._carry_ok[:] = False
            return self.weight_reloads

    # --- the micro-batch dispatch ---------------------------------------

    def dispatch(self, rng=None) -> list[dict]:
        """Serve every pending request in ONE batched search + step.

        Returns one result dict per served request: action, reward,
        done, score, queue_wait_ms, latency_ms. Empty list when the
        queue is empty (callers idle-wait)."""
        import jax

        with self._lock:
            if not self._queue:
                return []
            served: list = []
            mask = np.zeros(self.sessions.slots, dtype=bool)
            while self._queue:
                s = self.sessions.session(self._queue.popleft())
                mask[s.slot] = True
                served.append(s)
            t0 = self._clock()
            if rng is None:
                rng = jax.random.fold_in(self._base_rng, self.dispatch_count)
            # The trace_ids this wave serves (deduped, order-stable):
            # the flight intent/seal names them so an unsealed serve
            # intent — or the merged fleet timeline — identifies the
            # routed requests that were on the chip.
            wave_trace_ids = list(
                dict.fromkeys(
                    tid
                    for s in served
                    for tid in [
                        self._session_trace.get(s.sid, {}).get("trace_id")
                    ]
                    if tid
                )
            )
            with flight_span(
                self.flight,
                "serve",
                serve_program_name(self.sessions.slots),
                avals=f"b{len(served)}",
                trace=(
                    {"trace_ids": wave_trace_ids} if wave_trace_ids else None
                ),
            ):
                # Chaos hook (docs/ROBUSTNESS.md): env-gated so an
                # unarmed service never imports the fault module. Fires
                # INSIDE the flight bracket — a hang-serve leaves an
                # unsealed serve/b<B> intent (the probe's evidence), a
                # crash-serve seals ok:false and surfaces to the caller.
                if os.environ.get("ALPHATRIANGLE_FAULTS"):
                    from ..supervise.faults import fault_point

                    fault_point(
                        "serve-dispatch",
                        self.dispatch_count,
                        flight_path=getattr(self.flight, "path", None),
                    )
                note_dispatch(serve_program_name(self.sessions.slots))
                reused_d = None
                if self._tree_reuse:
                    import jax.numpy as jnp

                    # Same single dispatch: search seeded with the
                    # carried lanes + fused in-program promotion.
                    out, self._carried, reused_d = self._search(
                        self._serve_variables(),
                        self.sessions.states,
                        rng,
                        self._carried,
                        jnp.asarray(self._carry_ok),
                    )
                else:
                    out = self._search(
                        self._serve_variables(), self.sessions.states, rng
                    )
                actions = select_root_actions(out, self.use_gumbel)
                # The positions the search ran on; the pytree stays
                # valid after step() installs the successor states.
                pre_states = self.sessions.states
                rewards, dones = self.sessions.step(actions, mask)
                # Response materialization: the host sync IS the
                # product here (clients need their move) — ONE fetch
                # per dispatch for all result arrays, not one each.
                fetch = (rewards, dones, self.sessions.states.score)
                if reused_d is not None:
                    fetch += (reused_d,)
                # Serve-wave stat-pack rides the SAME fetch (appended
                # last so the positional `host[3]` reuse access below
                # is untouched) — no extra device_get.
                ds_dev = out.stats if self._device_stats else None
                if ds_dev is not None:
                    fetch += (ds_dev,)
                host = jax.device_get(fetch)  # graftlint: allow(host-sync-in-hot-path) the one deliberate response fetch per dispatch
                rewards_np, dones_np, scores_np = host[:3]
                if ds_dev is not None:
                    ds_fold = fold_search_stats(host[-1])
                    if ds_fold:
                        self._win_device_stats.append(ds_fold)
            t1 = self._clock()

            if self.emitter is not None:
                try:
                    self.emitter.on_dispatch(
                        pre_states,
                        out,
                        served,
                        rewards_np,
                        dones_np,
                        self.weight_reloads,
                    )
                except Exception:
                    logger.exception(
                        "trajectory emitter failed on dispatch %d; "
                        "serving continues",
                        self.dispatch_count,
                    )

            batch_ms = (t1 - t0) * 1e3
            results = []
            for s in served:
                wait_ms = (t0 - s.pending_since) * 1e3
                lat_ms = (t1 - s.pending_since) * 1e3
                s.pending_since = None
                done = bool(dones_np[s.slot])
                if done and not s.done:
                    s.done = True
                    self.episodes_done_total += 1
                s.score = float(scores_np[s.slot])
                result = {
                    "sid": s.sid,
                    "slot": s.slot,
                    "move": s.moves,
                    "action": int(actions[s.slot]),
                    "reward": float(rewards_np[s.slot]),
                    "done": done,
                    "score": s.score,
                    "queue_wait_ms": wait_ms,
                    "latency_ms": lat_ms,
                }
                strace = self._session_trace.get(s.sid)
                if strace and strace.get("trace_id"):
                    result["trace_id"] = strace["trace_id"]
                results.append(result)
                self._win_wait_ms.append(wait_ms)
                self._win_lat_ms.append(lat_ms)
            self.dispatch_count += 1
            self.requests_total += len(results)
            # Device work is the FULL slot array per wave regardless of
            # fill — honest sims accounting for MFU.
            self.simulations_total += (
                self.sessions.slots * self.mcts.config.max_simulations
            )
            if reused_d is not None:
                # Visits the wave inherited instead of re-searching
                # (same full-array accounting as simulations_total).
                self.reused_visits_total += int(host[3].sum())
                # Next wave may reuse only lanes this wave actually
                # advanced (served + stepped) and that didn't finish;
                # unserved lanes were promoted for a move never played.
                self._carry_ok = mask & ~np.asarray(dones_np, dtype=bool)
            self._win_requests += len(results)
            self._win_batch_ms.append(batch_ms)
            fill = len(results) / self.sessions.slots
            self._win_fill.append(fill)
            self._ladder_fill.append(fill)
            # Walk decision BETWEEN dispatches: this wave ran at the
            # old shape; the next one may run at the new.
            self._maybe_walk()
            if self.telemetry is not None:
                self.telemetry.on_rollout(
                    experiences=len(results),
                    episodes=sum(1 for r in results if r["done"]),
                )
            return results

    # --- SLO accounting ---------------------------------------------------

    def serve_stats(self, drain: bool = True) -> dict:
        """The `serve_*` fields for one utilization tick: current
        occupancy + this window's request percentiles. `drain` resets
        the window (the tick cadence).

        Snapshot + reset happen under the service lock — dispatch holds
        the same (reentrant) lock while appending window records, so a
        drain landing mid-dispatch can no longer read the lists and
        then reset them around a concurrent append (the lost-request
        race test_serving pins with a concurrent drainer)."""
        with self._lock:
            return self._serve_stats_locked(drain)

    def _serve_stats_locked(self, drain: bool) -> dict:
        now = self._clock()
        dt = max(1e-9, now - self._last_tick_t)
        snap = self.sessions.snapshot()
        stats = {
            "serve_slots": snap["slots"],
            # The current ladder rung + instantaneous fill gauges
            # (ledger -> Prometheus -> cli perf): serve_bucket tracks
            # the micro-batcher's compiled shape, serve_fill the most
            # recent dispatch's occupancy at that shape.
            "serve_bucket": snap["slots"],
            "serve_fill": (
                round(float(self._win_fill[-1]), 4)
                if self._win_fill
                else None
            ),
            "serve_rung_switches": self.rung_switches,
            "serve_sessions": snap["live"],
            "serve_sessions_admitted": snap["admitted_total"],
            "serve_sessions_retired": snap["retired_total"],
            "serve_queue_depth": self.queue_depth,
            "serve_requests_total": self.requests_total,
            "serve_window_requests": self._win_requests,
            "serve_requests_per_sec": round(self._win_requests / dt, 2),
            "serve_batch_fill": (
                round(float(np.mean(self._win_fill)), 4)
                if self._win_fill
                else None
            ),
            "serve_batch_ms_p50": _pct(self._win_batch_ms, 0.50),
            "serve_batch_ms_p95": _pct(self._win_batch_ms, 0.95),
            "serve_queue_wait_ms_p50": _pct(self._win_wait_ms, 0.50),
            "serve_queue_wait_ms_p95": _pct(self._win_wait_ms, 0.95),
            "serve_move_latency_ms_p50": _pct(self._win_lat_ms, 0.50),
            "serve_move_latency_ms_p95": _pct(self._win_lat_ms, 0.95),
            "serve_weight_reloads": self.weight_reloads,
        }
        if drain:
            # Merge this window's per-wave search folds into one serve
            # leg for tick() (device-stats plane; None when the feature
            # is off or no wave ran this window).
            self._last_serve_ds = merge_search_folds(self._win_device_stats)
            self._win_wait_ms = []
            self._win_lat_ms = []
            self._win_batch_ms = []
            self._win_fill = []
            self._win_requests = 0
            self._win_device_stats = []
            self._last_tick_t = now
        return stats

    def tick(self) -> "dict | None":
        """One telemetry tick: derive + ledger a utilization record
        carrying the serve SLO fields, update the heartbeat. Returns
        the record (None on the baseline tick or without telemetry)."""
        if self.telemetry is None:
            return None
        stats = self.serve_stats(drain=True)
        extra = {k: v for k, v in stats.items() if v is not None}
        serve_ds = getattr(self, "_last_serve_ds", None)
        if serve_ds:
            # Gauge fields for metrics.prom (ledger._PROM_HELP) ride
            # the util record; the full leg lands as a device_stats
            # ledger record below.
            if serve_ds.get("root_entropy") is not None:
                extra["root_visit_entropy"] = serve_ds["root_entropy"]
            if serve_ds.get("occupancy") is not None:
                extra["tree_occupancy"] = serve_ds["occupancy"]
            extra["beacons_armed"] = int(beacons_armed())
        flight = getattr(self.telemetry, "flight", None)
        dispatch_wall = getattr(flight, "sealed_wall_seconds", None)
        record = self.telemetry.on_util_tick(
            step=self.dispatch_count,
            episodes=self.episodes_done_total,
            experiences=self.requests_total,
            simulations=self.simulations_total,
            reused_visits=self.reused_visits_total,
            buffer_size=self.queue_depth,
            dispatch_wall_s=dispatch_wall,
            extra=extra,
        )
        if serve_ds and hasattr(self.telemetry, "record_device_stats"):
            self.telemetry.record_device_stats(
                self.dispatch_count,
                serve=serve_ds,
                program=serve_program_name(self.sessions.slots),
            )
            self._last_serve_ds = None
        self.telemetry.on_tick(
            self.dispatch_count, buffer_size=self.queue_depth
        )
        return record


def build_serve_telemetry(
    run_dir,
    run_name: str,
    env_config,
    model_config,
    telemetry_config=None,
):
    """A RunTelemetry for a serve run: same heartbeat/watchdog/ledger
    stack as training, with a meter whose FLOPs model is the serve
    path's (network forwards only — there is no learner here)."""
    import jax

    from ..telemetry import RunTelemetry
    from ..telemetry.perf import UtilizationMeter
    from ..utils.flops import forward_flops

    device = jax.devices()[0]
    meter = UtilizationMeter(
        forward_flops=forward_flops(
            model_config, env_config, env_config.action_dim
        ),
        train_step_flops=0,
        device_kind=str(getattr(device, "device_kind", device.platform)),
        buffer_capacity=0,
    )
    return RunTelemetry(
        telemetry_config,
        run_dir=run_dir,
        run_name=run_name,
        perf=meter,
    )
