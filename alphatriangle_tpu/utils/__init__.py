"""Utility layer: types, sum tree, helpers, geometry."""

from alphatriangle_tpu.utils.geometry import is_point_in_polygon
from alphatriangle_tpu.utils.helpers import (
    format_eta,
    get_device,
    normalize_color_for_matplotlib,
    set_random_seeds,
)
from alphatriangle_tpu.utils.sumtree import SumTree
from alphatriangle_tpu.utils.types import (
    ActionType,
    DenseBatch,
    Experience,
    PERBatchSample,
    PolicyTargetMapping,
    StateType,
    dense_policy_from_mapping,
    mapping_from_dense_policy,
)

__all__ = [
    "ActionType",
    "DenseBatch",
    "Experience",
    "PERBatchSample",
    "PolicyTargetMapping",
    "StateType",
    "SumTree",
    "dense_policy_from_mapping",
    "format_eta",
    "get_device",
    "is_point_in_polygon",
    "mapping_from_dense_policy",
    "normalize_color_for_matplotlib",
    "set_random_seeds",
]
