"""Utility layer: types, sum tree, helpers, geometry.

The helpers module imports JAX at module level (platform enforcement,
persistent-cache wiring), but this package also hosts `flops.py`, which
JAX-free reader processes (`cli perf/mem/watch/health` beside a wedged
chip) import through here — so the helpers re-exports resolve lazily
(PEP 562) instead of dragging the JAX runtime into every reader.
"""

from alphatriangle_tpu.utils.geometry import is_point_in_polygon
from alphatriangle_tpu.utils.sumtree import SumTree
from alphatriangle_tpu.utils.types import (
    ActionType,
    DenseBatch,
    Experience,
    PERBatchSample,
    PolicyTargetMapping,
    StateType,
    dense_policy_from_mapping,
    mapping_from_dense_policy,
)

_HELPER_EXPORTS = frozenset(
    {
        "format_eta",
        "get_device",
        "normalize_color_for_matplotlib",
        "set_random_seeds",
    }
)


def __getattr__(name: str):
    if name in _HELPER_EXPORTS:
        from alphatriangle_tpu.utils import helpers

        return getattr(helpers, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


__all__ = [
    "ActionType",
    "DenseBatch",
    "Experience",
    "PERBatchSample",
    "PolicyTargetMapping",
    "StateType",
    "SumTree",
    "dense_policy_from_mapping",
    "format_eta",
    "get_device",
    "is_point_in_polygon",
    "mapping_from_dense_policy",
    "normalize_color_for_matplotlib",
    "set_random_seeds",
]
