"""Planar geometry helpers (reference: `alphatriangle/utils/geometry.py:1-44`).

Kept for visualization tooling; not on the training path.
"""


def is_point_in_polygon(
    point: tuple[float, float], polygon: list[tuple[float, float]]
) -> bool:
    """Ray-casting point-in-polygon test (boundary counts as inside)."""
    x, y = point
    n = len(polygon)
    if n < 3:
        return False
    inside = False
    j = n - 1
    for i in range(n):
        xi, yi = polygon[i]
        xj, yj = polygon[j]
        # On-vertex / on-edge quick accept.
        if (xi, yi) == (x, y):
            return True
        # Collinear-on-horizontal-edge: the ray-crossing test below skips
        # edges with yi == yj, so points lying on them need this check.
        if yi == yj == y and min(xi, xj) <= x <= max(xi, xj):
            return True
        if (yi > y) != (yj > y):
            x_cross = (xj - xi) * (y - yi) / (yj - yi) + xi
            if abs(x - x_cross) < 1e-12:
                return True
            if x < x_cross:
                inside = not inside
        j = i
    return inside
