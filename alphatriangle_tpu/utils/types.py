"""Shared type definitions (reference: `alphatriangle/utils/types.py:8-56`).

Two families live here:

- **Parity types** — the per-sample dict/tuple forms the reference uses
  (`StateType`, `Experience`, `PERBatchSample`), kept so the external
  API reads the same.
- **TPU-native batched types** — fixed-shape struct-of-arrays forms used
  on device. XLA wants dense, static shapes, so the reference's
  `dict[int, float]` policy mapping becomes a dense `(action_dim,)`
  vector with zeros at illegal actions.
"""

from typing import TypedDict

import numpy as np


class StateType(TypedDict):
    """NN input for one game state."""

    grid: np.ndarray  # (C, H, W) float32; 1.0 occupied / 0.0 empty / -1.0 death
    other_features: np.ndarray  # (OTHER_NN_INPUT_FEATURES_DIM,) float32


ActionType = int

# Sparse policy target {action: prob} — parity with the reference surface.
PolicyTargetMapping = dict[ActionType, float]

# (state, policy_target, n_step_return)
Experience = tuple[StateType, PolicyTargetMapping, float]


class PERBatchSample(TypedDict):
    """One prioritized sample: batch plus tree bookkeeping."""

    batch: list[Experience]
    indices: np.ndarray  # (B,) int64 tree leaf indices
    weights: np.ndarray  # (B,) float32 importance-sampling weights


class DenseBatch(TypedDict):
    """Fixed-shape training batch, ready for device transfer."""

    grid: np.ndarray  # (B, C, H, W) float32
    other_features: np.ndarray  # (B, F) float32
    policy_target: np.ndarray  # (B, A) float32, rows sum to 1
    value_target: np.ndarray  # (B,) float32 n-step returns
    weights: np.ndarray  # (B,) float32 IS weights (ones if uniform)
    policy_weight: np.ndarray  # (B,) float32 policy-loss mask (PCR)


def dense_policy_from_mapping(mapping: PolicyTargetMapping, action_dim: int) -> np.ndarray:
    """Scatter a sparse {action: prob} mapping into a dense vector."""
    dense = np.zeros(action_dim, dtype=np.float32)
    for a, p in mapping.items():
        if 0 <= a < action_dim:
            dense[a] = p
    return dense


def mapping_from_dense_policy(dense: np.ndarray, eps: float = 0.0) -> PolicyTargetMapping:
    """Inverse of dense_policy_from_mapping; drops entries <= eps."""
    return {int(a): float(p) for a, p in enumerate(dense) if p > eps}
