"""Small shared helpers (reference: `alphatriangle/utils/helpers.py:12-108`)."""

import logging
import os
import random

import jax
import numpy as np

logger = logging.getLogger(__name__)


def enforce_platform(device: str = "auto") -> None:
    """Pin the JAX platform BEFORE any backend initializes.

    `JAX_PLATFORMS=cpu` in the environment is not sufficient on hosts
    whose accelerator plugin ships a sitecustomize that re-forces the
    config value at interpreter startup (observed with the axon TPU
    plugin) — and a wedged TPU then hangs backend init for minutes.
    Re-asserting at the config layer wins as long as no backend has
    been created yet. `device="cpu"` forces CPU; `"auto"` honors an
    explicit `JAX_PLATFORMS=cpu` env request; anything else is a no-op.
    """
    want_cpu = device == "cpu" or (
        device == "auto"
        and os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
    )
    if want_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    # Every runtime entry point passes through here, so it doubles as
    # the hook for the cross-process executable cache; the helper
    # itself skips CPU runs, defers when the backend is still unknown
    # (entry points re-call it with the resolved backend), and honors
    # the opt-out env. An explicit accelerator request counts as a
    # known backend.
    enable_persistent_compilation_cache(
        backend=device if device in ("tpu", "gpu") else None
    )


def enable_persistent_compilation_cache(
    cache_dir: str | None = None,
    backend: str | None = None,
) -> None:
    """Cache compiled XLA executables on disk across processes.

    The flagship self-play program costs ~70s to compile on the
    tunneled TPU; every CLI invocation, bench section, and training-run
    restart used to pay it again. The persistent cache keys serialized
    executables by HLO + backend, so repeat invocations skip straight
    to dispatch. Honors `JAX_COMPILATION_CACHE_DIR` if set; safe to
    call before or after backend init (the cache is consulted per
    compile, not at client creation).

    ACCELERATOR BACKENDS ONLY: XLA:CPU's cached AOT results record
    compile-time tuning pseudo-features (`+prefer-no-scatter`, ...)
    that fail the host feature check on reload, logging SIGILL-risk
    errors — and CPU compiles are cheap anyway. The gate: callers that
    already know the resolved backend pass it via `backend` (skipped on
    'cpu'); without it, a run whose platform is pinned to cpu (env or
    config — the `enforce_platform` pattern) is skipped, and an
    *unpinned* auto run is DEFERRED — an auto run on a CPU-only host
    resolves to the CPU backend, exactly the AOT-reload path the gate
    exists to prevent, so entry points re-call this with
    `backend=jax.default_backend()` once the backend is live.
    """
    if os.environ.get("ALPHATRIANGLE_NO_COMPILE_CACHE") == "1":
        return  # operator opt-out (e.g. suspected stale/corrupt cache)
    if backend is not None:
        if backend.strip().lower() == "cpu":
            return
    else:
        platforms = (
            os.environ.get("JAX_PLATFORMS", "")
            or str(getattr(jax.config, "jax_platforms", None) or "")
        ).strip().lower()
        if platforms == "cpu" or not platforms:
            # Pinned cpu, or unpinned (backend unknown): skip/defer.
            return
    path = (
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or "/tmp/alphatriangle_tpu_jax_cache"
    )
    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception as exc:  # unknown flag on an old jax: not fatal
        logger.warning("persistent compilation cache unavailable: %s", exc)


def get_device(preference: str = "auto") -> jax.Device:
    """Pick the compute device: TPU > GPU > CPU (reference picked CUDA>MPS>CPU).

    An explicit preference that cannot be satisfied raises (RuntimeError
    from `jax.devices(platform)`) — it never silently falls back to CPU.
    """
    if preference not in ("auto", "tpu", "gpu", "cpu"):
        raise ValueError(f"unknown device preference: {preference}")
    if preference != "auto":
        return jax.devices(preference)[0]
    return jax.devices()[0]


def set_random_seeds(seed: int) -> jax.Array:
    """Seed python/numpy and return the root JAX PRNG key.

    JAX randomness is functional: unlike the reference's global
    torch/cuda seeding (`helpers.py:51-77`), all device-side randomness
    flows from this key explicitly.
    """
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def format_eta(seconds: float | None) -> str:
    """Seconds → 'Xd HH:MM:SS' (reference: `helpers.py:80-95`)."""
    if seconds is None or not np.isfinite(seconds) or seconds < 0:
        return "N/A"
    seconds = int(seconds)
    days, rem = divmod(seconds, 86400)
    hours, rem = divmod(rem, 3600)
    minutes, secs = divmod(rem, 60)
    if days > 0:
        return f"{days}d {hours:02d}:{minutes:02d}:{secs:02d}"
    return f"{hours:02d}:{minutes:02d}:{secs:02d}"


def normalize_color_for_matplotlib(color_tuple_0_255: tuple) -> tuple:
    """(r,g,b) in 0..255 → 0..1 floats (reference: `helpers.py:98-108`)."""
    return tuple(max(0.0, min(1.0, c / 255.0)) for c in color_tuple_0_255)
