"""Vectorized array-backed sum tree for prioritized replay.

Functional equivalent of the reference's `SumTree`
(`alphatriangle/utils/sumtree.py:6-98`) with the same surface
(`add`, `update`, `get_leaf`, `total_priority`, `max_priority`) plus
batched variants (`update_batch`, `sample_batch`) — the hot paths the
reference runs in a Python loop (256 sequential `get_leaf` descents per
train step) are here single vectorized NumPy sweeps over tree levels.

Layout: capacity is rounded up to a power of two; `self.tree` stores
internal nodes in [1, cap) and leaves in [cap, 2*cap) (1-indexed heap),
which makes the batched descent a fixed `log2(cap)`-step loop.
"""

import numpy as np


class SumTree:
    """Array sum tree over `capacity` slots holding priorities + data refs."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._cap2 = 1 << (capacity - 1).bit_length()  # power-of-two leaf count
        self.tree = np.zeros(2 * self._cap2, dtype=np.float64)
        self.data: list = [None] * capacity
        self.data_pointer = 0  # ring pointer over [0, capacity)
        self.n_entries = 0
        self._max_priority_seen = 1.0

    # --- writes -----------------------------------------------------------

    def add(self, priority: float, data) -> int:
        """Insert at the ring pointer; returns the slot index used."""
        idx = self.data_pointer
        self.data[idx] = data
        self.update(idx, priority)
        self.data_pointer = (self.data_pointer + 1) % self.capacity
        self.n_entries = min(self.n_entries + 1, self.capacity)
        return idx

    def add_batch(self, priorities: np.ndarray, items: list) -> np.ndarray:
        """Ring-insert a batch; returns slot indices (vectorized update)."""
        k = len(items)
        idxs = (self.data_pointer + np.arange(k)) % self.capacity
        for i, item in zip(idxs, items):
            self.data[int(i)] = item
        self.update_batch(idxs, np.asarray(priorities, dtype=np.float64))
        self.data_pointer = int((self.data_pointer + k) % self.capacity)
        self.n_entries = min(self.n_entries + k, self.capacity)
        return idxs

    def update(self, idx: int, priority: float) -> None:
        self.update_batch(np.asarray([idx]), np.asarray([priority]))

    def update_batch(self, idxs: np.ndarray, priorities: np.ndarray) -> None:
        """Set priorities for slots `idxs`, propagating sums level-by-level.

        Duplicate indices are resolved last-write-wins before propagation
        (the reference's sequential loop has the same net effect).
        """
        idxs = np.asarray(idxs, dtype=np.int64)
        priorities = np.asarray(priorities, dtype=np.float64)
        if len(idxs) == 0:
            return
        if np.any(priorities < 0) or not np.all(np.isfinite(priorities)):
            raise ValueError("priorities must be finite and non-negative")
        # Last-write-wins dedupe.
        if len(idxs) > 1:
            _, last = np.unique(idxs[::-1], return_index=True)
            keep = len(idxs) - 1 - last
            idxs, priorities = idxs[keep], priorities[keep]
        self._max_priority_seen = max(
            self._max_priority_seen, float(priorities.max(initial=0.0))
        )
        nodes = idxs + self._cap2
        self.tree[nodes] = priorities
        nodes = np.unique(nodes >> 1)
        while nodes[0] >= 1:
            left = self.tree[2 * nodes]
            right = self.tree[2 * nodes + 1]
            self.tree[nodes] = left + right
            if nodes[0] == 1:
                break
            nodes = np.unique(nodes >> 1)

    # --- reads ------------------------------------------------------------

    @property
    def total_priority(self) -> float:
        return float(self.tree[1])

    @property
    def max_priority(self) -> float:
        """Max priority ever seen (1.0 before any update), for new-item init."""
        return float(self._max_priority_seen)

    def get_leaf(self, value: float) -> tuple[int, float, object]:
        """Prefix-sum descent for one value → (slot, priority, data)."""
        idx, prio = self.get_leaves(np.asarray([value]))
        i = int(idx[0])
        return i, float(prio[0]), self.data[i]

    def get_leaves(self, values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized descent: (K,) prefix values → (slots, priorities)."""
        values = np.asarray(values, dtype=np.float64).copy()
        if len(values) == 0:
            return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float64)
        nodes = np.ones(len(values), dtype=np.int64)
        while nodes[0] < self._cap2:
            left = 2 * nodes
            left_sum = self.tree[left]
            go_right = values > left_sum
            values = np.where(go_right, values - left_sum, values)
            nodes = np.where(go_right, left + 1, left)
        slots = nodes - self._cap2
        # Guard against float drift landing on an out-of-range/empty slot.
        slots = np.clip(slots, 0, max(self.n_entries - 1, 0))
        return slots, self.tree[slots + self._cap2]

    def sample_batch(
        self, k: int, rng: np.random.Generator
    ) -> tuple[np.ndarray, np.ndarray]:
        """Stratified proportional sampling of k slots → (slots, priorities)."""
        total = self.total_priority
        if total <= 0 or self.n_entries == 0:
            raise ValueError("cannot sample from an empty tree")
        edges = np.linspace(0.0, total, k + 1)
        values = rng.uniform(edges[:-1], edges[1:])
        return self.get_leaves(values)

    def __len__(self) -> int:
        return self.n_entries
