"""Analytic FLOP accounting + MFU for the benchmark and profiler.

The reference publishes no utilization numbers at all; BASELINE.md's
throughput rows say nothing about how much of the chip they use. This
module turns ModelConfig/EnvConfig into an analytic forward FLOP count
(matmul/conv terms only — norms, activations and elementwise adds are
bandwidth, not FLOP, bound on TPU) so `bench.py` can report achieved
TFLOP/s and %-of-peak (MFU) next to every games/h row.

Conventions:
- 1 MAC = 2 FLOPs.
- A backward pass costs ~2x the forward matmul FLOPs (grad wrt inputs
  + grad wrt weights), so a train step is ~3x forward; `nn.remat`
  recomputes the forward once more (~4x). `train_step_flops` applies
  the right multiplier from ModelConfig.REMAT.
- Peak table covers the chips this framework targets; unknown device
  kinds return None and the bench reports MFU as null rather than
  guessing.
"""

import logging
import os

from ..config.env_config import EnvConfig
from ..config.model_config import ModelConfig

logger = logging.getLogger(__name__)

# Operator-supplied peak override: lets CPU/smoke runs (and chips not
# yet in the table) still produce an MFU ratio instead of null — the
# denominator is then whatever the operator declares, recorded as
# peak_source="env" wherever the number is published.
PEAK_TFLOPS_ENV = "ALPHATRIANGLE_PEAK_TFLOPS"


def _conv2d_flops(h: int, w: int, cin: int, cout: int, k: int, s: int) -> int:
    """SAME-padded k x k conv at stride s over (h, w): 2*HWK^2*Cin*Cout."""
    ho = -(-h // s)
    wo = -(-w // s)
    return 2 * ho * wo * k * k * cin * cout


def forward_flops(model: ModelConfig, env: EnvConfig, action_dim: int) -> int:
    """Matmul/conv FLOPs of ONE forward pass of `AlphaTriangleNet`
    (nn/model.py) for ONE example."""
    h, w = env.ROWS, env.COLS
    total = 0

    # Conv trunk.
    cin = model.GRID_INPUT_CHANNELS
    for f, k, s in zip(
        model.CONV_FILTERS, model.CONV_KERNEL_SIZES, model.CONV_STRIDES
    ):
        total += _conv2d_flops(h, w, cin, f, k, s)
        h, w = -(-h // s), -(-w // s)
        cin = f

    # Residual stack (+ 1x1 adapter when widths differ).
    if model.NUM_RESIDUAL_BLOCKS > 0:
        rf = model.RESIDUAL_BLOCK_FILTERS
        if cin != rf:
            total += _conv2d_flops(h, w, cin, rf, 1, 1)
            cin = rf
        total += model.NUM_RESIDUAL_BLOCKS * 2 * _conv2d_flops(
            h, w, rf, rf, 3, 1
        )

    # Transformer over the S = h*w token sequence.
    if model.USE_TRANSFORMER and model.TRANSFORMER_LAYERS > 0:
        d = model.TRANSFORMER_DIM
        if cin != d:
            total += _conv2d_flops(h, w, cin, d, 1, 1)
            cin = d
        s_len = h * w
        per_layer = (
            4 * 2 * s_len * d * d  # Q, K, V, out projections
            + 2 * 2 * s_len * s_len * d  # QK^T and attn @ V
            + 2 * 2 * s_len * d * model.TRANSFORMER_FC_DIM  # MLP in + out
        )
        total += model.TRANSFORMER_LAYERS * per_layer

    # Heads over the flattened features (+ the auxiliary scalar input).
    flat = h * w * cin + model.OTHER_NN_INPUT_FEATURES_DIM
    dim = flat
    for fc in model.FC_DIMS_SHARED:
        total += 2 * dim * fc
        dim = fc
    for dims, out in (
        (model.POLICY_HEAD_DIMS, action_dim),
        (model.VALUE_HEAD_DIMS, model.NUM_VALUE_ATOMS),
    ):
        hd = dim
        for fc in dims:
            total += 2 * hd * fc
            hd = fc
        total += 2 * hd * out
    return total


def train_step_flops(
    model: ModelConfig, env: EnvConfig, action_dim: int, batch: int
) -> int:
    """Matmul FLOPs of one SGD step on a `batch`: forward + ~2x
    backward (+1x forward recompute under REMAT)."""
    mult = 4 if model.REMAT else 3
    return mult * batch * forward_flops(model, env, action_dim)


def gather_einsum_flops(batch: int, wave: int, nodes: int, width: int) -> int:
    """FLOPs of ONE einsum descent row-gather (`ops/gather_rows.py`):
    (B, W, N) one-hot x (B, N, K). The take/pallas lowerings do the
    same row select with zero matmul FLOPs."""
    return 2 * batch * wave * nodes * width


# Peak dense bf16 matmul throughput per chip, TFLOP/s. Public figures:
# v4 275, v5e (v5 lite) 394, v5p 459, v6e (Trillium) 918.
_PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 394.0,
    "TPU v5e": 394.0,
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def peak_bf16_tflops_info(device_kind: str) -> tuple[float | None, str]:
    """(peak bf16 TFLOP/s, source) for a `jax.Device.device_kind`.

    Source is "env" (ALPHATRIANGLE_PEAK_TFLOPS override — wins so
    operators can assert a denominator for unlisted chips or CPU
    smokes), "table" (known chip), or "unknown" (peak None — an
    explicit marker, never a guessed denominator).
    """
    override = os.environ.get(PEAK_TFLOPS_ENV, "").strip()
    if override:
        try:
            value = float(override)
            if value > 0:
                return value, "env"
            logger.warning(
                "%s=%r is not positive; ignoring.", PEAK_TFLOPS_ENV, override
            )
        except ValueError:
            logger.warning(
                "%s=%r is not a number; ignoring.", PEAK_TFLOPS_ENV, override
            )
    kind = (device_kind or "").strip()
    if kind in _PEAK_BF16_TFLOPS:
        return _PEAK_BF16_TFLOPS[kind], "table"
    # Longest-prefix fallback, space-insensitive: device kinds vary
    # across runtime versions ("TPU v5 lite" vs "TPU v5litepod-8").
    norm = kind.lower().replace(" ", "")
    best = None
    for name, peak in _PEAK_BF16_TFLOPS.items():
        key = name.lower().replace(" ", "")
        if norm.startswith(key) and (best is None or len(key) > best[0]):
            best = (len(key), peak)
    if best:
        return best[1], "table"
    return None, "unknown"


def peak_bf16_tflops(device_kind: str) -> float | None:
    """Peak bf16 TFLOP/s for a `jax.Device.device_kind`, or None
    (honors the ALPHATRIANGLE_PEAK_TFLOPS override)."""
    return peak_bf16_tflops_info(device_kind)[0]


def mfu(achieved_flops_per_sec: float, device_kind: str) -> float | None:
    """Fraction of the chip's bf16 peak actually achieved, or None for
    unknown hardware (never guess a denominator)."""
    peak = peak_bf16_tflops(device_kind)
    if peak is None or achieved_flops_per_sec <= 0:
        return None
    return achieved_flops_per_sec / (peak * 1e12)
