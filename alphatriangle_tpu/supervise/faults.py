"""Fault injection for the chaos harness (docs/ROBUSTNESS.md).

Armed entirely by environment (no config plumbing — the point is that
production code paths are exercised untouched):

    ALPHATRIANGLE_FAULTS="hang-dispatch@after=6,sigterm@step=3"
    ALPHATRIANGLE_FAULT_STATE_DIR=/tmp/faults   # once-per-run sentinels

Spec grammar: comma-separated `name@key=N` entries. The key names the
trigger counter (`after` = call ordinal at the site, `step` = global
training step); the threshold fires on `>=` so a skipped step can't
dodge a fault. Every fault fires AT MOST ONCE per state dir — the
sentinel file survives a supervised restart, which is exactly what lets
`make chaos-smoke` assert "injected wedge -> restart -> completes":
the restarted child sees the sentinel and runs clean.

Faults and their hook sites (all hooks are env-gated lazy imports in
the production modules, so an unarmed process never touches this file):

    hang-dispatch@after=N   flight.FlightRecorder.begin — block the
                            dispatching thread past the watchdog
                            deadline; dies by real `os._exit(113)`
    corrupt-ring@after=N    same site — append a torn record to
                            flight.jsonl (tolerant-reader drill)
    sigterm@step=N          loop._record_step — deliver SIGTERM to
                            self (preemption drill)
    sigkill@step=N          same site — SIGKILL, no cleanup at all
    crash@step=N            same site — raise RuntimeError
    sigkill-save@step=N     persistence.save, after the async Orbax
                            dispatch + meta write but BEFORE the commit
                            marker — the torn-checkpoint drill
    hang-serve@after=N      serving.PolicyService.dispatch — block the
                            serve dispatch inside its flight bracket
                            (unsealed `serve/b<B>` intent; the replica's
                            watchdog fires 113, the fleet re-routes)
    crash-serve@after=N     same site — raise RuntimeError inside the
                            bracket (seals ok:false, replica survives)

JAX-free (stdlib only): imported by telemetry + the supervisor parent.
"""

import logging
import os
import signal
import time
from pathlib import Path

logger = logging.getLogger(__name__)

FAULTS_ENV = "ALPHATRIANGLE_FAULTS"
FAULT_STATE_DIR_ENV = "ALPHATRIANGLE_FAULT_STATE_DIR"

#: site -> fault names it can fire (anything else in the spec is
#: ignored at that site).
SITE_FAULTS = {
    "dispatch": ("hang-dispatch", "corrupt-ring"),
    "step": ("sigterm", "sigkill", "crash"),
    "checkpoint-save": ("sigkill-save",),
    "serve-dispatch": ("hang-serve", "crash-serve"),
}

# A hung dispatch must die by watchdog, not hang forever if the
# watchdog is misconfigured/off; past the cap the fault aborts loudly.
_HANG_CAP_S = 180.0

_parse_cache: "tuple[str, dict[str, int]] | None" = None
_fired_in_process: set[str] = set()


def parse_spec(spec: str) -> dict[str, int]:
    """`"hang-dispatch@after=6,sigterm@step=3"` -> {name: threshold}.
    Malformed entries are skipped with a warning, never raised — a typo
    in a chaos env var must not change the run's control flow."""
    out: dict[str, int] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            name, cond = entry.split("@", 1)
            _key, value = cond.split("=", 1)
            out[name.strip()] = int(value)
        except ValueError:
            logger.warning("Unparseable fault spec entry %r; ignoring", entry)
    return out


def _armed_faults() -> dict[str, int]:
    global _parse_cache
    spec = os.environ.get(FAULTS_ENV, "")
    if _parse_cache is None or _parse_cache[0] != spec:
        _parse_cache = (spec, parse_spec(spec))
    return _parse_cache[1]


def _claim(name: str) -> bool:
    """Atomically claim the once-per-run sentinel for `name`. With no
    state dir the claim is once-per-process only."""
    state_dir = os.environ.get(FAULT_STATE_DIR_ENV)
    if not state_dir:
        if name in _fired_in_process:
            return False
        _fired_in_process.add(name)
        return True
    path = Path(state_dir) / f"{name}.fired"
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.close(fd)
        return True
    except FileExistsError:
        return False
    except OSError:
        logger.exception("fault sentinel claim failed for %s", name)
        return False


def fault_point(
    site: str, n: int, flight_path: "Path | str | None" = None
) -> None:
    """Evaluate the armed faults for `site` at counter value `n` and
    fire any whose threshold is reached (once per state dir each)."""
    armed = _armed_faults()
    if not armed:
        return
    for name in SITE_FAULTS.get(site, ()):
        threshold = armed.get(name)
        if threshold is None or n < threshold or not _claim(name):
            continue
        logger.error("FAULT %s firing at %s=%d", name, site, n)
        if name in ("hang-dispatch", "hang-serve"):
            _hang()
        elif name == "crash-serve":
            raise RuntimeError(
                f"injected serve-dispatch crash at dispatch {n}"
            )
        elif name == "corrupt-ring":
            _corrupt_ring(flight_path)
        elif name == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif name == "sigkill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif name == "sigkill-save":
            os.kill(os.getpid(), signal.SIGKILL)
        elif name == "crash":
            raise RuntimeError(f"injected crash fault at step {n}")


def _hang() -> None:
    """Block this thread like a wedged device program: the armed
    DispatchWatchdog is expected to fire `os._exit(113)` mid-sleep."""
    deadline = time.monotonic() + _HANG_CAP_S
    while time.monotonic() < deadline:
        time.sleep(0.05)
    raise RuntimeError(
        "hang-dispatch fault outlived its cap without the dispatch "
        "watchdog firing — is the watchdog disabled?"
    )


def _corrupt_ring(flight_path: "Path | str | None") -> None:
    """Append a torn (newline-less, truncated-JSON) record to the
    flight ring, mimicking a kill mid-append; the tolerant readers must
    skip it without losing the rest of the ring."""
    if flight_path is None:
        return
    try:
        with open(flight_path, "ab") as f:
            f.write(b'{"kind": "flight", "phase": "inte')
    except OSError:
        logger.exception("corrupt-ring fault could not write %s", flight_path)
