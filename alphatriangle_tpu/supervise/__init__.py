"""Self-healing supervision: verdict-driven restarts + fault injection.

JAX-free by contract (the parent must outlive a wedged chip). See
docs/ROBUSTNESS.md for the policy matrix and fault knobs.
"""

from .policy import (
    QUARANTINE_OVERRIDES,
    Action,
    RecoveryPolicy,
)
from .supervisor import (
    OVERRIDES_ENV,
    SUPERVISOR_FILENAME,
    Supervisor,
    diagnose,
    latest_committed_step,
    supervise_command,
)

__all__ = [
    "Action",
    "OVERRIDES_ENV",
    "QUARANTINE_OVERRIDES",
    "RecoveryPolicy",
    "SUPERVISOR_FILENAME",
    "Supervisor",
    "diagnose",
    "latest_committed_step",
    "supervise_command",
]
