"""Self-healing run supervisor (`cli supervise`).

A JAX-free parent that runs a training (or league) child, classifies
every death with the same evidence `cli doctor` reads, and applies the
`RecoveryPolicy` verdict->action matrix: restart from the latest
committed checkpoint with backoff, degrade/quarantine knobs, or give
up with `SUPERVISOR_GIVEUP_EXIT_CODE` when the chip is permanently
sick. Podracer-style (arXiv:2104.06272): preemptible accelerators are
the NORMAL case, so checkpoint-restart is the availability story, not
an operator heroic.

Everything is logged to `runs/<run>/supervisor.jsonl` as crash-safe
one-line events (`MetricsLedger` append discipline): spawn, death
(with verdict + evidence + the action taken), give-up, complete.
`tpu_watch.sh` archives the file per window and windows.jsonl keeps
the death->verdict->restart chain forever.

JAX-free contract: like `cli doctor`, this module must keep working
beside a wedged chip — it imports only stdlib + the telemetry readers
+ the policy. The child is where JAX lives. (Pinned by the import
guard in benchmarks/chaos_smoke.py.)
"""

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

from ..telemetry.flight import (
    FLIGHT_FILENAME,
    PREEMPT_EXIT_CODE,
    PREEMPT_REPORT_FILENAME,
    SUPERVISOR_GIVEUP_EXIT_CODE,
    WEDGE_REPORT_FILENAME,
    WEDGE_STACKS_FILENAME,
    classify_run,
    read_flight,
    read_preempt_report,
    read_wedge_report,
)
from ..telemetry import tracectx
from ..telemetry.ledger import MetricsLedger, read_ledger, resolve_ledger_path
from .policy import Action, RecoveryPolicy

logger = logging.getLogger(__name__)

SUPERVISOR_FILENAME = "supervisor.jsonl"

#: Env var carrying the accumulated recovery overrides to the child
#: (JSON object; applied by training/runner.py onto TrainConfig).
OVERRIDES_ENV = "ALPHATRIANGLE_SUPERVISE_OVERRIDES"


def latest_committed_step(run_dir: Path | str) -> "int | None":
    """Newest trustworthy checkpoint step in a run dir, read straight
    off the filesystem (this parent must stay JAX-free, so it cannot
    import stats.persistence — same marker semantics though: commit
    markers when the run has any, meta-parseable step dirs otherwise)."""
    ckpt_dir = Path(run_dir) / "checkpoints"
    if not ckpt_dir.is_dir():
        return None
    committed = set()
    for p in ckpt_dir.glob("step_*.commit"):
        stem = p.name[len("step_"):-len(".commit")]
        if stem.isdigit():
            committed.add(int(stem))
    if committed:
        return max(committed)
    steps = []
    for p in ckpt_dir.iterdir():
        if not (p.is_dir() and p.name.startswith("step_")):
            continue
        suffix = p.name[len("step_"):]
        if not suffix.isdigit():
            continue
        meta = ckpt_dir / f"{p.name}.meta.json"
        try:
            json.loads(meta.read_text())
        except (OSError, ValueError):
            continue
        steps.append(int(suffix))
    return max(steps) if steps else None


def diagnose(run_dir: Path | str, since: float = 0.0) -> dict:
    """`cli doctor`'s classification over the run dir's evidence,
    restricted to records from the current attempt (`since`, an epoch
    time): a prior attempt's torn intent or stale heartbeat must not
    pollute the verdict for THIS death."""
    run_dir = Path(run_dir)
    flight = [
        r
        for r in read_flight(run_dir / FLIGHT_FILENAME)
        if float(r.get("time") or 0.0) >= since
    ]
    health = None
    try:
        payload = json.loads((run_dir / "health.json").read_text())
        if (
            isinstance(payload, dict)
            and float(payload.get("time") or 0.0) >= since
        ):
            health = payload
    except (OSError, ValueError):
        pass
    ledger = resolve_ledger_path(run_dir)
    utils = [
        r
        for r in (read_ledger(ledger, kinds={"util"}) if ledger else [])
        if float(r.get("time") or 0.0) >= since
    ]
    wedge = read_wedge_report(run_dir / WEDGE_REPORT_FILENAME)
    if wedge is not None and float(wedge.get("time") or 0.0) < since:
        wedge = None
    preempt = read_preempt_report(run_dir / PREEMPT_REPORT_FILENAME)
    if preempt is not None and float(preempt.get("time") or 0.0) < since:
        preempt = None
    return classify_run(
        flight, health=health, utils=utils, wedge=wedge, preempt=preempt
    )


class Supervisor:
    """Spawn/classify/recover loop around one child command.

    `popen` and `sleep` are injectable for tests; the production path
    is `subprocess.Popen` + `time.sleep`.
    """

    def __init__(
        self,
        child_argv: list[str],
        run_dir: Path | str,
        policy: "RecoveryPolicy | None" = None,
        *,
        popen=subprocess.Popen,
        sleep=time.sleep,
        now=time.time,
    ) -> None:
        self.child_argv = list(child_argv)
        self.run_dir = Path(run_dir)
        self.policy = policy or RecoveryPolicy()
        self._popen = popen
        self._sleep = sleep
        self._now = now
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self._ledger = MetricsLedger(self.run_dir / SUPERVISOR_FILENAME)
        # Supervision-lifetime root trace (telemetry/tracectx.py):
        # every attempt gets a child context, stamped on its
        # supervisor.jsonl events and handed to the child via the
        # traceparent env seam (its flight ring adopts it), so one
        # trace_id links a spawn to everything that attempt dispatched.
        self.trace_ctx = tracectx.mint(parent=tracectx.from_env())
        self._attempt_ctx: "tracectx.TraceContext | None" = None
        self._child = None
        self._terminating = False

    # --- events -----------------------------------------------------------

    def _event(self, event: str, **fields) -> None:
        ctx = self._attempt_ctx or self.trace_ctx
        self._ledger.append(
            {
                "kind": "supervisor",
                "event": event,
                "time": self._now(),
                "pid": os.getpid(),
                **ctx.fields(),
                **fields,
            }
        )

    # --- signals ----------------------------------------------------------

    def _forward_signal(self, signum, frame) -> None:
        self._terminating = True
        child = self._child
        self._event("forward-signal", signum=int(signum))
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signum)
            except OSError:
                pass

    # --- restart hygiene --------------------------------------------------

    def _archive_attempt_reports(self, attempt: int) -> None:
        """Move the one-shot report files aside so the next attempt's
        diagnosis can't read this attempt's death certificate."""
        for name in (
            WEDGE_REPORT_FILENAME,
            PREEMPT_REPORT_FILENAME,
            WEDGE_STACKS_FILENAME,
        ):
            path = self.run_dir / name
            if path.exists():
                try:
                    os.replace(path, self.run_dir / f"{name}.attempt{attempt}")
                except OSError:
                    pass

    # --- main loop --------------------------------------------------------

    def run(self) -> int:
        """Supervise until the child completes (0), the policy gives up
        (115), or a forwarded SIGTERM/SIGINT ends the window (child's
        own exit code, normally 114)."""
        overrides: dict = {}
        installed = threading.current_thread() is threading.main_thread()
        prev_handlers = {}
        if installed:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev_handlers[sig] = signal.signal(sig, self._forward_signal)
        attempt = 0
        try:
            while True:
                attempt += 1
                self._attempt_ctx = self.trace_ctx.child()
                env = tracectx.child_env(self._attempt_ctx)
                if overrides:
                    env[OVERRIDES_ENV] = json.dumps(overrides)
                spawn_t = self._now()
                self._event(
                    "spawn",
                    attempt=attempt,
                    argv=self.child_argv,
                    overrides=overrides,
                )
                self._child = self._popen(self.child_argv, env=env)
                rc = self._child.wait()
                self._child = None
                if self._terminating:
                    self._event("terminated", attempt=attempt, rc=rc)
                    return rc if rc else PREEMPT_EXIT_CODE
                if rc == 0:
                    self._event("complete", attempt=attempt)
                    return 0
                verdict = diagnose(self.run_dir, since=spawn_t)
                progress = latest_committed_step(self.run_dir)
                action = self.policy.decide(
                    verdict=verdict["verdict"],
                    exit_code=rc,
                    family=verdict.get("family"),
                    progress_step=progress,
                )
                self._event(
                    "death",
                    attempt=attempt,
                    rc=rc,
                    verdict=verdict["verdict"],
                    program=verdict.get("program"),
                    family=verdict.get("family"),
                    detail=verdict.get("detail"),
                    progress_step=progress,
                    action=action.kind,
                    delay_s=action.delay_s,
                    overrides=action.overrides,
                    reason=action.reason,
                )
                logger.warning(
                    "child died (rc=%d, verdict=%s, progress=%s) -> %s: %s",
                    rc,
                    verdict["verdict"],
                    progress,
                    action.kind,
                    action.reason,
                )
                if action.kind != "restart":
                    self._event("give-up", reason=action.reason)
                    return SUPERVISOR_GIVEUP_EXIT_CODE
                self._archive_attempt_reports(attempt)
                overrides = action.overrides
                if action.delay_s > 0:
                    self._sleep(action.delay_s)
        finally:
            if installed:
                for sig, handler in prev_handlers.items():
                    signal.signal(sig, handler)


def supervise_command(
    child_argv: list[str],
    run_dir: Path | str,
    policy: "RecoveryPolicy | None" = None,
) -> int:
    """Convenience wrapper for `cli supervise`."""
    return Supervisor(child_argv, run_dir, policy=policy).run()
