"""Verdict-driven recovery policy (the supervisor's brain).

Pure and process-free: `RecoveryPolicy.decide` maps one child death —
a `cli doctor` verdict + exit code + the run's checkpoint progress —
to one `Action` (restart with delay/overrides, or give up). All state
(backoff streak, restart budget, per-family wedge counts, the OOM
degrade ladder) lives here so tests drive the whole matrix with an
injectable clock and zero subprocesses (tests/test_supervise.py).

Verdict -> action matrix (docs/ROBUSTNESS.md):

    wedge (113 / dispatch-hung / compile-hung)
        restart from latest checkpoint, exponential backoff; the
        SECOND wedge on the same program family quarantines that
        family's riskiest knob (megastep -> sync mode, learner ->
        fused K=1, rollout -> sync rollouts)
    oom             restart with a degraded knob: halve
                    SELF_PLAY_BATCH_SIZE each time (floor 1); from the
                    second OOM also force FUSED_LEARNER_STEPS=1
    preempted (114) restart at base delay; a preemption is external,
                    so it resets the backoff streak
    anything else   restart with exponential backoff

    circuit breaker: consecutive deaths with NO new committed
    checkpoint between them, or total deaths past the restart budget,
    -> give up with SUPERVISOR_GIVEUP_EXIT_CODE (115).

Overrides accumulate across restarts (a quarantined megastep stays
quarantined) and are delivered to the child as JSON in
`ALPHATRIANGLE_SUPERVISE_OVERRIDES` (training/runner.py applies them
through the TrainConfig constructor). `<FIELD>__scale` keys multiply
the child's current value instead of replacing it.
"""

import time
from dataclasses import dataclass, field

from ..telemetry.flight import (  # noqa: F401  (re-exported for callers)
    PREEMPT_EXIT_CODE,
    SUPERVISOR_GIVEUP_EXIT_CODE,
    WEDGE_EXIT_CODE,
)

#: Verdicts that mean "a device program hung" — the family counts
#: toward quarantine.
WEDGE_VERDICTS = ("dispatch-hung", "compile-hung")

#: program family -> the override that removes that family's riskiest
#: moving part. Applied after `quarantine_after` wedges on the family.
QUARANTINE_OVERRIDES: dict[str, dict] = {
    "megastep": {"FUSED_MEGASTEP": False},
    "learner": {"FUSED_LEARNER_STEPS": 1},
    "rollout": {"ASYNC_ROLLOUTS": False},
    # Serve replicas: halve the compiled serve bucket. Interpreted by
    # the fleet supervisor (serving/fleet.py maps it onto the replica's
    # --slots argv), not by TrainConfig — a smaller bucket is the
    # degraded fallback docs/SERVING.md "Fleet" describes.
    "serve": {"SERVE_SLOTS__scale": 0.5},
}


@dataclass
class Action:
    """One recovery decision for one child death."""

    kind: str  # "restart" | "give-up"
    delay_s: float = 0.0
    overrides: dict = field(default_factory=dict)
    reason: str = ""


class RecoveryPolicy:
    """Stateful verdict->action mapper. One instance per supervised
    run; `clock` is injectable so tests freeze time."""

    def __init__(
        self,
        *,
        max_restarts: int = 8,
        circuit_breaker_deaths: int = 3,
        backoff_base_s: float = 5.0,
        backoff_max_s: float = 300.0,
        quarantine_after: int = 2,
        oom_scale: float = 0.5,
        clock=time.monotonic,
    ) -> None:
        self.max_restarts = max_restarts
        self.circuit_breaker_deaths = circuit_breaker_deaths
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.quarantine_after = quarantine_after
        self.oom_scale = oom_scale
        self._clock = clock
        self.deaths = 0
        self.streak = 0  # consecutive deaths without checkpoint progress
        self._last_progress: "int | None" = None
        self._family_wedges: dict[str, int] = {}
        self._oom_count = 0
        self._overrides: dict = {}
        self.history: list[dict] = []

    def decide(
        self,
        verdict: str,
        exit_code: int,
        family: "str | None" = None,
        progress_step: "int | None" = None,
    ) -> Action:
        """Record one child death and return the recovery action.

        `progress_step` is the newest COMMITTED checkpoint step in the
        run dir — forward motion between deaths is what resets the
        backoff streak and holds the circuit breaker open.
        """
        self.deaths += 1
        progressed = progress_step is not None and (
            self._last_progress is None or progress_step > self._last_progress
        )
        preempted = verdict == "preempted" or exit_code == PREEMPT_EXIT_CODE
        if progressed or preempted:
            self.streak = 1
        else:
            self.streak += 1
        if progress_step is not None:
            self._last_progress = progress_step
        self.history.append(
            {
                "t": self._clock(),
                "verdict": verdict,
                "exit_code": exit_code,
                "family": family,
                "progress_step": progress_step,
            }
        )

        if self.deaths > self.max_restarts:
            return Action(
                kind="give-up",
                reason=f"restart budget exhausted ({self.deaths - 1} "
                f"restarts > {self.max_restarts})",
            )
        if self.streak > self.circuit_breaker_deaths:
            return Action(
                kind="give-up",
                reason=f"circuit breaker: {self.streak} consecutive "
                "deaths without a new committed checkpoint",
            )

        reasons: list[str] = []
        wedged = verdict in WEDGE_VERDICTS or exit_code == WEDGE_EXIT_CODE
        if wedged:
            # A wedge respawn rebuilds every program anyway, so build
            # them beacon-armed: if the SAME wedge recurs the next
            # wedge_report / doctor verdict names its phase
            # (telemetry/device_stats.py). `TELEMETRY__` keys are
            # reserved telemetry directives — the runner pops them
            # before TrainConfig construction.
            if not self._overrides.get("TELEMETRY__BEACONS"):
                self._overrides["TELEMETRY__BEACONS"] = True
                reasons.append(
                    "arming progress beacons for the respawn (a repeat "
                    "wedge will name its phase)"
                )
        if wedged and family:
            count = self._family_wedges.get(family, 0) + 1
            self._family_wedges[family] = count
            if count >= self.quarantine_after:
                quarantine = QUARANTINE_OVERRIDES.get(family)
                if quarantine:
                    self._overrides.update(quarantine)
                    reasons.append(
                        f"quarantined family '{family}' after {count} "
                        f"wedges ({quarantine})"
                    )
        if verdict == "oom":
            self._oom_count += 1
            scale = self.oom_scale**self._oom_count
            self._overrides["SELF_PLAY_BATCH_SIZE__scale"] = scale
            reasons.append(
                f"oom #{self._oom_count}: scaling SELF_PLAY_BATCH_SIZE "
                f"by {scale:g}"
            )
            if self._oom_count >= 2:
                self._overrides["FUSED_LEARNER_STEPS"] = 1
                reasons.append("oom repeat: forcing FUSED_LEARNER_STEPS=1")

        delay = min(
            self.backoff_max_s,
            self.backoff_base_s * 2 ** (self.streak - 1),
        )
        reasons.append(
            f"backoff {delay:g}s (streak {self.streak}, "
            f"death {self.deaths}/{self.max_restarts})"
        )
        return Action(
            kind="restart",
            delay_s=delay,
            overrides=dict(self._overrides),
            reason="; ".join(reasons),
        )
