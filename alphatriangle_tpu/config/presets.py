"""The five BASELINE benchmark configurations as first-class presets.

BASELINE.md lists the driver-mandated configs to measure (derived from
BASELINE.json; the reference publishes no numbers of its own):

1. Default TrainConfig, CNN-only net, 50 MCTS sims — CPU smoke.
2. CNN-only net, 200 MCTS sims, batched leaf-eval on one TPU core.
3. CNN + 4-layer TransformerEncoder, dp learner on v4-8 — the
   ≥10k-games/hour north-star config.
4. Distributional (C51) value head, 400 MCTS sims, v4-8.
5. Large board + 8-layer Transformer, v5p-16.

The reference's "N self-play workers" knob (Ray actors,
`alphatriangle/config/train_config.py:34-38`) maps here to the number
of lockstep games per device dispatch (`SELF_PLAY_BATCH_SIZE`): one
actor stepping one game becomes one batch lane, so worker counts scale
the lane count (x16, keeping the MXU fed rather than matching actor
count 1:1). Mesh sizes state the intended hardware; on fewer devices
`MeshConfig(DP_SIZE=-1)` resolves to whatever is present, so every
preset also runs single-chip or on the virtual CPU mesh.

`bench.py` selects a preset via BENCH_CONFIG=1..5; the CLI via
`train --preset N`.
"""

from .env_config import EnvConfig
from .mcts_config import AlphaTriangleMCTSConfig
from .mesh_config import MeshConfig
from .model_config import ModelConfig
from .train_config import TrainConfig
from .validation import expected_other_features_dim

# Versioned schema tag for `tuned_preset.json` artifacts written by the
# fit-driven autotuner (alphatriangle_tpu/autotune/). Bump when the
# artifact layout changes incompatibly; `load_tuned_preset` refuses
# mismatched versions with an explicit error instead of constructing a
# half-understood config.
TUNED_PRESET_SCHEMA = "alphatriangle.tuned_preset.v1"

PRESET_DESCRIPTIONS = {
    1: "CNN-only, 50 sims, CPU smoke (BASELINE config 1)",
    2: "CNN-only, 200 sims, single TPU core (BASELINE config 2)",
    3: (
        "CNN + 4-layer transformer, dp learner, Gumbel+PCR recipe "
        "(BASELINE config 3, north star)"
    ),
    4: "C51 + 400 sims (BASELINE config 4)",
    5: "Large board + 8-layer transformer (BASELINE config 5)",
}


def _large_board() -> EnvConfig:
    """12x21 symmetric board for preset 5 (same hexagon-ish widening
    as the default 8x15)."""
    rows, cols = 12, 21
    half = rows // 2
    ranges = []
    for r in range(rows):
        d = (half - 1 - r) if r < half else (r - half)
        inset = max(0, d)
        ranges.append((inset, cols - inset))
    return EnvConfig(ROWS=rows, COLS=cols, PLAYABLE_RANGE_PER_ROW=ranges)


def _tiny_board() -> EnvConfig:
    """3x4 fully-playable board, 1 preview slot — the test-world
    geometry (tests/conftest.py) as a named preset so the autotuner can
    search it cheaply."""
    return EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )


# Named board geometries the autotuner's search space can range over
# (docs/AUTOTUNE.md). Values are zero-arg constructors so importing
# this module never validates configs eagerly.
GEOMETRY_PRESETS = {
    "tiny": _tiny_board,
    "default": EnvConfig,
    "large": _large_board,
}


def geometry_preset(name: str) -> EnvConfig:
    """EnvConfig for a named board geometry preset."""
    if name not in GEOMETRY_PRESETS:
        raise ValueError(
            f"Unknown geometry preset {name!r} "
            f"(valid: {', '.join(sorted(GEOMETRY_PRESETS))})"
        )
    return GEOMETRY_PRESETS[name]()


def load_tuned_preset(path) -> dict[str, object]:
    """Round-trip a `tuned_preset.json` artifact into a
    `baseline_preset`-shaped bundle {env, model, train, mcts, mesh,
    description, tuned}.

    `tuned` carries the artifact payload itself (schema, predicted
    throughput, composed budget, search provenance) so consumers like
    `cli train --preset <path>` can ledger predicted-vs-observed
    outcomes after the run. Raises ValueError with a precise reason on
    a missing/garbled file or a schema version mismatch — a tuned
    preset from an incompatible autotuner must fail loudly, not
    half-construct.
    """
    import json
    from pathlib import Path

    p = Path(path)
    try:
        payload = json.loads(p.read_text())
    except OSError as exc:
        raise ValueError(f"tuned preset {p}: unreadable ({exc})") from exc
    except json.JSONDecodeError as exc:
        raise ValueError(f"tuned preset {p}: invalid JSON ({exc})") from exc
    if not isinstance(payload, dict):
        raise ValueError(f"tuned preset {p}: expected a JSON object")
    schema = payload.get("schema")
    if schema != TUNED_PRESET_SCHEMA:
        raise ValueError(
            f"tuned preset {p}: schema {schema!r} does not match this "
            f"build's {TUNED_PRESET_SCHEMA!r} — re-run `cli tune` with "
            "the current code instead of reusing a stale artifact."
        )
    configs = payload.get("configs")
    if not isinstance(configs, dict):
        raise ValueError(f"tuned preset {p}: missing 'configs' section")
    try:
        env = EnvConfig(**configs["env"])
        model = ModelConfig(**configs["model"])
        train = TrainConfig(**configs["train"])
        mcts = AlphaTriangleMCTSConfig(**configs["mcts"])
    except KeyError as exc:
        raise ValueError(
            f"tuned preset {p}: configs section missing {exc}"
        ) from exc
    except Exception as exc:
        raise ValueError(
            f"tuned preset {p}: config validation failed ({exc})"
        ) from exc
    return {
        "env": env,
        "model": model,
        "train": train,
        "mcts": mcts,
        # The artifact records the dp width it tuned FOR; DP_SIZE=-1
        # still resolves to the devices actually present so the preset
        # runs anywhere (same contract as the BASELINE presets).
        "mesh": MeshConfig(DP_SIZE=-1),
        "description": payload.get(
            "description", f"tuned preset ({p.name})"
        ),
        "tuned": payload,
    }


def baseline_preset(
    n: int, run_name: str | None = None
) -> dict[str, object]:
    """Config bundle {env, model, train, mcts, mesh} for BASELINE
    config `n` (1..5). Training-loop knobs not pinned by BASELINE.md
    keep their TrainConfig defaults."""
    if n not in PRESET_DESCRIPTIONS:
        raise ValueError(f"Unknown BASELINE preset {n} (valid: 1..5)")

    env = _large_board() if n == 5 else EnvConfig()
    feat = expected_other_features_dim(env)

    model_kw: dict = {"OTHER_NN_INPUT_FEATURES_DIM": feat}
    if n in (1, 2):
        model_kw["USE_TRANSFORMER"] = False
    elif n in (3, 4):
        model_kw["TRANSFORMER_LAYERS"] = 4
    elif n == 5:
        model_kw["TRANSFORMER_LAYERS"] = 8
        model_kw["REMAT"] = True
    if n == 1:
        model_kw["COMPUTE_DTYPE"] = "float32"  # CPU smoke
    model = ModelConfig(**model_kw)

    train_kw: dict = {}
    if n == 1:
        # "CPU smoke" by definition: pin the platform so the numbers
        # stay comparable even on a TPU host.
        train_kw["DEVICE"] = "cpu"
        train_kw["WORKER_DEVICE"] = "cpu"

    sims = {1: 50, 2: 200, 3: 64, 4: 400, 5: 64}[n]
    mcts_kw: dict = {}
    if n == 3:
        # The flagship preset runs the measured-best training recipe:
        # Gumbel sequential-halving root + playout cap randomization
        # converged +11% above every other arm at under half the
        # search cost (BASELINE.md A/Bs; docs/MCTS_DESIGN.md §d-e).
        # The other presets keep reference-parity PUCT so the BASELINE
        # table stays comparable config-for-config.
        mcts_kw.update(
            root_selection="gumbel",
            fast_simulations=16,
            full_search_prob=0.25,
        )
    mcts = AlphaTriangleMCTSConfig(max_simulations=sims, **mcts_kw)

    # Reference worker counts 1/8/32/32/64 -> lockstep lanes x16.
    lanes = {1: 16, 2: 128, 3: 512, 4: 512, 5: 1024}[n]
    train = TrainConfig(
        SELF_PLAY_BATCH_SIZE=lanes,
        RUN_NAME=run_name or f"baseline_preset_{n}",
        FUSED_LEARNER_STEPS=1 if n == 1 else 16,
        **train_kw,
    )

    # Intended hardware: 1 chip (1, 2), v4-8 (3, 4), v5p-16 (5).
    # DP_SIZE=-1 resolves to the devices actually present.
    mesh = MeshConfig(DP_SIZE=-1)

    return {
        "env": env,
        "model": model,
        "train": train,
        "mcts": mcts,
        "mesh": mesh,
        "description": PRESET_DESCRIPTIONS[n],
    }
