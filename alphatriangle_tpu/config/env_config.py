"""Environment (game) configuration.

Reconstruction of the `trianglengin.EnvConfig` surface observed in the
reference (`tests/conftest.py:34-41`, `alphatriangle/nn/model.py:122-125`,
`alphatriangle/features/extractor.py:25-118`): a triangular-lattice
puzzle board described by ROWS x COLS cells, a per-row playable column
range (cells outside it are permanent "death" cells), and
NUM_SHAPE_SLOTS preview slots holding placeable shapes.

The engine package itself is not vendored in the reference, so the rule
constants below (rewards, clearable-line minimum, shape sizes) are this
framework's documented reconstruction, kept configurable.

Geometry conventions (used consistently by engine/features/models):
- Cell (r, c) is an up-pointing triangle iff (r + c) is even.
- An up cell shares edges with (r, c-1), (r, c+1), (r+1, c);
  a down cell with (r, c-1), (r, c+1), (r-1, c).
- Action encoding is the flat integer `slot * ROWS * COLS + r * COLS + c`
  (reference: `alphatriangle/nn/model.py:122-125`).
"""

from pydantic import BaseModel, Field, model_validator


def _default_playable_range() -> list[tuple[int, int]]:
    # Symmetric hexagon-ish board on an 8x15 lattice: row r (and its
    # mirror) exposes a contiguous window that widens toward the middle.
    return [
        (3, 12),
        (2, 13),
        (1, 14),
        (0, 15),
        (0, 15),
        (1, 14),
        (2, 13),
        (3, 12),
    ]


class EnvConfig(BaseModel):
    """Triangle puzzle environment config (pydantic, frozen)."""

    model_config = {"frozen": True}

    ROWS: int = Field(default=8, gt=0)
    COLS: int = Field(default=15, gt=0)
    # [start_col, end_col) playable window per row; everything else is a
    # death cell (never playable, rendered -1.0 in the feature grid).
    PLAYABLE_RANGE_PER_ROW: list[tuple[int, int]] = Field(
        default_factory=_default_playable_range
    )
    NUM_SHAPE_SLOTS: int = Field(default=3, gt=0)

    # --- Rule constants (reconstruction; configurable) ---
    # Largest shape in the bank, in triangles. The reference's feature
    # extractor normalizes triangle count by 5 (`features/extractor.py:70`).
    MAX_SHAPE_TRIANGLES: int = Field(default=5, ge=1, le=8)
    MIN_SHAPE_TRIANGLES: int = Field(default=1, ge=1)
    # A maximal line (horizontal / both lattice diagonals) is clearable
    # only if it spans at least this many cells.
    LINE_MIN_LENGTH: int = Field(default=3, ge=2)
    # Rewards: placement pays per triangle placed, clears pay per
    # triangle cleared, and ending the game costs a flat penalty.
    REWARD_PER_PLACED_TRIANGLE: float = Field(default=1.0)
    REWARD_PER_CLEARED_TRIANGLE: float = Field(default=2.0)
    PENALTY_GAME_OVER: float = Field(default=-10.0)
    # Number of distinct shape colors (cosmetic; carried in color_id).
    NUM_COLORS: int = Field(default=7, ge=1)

    @model_validator(mode="after")
    def _check_ranges(self) -> "EnvConfig":
        if len(self.PLAYABLE_RANGE_PER_ROW) != self.ROWS:
            raise ValueError(
                f"PLAYABLE_RANGE_PER_ROW must have ROWS={self.ROWS} entries, "
                f"got {len(self.PLAYABLE_RANGE_PER_ROW)}."
            )
        for r, (lo, hi) in enumerate(self.PLAYABLE_RANGE_PER_ROW):
            if not (0 <= lo < hi <= self.COLS):
                raise ValueError(
                    f"Row {r}: playable range ({lo}, {hi}) must satisfy "
                    f"0 <= start < end <= COLS={self.COLS}."
                )
        if self.MIN_SHAPE_TRIANGLES > self.MAX_SHAPE_TRIANGLES:
            raise ValueError("MIN_SHAPE_TRIANGLES must be <= MAX_SHAPE_TRIANGLES.")
        return self

    @property
    def action_dim(self) -> int:
        """Flat action-space size: NUM_SHAPE_SLOTS * ROWS * COLS."""
        return self.NUM_SHAPE_SLOTS * self.ROWS * self.COLS


EnvConfig.model_rebuild(force=True)
