"""Startup config validation (reference: `config/validation.py:20-101`).

Instantiates every config model, checks cross-config invariants that
pydantic cannot see (feature dim vs slots, action dim vs heads), prints
a summary, and raises on any failure.
"""

import logging

from alphatriangle_tpu.config.env_config import EnvConfig
from alphatriangle_tpu.config.mcts_config import AlphaTriangleMCTSConfig
from alphatriangle_tpu.config.mesh_config import MeshConfig
from alphatriangle_tpu.config.model_config import ModelConfig
from alphatriangle_tpu.config.persistence_config import PersistenceConfig
from alphatriangle_tpu.config.train_config import TrainConfig

logger = logging.getLogger(__name__)

# Feature layout constants shared with features/ (see features.core).
FEATURES_PER_SHAPE = 7
EXPLICIT_FEATURES_DIM = 6


def expected_other_features_dim(env: EnvConfig) -> int:
    """Per-slot shape feats + slot availability + scalar feats."""
    return env.NUM_SHAPE_SLOTS * FEATURES_PER_SHAPE + env.NUM_SHAPE_SLOTS + (
        EXPLICIT_FEATURES_DIM
    )


def print_config_info_and_validate(
    env: EnvConfig | None = None,
    model: ModelConfig | None = None,
    train: TrainConfig | None = None,
    mcts: AlphaTriangleMCTSConfig | None = None,
    mesh: MeshConfig | None = None,
    persistence: PersistenceConfig | None = None,
) -> dict:
    """Validate all configs together; returns them as a dict."""
    env = env or EnvConfig()
    model = model or ModelConfig()
    train = train or TrainConfig()
    mcts = mcts or AlphaTriangleMCTSConfig()
    mesh = mesh or MeshConfig()
    persistence = persistence or PersistenceConfig()

    expected_dim = expected_other_features_dim(env)
    if model.OTHER_NN_INPUT_FEATURES_DIM != expected_dim:
        raise ValueError(
            f"ModelConfig.OTHER_NN_INPUT_FEATURES_DIM="
            f"{model.OTHER_NN_INPUT_FEATURES_DIM} does not match the feature "
            f"layout for NUM_SHAPE_SLOTS={env.NUM_SHAPE_SLOTS}: expected "
            f"{expected_dim} ({env.NUM_SHAPE_SLOTS}x{FEATURES_PER_SHAPE} shape "
            f"+ {env.NUM_SHAPE_SLOTS} availability + {EXPLICIT_FEATURES_DIM} scalars)."
        )

    logger.info(
        "Config OK: board %dx%d (%d slots, action_dim=%d), net %s conv=%s "
        "transformer=%s params-dtype=%s, train batch=%d buffer=%d per=%s, "
        "mcts sims=%d depth=%d",
        env.ROWS,
        env.COLS,
        env.NUM_SHAPE_SLOTS,
        env.action_dim,
        model.ACTIVATION_FUNCTION,
        model.CONV_FILTERS,
        model.USE_TRANSFORMER and model.TRANSFORMER_LAYERS,
        model.PARAM_DTYPE,
        train.BATCH_SIZE,
        train.BUFFER_CAPACITY,
        train.USE_PER,
        mcts.max_simulations,
        mcts.max_depth,
    )
    return {
        "env": env,
        "model": model,
        "train": train,
        "mcts": mcts,
        "mesh": mesh,
        "persistence": persistence,
    }
