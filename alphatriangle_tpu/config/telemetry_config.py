"""Run-telemetry configuration (span tracing, health/watchdog, anomaly
detection — see `alphatriangle_tpu/telemetry/` and docs/OBSERVABILITY.md).

Telemetry is on by default: every knob here bounds host-side memory or
IO cadence, and nothing in the package touches the device dispatch path
(span/beat ingestion is an O(1) append or field write under a lock; IO
happens on loop ticks and watchdog polls only).
"""

from pydantic import BaseModel, Field


class TelemetryConfig(BaseModel):
    """Knobs for the telemetry subsystem."""

    ENABLED: bool = Field(default=True)

    # --- span tracer ---
    # Ring capacity for in-memory spans; the newest SPAN_BUFFER_SIZE
    # spans are exported to runs/<run>/trace.json at exit and on stall.
    SPAN_BUFFER_SIZE: int = Field(default=65536, ge=1)

    # --- health heartbeat + watchdog ---
    # health.json is rewritten when the learner step advances, and at
    # least this often while the loop ticks (so a stalled-but-alive run
    # keeps a fresh heartbeat carrying its stall flag).
    HEALTH_WRITE_INTERVAL_S: float = Field(default=5.0, gt=0)
    WATCHDOG_ENABLED: bool = Field(default=True)
    # No learner step AND no rollout harvest for this long => stall.
    # Generous default: a flagship compile is ~70s and a rollout chunk
    # is multi-second; 300s of neither is a wedged run, not a slow one.
    WATCHDOG_DEADLINE_S: float = Field(default=300.0, gt=0)
    WATCHDOG_POLL_S: float = Field(default=10.0, gt=0)
    # On stall, also export the span ring to trace.json so the timeline
    # leading INTO the stall is on disk before anyone kills the process.
    FLUSH_TRACE_ON_STALL: bool = Field(default=True)

    # --- metrics ledger (telemetry/ledger.py) ---
    # Durable per-run timeseries: every processed metric batch and one
    # derived utilization record per tick appended crash-safely to
    # runs/<run>/metrics.jsonl (`cli perf` / `cli compare` read it).
    LEDGER_ENABLED: bool = Field(default=True)
    # Rotation: metrics.jsonl -> .1 -> .2 when a file crosses this size
    # (0 disables rotation; the file then grows unbounded).
    LEDGER_MAX_BYTES: int = Field(default=16 * 1024 * 1024, ge=0)
    LEDGER_KEEP_ROTATIONS: int = Field(default=2, ge=0)
    # fsync every append: maximally crash-durable, but a per-tick disk
    # sync is unnecessary for observability — flush-on-close already
    # survives process death; only a kernel crash loses the tail.
    LEDGER_FSYNC: bool = Field(default=False)
    # Opt-in Prometheus textfile exporter: the newest utilization
    # record rendered as gauges into runs/<run>/metrics.prom (point a
    # node_exporter textfile collector or any scraper at it).
    PROMETHEUS_TEXTFILE: bool = Field(default=False)

    # --- dispatch flight recorder (telemetry/flight.py) ---
    # Intent-before / seal-after records for every hot-family device
    # dispatch, appended crash-safely to runs/<run>/flight.jsonl so a
    # SIGKILLed or wedged run still names the program it died inside
    # (`cli doctor`). Two tiny appends per dispatch; perf-smoke pins
    # the overhead under ~1% of iteration time.
    FLIGHT_ENABLED: bool = Field(default=True)
    FLIGHT_MAX_BYTES: int = Field(default=8 * 1024 * 1024, ge=0)
    FLIGHT_KEEP_ROTATIONS: int = Field(default=1, ge=0)
    # Per-dispatch deadline watchdog: a dispatch in flight past
    # FACTOR x its expected duration (EWMA of this run's own sealed
    # walls; MIN floors noisy fast programs) dumps stacks + trace,
    # writes wedge_report.json, and exits WEDGE_EXIT_CODE (113) so the
    # supervisor reclassifies the window in minutes. A program's FIRST
    # dispatch includes its compile, hence the generous allowance.
    DISPATCH_WATCHDOG_ENABLED: bool = Field(default=True)
    DISPATCH_DEADLINE_FACTOR: float = Field(default=10.0, gt=1.0)
    DISPATCH_MIN_DEADLINE_S: float = Field(default=60.0, gt=0)
    DISPATCH_FIRST_DEADLINE_S: float = Field(default=900.0, gt=0)
    DISPATCH_WATCHDOG_POLL_S: float = Field(default=5.0, gt=0)
    # Exit-on-wedge is what turns a 10h silent window into a minutes-
    # scale reclassification; tests and doctor-smoke disable it to
    # observe the report without dying.
    DISPATCH_EXIT_ON_WEDGE: bool = Field(default=True)

    # --- device telemetry plane (telemetry/device_stats.py) ---
    # Fixed-shape in-program stat-packs (KataGo-style search health:
    # root-visit entropy/concentration, value bounds, tree occupancy;
    # PER skew; per-fused-step grad/update norms) computed inside the
    # hot programs and returned through the EXISTING single
    # per-iteration fetch — no extra dispatch, no host sync. Ledgered
    # as kind:"device_stats" records (`cli perf`, `cli watch`,
    # bench.py) and fed to AnomalyDetector.observe_search.
    DEVICE_STATS: bool = Field(default=True)
    # Progress beacons (`jax.debug.callback` phase markers appended to
    # runs/<run>/beacons.jsonl) are OFF on hot paths by default; they
    # arm via ALPHATRIANGLE_BEACONS=1, the dispatch watchdog's
    # near-deadline warning, or a supervised dispatch-hung respawn.
    # When armed, search-wave beacons subsample to every Nth wave.
    BEACON_EVERY_N_WAVES: int = Field(default=8, ge=1)
    # Fraction of the dispatch deadline after which the watchdog warns
    # and arms beacons for programs built from then on (the wedge's
    # SECOND occurrence then names its phase).
    DISPATCH_WARN_FRACTION: float = Field(default=0.5, gt=0, lt=1.0)

    # --- anomaly detection ---
    ANOMALY_ENABLED: bool = Field(default=True)
    ANOMALY_EWMA_ALPHA: float = Field(default=0.02, gt=0, le=1.0)
    ANOMALY_Z_THRESHOLD: float = Field(default=6.0, gt=0)
    ANOMALY_WARMUP_STEPS: int = Field(default=20, ge=1)
    ANOMALY_WINDOW: int = Field(default=32, ge=1)
    # Policy entropy at/below this after warmup counts as a collapse.
    ENTROPY_COLLAPSE_THRESHOLD: float = Field(default=0.01, ge=0)

    # --- memory observability (telemetry/memory.py) ---
    # Leak detector (`Anomaly/memory_growth`): device bytes_in_use
    # rising MONOTONICALLY for this many utilization ticks, with total
    # growth over the run of at least this fraction, fires once per
    # excursion (a healthy allocator sawtooths; a leak only climbs).
    MEMORY_GROWTH_TICKS: int = Field(default=12, ge=2)
    MEMORY_GROWTH_MIN_FRACTION: float = Field(default=0.05, ge=0)


TelemetryConfig.model_rebuild(force=True)
