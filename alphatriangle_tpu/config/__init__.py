"""Config package: pydantic models per concern (reference: alphatriangle/config)."""

from alphatriangle_tpu.config.app_config import APP_NAME
from alphatriangle_tpu.config.env_config import EnvConfig
from alphatriangle_tpu.config.league_config import LeagueConfig
from alphatriangle_tpu.config.mcts_config import AlphaTriangleMCTSConfig, MCTSConfig
from alphatriangle_tpu.config.mesh_config import MeshConfig
from alphatriangle_tpu.config.model_config import ModelConfig
from alphatriangle_tpu.config.persistence_config import PersistenceConfig
from alphatriangle_tpu.config.presets import (
    GEOMETRY_PRESETS,
    PRESET_DESCRIPTIONS,
    TUNED_PRESET_SCHEMA,
    baseline_preset,
    geometry_preset,
    load_tuned_preset,
)
from alphatriangle_tpu.config.telemetry_config import TelemetryConfig
from alphatriangle_tpu.config.train_config import TrainConfig
from alphatriangle_tpu.config.validation import (
    expected_other_features_dim,
    print_config_info_and_validate,
)

__all__ = [
    "APP_NAME",
    "AlphaTriangleMCTSConfig",
    "EnvConfig",
    "GEOMETRY_PRESETS",
    "LeagueConfig",
    "MCTSConfig",
    "MeshConfig",
    "ModelConfig",
    "PRESET_DESCRIPTIONS",
    "PersistenceConfig",
    "TUNED_PRESET_SCHEMA",
    "TelemetryConfig",
    "TrainConfig",
    "baseline_preset",
    "expected_other_features_dim",
    "geometry_preset",
    "load_tuned_preset",
    "print_config_info_and_validate",
]
