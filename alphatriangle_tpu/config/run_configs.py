"""Reload a run's own configs from its `configs.json` dump.

Every run writes its validated config set to
`runs/<run>/configs.json` (stats/persistence.py; reference parity:
`README.md:79`). Post-hoc tools — arena eval, the Elo ladder — must
rebuild the SAME env/model the checkpoints were trained with, not
assume the flagship defaults, or restores fail (or silently evaluate a
mismatched board).
"""

import json
import logging
from pathlib import Path

from .env_config import EnvConfig
from .model_config import ModelConfig

logger = logging.getLogger(__name__)


def load_run_configs(run_dir: Path) -> dict | None:
    """{'env': EnvConfig, 'model': ModelConfig} from a run directory's
    configs.json, or None when the dump is absent/unreadable."""
    path = Path(run_dir) / "configs.json"
    if not path.is_file():
        return None
    try:
        raw = json.loads(path.read_text())
        return {
            "env": EnvConfig(**raw["env"]),
            "model": ModelConfig(**raw["model"]),
        }
    except (ValueError, KeyError, TypeError, OSError) as exc:
        logger.warning("Could not load %s (%s); using defaults.", path, exc)
        return None


def load_run_configs_or_default(run_dir: Path) -> tuple[EnvConfig, ModelConfig]:
    """The run's own (env, model) configs, or the flagship defaults
    when no usable configs.json exists — the shared fallback for
    post-hoc tools (cli eval, the Elo ladder)."""
    from .validation import expected_other_features_dim

    loaded = load_run_configs(run_dir)
    if loaded:
        return loaded["env"], loaded["model"]
    env = EnvConfig()
    return env, ModelConfig(
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env)
    )
