"""Training-loop configuration.

Capability parity with the reference `TrainConfig`
(`alphatriangle/config/train_config.py:18-103`): loop length, batching,
n-step returns, optimizer/scheduler, loss weights, checkpoint cadence,
PER knobs, profiling. TPU-specific additions replace the reference's
per-worker-CPU knobs with on-device self-play sizing: the number of
games stepped in parallel on the accelerator and the rollout chunk
length per dispatch.
"""

import time
from typing import Literal

from pydantic import BaseModel, Field, field_validator, model_validator


class TrainConfig(BaseModel):
    """Training hyperparameters (pydantic)."""

    RUN_NAME: str = Field(
        default_factory=lambda: f"train_{time.strftime('%Y%m%d_%H%M%S')}"
    )
    LOAD_CHECKPOINT_PATH: str | None = Field(default=None)
    LOAD_BUFFER_PATH: str | None = Field(default=None)
    AUTO_RESUME_LATEST: bool = Field(default=True)
    RANDOM_SEED: int = Field(default=42)

    # --- Loop ---
    MAX_TRAINING_STEPS: int | None = Field(default=100_000, ge=1)

    # --- Self-play (TPU-native: games batched on device, not Ray actors) ---
    # Number of games stepped in lockstep per device dispatch. This is
    # the MCTS leaf-eval batch seen by the MXU (replaces the reference's
    # NUM_SELF_PLAY_WORKERS x mcts_batch_size CPU batching).
    SELF_PLAY_BATCH_SIZE: int = Field(default=512, ge=1)
    # Moves played per jitted rollout dispatch before results return to host.
    ROLLOUT_CHUNK_MOVES: int = Field(default=16, ge=1)
    # The reference's worker-count knob, re-expressed: in overlapped
    # mode (ASYNC_ROLLOUTS) this many independent rollout streams run,
    # each a producer thread driving its own SELF_PLAY_BATCH_SIZE-lane
    # engine (own PRNG stream + game carry, shared weights), all
    # feeding one harvest queue. Streams pipeline host-side harvest
    # compaction against device compute. Ignored by the synchronous
    # loop (one stream).
    NUM_SELF_PLAY_WORKERS: int = Field(default=1, ge=1)
    WORKER_UPDATE_FREQ_STEPS: int = Field(default=10, ge=1)
    # Hard cap on moves per episode (safety net for jitted rollouts).
    MAX_EPISODE_MOVES: int = Field(default=1000, ge=1)
    # Learner steps per rollout chunk. None = auto: match the production
    # rate (experiences harvested / BATCH_SIZE), the synchronous
    # equivalent of the reference's free-running async learner.
    LEARNER_STEPS_PER_ROLLOUT: int | None = Field(default=None, ge=1)

    # --- Overlapped (async) orchestration ---
    # Run self-play in a producer thread feeding a bounded queue while
    # the learner consumes at REPLAY_RATIO; host work (harvest
    # compaction, PER sampling, priority updates) then overlaps with
    # device compute instead of serializing with it (the reference's
    # async producer/consumer topology, `training/loop.py:298-416`,
    # re-expressed for one process).
    ASYNC_ROLLOUTS: bool = Field(default=False)
    # Target learner consumption rate: samples consumed per experience
    # produced (steps * BATCH_SIZE / experiences). The synchronous
    # loop's implicit `added/BATCH_SIZE` matching corresponds to 1.0;
    # here it is an explicit, measured knob.
    REPLAY_RATIO: float = Field(default=1.0, gt=0)
    # Bounded harvest queue between producer and learner (backpressure:
    # the producer blocks when the learner falls this many chunks behind).
    ROLLOUT_QUEUE_MAX: int = Field(default=4, ge=1)
    # Pipelined learner (overlapped mode only): dispatch fused group
    # N+1 to the device BEFORE fetching group N's results, so the
    # learner always has a program queued behind the producers' rollout
    # chunks and never blocks a full tunnel round trip per group. Costs
    # one extra group of PER-priority staleness (bounded by
    # FUSED_LEARNER_STEPS); False restores strictly serial fetches.
    PIPELINE_LEARNER: bool = Field(default=True)
    # Target wall-clock seconds per producer rollout dispatch in
    # overlapped mode. A flagship chunk of ROLLOUT_CHUNK_MOVES moves is
    # a single multi-second device program the learner's dispatches
    # must queue behind (measured 0.02 learner steps/s at 16-move
    # ~10 s chunks); producers auto-shrink their per-dispatch move
    # count until a chunk fits this budget, bounding the learner's
    # queue wait. None disables auto-tuning (dispatch
    # ROLLOUT_CHUNK_MOVES every time).
    ASYNC_CHUNK_SECONDS: float | None = Field(default=2.0, gt=0)
    # Producer stream supervision: a crashed rollout stream is
    # respawned with a fresh engine (carry + PRNG; compiled programs
    # shared, so no recompile) after an exponential backoff, up to
    # this many times per stream; exhausted, the run aborts with the
    # original error. The reference detects dead actors and merely
    # removes them (`worker_manager.py:153-159`) — SURVEY §7.9 asked
    # for restart. 0 = abort on first crash.
    PRODUCER_MAX_RESTARTS: int = Field(default=3, ge=0)
    PRODUCER_RESTART_BACKOFF_S: float = Field(default=1.0, gt=0)

    # --- Fused megastep (Anakin) orchestration ---
    # Third loop mode (rl/megastep.py, docs/PARALLELISM.md "Megastep"):
    # rollout chunk + device-ring ingest + on-device PER sampling + K
    # fused learner steps run as ONE jitted device program, so the only
    # per-iteration host work is fetching stats/metrics (one dispatch,
    # one fetch). Weight sync is free and zero-staleness — the rollout
    # reads the learner's live on-device params; there is no
    # sync_to_network copy on the hot path. Requires the device-resident
    # replay ring on a single-device, single-process mesh
    # (DEVICE_REPLAY must not be "off"; megastep forces the ring on
    # otherwise-ineligible backends the way DEVICE_REPLAY="on" does).
    # Learner steps per megastep = LEARNER_STEPS_PER_ROLLOUT when set,
    # else FUSED_LEARNER_STEPS. Mutually exclusive with ASYNC_ROLLOUTS.
    FUSED_MEGASTEP: bool = Field(default=False)

    # --- Batching / buffer ---
    BATCH_SIZE: int = Field(default=256, ge=1)
    # Learner steps fused into ONE device dispatch (a lax.scan over
    # pre-sampled batches). 1 = exact reference semantics (PER
    # priorities update between consecutive steps). >1 trades bounded
    # priority staleness (< FUSED_LEARNER_STEPS steps) for one host
    # round trip per group instead of per step — the difference between
    # ~2 and >100 steps/s when the accelerator sits behind a network
    # tunnel, and what lets the learner keep pace with multi-second
    # self-play chunks on a single shared chip.
    FUSED_LEARNER_STEPS: int = Field(default=1, ge=1)
    BUFFER_CAPACITY: int = Field(default=250_000, ge=1)
    MIN_BUFFER_SIZE_TO_TRAIN: int = Field(default=25_000, ge=1)
    # Device-resident replay ring: experiences stream from the rollout
    # program into an on-device ring buffer and training batches are
    # gathered on device from host-chosen indices, so the steady-state
    # training loop moves only scalars, indices and metrics between
    # host and device. "auto" enables it on single-process accelerator
    # meshes (where the host<->device link — PCIe, or a network tunnel
    # in dev — is the measured learner bottleneck): one chip gets the
    # single ring (rl/device_buffer.py); a dp-only multi-device mesh
    # gets the dp-SHARDED ring (rl/sharded_device_buffer.py) — each
    # device ingests its own rollout lanes and gathers its own batch
    # shard, so no experience bytes cross devices either. "off" keeps
    # the host SoA ring; "on" forces the device ring (CPU backend
    # included — used by tests).
    DEVICE_REPLAY: Literal["auto", "on", "off"] = Field(default="auto")

    # --- N-step returns ---
    N_STEP_RETURNS: int = Field(default=5, ge=1)
    GAMMA: float = Field(default=0.99, gt=0, le=1.0)

    # --- Optimizer ---
    OPTIMIZER_TYPE: Literal["Adam", "AdamW", "SGD"] = Field(default="AdamW")
    LEARNING_RATE: float = Field(default=2e-4, gt=0)
    WEIGHT_DECAY: float = Field(default=1e-4, ge=0)
    GRADIENT_CLIP_VALUE: float | None = Field(default=1.0)

    # --- LR schedule ---
    LR_SCHEDULER_TYPE: Literal["StepLR", "CosineAnnealingLR"] | None = Field(
        default="CosineAnnealingLR"
    )
    LR_SCHEDULER_T_MAX: int | None = Field(default=None)
    LR_SCHEDULER_ETA_MIN: float = Field(default=1e-6, ge=0)
    LR_SCHEDULER_STEP_SIZE: int = Field(default=10_000, ge=1)
    LR_SCHEDULER_GAMMA: float = Field(default=0.5, gt=0, le=1.0)

    # --- Loss weights ---
    POLICY_LOSS_WEIGHT: float = Field(default=1.0, ge=0)
    VALUE_LOSS_WEIGHT: float = Field(default=1.0, ge=0)
    ENTROPY_BONUS_WEIGHT: float = Field(default=0.001, ge=0)

    # --- Checkpointing ---
    CHECKPOINT_SAVE_FREQ_STEPS: int = Field(default=2500, ge=1)

    # --- PER ---
    USE_PER: bool = Field(default=True)
    PER_ALPHA: float = Field(default=0.6, ge=0)
    PER_BETA_INITIAL: float = Field(default=0.4, ge=0, le=1.0)
    PER_BETA_FINAL: float = Field(default=1.0, ge=0, le=1.0)
    PER_BETA_ANNEAL_STEPS: int | None = Field(default=None)
    PER_EPSILON: float = Field(default=1e-5, gt=0)
    # How the on-device stratified PER draw locates its cumsum indices:
    # "xla" (searchsorted) or "pallas" (tiled compare-count kernel,
    # ops/per_sample.py). Bit-identical selections (exact float
    # compares over a shared prefix-sum); a pure performance knob to
    # be settled by on-hardware benchmarks.
    PER_SAMPLE_BACKEND: str = Field(default="xla", pattern="^(xla|pallas)$")

    # --- Temperature schedule for action selection (move-indexed) ---
    TEMPERATURE_INITIAL: float = Field(default=1.0, ge=0)
    TEMPERATURE_FINAL: float = Field(default=0.1, ge=0)
    TEMPERATURE_ANNEAL_MOVES: int = Field(default=30, ge=1)

    # --- Device / compile ---
    # DEVICE is enforced at startup (utils.helpers.enforce_platform).
    # WORKER_DEVICE and COMPILE_MODEL are config-surface parity stubs:
    # self-play shares the learner's device by design (there are no
    # separate worker processes), and JAX jits everything regardless.
    DEVICE: Literal["auto", "tpu", "cpu"] = Field(default="auto")
    WORKER_DEVICE: Literal["auto", "tpu", "cpu"] = Field(default="auto")
    COMPILE_MODEL: bool = Field(default=True)

    # --- Profiling ---
    PROFILE_WORKERS: bool = Field(default=False)

    @model_validator(mode="after")
    def _check_buffer_sizes(self) -> "TrainConfig":
        if self.MIN_BUFFER_SIZE_TO_TRAIN > self.BUFFER_CAPACITY:
            raise ValueError(
                "MIN_BUFFER_SIZE_TO_TRAIN cannot be greater than BUFFER_CAPACITY."
            )
        if self.BATCH_SIZE > self.BUFFER_CAPACITY:
            raise ValueError("BATCH_SIZE cannot be greater than BUFFER_CAPACITY.")
        return self

    @model_validator(mode="after")
    def _derive_schedule_lengths(self) -> "TrainConfig":
        # Auto-derive cosine horizon and PER beta anneal from the run
        # length, as the reference does (`train_config.py:131-209`).
        horizon = self.MAX_TRAINING_STEPS or 100_000
        if self.LR_SCHEDULER_TYPE == "CosineAnnealingLR" and self.LR_SCHEDULER_T_MAX is None:
            self.LR_SCHEDULER_T_MAX = horizon
        if self.USE_PER and self.PER_BETA_ANNEAL_STEPS is None:
            self.PER_BETA_ANNEAL_STEPS = horizon
        if self.LR_SCHEDULER_T_MAX is not None and self.LR_SCHEDULER_T_MAX <= 0:
            raise ValueError("LR_SCHEDULER_T_MAX must be positive if set.")
        if self.PER_BETA_ANNEAL_STEPS is not None and self.PER_BETA_ANNEAL_STEPS <= 0:
            raise ValueError("PER_BETA_ANNEAL_STEPS must be positive if set.")
        return self

    @field_validator("GRADIENT_CLIP_VALUE")
    @classmethod
    def _check_grad_clip(cls, v: float | None) -> float | None:
        if v is not None and v <= 0:
            raise ValueError("GRADIENT_CLIP_VALUE must be positive if set.")
        return v

    @model_validator(mode="after")
    def _check_megastep(self) -> "TrainConfig":
        if self.FUSED_MEGASTEP and self.ASYNC_ROLLOUTS:
            raise ValueError(
                "FUSED_MEGASTEP and ASYNC_ROLLOUTS are mutually "
                "exclusive loop modes (the megastep already overlaps "
                "acting and learning inside one device program)."
            )
        if self.FUSED_MEGASTEP and self.DEVICE_REPLAY == "off":
            raise ValueError(
                "FUSED_MEGASTEP needs the device-resident replay ring "
                "(its sampling and ingest run on device); set "
                "DEVICE_REPLAY to 'auto' or 'on'."
            )
        return self

    @model_validator(mode="after")
    def _check_beta(self) -> "TrainConfig":
        if self.PER_BETA_FINAL < self.PER_BETA_INITIAL:
            raise ValueError("PER_BETA_FINAL cannot be less than PER_BETA_INITIAL.")
        return self


TrainConfig.model_rebuild(force=True)
