"""Application namespace constant (reference: `config/app_config.py:1`)."""

APP_NAME = "AlphaTriangleTPU"
