"""League / flywheel configuration.

Knobs for the experience flywheel (`alphatriangle_tpu/league/`): how
many service lanes play matchmade games, how league data mixes with
self-play in the learner's diet, the params-broadcast cadence, the
staleness window the ingest guard enforces, and the KataGo-style
matchmaking + promotion parameters. One pydantic model, same idiom as
the sibling configs — constructed by `cli league` from flags and
serialized into the run's configs.json.
"""

from pydantic import BaseModel, Field, model_validator


class LeagueConfig(BaseModel):
    """Flywheel-mode hyperparameters (pydantic)."""

    # --- Service sizing ---
    # Session slots on the league PolicyService: games per matchmade
    # round play in lockstep through the serve dispatch path.
    LEAGUE_SLOTS: int = Field(default=8, ge=1)
    # Games per side per pairing (live vs. opponent each play this
    # many); the win fraction of the pairing is the Elo observation.
    GAMES_PER_ROUND: int = Field(default=4, ge=1)
    # Hard cap on moves per league game (mirrors MAX_EPISODE_MOVES).
    MAX_GAME_MOVES: int = Field(default=200, ge=1)

    # --- Learner diet ---
    # Fraction of loop iterations that run a league round instead of a
    # self-play rollout chunk. 0.0 = pure self-play (flywheel off),
    # 1.0 = every iteration plays league games. Fractions accumulate:
    # 0.25 plays one league round every 4th iteration.
    LEAGUE_MIX_RATIO: float = Field(default=0.25, ge=0.0, le=1.0)
    # Broadcast fresh learner params to the league service every N
    # learner steps (RLAX-style step-clock broadcast). The broadcast
    # bumps the service's hot-reload counter — the staleness tag.
    RELOAD_EVERY_STEPS: int = Field(default=8, ge=1)
    # Drop harvested rows whose params version trails the learner's
    # broadcast clock by more than this many reloads (None/negative =
    # guard off). Counted in Stats/stale_dropped.
    STALENESS_WINDOW: int | None = Field(default=4)

    # --- Matchmaking (KataGo-style) ---
    # Elo-gap scale of the proximity kernel.
    MATCH_TEMPERATURE: float = Field(default=200.0, gt=0.0)
    # Uniform mass spread over the whole pool so no member is starved.
    EXPLORATION_FLOOR: float = Field(default=0.1, ge=0.0, le=1.0)
    ELO_K: float = Field(default=32.0, gt=0.0)

    # --- Promotion gate ---
    # Live net joins the pool once its matchmade win-rate clears the
    # gate over at least this many pairings; the window then resets.
    PROMOTION_MIN_GAMES: int = Field(default=4, ge=1)
    PROMOTION_WIN_RATE: float = Field(default=0.55, ge=0.0, le=1.0)

    @model_validator(mode="after")
    def _check(self) -> "LeagueConfig":
        if self.GAMES_PER_ROUND > self.LEAGUE_SLOTS:
            raise ValueError(
                "GAMES_PER_ROUND cannot exceed LEAGUE_SLOTS "
                f"({self.GAMES_PER_ROUND} > {self.LEAGUE_SLOTS}): a round's "
                "games play in one set of service sessions."
            )
        return self
