"""MCTS search configuration.

Parity with `trimcts.SearchConfiguration` as mirrored by the reference's
`AlphaTriangleMCTSConfig` (`alphatriangle/config/mcts_config.py:10-77`).

`mcts_batch_size` (the reference's C++ leaf-collection size,
`mcts_config.py:57-62`) maps to the TPU search's *wave size*: the
number of simulations whose leaves are collected in parallel per tree
before one fused network evaluation. The effective MXU batch per eval
is SELF_PLAY_BATCH_SIZE games x mcts_batch_size wave members.
"""

import logging

from pydantic import BaseModel, Field, model_validator


class AlphaTriangleMCTSConfig(BaseModel):
    """PUCT search hyperparameters (pydantic)."""

    max_simulations: int = Field(default=64, gt=0)
    max_depth: int = Field(default=8, gt=0)
    cpuct: float = Field(default=1.5, gt=0)
    # alpha=0 legitimately disables root noise (reference allows ge=0).
    dirichlet_alpha: float = Field(default=0.3, ge=0)
    dirichlet_epsilon: float = Field(default=0.25, ge=0, le=1.0)
    discount: float = Field(default=1.0, ge=0, le=1.0)
    # Wave size: simulations selected/evaluated in parallel per tree
    # (the reference's leaf-collection batch; see module docstring).
    # Clamped at runtime to the largest divisor of max_simulations.
    # Default matches the reference (`mcts_config.py:14`).
    mcts_batch_size: int = Field(default=32, gt=0)
    # Gumbel perturbation scale applied to PUCT scores per wave member
    # during parallel descent, so the wave's descents diverge without
    # sequential virtual-loss bookkeeping. 0 disables (wave members
    # then collapse onto one leaf; the duplicate shows up in
    # `SearchOutput.wasted_slots`).
    wave_noise_scale: float = Field(default=0.25, ge=0)
    # How descent reads tree rows: "einsum" (one-hot matmul on the
    # MXU), "pallas" (custom VMEM row-copy kernel, ops/gather_rows.py),
    # or "take" (XLA native gather). Numerically identical; a pure
    # performance knob to be settled by on-hardware benchmarks.
    descent_gather: str = Field(default="einsum", pattern="^(einsum|pallas|take)$")
    # How the wave's insertion + discounted backup writes the edge
    # planes: "xla" (the original scatter chain) or "pallas" (one fused
    # per-game VMEM kernel, ops/mcts_backup.py). Parity-pinned; a pure
    # performance knob to be settled by on-hardware benchmarks.
    backup_update: str = Field(default="xla", pattern="^(xla|pallas)$")
    # --- Subtree reuse across moves (the reference's opaque C++ tree
    # handle, `rl/self_play/worker.py:273-280`; KataGo keeps the chosen
    # child's subtree too, arXiv:1902.10565). Off by default: fresh-root
    # search is the v1 reference behavior and stays bit-identical when
    # this is False. When True, the search runs over a widened node
    # budget (max_simulations + tree_reuse_budget + 1 slots) and after
    # each move a static-shape root-promotion pass
    # (ops/subtree_reuse.py) compacts the chosen child's subtree into
    # the leading rows — BFS order, freed slots zeroed — so the next
    # move's search starts with the retained visits already on the
    # root row. Root prior + Dirichlet noise are always re-taken from a
    # fresh root evaluation; only edge statistics and interior priors
    # are carried.
    tree_reuse: bool = Field(default=False)
    # How the promotion pass reorders the (B, N, A) edge planes:
    # "xla" (take_along_axis gathers) or "pallas" (one fused per-game
    # VMEM row-reorder kernel). Pure copies of identical values, so the
    # two are bit-identical by construction; parity-pinned anyway.
    tree_reuse_backend: str = Field(default="xla", pattern="^(xla|pallas)$")
    # Max nodes retained across a move (root + interior), excluding the
    # +1 root slot. None -> max_simulations (retain up to a full
    # search's worth of subtree).
    tree_reuse_budget: int | None = Field(default=None, gt=0)
    # --- Playout cap randomization (KataGo, arXiv:1902.10565 §3.1;
    # PAPERS.md) — beyond-reference acceleration, off by default. When
    # `fast_simulations` is set, each lockstep move runs the full
    # `max_simulations` search with probability `full_search_prob` and
    # a cheap noiseless `fast_simulations` search otherwise. Only
    # full-search moves produce policy-training targets (their
    # experiences carry policy weight 1, fast moves 0); value targets
    # come from every move. Self-play cost per move drops toward the
    # fast budget while policy targets keep full-search quality.
    fast_simulations: int | None = Field(default=None, gt=0)
    full_search_prob: float = Field(default=0.25, gt=0, le=1.0)
    # KataGo-faithful (default): fast-search positions produce NO
    # training rows at all — they only advance the game cheaply.
    # True keeps them as value-only rows (policy weight 0); measured
    # on the tiny-board harness this degrades the value head (their
    # n-step bootstraps come from the noisy fast-search roots).
    pcr_record_fast_rows: bool = Field(default=False)
    # --- Gumbel root search (Danihelka et al. 2022 / mctx; beyond-
    # reference, mcts/gumbel.py). "gumbel": root actions are explored
    # by sampled Gumbel noise + sequential halving across waves, the
    # played move is the final-candidate argmax (no temperature), and
    # policy targets are the completed-Q improved policy. "puct":
    # reference-parity Dirichlet + visit-count behavior.
    root_selection: str = Field(default="puct", pattern="^(puct|gumbel)$")
    # Max root candidates considered by sequential halving.
    gumbel_m: int = Field(default=16, gt=1)
    # sigma(q) = (c_visit + max_visits) * c_scale * q   (paper Eq. 8).
    # c_scale default follows the paper's 1.0 (mctx ships 0.1): on the
    # tiny-board learning harness 0.1 plateaued the trained net at
    # 7.65 while 0.5/1.0 reach ~7.75 (docs/MCTS_DESIGN.md §d sweep) —
    # too-small sigma keeps completed-Q targets glued to the prior.
    gumbel_c_visit: float = Field(default=50.0, ge=0)
    gumbel_c_scale: float = Field(default=1.0, gt=0)

    @model_validator(mode="after")
    def _check_fast(self) -> "AlphaTriangleMCTSConfig":
        if (
            self.fast_simulations is not None
            and self.fast_simulations >= self.max_simulations
        ):
            raise ValueError(
                "fast_simulations must be < max_simulations "
                f"({self.fast_simulations} >= {self.max_simulations})"
            )
        return self

    @model_validator(mode="after")
    def _check_reuse(self) -> "AlphaTriangleMCTSConfig":
        if self.tree_reuse and self.fast_simulations is not None:
            # PCR's fast/full lax.cond needs both branches to share one
            # carried-tree shape; the fast search has no carried tree.
            raise ValueError(
                "tree_reuse is incompatible with playout cap "
                "randomization (fast_simulations); pick one."
            )
        if self.tree_reuse and self.root_selection == "gumbel":
            # Sequential halving re-plans the root candidate set per
            # move; carrying a PUCT-shaped subtree across moves would
            # bias the halving allocation. Not supported.
            raise ValueError(
                "tree_reuse is incompatible with root_selection='gumbel'"
            )
        return self

    @model_validator(mode="after")
    def _warn_depth(self) -> "AlphaTriangleMCTSConfig":
        if self.max_depth > self.max_simulations + 1:
            # Deeper than the number of expansions wastes fixed-size
            # path buffers in the jitted search.
            logging.getLogger(__name__).warning(
                "max_depth=%d exceeds max_simulations+1=%d; the extra depth "
                "can never be reached and only widens jitted path buffers.",
                self.max_depth,
                self.max_simulations + 1,
            )
        return self


# Short alias used throughout this package.
MCTSConfig = AlphaTriangleMCTSConfig

AlphaTriangleMCTSConfig.model_rebuild(force=True)
