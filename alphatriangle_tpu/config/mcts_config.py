"""MCTS search configuration.

Parity with `trimcts.SearchConfiguration` as mirrored by the reference's
`AlphaTriangleMCTSConfig` (`alphatriangle/config/mcts_config.py:10-77`).

The TPU search evaluates one leaf per parallel game per simulation, so
`mcts_batch_size` (the reference's C++ leaf-collection size,
`mcts_config.py:57-62`) is kept for config parity but the effective
MXU batch is SELF_PLAY_BATCH_SIZE games wide.
"""

import logging

from pydantic import BaseModel, Field, model_validator


class AlphaTriangleMCTSConfig(BaseModel):
    """PUCT search hyperparameters (pydantic)."""

    max_simulations: int = Field(default=64, gt=0)
    max_depth: int = Field(default=8, gt=0)
    cpuct: float = Field(default=1.5, gt=0)
    # alpha=0 legitimately disables root noise (reference allows ge=0).
    dirichlet_alpha: float = Field(default=0.3, ge=0)
    dirichlet_epsilon: float = Field(default=0.25, ge=0, le=1.0)
    discount: float = Field(default=1.0, ge=0, le=1.0)
    # Parity knob (see module docstring); not a TPU batching control.
    mcts_batch_size: int = Field(default=32, gt=0)

    @model_validator(mode="after")
    def _warn_depth(self) -> "AlphaTriangleMCTSConfig":
        if self.max_depth > self.max_simulations + 1:
            # Deeper than the number of expansions wastes fixed-size
            # path buffers in the jitted search.
            logging.getLogger(__name__).warning(
                "max_depth=%d exceeds max_simulations+1=%d; the extra depth "
                "can never be reached and only widens jitted path buffers.",
                self.max_depth,
                self.max_simulations + 1,
            )
        return self


# Short alias used throughout this package.
MCTSConfig = AlphaTriangleMCTSConfig

AlphaTriangleMCTSConfig.model_rebuild(force=True)
