"""Neural-network architecture configuration.

Capability parity with the reference `ModelConfig`
(`alphatriangle/config/model_config.py:17-59`): conv trunk, residual
blocks, optional transformer encoder, shared FC, policy head, C51
distributional value head. TPU-specific additions: compute dtype
(bfloat16 on MXU), rematerialization, and a norm choice that defaults to
GroupNorm — BatchNorm cross-example state is hostile to pjit sharding,
so it is supported but not the default.
"""

from typing import Literal

from pydantic import BaseModel, Field, model_validator


class ModelConfig(BaseModel):
    """Policy/value network hyperparameters (pydantic)."""

    GRID_INPUT_CHANNELS: int = Field(default=1, gt=0)

    # --- CNN trunk ---
    CONV_FILTERS: list[int] = Field(default=[32, 64, 128])
    CONV_KERNEL_SIZES: list[int] = Field(default=[3, 3, 3])
    CONV_STRIDES: list[int] = Field(default=[1, 1, 1])

    # --- Residual blocks ---
    NUM_RESIDUAL_BLOCKS: int = Field(default=2, ge=0)
    RESIDUAL_BLOCK_FILTERS: int = Field(default=128, gt=0)

    # --- Optional transformer encoder over the spatial sequence ---
    USE_TRANSFORMER: bool = Field(default=True)
    TRANSFORMER_DIM: int = Field(default=128, gt=0)
    TRANSFORMER_HEADS: int = Field(default=4, gt=0)
    TRANSFORMER_LAYERS: int = Field(default=2, ge=0)
    TRANSFORMER_FC_DIM: int = Field(default=256, gt=0)

    # --- Heads ---
    FC_DIMS_SHARED: list[int] = Field(default=[128])
    POLICY_HEAD_DIMS: list[int] = Field(default=[128])
    VALUE_HEAD_DIMS: list[int] = Field(default=[128])

    # --- Distributional (C51) value head ---
    NUM_VALUE_ATOMS: int = Field(default=51, gt=1)
    VALUE_MIN: float = Field(default=-10.0)
    VALUE_MAX: float = Field(default=10.0)

    # --- Misc ---
    ACTIVATION_FUNCTION: Literal["ReLU", "GELU", "SiLU", "Tanh", "Sigmoid"] = Field(
        default="ReLU"
    )
    # Norm layer. "batch" matches the reference (`model_config.py:54`) but
    # carries running statistics; "group" is stateless and shards cleanly.
    NORM_TYPE: Literal["group", "layer", "batch", "none"] = Field(default="group")

    OTHER_NN_INPUT_FEATURES_DIM: int = Field(default=30, gt=0)

    # --- TPU-specific ---
    COMPUTE_DTYPE: Literal["bfloat16", "float32"] = Field(default="bfloat16")
    PARAM_DTYPE: Literal["float32"] = Field(default="float32")
    # jax.checkpoint the residual + transformer blocks to trade FLOPs for HBM.
    REMAT: bool = Field(default=False)
    # Param dtype the INFERENCE family (rollout chunk, serve dispatch,
    # arena/eval) reads the network at; the learner family always
    # trains the f32 originals (nn/precision.py, docs/KERNELS.md).
    # "int8" is weight-only: matrix weights become int8 tensors with
    # per-channel f32 scales, dequantized to bf16 on the forward trunk.
    INFERENCE_PRECISION: Literal["float32", "bfloat16", "int8"] = Field(
        default="float32"
    )

    @property
    def USE_BATCH_NORM(self) -> bool:
        """Parity alias for the reference knob, derived from NORM_TYPE so
        the two can never disagree (`alphatriangle/config/model_config.py:54`)."""
        return self.NORM_TYPE == "batch"

    @model_validator(mode="before")
    @classmethod
    def _map_use_batch_norm(cls, data):
        # Accept the reference's USE_BATCH_NORM kwarg by mapping it onto
        # NORM_TYPE (explicit NORM_TYPE wins if both are given). False
        # means "no normalization" in the reference architecture, not an
        # alternative norm.
        if isinstance(data, dict) and "USE_BATCH_NORM" in data:
            data = {**data}
            use_bn = data.pop("USE_BATCH_NORM")
            if "NORM_TYPE" not in data:
                data["NORM_TYPE"] = "batch" if use_bn else "none"
        return data

    @model_validator(mode="after")
    def _check_conv_consistency(self) -> "ModelConfig":
        n = len(self.CONV_FILTERS)
        if len(self.CONV_KERNEL_SIZES) != n or len(self.CONV_STRIDES) != n:
            raise ValueError(
                "CONV_FILTERS, CONV_KERNEL_SIZES and CONV_STRIDES must have "
                "matching lengths."
            )
        return self

    @model_validator(mode="after")
    def _check_transformer(self) -> "ModelConfig":
        if self.USE_TRANSFORMER and self.TRANSFORMER_LAYERS > 0:
            if self.TRANSFORMER_DIM % self.TRANSFORMER_HEADS != 0:
                raise ValueError(
                    f"TRANSFORMER_DIM ({self.TRANSFORMER_DIM}) must be divisible "
                    f"by TRANSFORMER_HEADS ({self.TRANSFORMER_HEADS})."
                )
        return self

    @model_validator(mode="after")
    def _check_value_support(self) -> "ModelConfig":
        if self.VALUE_MIN >= self.VALUE_MAX:
            raise ValueError("VALUE_MIN must be strictly less than VALUE_MAX.")
        return self


ModelConfig.model_rebuild(force=True)
