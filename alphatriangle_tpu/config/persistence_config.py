"""Run-directory + persistence configuration.

Equivalent of trieye's `PersistenceConfig` as used by the reference
(`alphatriangle/cli.py:165-172`, SURVEY.md §2b trieye row): where runs
live, whether/how often the replay buffer is spilled to disk, and where
MLflow/TensorBoard artifacts go. Layout mirrors the reference's
`.trieye_data/<app>/runs/<run>/{checkpoints,buffers,logs,tensorboard,
profile_data}` tree (reference README.md:63-79).
"""

from pathlib import Path

from pydantic import BaseModel, Field

from alphatriangle_tpu.config.app_config import APP_NAME


class PersistenceConfig(BaseModel):
    """Filesystem layout + save cadences for a training run."""

    APP_NAME: str = Field(default=APP_NAME)
    RUN_NAME: str = Field(default="default_run")
    ROOT_DATA_DIR: str = Field(default=".alphatriangle_data")
    SAVE_BUFFER: bool = Field(default=True)
    BUFFER_SAVE_FREQ_STEPS: int = Field(default=10_000, ge=1)
    MLFLOW_TRACKING_URI: str | None = Field(default=None)
    # Retention: keep only the newest K checkpoints / buffer spills
    # (0 = unlimited). A 100k-step run at the reference cadence would
    # otherwise accumulate 40 checkpoints + full-capacity spills.
    KEEP_LAST_CHECKPOINTS: int = Field(default=5, ge=0)
    KEEP_LAST_BUFFERS: int = Field(default=2, ge=0)

    def get_app_root_dir(self) -> Path:
        return Path(self.ROOT_DATA_DIR) / self.APP_NAME

    def get_runs_root_dir(self) -> Path:
        return self.get_app_root_dir() / "runs"

    def get_run_base_dir(self) -> Path:
        return self.get_runs_root_dir() / self.RUN_NAME

    def get_checkpoint_dir(self) -> Path:
        return self.get_run_base_dir() / "checkpoints"

    def get_buffer_dir(self) -> Path:
        return self.get_run_base_dir() / "buffers"

    def get_log_dir(self) -> Path:
        return self.get_run_base_dir() / "logs"

    def get_tensorboard_dir(self) -> Path:
        return self.get_run_base_dir() / "tensorboard"

    def get_profile_dir(self) -> Path:
        return self.get_run_base_dir() / "profile_data"

    def get_mlflow_abs_path(self) -> str:
        return str((self.get_app_root_dir() / "mlruns").resolve())

    def create_run_dirs(self) -> None:
        for d in (
            self.get_checkpoint_dir(),
            self.get_buffer_dir(),
            self.get_log_dir(),
            self.get_tensorboard_dir(),
            self.get_profile_dir(),
        ):
            d.mkdir(parents=True, exist_ok=True)


PersistenceConfig.model_rebuild(force=True)
