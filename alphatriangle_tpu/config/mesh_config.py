"""Device-mesh / parallelism configuration (no reference equivalent).

The reference has no collective backend at all — its learner is a single
torch device and its "distribution" is Ray actor RPC (SURVEY.md §2c).
Here the parallelism story is first-class: a `jax.sharding.Mesh` with
named axes, over which the learner train step and the self-play
inference path are pjit-sharded. XLA inserts the ICI collectives.

Axes:
- "dp": data parallel (batch sharding, psum of grads).
- "mdl": model parallel (tensor sharding of wide layers; size 1 by
  default — the flagship net is ~3M params — but the sharding rules are
  written against this axis so scaling it up requires no code change).
- "sp": sequence/context parallel (ring or all-to-all attention over
  sequence shards, `parallel/ring_attention.py`; size 1 by default —
  the flagship spatial sequence is 120 tokens — but long-context runs
  shard attention over this axis with no model-code change).
"""

import math
from typing import TYPE_CHECKING, Literal

from pydantic import BaseModel, Field

if TYPE_CHECKING:  # JAX is imported lazily inside the mesh builders:
    # this module rides the config package, which every JAX-free reader
    # process (`cli perf/mem/watch/health` beside a wedged chip)
    # imports — a module-level jax import here would drag the whole
    # runtime into them.
    from jax.sharding import Mesh


class MeshConfig(BaseModel):
    """Mesh shape + axis names for pjit sharding."""

    # -1 means "all remaining devices" on the dp axis.
    DP_SIZE: int = Field(default=-1)
    MDL_SIZE: int = Field(default=1, ge=1)
    SP_SIZE: int = Field(default=1, ge=1)
    DP_AXIS: str = Field(default="dp")
    MDL_AXIS: str = Field(default="mdl")
    SP_AXIS: str = Field(default="sp")
    # Attention kind used when SP_SIZE > 1 (parallel/ring_attention.py).
    SP_ATTENTION: Literal["ring", "ulysses"] = Field(default="ring")
    # Which JAX platform to build the mesh on ("auto" = default backend).
    PLATFORM: Literal["auto", "tpu", "cpu"] = Field(default="auto")

    def resolve_dp_size(self, n_devices: int) -> int:
        other = self.MDL_SIZE * self.SP_SIZE
        if self.DP_SIZE == -1:
            if n_devices % other != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"MDL_SIZE*SP_SIZE={other}"
                )
            return n_devices // other
        return self.DP_SIZE

    def build_mesh(self, devices: list | None = None) -> "Mesh":
        """Construct the (dp, mdl, sp) mesh over the available devices."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        if devices is None:
            devices = (
                jax.devices()
                if self.PLATFORM == "auto"
                else jax.devices(self.PLATFORM)
            )
        dp = self.resolve_dp_size(len(devices))
        needed = dp * self.MDL_SIZE * self.SP_SIZE
        if needed > len(devices):
            raise ValueError(
                f"Mesh needs {needed} devices (dp={dp} x mdl={self.MDL_SIZE}"
                f" x sp={self.SP_SIZE}), only {len(devices)} available."
            )
        grid = np.asarray(devices[:needed]).reshape(
            dp, self.MDL_SIZE, self.SP_SIZE
        )
        return Mesh(grid, (self.DP_AXIS, self.MDL_AXIS, self.SP_AXIS))

    @staticmethod
    def single_device_mesh() -> "Mesh":
        """A 1x1x1 mesh on the default device (works everywhere)."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        dev = np.asarray(jax.devices()[:1]).reshape(1, 1, 1)
        return Mesh(dev, ("dp", "mdl", "sp"))


def largest_pow2_leq(n: int) -> int:
    """Largest power of two <= n (mesh sizing helper)."""
    return 1 << int(math.log2(n)) if n >= 1 else 1


def rollout_lane_axes(
    mesh: "Mesh", dp_axis: str = "dp", sp_axis: str = "sp"
) -> tuple:
    """Mesh axes the self-play lockstep lanes shard over.

    Lanes ride dp — plus sp when that axis is real: sequence
    parallelism never applies to the board-sized rollout net, so a
    configured sp axis would otherwise idle (or duplicate rollout
    work) during self-play. The single source of this rule for
    training/setup.py, the driver dryrun, and the engine's
    divisibility check — they must exercise the SAME sharding.
    """
    if mesh.shape.get(sp_axis, 1) > 1:
        return (dp_axis, sp_axis)
    return (dp_axis,)


def lane_shard_count(mesh: "Mesh", axes: tuple) -> int:
    """How many ways the lane dim splits over `axes` of `mesh`."""
    n = 1
    for ax in axes:
        n *= mesh.shape.get(ax, 1)
    return n


MeshConfig.model_rebuild(force=True)
