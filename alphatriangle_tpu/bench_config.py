"""Shared bench configuration resolution: one source of truth for the
exact shapes `bench.py` measures and `cli warm` precompiles.

The compile-latency subsystem (compile_cache.py) only pays off when the
warmer lowers PRECISELY the programs the bench will dispatch — same
configs, same batch/chunk/K shapes, same dtypes. Duplicating the bench's
config-building logic in the warm path would drift; both now call
`resolve_bench_plan`, which honors the same env knobs (BENCH_CONFIG,
BENCH_RECIPE, BENCH_GATHER, BENCH_BACKUP, BENCH_PER_SAMPLE,
BENCH_PRECISION, BENCH_WAVE, BENCH_FAST_SIMS, BENCH_FULL_PROB,
BENCH_BATCH) and the same cpu/smoke clamps.
"""

import os
from dataclasses import dataclass, field


@dataclass
class BenchPlan:
    """Everything run_bench / warm need about one measurement config."""

    env: object
    model: object
    mcts: object
    train: object
    scale: str
    sims: int
    sp_batch: int
    chunk: int
    lbatch: int
    description: str = ""
    # Secondary-section shapes, derived the way run_bench derives them.
    fused_k: int = 4
    overlap_k: int = 4
    device_replay: bool = False
    # Policy-service slot count (serving/service.py): the compiled
    # `serve/b<B>` search shape `cli warm` precompiles, `cli fit
    # --serve` analyzes, and bench's serve section measures. Defaults
    # to the scale's self-play lane count (same MXU-batch family).
    serve_batch: int = 0
    # Serve-shape ladder (serving/buckets.py): CSV rung list from
    # BENCH_SERVE_BUCKETS, e.g. "64,256,1024". None means a single
    # fixed rung at serve_batch. Feeds `cli warm` (every rung is
    # AOT-warmed), `cli fit --serve` (per-rung analysis), and bench's
    # serve A/B section (fill-vs-fixed ratio).
    serve_buckets: "str | None" = None
    extras: dict = field(default_factory=dict)


def plan_from_tuned_preset(
    path: str, smoke: bool, backend: str, environ=None
) -> BenchPlan:
    """BenchPlan from a `tuned_preset.json` artifact (`cli tune`).

    The plan's shapes come from the artifact's winning configs, so
    `cli warm <path>`, `cli fit <path>` and a BENCH_TUNED_PRESET bench
    run compile/measure EXACTLY the program shapes the tuned run will
    dispatch. Raises SystemExit on schema mismatch/garbled artifacts
    (same fail-loud contract as BENCH_RECIPE)."""
    env = os.environ if environ is None else environ
    from .config import load_tuned_preset

    try:
        bundle = load_tuned_preset(path)
    except ValueError as exc:
        raise SystemExit(str(exc)) from exc
    payload = bundle["tuned"]
    train_cfg = bundle["train"]
    mode = payload.get("mode", "sync")
    device_replay = bool(
        train_cfg.FUSED_MEGASTEP
        or train_cfg.DEVICE_REPLAY == "on"
        or (
            train_cfg.DEVICE_REPLAY == "auto"
            and backend != "cpu"
            and not smoke
        )
    )
    fused_k = train_cfg.FUSED_LEARNER_STEPS
    sp_batch = train_cfg.SELF_PLAY_BATCH_SIZE
    return BenchPlan(
        env=bundle["env"],
        model=bundle["model"],
        mcts=bundle["mcts"],
        train=train_cfg,
        scale=f"tuned_{payload.get('scale', 'preset')}",
        sims=bundle["mcts"].max_simulations,
        sp_batch=sp_batch,
        chunk=train_cfg.ROLLOUT_CHUNK_MOVES,
        lbatch=train_cfg.BATCH_SIZE,
        description=str(bundle["description"]),
        fused_k=fused_k,
        overlap_k=fused_k,
        device_replay=device_replay,
        serve_batch=int(env.get("BENCH_SERVE_SLOTS") or sp_batch),
        serve_buckets=env.get("BENCH_SERVE_BUCKETS") or None,
        extras={"tuned_preset": str(path), "mode": mode},
    )


def resolve_bench_plan(
    smoke: bool, backend: str, environ=None
) -> BenchPlan:
    """Build the measurement configs for this (backend, env) pair.

    Raises SystemExit on a mislabeled-measurement request (unknown
    BENCH_RECIPE), exactly like the bench always has. BENCH_TUNED_PRESET
    (a `tuned_preset.json` path from `cli tune`) wins over every other
    knob: the plan then measures the tuned shapes verbatim.
    """
    env = os.environ if environ is None else environ
    tuned = env.get("BENCH_TUNED_PRESET")
    if tuned:
        return plan_from_tuned_preset(tuned, smoke, backend, environ=env)
    from .config import (
        AlphaTriangleMCTSConfig,
        EnvConfig,
        ModelConfig,
        TrainConfig,
        expected_other_features_dim,
    )

    preset = env.get("BENCH_CONFIG")
    if preset:
        # One of the five BASELINE configs (config/presets.py).
        from .config import baseline_preset

        bundle = baseline_preset(int(preset), run_name="bench")
        env_cfg, model_cfg = bundle["env"], bundle["model"]
        # Honor the A/B knobs in the preset path too (a silently
        # ignored knob would mislabel the measurement).
        preset_mcts_updates: dict = {
            "descent_gather": env.get("BENCH_GATHER", "einsum"),
            "backup_update": env.get("BENCH_BACKUP", "xla"),
        }
        if env.get("BENCH_WAVE"):
            preset_mcts_updates["mcts_batch_size"] = int(env["BENCH_WAVE"])
        if env.get("BENCH_FAST_SIMS"):
            preset_mcts_updates["fast_simulations"] = int(
                env["BENCH_FAST_SIMS"]
            )
            preset_mcts_updates["full_search_prob"] = float(
                env.get("BENCH_FULL_PROB", "0.25")
            )
        preset_recipe = env.get("BENCH_RECIPE")
        if preset_recipe not in (None, "", "puct", "gumbel_pcr"):
            raise SystemExit(
                f"Unknown BENCH_RECIPE={preset_recipe!r} "
                "(valid: puct, gumbel_pcr) — refusing to run a "
                "mislabeled measurement."
            )
        if preset_recipe == "puct":
            preset_mcts_updates["root_selection"] = "puct"
            preset_mcts_updates.setdefault("fast_simulations", None)
        elif preset_recipe == "gumbel_pcr":
            preset_mcts_updates["root_selection"] = "gumbel"
            preset_mcts_updates.setdefault(
                "fast_simulations",
                max(1, bundle["mcts"].max_simulations // 4),
            )
            preset_mcts_updates.setdefault("full_search_prob", 0.25)
        mcts_cfg = bundle["mcts"].model_copy(update=preset_mcts_updates)
        train_updates = {
            "BUFFER_CAPACITY": 10_000,
            "MIN_BUFFER_SIZE_TO_TRAIN": 1_000,
            "MAX_TRAINING_STEPS": 1_000,
        }
        if backend == "cpu" or smoke:
            # Neither a CPU nor a smoke run can push the preset's full
            # lane count; keep the net/search knobs, shrink lanes.
            cap = 16 if smoke else 64
            train_updates["SELF_PLAY_BATCH_SIZE"] = min(
                cap, bundle["train"].SELF_PLAY_BATCH_SIZE
            )
            train_updates["ROLLOUT_CHUNK_MOVES"] = 4
        if env.get("BENCH_BATCH"):
            # Lane-count A/B (see the non-preset path note). Still
            # bounded by the cpu/smoke clamp above: a flagship lane
            # count on a CPU fallback would blow the whole budget on
            # one chunk.
            requested = int(env["BENCH_BATCH"])
            if backend == "cpu" or smoke:
                requested = min(
                    requested, train_updates["SELF_PLAY_BATCH_SIZE"]
                )
            train_updates["SELF_PLAY_BATCH_SIZE"] = requested
        if backend == "cpu":
            model_cfg = model_cfg.model_copy(
                update={"COMPUTE_DTYPE": "float32"}
            )
        # Rollout/serve inference precision A/B (nn/precision.py,
        # docs/KERNELS.md); the learner keeps consuming f32 params.
        model_cfg = model_cfg.model_copy(
            update={
                "INFERENCE_PRECISION": env.get(
                    "BENCH_PRECISION", "float32"
                )
            }
        )
        train_updates["PER_SAMPLE_BACKEND"] = env.get(
            "BENCH_PER_SAMPLE", "xla"
        )
        # Rebuild via the constructor so validation + schedule-length
        # derivation run against the bench horizon.
        base_kw = bundle["train"].model_dump()
        base_kw.pop("LR_SCHEDULER_T_MAX", None)
        base_kw.pop("PER_BETA_ANNEAL_STEPS", None)
        base_kw.update(train_updates)
        train_cfg = TrainConfig(**base_kw)
        scale = f"baseline_config_{preset}"
        sims = mcts_cfg.max_simulations
        sp_batch = train_cfg.SELF_PLAY_BATCH_SIZE
        chunk = train_cfg.ROLLOUT_CHUNK_MOVES
        lbatch = train_cfg.BATCH_SIZE
        description = bundle["description"]
    else:
        # Three scales: smoke (sanity), cpu (a CPU can't push the
        # flagship load — one flagship chunk is ~30 min of CPU leaf
        # evals — so the fallback measures a reduced but honest
        # config), flagship (TPU).
        if smoke:
            scale, sims, depth, sp_batch, chunk, lbatch = (
                "smoke", 8, 4, 16, 4, 32,
            )
        elif backend == "cpu":
            scale, sims, depth, sp_batch, chunk, lbatch = (
                "cpu", 16, 8, 64, 4, 128,
            )
        else:
            scale, sims, depth, sp_batch, chunk, lbatch = (
                "flagship", 64, 8, 512, 16, 256,
            )
        env_cfg = EnvConfig()
        model_cfg = ModelConfig(
            OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
            COMPUTE_DTYPE="float32" if backend == "cpu" else "bfloat16",
            # Rollout/serve inference precision A/B (nn/precision.py,
            # docs/KERNELS.md); the learner keeps f32 params.
            INFERENCE_PRECISION=env.get("BENCH_PRECISION", "float32"),
        )
        mcts_kw: dict = {}
        if env.get("BENCH_FAST_SIMS"):
            # Playout cap randomization A/B (KataGo; docs in
            # config/mcts_config.py): BENCH_FAST_SIMS=16 [BENCH_FULL_PROB=0.25]
            mcts_kw["fast_simulations"] = int(env["BENCH_FAST_SIMS"])
            mcts_kw["full_search_prob"] = float(
                env.get("BENCH_FULL_PROB", "0.25")
            )
        if env.get("BENCH_WAVE"):
            # Wave-size A/B: simulations evaluated in parallel per tree
            # (the MXU batch per eval is SELF_PLAY_BATCH_SIZE x wave).
            mcts_kw["mcts_batch_size"] = int(env["BENCH_WAVE"])
        if env.get("BENCH_BATCH"):
            # Lane-count A/B: more lockstep games per dispatch = bigger
            # MXU batches per wave eval (flagship B=512 measured 1.4%
            # self-play MFU — lane count is the direct lever on it).
            # On cpu/smoke the scale's own lane count is the ceiling: a
            # flagship lane count on a CPU fallback would blow the whole
            # budget on one chunk.
            requested = int(env["BENCH_BATCH"])
            if scale in ("cpu", "smoke"):
                requested = min(requested, sp_batch)
            sp_batch = requested
        recipe = env.get(
            "BENCH_RECIPE", "gumbel_pcr" if scale == "flagship" else "puct"
        )
        if recipe not in ("puct", "gumbel_pcr"):
            raise SystemExit(
                f"Unknown BENCH_RECIPE={recipe!r} (valid: puct, "
                "gumbel_pcr) — refusing to run a mislabeled measurement."
            )
        if recipe == "gumbel_pcr":
            # The flagship training recipe: Gumbel root + playout cap
            # randomization — the measured-best learning arm (+11%
            # converged eval at <1/2 search cost, BASELINE.md A/Bs).
            # BENCH_RECIPE=puct measures the reference-parity search.
            mcts_kw["root_selection"] = "gumbel"
            mcts_kw.setdefault("fast_simulations", max(1, sims // 4))
            mcts_kw.setdefault("full_search_prob", 0.25)
        mcts_cfg = AlphaTriangleMCTSConfig(
            max_simulations=sims,
            max_depth=depth,
            # A/B knobs for the descent row-gather and fused-backup
            # lowerings (ops/gather_rows.py, ops/mcts_backup.py).
            descent_gather=env.get("BENCH_GATHER", "einsum"),
            backup_update=env.get("BENCH_BACKUP", "xla"),
            **mcts_kw,
        )
        train_cfg = TrainConfig(
            SELF_PLAY_BATCH_SIZE=sp_batch,
            ROLLOUT_CHUNK_MOVES=chunk,
            BATCH_SIZE=lbatch,
            BUFFER_CAPACITY=10_000,
            MIN_BUFFER_SIZE_TO_TRAIN=1_000,
            MAX_TRAINING_STEPS=1_000,
            PER_SAMPLE_BACKEND=env.get("BENCH_PER_SAMPLE", "xla"),
            RUN_NAME="bench",
        )
        description = f"{scale} scale"

    # Secondary-section shapes, exactly as run_bench derives them:
    # fused groups keep K small where the scan unrolls (cpu/smoke), the
    # overlapped section amortizes the producer interleave with K=64 on
    # accelerators, and device-resident replay only exists off-CPU.
    fused_k = 4 if (smoke or backend == "cpu") else 16
    overlap_k = fused_k if (smoke or backend == "cpu") else 64
    device_replay = backend != "cpu" and not smoke
    # Serve slot count: the self-play lane count unless overridden
    # (BENCH_SERVE_SLOTS) — one compiled search shape shared between
    # the rollout's search and the policy service's.
    serve_batch = int(env.get("BENCH_SERVE_SLOTS") or sp_batch)
    return BenchPlan(
        env=env_cfg,
        model=model_cfg,
        mcts=mcts_cfg,
        train=train_cfg,
        scale=scale,
        sims=sims,
        sp_batch=sp_batch,
        chunk=chunk,
        lbatch=lbatch,
        description=description,
        fused_k=fused_k,
        overlap_k=overlap_k,
        device_replay=device_replay,
        serve_batch=serve_batch,
        serve_buckets=env.get("BENCH_SERVE_BUCKETS") or None,
    )
