"""Live-run console: tail a run's `live_metrics.jsonl` and render rates.

The reference ships a dashboard path for watching a run (Ray dashboard
+ MLflow UI as first-class CLI concerns, `alphatriangle/cli.py:301-326`,
its `README.md:63-79`). Here the equivalent is file-shaped: the
`StatsCollector` appends one JSON line per aggregation tick to the run
dir, and `cli watch` tails it from any shell — including one on a
laptop reading a mounted/rsynced run dir — without importing JAX or
touching the (possibly wedged) accelerator.

Pure functions + a small folding state so the rendering is unit-testable
without a live run.
"""

import json
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path

# Window over which rates (games/h, steps/s) are computed: long enough
# to smooth chunked arrivals, short enough to track a run going sick.
RATE_WINDOW_S = 120.0

# Shed-rate window for the fleet line: short — back-pressure is a
# now-problem, and the rate should fall back to zero quickly once the
# brownout passes.
FLEET_RATE_WINDOW_S = 60.0


@dataclass
class WatchState:
    """Folds live-metric ticks; exposes latest values + windowed rates."""

    latest: dict = field(default_factory=dict)
    latest_step: int = 0
    latest_time: float = 0.0
    # Newest utilization record from the metrics ledger
    # (telemetry/perf.py): MFU, step time, transfer costs.
    util: dict = field(default_factory=dict)
    # Newest flight-ring records (telemetry/flight.py): the last intent
    # written and the last seal — together they say what the device is
    # doing RIGHT NOW (or what it finished last).
    flight_intent: dict = field(default_factory=dict)
    flight_seal: dict = field(default_factory=dict)
    # (wall time, step, cumulative episodes) samples for rate windows.
    _samples: deque = field(default_factory=lambda: deque(maxlen=512))

    def fold_line(self, line: str) -> bool:
        """Fold one JSONL line; returns False for junk (torn writes)."""
        line = line.strip()
        if not line:
            return False
        try:
            tick = json.loads(line)
            step = int(tick["step"])
            wall = float(tick["time"])
            means = tick["means"]
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            return False
        self.latest.update(means)
        self.latest_step = step
        self.latest_time = wall
        self._samples.append(
            (wall, step, means.get("Progress/Episodes_Played"))
        )
        return True

    def fold_util_line(self, line: str) -> bool:
        """Fold one metrics-ledger line; only `kind: util` records are
        kept (tick records duplicate live_metrics.jsonl). Returns False
        for junk/torn/non-util lines."""
        line = line.strip()
        if not line:
            return False
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            return False
        if not isinstance(rec, dict) or rec.get("kind") != "util":
            return False
        self.util = rec
        return True

    def fold_flight_line(self, line: str) -> bool:
        """Fold one flight-ring line (telemetry/flight.py schema);
        keeps the newest intent and the newest seal. Returns False for
        junk/torn/non-flight lines."""
        line = line.strip()
        if not line:
            return False
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            return False
        if not isinstance(rec, dict) or rec.get("kind") != "flight":
            return False
        phase = rec.get("phase")
        if phase == "intent":
            self.flight_intent = rec
        elif phase == "seal":
            self.flight_seal = rec
        else:
            return False
        return True

    def _window(self) -> "tuple | None":
        """(oldest, newest) samples spanning <= RATE_WINDOW_S, or None."""
        if len(self._samples) < 2:
            return None
        newest = self._samples[-1]
        oldest = None
        for s in self._samples:
            if newest[0] - s[0] <= RATE_WINDOW_S:
                oldest = s
                break
        if oldest is None or newest[0] <= oldest[0]:
            return None
        return oldest, newest

    @property
    def steps_per_sec(self) -> "float | None":
        w = self._window()
        if w is None:
            return None
        (t0, s0, _), (t1, s1, _) = w
        return (s1 - s0) / (t1 - t0)

    @property
    def games_per_hour(self) -> "float | None":
        # The collector flushes only metrics logged since the last
        # tick, so learner-only ticks carry no episode count; take the
        # oldest/newest samples IN the window that have one, not the
        # literal endpoints — otherwise the headline rate flaps to "—"
        # whenever a learner-dominated tick lands last.
        if len(self._samples) < 2:
            return None
        newest_t = self._samples[-1][0]
        with_eps = [
            s
            for s in self._samples
            if s[2] is not None and newest_t - s[0] <= RATE_WINDOW_S
        ]
        if len(with_eps) < 2:
            return None
        (t0, _, e0), (t1, _, e1) = with_eps[0], with_eps[-1]
        if t1 <= t0:
            return None
        return (e1 - e0) * 3600.0 / (t1 - t0)

    @property
    def age_seconds(self) -> "float | None":
        """Seconds since the last tick (stall indicator)."""
        if not self.latest_time:
            return None
        return max(0.0, time.time() - self.latest_time)


def _fmt(value: "float | None", spec: str = ",.1f", unit: str = "") -> str:
    if value is None:
        return "—"
    return f"{value:{spec}}{unit}"


#: fleet.jsonl lifecycle events -> the replica status they imply.
_FLEET_STATUS = {
    "spawn": "starting",
    "respawn": "starting",
    "replica-ready": "up",
    "readmit": "up",
    "evict": "evicted",
    "death": "down",
    "give-up": "gone",
}

#: Router decision events worth echoing as "last decision".
_ROUTER_EVENTS = {"shed", "retry", "exhausted", "hedge", "hedge-win"}


@dataclass
class FleetWatchState:
    """Folds `fleet.jsonl` events (serving/fleet.py `_event` schema:
    lifecycle spawns/deaths/evictions interleaved with router
    shed/retry/hedge decisions) into the `cli watch` fleet line."""

    #: replica name -> last lifecycle status (see _FLEET_STATUS).
    replicas: dict = field(default_factory=dict)
    #: replica name -> current serve rung (slot count). Spawn/ready/
    #: readmit events carry `slots`, so a quarantine-halved or
    #: ladder-walked replica shows its real shape here.
    rungs: dict = field(default_factory=dict)
    #: replica name -> inference precision ("int8"/"bfloat16"/...).
    precisions: dict = field(default_factory=dict)
    #: newest router admission level (requests in flight at the router).
    inflight: "int | None" = None
    sheds: int = 0
    retries: int = 0
    exhausted: int = 0
    hedges: int = 0
    hedge_wins: int = 0
    deaths: int = 0
    #: newest router decision record, verbatim.
    last_decision: dict = field(default_factory=dict)
    latest_time: float = 0.0
    _shed_times: deque = field(default_factory=lambda: deque(maxlen=2048))

    def fold_fleet_line(self, line: str) -> bool:
        """Fold one fleet.jsonl line; False for junk/torn/non-fleet
        lines (same contract as the other folders — tolerant of
        legacy records without trace ids)."""
        line = line.strip()
        if not line:
            return False
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            return False
        if not isinstance(rec, dict) or rec.get("kind") != "fleet":
            return False
        event = rec.get("event")
        t = rec.get("time")
        if isinstance(t, (int, float)):
            self.latest_time = max(self.latest_time, float(t))
        name = rec.get("replica")
        status = _FLEET_STATUS.get(event)
        if status is not None and name:
            self.replicas[str(name)] = status
            # Legacy records carry neither field; fold only what's there.
            if isinstance(rec.get("slots"), int):
                self.rungs[str(name)] = rec["slots"]
            if isinstance(rec.get("precision"), str):
                self.precisions[str(name)] = rec["precision"]
        if isinstance(rec.get("inflight"), int):
            self.inflight = rec["inflight"]
        if event == "shed":
            self.sheds += 1
            if isinstance(t, (int, float)):
                self._shed_times.append(float(t))
        elif event == "retry":
            self.retries += 1
        elif event == "exhausted":
            self.exhausted += 1
        elif event == "hedge":
            self.hedges += 1
        elif event == "hedge-win":
            self.hedge_wins += 1
        elif event == "death":
            self.deaths += 1
        if event in _ROUTER_EVENTS:
            self.last_decision = rec
        return True

    @property
    def routable(self) -> int:
        return sum(1 for s in self.replicas.values() if s == "up")

    @property
    def shed_per_min(self) -> float:
        """Sheds per minute over the trailing event-time window (event
        time, not wall time — a finished ledger renders its own end)."""
        if not self._shed_times or not self.latest_time:
            return 0.0
        cutoff = self.latest_time - FLEET_RATE_WINDOW_S
        n = sum(1 for t in self._shed_times if t > cutoff)
        return n * 60.0 / FLEET_RATE_WINDOW_S


def fleet_line(state: FleetWatchState) -> "str | None":
    """Render the fleet's routing vitals as watch lines: routable
    replicas, router queue depth, windowed shed rate, and the last
    router decision (with its trace id, the hook into `cli trace
    --fleet`). None when no fleet events have been folded (not a
    fleet-parent run dir)."""
    if not state.replicas and not state.last_decision:
        return None
    total = len(state.replicas)
    line = (
        f"  fleet        {state.routable}/{total} routable"
        f"   inflight {_fmt(state.inflight, ',.0f')}"
        f"   sheds {state.sheds:,} ({state.shed_per_min:,.1f}/min)"
        f"   retries {state.retries:,}"
        f"   hedges {state.hedges:,} ({state.hedge_wins:,} won)"
        f"   deaths {state.deaths:,}"
    )
    if state.rungs or state.precisions:
        # One segment per replica that reported a shape: "r0 up b4 int8".
        # Fleets started before rung/precision reporting render nothing
        # extra here (legacy fleet.jsonl stays byte-identical above).
        segs = []
        for name in sorted(set(state.rungs) | set(state.precisions)):
            seg = f"{name} {state.replicas.get(name, '?')}"
            if name in state.rungs:
                seg += f" b{state.rungs[name]}"
            if name in state.precisions:
                seg += f" {state.precisions[name]}"
            segs.append(seg)
        line += "\n  replicas     " + "   ".join(segs)
    d = state.last_decision
    if d:
        parts = [f"last {d.get('event')}"]
        if d.get("rejection"):
            parts.append(str(d["rejection"]))
        if d.get("replica"):
            parts.append(f"-> {d['replica']}")
        if isinstance(d.get("attempt"), int):
            parts.append(f"attempt {d['attempt']}")
        tid = d.get("trace_id")
        if isinstance(tid, str) and tid:
            parts.append(f"trace {tid[:8]}…")
        line += "\n  router       " + " ".join(parts)
    return line


def health_line(health: "dict | None", now: "float | None" = None) -> "str | None":
    """Render the heartbeat (`health.json`, telemetry.HealthMonitor) as
    one liveness line with an explicit stall verdict: heartbeat age past
    the watchdog deadline, or a watchdog-flagged stall, both render as
    STALLED. None when no heartbeat exists (pre-telemetry run)."""
    if not isinstance(health, dict) or "time" not in health:
        return None
    now = time.time() if now is None else now
    age = max(0.0, now - float(health.get("time") or 0.0))
    deadline = float(health.get("watchdog_deadline_s") or 300.0)
    step = health.get("learner_step") or 0
    if age > deadline:
        return f"  health       STALLED (no heartbeat for {age:,.0f}s)"
    if health.get("stalled"):
        return (
            "  health       STALLED (watchdog: no training progress; "
            f"heartbeat {age:,.0f}s ago)"
        )
    return (
        f"  health       live (heartbeat {age:,.0f}s ago, "
        f"learner step {step:,})"
    )


def _gib(value: "float | None") -> str:
    if not isinstance(value, (int, float)):
        return "—"
    return f"{value / 2**30:,.2f} GiB"


def memory_line(util: dict) -> "str | None":
    """Render the newest utilization record's device-memory fields
    (telemetry/memory.py: in-use / run-peak / % of limit) as one watch
    line; None when the record predates memory accounting."""
    in_use = util.get("mem_bytes_in_use")
    if not isinstance(in_use, (int, float)):
        return None
    peak = util.get("mem_peak_bytes_in_use")
    limit = util.get("mem_bytes_limit")
    pct = util.get("mem_utilization")
    line = f"  memory       {_gib(in_use)} in use   peak {_gib(peak)}"
    if isinstance(limit, (int, float)) and limit:
        line += f"   limit {_gib(limit)}"
        if isinstance(pct, (int, float)):
            line += f" ({pct:.1%})"
    return line


def device_stats_line(util: dict) -> "str | None":
    """Render the newest util record's device-stats gauges (the
    in-program stat-pack mirror — telemetry/device_stats.py) as one
    watch line; None when the run predates the plane or has it off."""
    entropy = util.get("root_visit_entropy")
    occupancy = util.get("tree_occupancy")
    if not isinstance(entropy, (int, float)) and not isinstance(
        occupancy, (int, float)
    ):
        return None
    line = (
        f"  search       root entropy {_fmt(entropy, ',.2f')}"
        f"   tree occupancy {_fmt(occupancy * 100 if isinstance(occupancy, (int, float)) else None, ',.0f', '%')}"
    )
    if util.get("beacons_armed"):
        line += "   BEACONS ARMED"
    return line


def idle_line(util: dict) -> "str | None":
    """Render the newest util record's chip-idle gauge (the roofline
    attribution plane — telemetry/roofline.py: fraction of the last
    tick window with no dispatch in flight) as one watch line; None
    when the run predates the plane or the flight ring is off."""
    idle = util.get("chip_idle_fraction")
    if not isinstance(idle, (int, float)):
        return None
    line = f"  roofline     chip idle {_fmt(idle * 100, ',.1f', '%')}"
    if idle >= 0.5:
        line += "  — HOST-BOUND?"
    return line


def last_dispatch_line(
    state: WatchState, now: "float | None" = None
) -> "str | None":
    """Render the flight ring's freshest record as one line: the
    program in flight right now (age vs expected/deadline — the wedge
    early-warning), or the last sealed program's measured wall. None
    when the run has no flight records (recorder off or pre-flight)."""
    intent, seal = state.flight_intent, state.flight_seal
    if not intent and not seal:
        return None
    now = time.time() if now is None else now
    in_flight = bool(intent) and (
        not seal or (intent.get("seq", -1) or 0) > (seal.get("seq", -1) or 0)
    )
    if in_flight:
        t = intent.get("time")
        age = max(0.0, now - float(t)) if isinstance(t, (int, float)) else None
        expected = intent.get("expected_s")
        deadline = intent.get("deadline_s")
        line = (
            f"  dispatch     {intent.get('program')} "
            f"[{intent.get('family')}] in flight"
            f" {_fmt(age, ',.0f', 's')}"
        )
        if isinstance(expected, (int, float)):
            line += f"   expected {expected:,.1f}s"
        if isinstance(deadline, (int, float)):
            line += f"   deadline {deadline:,.0f}s"
            if age is not None and age > deadline:
                line += "  — OVER DEADLINE"
        return line
    t = seal.get("time")
    age = max(0.0, now - float(t)) if isinstance(t, (int, float)) else None
    ok = seal.get("ok", True)
    return (
        f"  dispatch     {seal.get('program')} [{seal.get('family')}]"
        f" sealed {_fmt(age, ',.0f', 's')} ago"
        f"   wall {_fmt(seal.get('wall_s'), ',.2f', 's')}"
        + ("" if ok else "  — ERROR")
    )


def render_frame(
    state: WatchState, run_name: str, health: "dict | None" = None
) -> str:
    """One console frame: the run's vital signs, newest tick first."""
    m = state.latest
    age = state.age_seconds
    stale = age is not None and age > 300
    lines = [
        f"run {run_name} @ step {state.latest_step:,}"
        + (
            f"   (last tick {_fmt(age, ',.0f', 's')} ago"
            + (" — STALLED?)" if stale else ")")
            if age is not None
            else ""
        ),
        "",
        f"  self-play    {_fmt(state.games_per_hour, ',.0f')} games/h"
        f"   episodes {_fmt(m.get('Progress/Episodes_Played'), ',.0f')}"
        f"   score {_fmt(m.get('SelfPlay/Episode_Score'), ',.2f')}"
        f"   len {_fmt(m.get('SelfPlay/Episode_Length'), ',.1f')}",
        f"  learner      {_fmt(state.steps_per_sec, ',.2f')} steps/s"
        f"   loss {_fmt(m.get('Loss/total_loss'), ',.4f')}"
        f"   grad-norm {_fmt(m.get('Loss/Grad_Norm'), ',.3f')}",
        f"  replay       ratio {_fmt(m.get('System/Replay_Ratio_Actual'), ',.3f')}"
        f"   buffer {_fmt(m.get('Buffer/Size'), ',.0f')}"
        f"   staleness {_fmt(m.get('SelfPlay/Staleness_Steps'), ',.1f')} steps",
        f"  pipeline     queue {_fmt(m.get('System/Rollout_Queue_Depth'), ',.1f')}"
        f"   producer restarts {_fmt(m.get('System/Producer_Restarts'), ',.0f')}"
        f"   full-search {_fmt(m.get('SelfPlay/Full_Search_Fraction'), ',.2f')}",
    ]
    u = state.util
    if u:
        mfu = u.get("mfu")
        lines.append(
            f"  utilization  MFU {_fmt(mfu * 100 if mfu is not None else None, ',.2f', '%')}"
            f"   {_fmt(u.get('tflops_per_sec'), ',.2f')} TFLOP/s"
            f"   step {_fmt(u.get('step_time_ms'), ',.0f', 'ms')}"
            f"   xfer h2d {_fmt(u.get('transfer_h2d_ms'), ',.0f', 'ms')}"
            f" d2h {_fmt(u.get('transfer_d2h_ms'), ',.0f', 'ms')}"
        )
        mline = memory_line(u)
        if mline is not None:
            lines.append(mline)
        dsline = device_stats_line(u)
        if dsline is not None:
            lines.append(dsline)
        iline = idle_line(u)
        if iline is not None:
            lines.append(iline)
    dline = last_dispatch_line(state)
    if dline is not None:
        lines.append(dline)
    hline = health_line(health)
    if hline is not None:
        lines.append(hline)
    return "\n".join(lines)


def tail_jsonl(path: Path, fold, offset: int = 0) -> int:
    """Fold JSONL lines appended past `offset`; returns the new offset.

    Tolerates the file not existing yet (run still compiling), a torn
    final line (kept un-consumed and reread next tick — a line only
    counts once its newline lands), junk bytes inside a line (the fold
    callbacks reject them), and undecodable bytes (replaced, so a
    partially-written multibyte character can't raise)."""
    try:
        size = path.stat().st_size
    except OSError:
        return offset
    if size <= offset:
        # Truncated (fresh run reusing the dir) — start over.
        return 0 if size < offset else offset
    try:
        with path.open("r", errors="replace") as f:
            f.seek(offset)
            chunk = f.read()
    except OSError:
        return offset
    # Keep a torn trailing line for the next read.
    end = chunk.rfind("\n")
    if end < 0:
        return offset
    for line in chunk[: end + 1].splitlines():
        fold(line)
    return offset + end + 1


def tail_live_metrics(
    path: Path,
    state: WatchState,
    offset: int = 0,
) -> int:
    """Fold `live_metrics.jsonl` ticks appended past `offset`."""
    return tail_jsonl(path, state.fold_line, offset)


def tail_ledger_utils(
    path: Path,
    state: WatchState,
    offset: int = 0,
) -> int:
    """Fold `metrics.jsonl` utilization records appended past `offset`."""
    return tail_jsonl(path, state.fold_util_line, offset)


def tail_flight(
    path: Path,
    state: WatchState,
    offset: int = 0,
) -> int:
    """Fold `flight.jsonl` dispatch records appended past `offset`."""
    return tail_jsonl(path, state.fold_flight_line, offset)


def tail_fleet(
    path: Path,
    state: FleetWatchState,
    offset: int = 0,
) -> int:
    """Fold `fleet.jsonl` events appended past `offset`."""
    return tail_jsonl(path, state.fold_fleet_line, offset)


def find_latest_run_dir(runs_root: Path) -> "Path | None":
    """Most recently modified run dir under the runs root (host-side
    twin of CheckpointManager.find_latest_run, importable without JAX)."""
    try:
        candidates = [p for p in runs_root.iterdir() if p.is_dir()]
    except OSError:
        return None
    if not candidates:
        return None

    def mtime(p: Path) -> float:
        # A run dir can be deleted (cleanup, tmpdir teardown) between
        # the listing above and this stat; treat it as infinitely old
        # instead of crashing `cli watch` at startup.
        try:
            return p.stat().st_mtime
        except OSError:
            return 0.0

    return max(candidates, key=mtime)
