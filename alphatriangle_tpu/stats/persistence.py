"""Checkpoint/resume + buffer spill (trieye persistence equivalent).

Parity surface per the reference call sites (`training/runner.py:28-163`,
`training/loop.py:173-211`, SURVEY.md §3.4): periodic checkpoint of
model/optimizer state + counters, optional replay-buffer spill,
`load_initial_state`-style restore, and auto-resume from the latest run.

TPU-native shape: the learner state is a jax pytree (`TrainState`), so
checkpoints are **Orbax** trees — standard, async-written, readable by
any JAX tool — instead of cloudpickled torch state dicts. The dense SoA
replay buffer spills to a compressed `.npz` (fixed-shape arrays, no
pickle). Improvement over the reference: PER priorities are persisted
and restored (the reference resets them to max on resume,
`runner.py:87-91`).

Crash-integrity contract (docs/ROBUSTNESS.md): every sidecar file
(meta.json, configs.json, buffer spills, commit markers) is written via
tmp + `os.replace`, so a SIGKILL mid-write can never leave a torn file
that auto-resume trusts. The Orbax tree itself is async-written and CAN
be torn by a kill — so a `step_XXXXXXXX.commit` marker is written only
after `wait_until_finished()` proves the tree landed, and restore skips
any step directory lacking its marker, falling back to the previous
valid step instead of crashing.
"""

import json
import logging
import os
import re
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import numpy as np
import orbax.checkpoint as ocp

from ..config.persistence_config import PersistenceConfig
from ..parallel.distributed import is_primary
from ..rl.buffer import ExperienceBuffer

logger = logging.getLogger(__name__)

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")
_COMMIT_RE = re.compile(r"^step_(\d+)\.commit$")


def _atomic_write_text(path: Path, text: str) -> None:
    """Write `text` to `path` via tmp + os.replace: readers see either
    the old content or the new, never a torn half-write."""
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


def _fault_point(site: str, n: int) -> None:
    """Fault-injection hook (supervise/faults.py). No-op unless armed
    via ALPHATRIANGLE_FAULTS; the lazy import keeps the common path
    free of any supervise dependency."""
    if not os.environ.get("ALPHATRIANGLE_FAULTS"):
        return
    from ..supervise.faults import fault_point

    fault_point(site, n)


@dataclass
class LoadedTrainingState:
    """Everything a resumed run needs (reference `LoadedTrainingState`)."""

    train_state: Any | None = None
    buffer_loaded: bool = False
    counters: dict[str, Any] = field(default_factory=dict)
    run_name: str | None = None
    global_step: int = 0


class CheckpointManager:
    """Owns one run's checkpoint/buffer directories."""

    def __init__(self, persistence: PersistenceConfig):
        self.config = persistence
        persistence.create_run_dirs()
        self._ckpt_dir = persistence.get_checkpoint_dir().resolve()
        self._buffer_dir = persistence.get_buffer_dir().resolve()
        self._ckptr = ocp.StandardCheckpointer()
        # Steps whose Orbax save has been dispatched but whose commit
        # marker is not yet on disk (written once the async write lands).
        # Guarded by the lock: the background flusher thread snapshots
        # and clears it concurrently with `save()` adding to it.
        self._pending_commits: set[int] = set()
        self._commit_lock = threading.Lock()
        self._flusher: threading.Thread | None = None

    # --- save -------------------------------------------------------------

    def save(
        self,
        step: int,
        train_state: Any,
        counters: dict[str, Any] | None = None,
    ) -> Path:
        """Checkpoint `train_state` (async) + counters; buffer spills go
        through `save_buffer`. Returns the checkpoint path.

        Multi-host discipline: EVERY process must call this (the Orbax
        save is a collective over the state's global arrays); the plain
        file writes (meta.json, commit markers, pruning) happen on
        process 0 only.
        """
        path = self._ckpt_dir / f"step_{step:08d}"
        if path.exists():  # overwrite-safe for forced final saves
            import shutil

            # An async save of this step may still be in flight; let it
            # land before removing, or the writer races the rmtree.
            self._ckptr.wait_until_finished()
            self._flush_commit_markers()
            if is_primary():
                shutil.rmtree(path, ignore_errors=True)
                self._commit_marker_path(step).unlink(missing_ok=True)
        elif self._pending_commits:
            # The previous async save had a full checkpoint cadence to
            # land; settle it so its commit marker certifies the tree
            # before a new save goes in flight.
            self._ckptr.wait_until_finished()
            self._flush_commit_markers()
        self._ckptr.save(path, train_state)
        if not is_primary():
            return path
        meta = {"global_step": step, **(counters or {})}
        _atomic_write_text(
            self._ckpt_dir / f"step_{step:08d}.meta.json",
            json.dumps(meta, indent=2),
        )
        with self._commit_lock:
            self._pending_commits.add(step)
        _fault_point("checkpoint-save", step)
        self._spawn_marker_flusher()
        logger.info("Checkpoint saved at step %d -> %s", step, path)
        self._prune_checkpoints(just_saved=step)
        return path

    def _commit_marker_path(self, step: int) -> Path:
        return self._ckpt_dir / f"step_{step:08d}.commit"

    def _spawn_marker_flusher(self) -> None:
        """Commit the in-flight save from a background thread as soon as
        it lands. Without this the marker would wait for the NEXT
        save/close to settle it, and a death between cadences would look
        a whole cadence staler than it is (`cli supervise` reads the
        markers to pick its restart point)."""
        if self._flusher is not None and self._flusher.is_alive():
            return  # the live flusher will settle everything pending
        self._flusher = threading.Thread(
            target=self._flush_after_wait,
            name="ckpt-commit-flush",
            daemon=True,
        )
        self._flusher.start()

    def _flush_after_wait(self) -> None:
        # Snapshot BEFORE waiting: steps added during the wait belong to
        # a save dispatched after it started, which the wait does not
        # prove landed.
        with self._commit_lock:
            steps = set(self._pending_commits)
        try:
            self._ckptr.wait_until_finished()
        except Exception:
            logger.exception("Async checkpoint wait failed; markers unflushed")
            return
        self._flush_commit_markers(steps)

    def _flush_commit_markers(self, steps: "set[int] | None" = None) -> None:
        """Write commit markers for landed saves. Only call after
        `wait_until_finished()`: the marker's existence certifies the
        Orbax tree is fully on disk. `steps=None` flushes everything
        pending (single-dispatcher callers that just waited)."""
        with self._commit_lock:
            if steps is None:
                steps = set(self._pending_commits)
        if not is_primary():
            with self._commit_lock:
                self._pending_commits -= steps
            return
        # Only settle steps whose tree is actually on disk: a flusher
        # whose wait raced a concurrent save dispatch may observe a
        # step before its directory finalizes, and dropping it from
        # pending here would silently lose the commit forever — keep
        # it pending for the next settle point instead.
        written = set()
        for step in sorted(steps):
            if (self._ckpt_dir / f"step_{step:08d}").is_dir():
                try:
                    _atomic_write_text(
                        self._commit_marker_path(step),
                        json.dumps({"global_step": step}),
                    )
                except OSError:
                    # The run dir vanished under the writer (external
                    # cleanup/teardown): nothing left to certify.
                    pass
                written.add(step)
        with self._commit_lock:
            self._pending_commits -= written

    def _prune_checkpoints(self, just_saved: int) -> None:
        keep = self.config.KEEP_LAST_CHECKPOINTS
        if keep <= 0:
            return
        # The save above is async; its directory may not be listable
        # yet, so count the just-saved step explicitly.
        steps = sorted(
            {
                int(m.group(1))
                for p in self._ckpt_dir.iterdir()
                if p.is_dir() and (m := _STEP_DIR_RE.match(p.name))
            }
            | {just_saved}
        )
        if len(steps) <= keep:
            return
        import shutil

        # Async writes to the survivors may be in flight; only the
        # doomed dirs matter, but Orbax tracks saves globally.
        self._ckptr.wait_until_finished()
        self._flush_commit_markers()
        for step in steps[:-keep]:
            shutil.rmtree(
                self._ckpt_dir / f"step_{step:08d}", ignore_errors=True
            )
            (self._ckpt_dir / f"step_{step:08d}.meta.json").unlink(
                missing_ok=True
            )
            self._commit_marker_path(step).unlink(missing_ok=True)
            logger.debug("Pruned checkpoint step %d", step)

    def _prune_buffers(self) -> None:
        keep = self.config.KEEP_LAST_BUFFERS
        if keep <= 0:
            return
        spills = sorted(self._buffer_dir.glob("buffer_*.npz"))
        for path in spills[:-keep] if len(spills) > keep else []:
            path.unlink(missing_ok=True)
            logger.debug("Pruned buffer spill %s", path.name)

    def save_buffer(self, step: int, buffer: ExperienceBuffer) -> Path | None:
        """Spill the (host-local) replay buffer. Multi-host: process 0
        only — the buffer is host state, not a collective."""
        if not is_primary():
            return None
        state = buffer.get_state()
        if state["storage"] is None:
            return None
        path = self._buffer_dir / f"buffer_{step:08d}.npz"
        arrays = {f"storage_{k}": v for k, v in state["storage"].items()}
        if state["priorities"] is not None:
            arrays["priorities"] = state["priorities"]
        # Atomic spill: the tmp name keeps the .npz suffix (np.savez
        # appends it otherwise) but dodges the buffer_*.npz glob, so a
        # kill mid-write never leaves a torn spill that restore trusts.
        tmp = self._buffer_dir / f".tmp_buffer_{step:08d}.npz"
        np.savez_compressed(
            tmp, pos=state["pos"], size=state["size"], **arrays
        )
        os.replace(tmp, path)
        logger.info("Buffer spilled (%d experiences) -> %s", state["size"], path)
        self._prune_buffers()
        return path

    def save_configs(self, configs: dict[str, Any]) -> None:
        """Dump config models to the run dir (reference README.md:79)."""
        if not is_primary():
            return
        out = {
            k: (v.model_dump() if hasattr(v, "model_dump") else v)
            for k, v in configs.items()
        }
        _atomic_write_text(
            self.config.get_run_base_dir() / "configs.json",
            json.dumps(out, indent=2, default=str),
        )

    def wait_until_finished(self) -> None:
        self._ckptr.wait_until_finished()
        # Settle the background flusher too: after this returns, every
        # landed save is marker-committed and no daemon write is still
        # in flight (callers may tear the run dir down next).
        flusher = self._flusher
        if flusher is not None and flusher.is_alive():
            flusher.join()
        self._flush_commit_markers()

    def close(self) -> None:
        self.wait_until_finished()
        self._ckptr.close()

    # --- load -------------------------------------------------------------

    def list_steps(self) -> list[int]:
        """Sorted steps of every canonical checkpoint directory
        (non-matching names — e.g. Orbax temp dirs from an interrupted
        save — are ignored, not crashed on)."""
        if not self._ckpt_dir.exists():
            return []
        return sorted(
            int(m.group(1))
            for p in self._ckpt_dir.iterdir()
            if p.is_dir() and (m := _STEP_DIR_RE.match(p.name))
        )

    def valid_steps(self) -> list[int]:
        """Steps restore may trust: commit marker present (when this run
        has markers at all — pre-marker runs fall back to meta-only
        validation) and meta.json parseable. Torn directories from a
        kill mid-save fail both tests and are skipped with a warning."""
        steps = self.list_steps()
        if not steps:
            return []
        committed = {
            int(m.group(1))
            for p in self._ckpt_dir.glob("step_*.commit")
            if (m := _COMMIT_RE.match(p.name))
        }
        valid: list[int] = []
        for step in steps:
            if committed and step not in committed:
                logger.warning(
                    "Checkpoint step %d has no commit marker (torn "
                    "save?); skipping it for restore",
                    step,
                )
                continue
            meta_path = self._ckpt_dir / f"step_{step:08d}.meta.json"
            try:
                json.loads(meta_path.read_text())
            except (OSError, ValueError):
                logger.warning(
                    "Checkpoint step %d has no parseable meta.json; "
                    "skipping it for restore",
                    step,
                )
                continue
            valid.append(step)
        return valid

    def latest_step(self) -> int | None:
        steps = self.valid_steps()
        return steps[-1] if steps else None

    def restore(
        self,
        template_state: Any,
        step: int | None = None,
        buffer: ExperienceBuffer | None = None,
    ) -> LoadedTrainingState:
        """Restore the checkpoint at `step` (default: newest valid).

        `template_state` supplies the pytree structure/shapes (the
        freshly-initialized `TrainState`). Restores the buffer in place
        when a spill at <= step exists and `buffer` is given.

        An explicit `step` is trusted (restore errors propagate). With
        `step=None` the newest valid step is tried first and an
        unreadable tree falls back to the previous valid step — a torn
        directory costs one checkpoint cadence, never the run.
        """
        if step is not None:
            candidates = [step]
            fallback = False
        else:
            candidates = list(reversed(self.valid_steps()))
            fallback = True
        if not candidates:
            torn = self.list_steps()
            if torn:
                logger.warning(
                    "No committed checkpoint among step dirs %s; "
                    "starting fresh",
                    torn,
                )
            return LoadedTrainingState(run_name=self.config.RUN_NAME)
        last_exc: Exception | None = None
        for cand in candidates:
            path = self._ckpt_dir / f"step_{cand:08d}"
            try:
                restored = self._ckptr.restore(path, target=template_state)
            except Exception as exc:
                if not fallback:
                    raise
                last_exc = exc
                logger.warning(
                    "Checkpoint step %d unreadable (%s); falling back "
                    "to the previous valid step",
                    cand,
                    exc,
                )
                continue
            meta_path = self._ckpt_dir / f"step_{cand:08d}.meta.json"
            counters: dict[str, Any] = {}
            if meta_path.exists():
                try:
                    counters = json.loads(meta_path.read_text())
                except ValueError:
                    counters = {}
            buffer_loaded = False
            if buffer is not None:
                buffer_loaded = self.restore_buffer(buffer, max_step=cand)
            logger.info(
                "Restored checkpoint step %d from %s (buffer=%s)",
                cand,
                path,
                buffer_loaded,
            )
            return LoadedTrainingState(
                train_state=restored,
                buffer_loaded=buffer_loaded,
                counters=counters,
                run_name=self.config.RUN_NAME,
                global_step=int(counters.get("global_step", cand)),
            )
        assert last_exc is not None
        raise last_exc

    def restore_path(
        self, path: str | Path, template_state: Any
    ) -> LoadedTrainingState:
        """Restore from an explicit checkpoint step directory
        (`TrainConfig.LOAD_CHECKPOINT_PATH`, reference `runner.py:36-38`)."""
        path = Path(path).resolve()
        if not path.is_dir():
            raise FileNotFoundError(f"No checkpoint directory at {path}")
        restored = self._ckptr.restore(path, target=template_state)
        counters: dict[str, Any] = {}
        meta_path = path.parent / f"{path.name}.meta.json"
        if meta_path.exists():
            counters = json.loads(meta_path.read_text())
        m = _STEP_DIR_RE.match(path.name)
        step = int(counters.get("global_step", int(m.group(1)) if m else 0))
        return LoadedTrainingState(
            train_state=restored,
            counters=counters,
            run_name=self.config.RUN_NAME,
            global_step=step,
        )

    @staticmethod
    def restore_buffer_path(buffer: ExperienceBuffer, path: str | Path) -> bool:
        """Load an explicit buffer spill (`TrainConfig.LOAD_BUFFER_PATH`)."""
        path = Path(path)
        if not path.is_file():
            raise FileNotFoundError(f"No buffer spill at {path}")
        CheckpointManager._load_spill_into(buffer, path)
        return True

    def restore_buffer(
        self, buffer: ExperienceBuffer, max_step: int | None = None
    ) -> bool:
        """Load the newest buffer spill (optionally <= max_step) in
        place. A torn spill (kill mid-write on a pre-atomic run) falls
        back to the next-oldest instead of crashing the resume."""
        if not self._buffer_dir.exists():
            return False
        spills = sorted(self._buffer_dir.glob("buffer_*.npz"))
        if max_step is not None:
            spills = [
                s
                for s in spills
                if int(s.stem.split("_")[1]) <= max_step
            ]
        for spill in reversed(spills):
            try:
                self._load_spill_into(buffer, spill)
                return True
            except Exception as exc:
                logger.warning(
                    "Buffer spill %s unreadable (%s); falling back to "
                    "the previous spill",
                    spill.name,
                    exc,
                )
        return False

    @staticmethod
    def _load_spill_into(buffer: ExperienceBuffer, path: Path) -> None:
        with np.load(path) as data:
            storage = {
                k[len("storage_"):]: data[k]
                for k in data.files
                if k.startswith("storage_")
            }
            state = {
                "pos": int(data["pos"]),
                "size": int(data["size"]),
                "storage": storage,
                "priorities": (
                    data["priorities"] if "priorities" in data.files else None
                ),
            }
        buffer.set_state(state)

    # --- auto-resume ------------------------------------------------------

    @staticmethod
    def find_latest_run(persistence: PersistenceConfig) -> str | None:
        """Newest run (by checkpoint mtime) with at least one valid
        checkpoint (reference auto-resume, `README.md:23`,
        `train_config.py:26`). Runs whose only checkpoints are torn
        (no commit marker where markers exist) are not candidates."""
        runs_root = persistence.get_runs_root_dir()
        if not runs_root.exists():
            return None
        candidates: list[tuple[float, str]] = []
        for run_dir in runs_root.iterdir():
            ckpts = run_dir / "checkpoints"
            if not ckpts.is_dir():
                continue
            committed = {
                int(m.group(1))
                for p in ckpts.glob("step_*.commit")
                if (m := _COMMIT_RE.match(p.name))
            }
            steps = [
                p
                for p in ckpts.iterdir()
                if p.is_dir()
                and (m := _STEP_DIR_RE.match(p.name))
                and (not committed or int(m.group(1)) in committed)
            ]
            if steps:
                candidates.append(
                    (max(p.stat().st_mtime for p in steps), run_dir.name)
                )
        if not candidates:
            return None
        return max(candidates)[1]
