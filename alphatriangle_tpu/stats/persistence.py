"""Checkpoint/resume + buffer spill (trieye persistence equivalent).

Parity surface per the reference call sites (`training/runner.py:28-163`,
`training/loop.py:173-211`, SURVEY.md §3.4): periodic checkpoint of
model/optimizer state + counters, optional replay-buffer spill,
`load_initial_state`-style restore, and auto-resume from the latest run.

TPU-native shape: the learner state is a jax pytree (`TrainState`), so
checkpoints are **Orbax** trees — standard, async-written, readable by
any JAX tool — instead of cloudpickled torch state dicts. The dense SoA
replay buffer spills to a compressed `.npz` (fixed-shape arrays, no
pickle). Improvement over the reference: PER priorities are persisted
and restored (the reference resets them to max on resume,
`runner.py:87-91`).
"""

import json
import logging
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import numpy as np
import orbax.checkpoint as ocp

from ..config.persistence_config import PersistenceConfig
from ..parallel.distributed import is_primary
from ..rl.buffer import ExperienceBuffer

logger = logging.getLogger(__name__)

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")


@dataclass
class LoadedTrainingState:
    """Everything a resumed run needs (reference `LoadedTrainingState`)."""

    train_state: Any | None = None
    buffer_loaded: bool = False
    counters: dict[str, Any] = field(default_factory=dict)
    run_name: str | None = None
    global_step: int = 0


class CheckpointManager:
    """Owns one run's checkpoint/buffer directories."""

    def __init__(self, persistence: PersistenceConfig):
        self.config = persistence
        persistence.create_run_dirs()
        self._ckpt_dir = persistence.get_checkpoint_dir().resolve()
        self._buffer_dir = persistence.get_buffer_dir().resolve()
        self._ckptr = ocp.StandardCheckpointer()

    # --- save -------------------------------------------------------------

    def save(
        self,
        step: int,
        train_state: Any,
        counters: dict[str, Any] | None = None,
    ) -> Path:
        """Checkpoint `train_state` (async) + counters; buffer spills go
        through `save_buffer`. Returns the checkpoint path.

        Multi-host discipline: EVERY process must call this (the Orbax
        save is a collective over the state's global arrays); the plain
        file writes (meta.json, pruning) happen on process 0 only.
        """
        path = self._ckpt_dir / f"step_{step:08d}"
        if path.exists():  # overwrite-safe for forced final saves
            import shutil

            # An async save of this step may still be in flight; let it
            # land before removing, or the writer races the rmtree.
            self._ckptr.wait_until_finished()
            if is_primary():
                shutil.rmtree(path, ignore_errors=True)
        self._ckptr.save(path, train_state)
        if not is_primary():
            return path
        meta = {"global_step": step, **(counters or {})}
        (self._ckpt_dir / f"step_{step:08d}.meta.json").write_text(
            json.dumps(meta, indent=2)
        )
        logger.info("Checkpoint saved at step %d -> %s", step, path)
        self._prune_checkpoints(just_saved=step)
        return path

    def _prune_checkpoints(self, just_saved: int) -> None:
        keep = self.config.KEEP_LAST_CHECKPOINTS
        if keep <= 0:
            return
        # The save above is async; its directory may not be listable
        # yet, so count the just-saved step explicitly.
        steps = sorted(
            {
                int(m.group(1))
                for p in self._ckpt_dir.iterdir()
                if p.is_dir() and (m := _STEP_DIR_RE.match(p.name))
            }
            | {just_saved}
        )
        if len(steps) <= keep:
            return
        import shutil

        # Async writes to the survivors may be in flight; only the
        # doomed dirs matter, but Orbax tracks saves globally.
        self._ckptr.wait_until_finished()
        for step in steps[:-keep]:
            shutil.rmtree(
                self._ckpt_dir / f"step_{step:08d}", ignore_errors=True
            )
            (self._ckpt_dir / f"step_{step:08d}.meta.json").unlink(
                missing_ok=True
            )
            logger.debug("Pruned checkpoint step %d", step)

    def _prune_buffers(self) -> None:
        keep = self.config.KEEP_LAST_BUFFERS
        if keep <= 0:
            return
        spills = sorted(self._buffer_dir.glob("buffer_*.npz"))
        for path in spills[:-keep] if len(spills) > keep else []:
            path.unlink(missing_ok=True)
            logger.debug("Pruned buffer spill %s", path.name)

    def save_buffer(self, step: int, buffer: ExperienceBuffer) -> Path | None:
        """Spill the (host-local) replay buffer. Multi-host: process 0
        only — the buffer is host state, not a collective."""
        if not is_primary():
            return None
        state = buffer.get_state()
        if state["storage"] is None:
            return None
        path = self._buffer_dir / f"buffer_{step:08d}.npz"
        arrays = {f"storage_{k}": v for k, v in state["storage"].items()}
        if state["priorities"] is not None:
            arrays["priorities"] = state["priorities"]
        np.savez_compressed(
            path, pos=state["pos"], size=state["size"], **arrays
        )
        logger.info("Buffer spilled (%d experiences) -> %s", state["size"], path)
        self._prune_buffers()
        return path

    def save_configs(self, configs: dict[str, Any]) -> None:
        """Dump config models to the run dir (reference README.md:79)."""
        if not is_primary():
            return
        out = {
            k: (v.model_dump() if hasattr(v, "model_dump") else v)
            for k, v in configs.items()
        }
        (self.config.get_run_base_dir() / "configs.json").write_text(
            json.dumps(out, indent=2, default=str)
        )

    def wait_until_finished(self) -> None:
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self.wait_until_finished()
        self._ckptr.close()

    # --- load -------------------------------------------------------------

    def list_steps(self) -> list[int]:
        """Sorted steps of every canonical checkpoint directory
        (non-matching names — e.g. Orbax temp dirs from an interrupted
        save — are ignored, not crashed on)."""
        if not self._ckpt_dir.exists():
            return []
        return sorted(
            int(m.group(1))
            for p in self._ckpt_dir.iterdir()
            if p.is_dir() and (m := _STEP_DIR_RE.match(p.name))
        )

    def latest_step(self) -> int | None:
        steps = self.list_steps()
        return steps[-1] if steps else None

    def restore(
        self,
        template_state: Any,
        step: int | None = None,
        buffer: ExperienceBuffer | None = None,
    ) -> LoadedTrainingState:
        """Restore the checkpoint at `step` (default: latest).

        `template_state` supplies the pytree structure/shapes (the
        freshly-initialized `TrainState`). Restores the buffer in place
        when a spill at <= step exists and `buffer` is given.
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            return LoadedTrainingState(run_name=self.config.RUN_NAME)
        path = self._ckpt_dir / f"step_{step:08d}"
        restored = self._ckptr.restore(path, target=template_state)
        meta_path = self._ckpt_dir / f"step_{step:08d}.meta.json"
        counters: dict[str, Any] = {}
        if meta_path.exists():
            counters = json.loads(meta_path.read_text())
        buffer_loaded = False
        if buffer is not None:
            buffer_loaded = self.restore_buffer(buffer, max_step=step)
        logger.info(
            "Restored checkpoint step %d from %s (buffer=%s)",
            step,
            path,
            buffer_loaded,
        )
        return LoadedTrainingState(
            train_state=restored,
            buffer_loaded=buffer_loaded,
            counters=counters,
            run_name=self.config.RUN_NAME,
            global_step=int(counters.get("global_step", step)),
        )

    def restore_path(
        self, path: str | Path, template_state: Any
    ) -> LoadedTrainingState:
        """Restore from an explicit checkpoint step directory
        (`TrainConfig.LOAD_CHECKPOINT_PATH`, reference `runner.py:36-38`)."""
        path = Path(path).resolve()
        if not path.is_dir():
            raise FileNotFoundError(f"No checkpoint directory at {path}")
        restored = self._ckptr.restore(path, target=template_state)
        counters: dict[str, Any] = {}
        meta_path = path.parent / f"{path.name}.meta.json"
        if meta_path.exists():
            counters = json.loads(meta_path.read_text())
        m = _STEP_DIR_RE.match(path.name)
        step = int(counters.get("global_step", int(m.group(1)) if m else 0))
        return LoadedTrainingState(
            train_state=restored,
            counters=counters,
            run_name=self.config.RUN_NAME,
            global_step=step,
        )

    @staticmethod
    def restore_buffer_path(buffer: ExperienceBuffer, path: str | Path) -> bool:
        """Load an explicit buffer spill (`TrainConfig.LOAD_BUFFER_PATH`)."""
        path = Path(path)
        if not path.is_file():
            raise FileNotFoundError(f"No buffer spill at {path}")
        CheckpointManager._load_spill_into(buffer, path)
        return True

    def restore_buffer(
        self, buffer: ExperienceBuffer, max_step: int | None = None
    ) -> bool:
        """Load the newest buffer spill (optionally <= max_step) in place."""
        if not self._buffer_dir.exists():
            return False
        spills = sorted(self._buffer_dir.glob("buffer_*.npz"))
        if max_step is not None:
            spills = [
                s
                for s in spills
                if int(s.stem.split("_")[1]) <= max_step
            ]
        if not spills:
            return False
        self._load_spill_into(buffer, spills[-1])
        return True

    @staticmethod
    def _load_spill_into(buffer: ExperienceBuffer, path: Path) -> None:
        with np.load(path) as data:
            storage = {
                k[len("storage_"):]: data[k]
                for k in data.files
                if k.startswith("storage_")
            }
            state = {
                "pos": int(data["pos"]),
                "size": int(data["size"]),
                "storage": storage,
                "priorities": (
                    data["priorities"] if "priorities" in data.files else None
                ),
            }
        buffer.set_state(state)

    # --- auto-resume ------------------------------------------------------

    @staticmethod
    def find_latest_run(persistence: PersistenceConfig) -> str | None:
        """Newest run (by checkpoint mtime) with at least one checkpoint
        (reference auto-resume, `README.md:23`, `train_config.py:26`)."""
        runs_root = persistence.get_runs_root_dir()
        if not runs_root.exists():
            return None
        candidates: list[tuple[float, str]] = []
        for run_dir in runs_root.iterdir():
            ckpts = run_dir / "checkpoints"
            if not ckpts.is_dir():
                continue
            steps = [
                p for p in ckpts.iterdir()
                if p.is_dir() and _STEP_DIR_RE.match(p.name)
            ]
            if steps:
                candidates.append(
                    (max(p.stat().st_mtime for p in steps), run_dir.name)
                )
        if not candidates:
            return None
        return max(candidates)[1]
