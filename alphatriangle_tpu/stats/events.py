"""Metric event type (reference: trieye `RawMetricEvent`, observed at
`alphatriangle/rl/self_play/worker.py:147-153`)."""

import time
from typing import Any

from pydantic import BaseModel, Field


class RawMetricEvent(BaseModel):
    """One raw metric observation, aggregated by the collector."""

    name: str
    value: float
    global_step: int = 0
    timestamp: float = Field(default_factory=time.time)
    context: dict[str, Any] = {}
