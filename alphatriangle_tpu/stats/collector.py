"""In-process metric aggregation -> TensorBoard.

Capability parity with the trieye actor surface the reference calls
(`log_event` / `log_batch_events` / `process_and_log` /
`force_process_and_log`, SURVEY.md §2b): subsystems fire events at any
rate; aggregation + IO happen only on `process_and_log` ticks.

Design: the reference needed a Ray actor because producers lived in
other processes. Here producers share the learner process (self-play is
device-batched), so the "actor" collapses to a lock-guarded buffer —
`log_event` is an O(1) append off the device path, and TensorBoard
writes occur on the tick, never blocking a dispatch. MLflow is absent
from this environment; the writer degrades to TensorBoard-only
(reference logs to both, `README.md:63-79`).
"""

import atexit
import json
import logging
import threading
import time
from collections import defaultdict, deque
from pathlib import Path

import numpy as np

from ..config.persistence_config import PersistenceConfig
from .events import RawMetricEvent

logger = logging.getLogger(__name__)

try:  # tensorboardX is baked into the image; guard anyway.
    from tensorboardX import SummaryWriter
except Exception:  # pragma: no cover
    SummaryWriter = None


def _import_mlflow():
    """mlflow is an optional dependency (absent from this image); the
    collector mirrors to it only when importable AND a tracking URI is
    configured (reference logs to MLflow + TB, its README.md:63-79)."""
    try:
        import mlflow
    except Exception:
        return None
    return mlflow


class StatsCollector:
    """Aggregates raw metric events; writes means per tick to TensorBoard."""

    def __init__(
        self,
        persistence: PersistenceConfig | None = None,
        use_tensorboard: bool = True,
        log_dir: str | Path | None = None,
        history_limit: int = 1024,
        use_live_file: bool = True,
    ):
        self._lock = threading.Lock()
        self._pending: dict[str, list[tuple[int, float]]] = defaultdict(list)
        # Non-finite observations are dropped from aggregation, but not
        # silently: counted per metric name, surfaced as one cumulative
        # `Stats/nonfinite_dropped` scalar on each tick, and warned once
        # per name (a NaN loss is a training signal, not log noise).
        self._nonfinite: dict[str, int] = defaultdict(int)
        self._nonfinite_warned: set[str] = set()
        # In-memory aggregate history is a convenience for tests and the
        # console; TensorBoard owns the full series. Bound it so a 100k
        # step run doesn't grow without limit (0 = unbounded).
        maxlen = history_limit if history_limit > 0 else None
        self._history: dict[str, deque[tuple[int, float]]] = defaultdict(
            lambda: deque(maxlen=maxlen)
        )
        self._writer = None
        if use_tensorboard and SummaryWriter is not None:
            tb_dir = Path(log_dir) if log_dir else (
                persistence.get_tensorboard_dir() if persistence else None
            )
            if tb_dir is not None:
                tb_dir.mkdir(parents=True, exist_ok=True)
                self._writer = SummaryWriter(str(tb_dir))
        # Live-console channel (`cli watch`): one JSON line per tick in
        # the run dir, readable by a process that never touches JAX —
        # the run-dir-tail observability the reference served through
        # its Ray dashboard + MLflow UI (`alphatriangle/cli.py:301-326`).
        self._live_path: Path | None = None
        if use_live_file and persistence is not None:
            base = persistence.get_run_base_dir()
            base.mkdir(parents=True, exist_ok=True)
            self._live_path = base / "live_metrics.jsonl"
        self._mlflow = None
        self._mlflow_run = None
        uri = persistence.MLFLOW_TRACKING_URI if persistence else None
        if uri:
            mlflow = _import_mlflow()
            if mlflow is None:
                logger.warning(
                    "MLFLOW_TRACKING_URI set but mlflow is not installed; "
                    "TensorBoard-only."
                )
            else:
                try:
                    mlflow.set_tracking_uri(uri)
                    run_name = (
                        persistence.RUN_NAME if persistence else "run"
                    )
                    self._mlflow_run = mlflow.start_run(run_name=run_name)
                    self._mlflow = mlflow
                except Exception:
                    logger.exception(
                        "MLflow init failed; TensorBoard-only."
                    )
        # Optional durable sink: called with (step, means) after every
        # processed batch (telemetry.RunTelemetry.record_metrics wires
        # the metrics ledger here in setup).
        self._tick_sink = None
        # Trailing sub-interval metrics used to be silently lost when
        # a run shut down between ticks; close() now flushes pending
        # events, and an atexit hook covers paths that never call
        # close() (crash-adjacent teardown, forgotten cleanup).
        self._last_event_step = 0
        self._closed = False
        self._atexit_cb = self.close
        atexit.register(self._atexit_cb)

    def set_tick_sink(self, sink) -> None:
        """Attach a callable(step, means) invoked after each tick."""
        self._tick_sink = sink

    # --- ingestion (cheap, any thread) ------------------------------------

    def log_event(self, event: RawMetricEvent) -> None:
        if not np.isfinite(event.value):
            with self._lock:
                self._nonfinite[event.name] += 1
                first = event.name not in self._nonfinite_warned
                if first:
                    self._nonfinite_warned.add(event.name)
            if first:
                logger.warning(
                    "Non-finite value for metric %s at step %d; dropping "
                    "(further drops counted in Stats/nonfinite_dropped).",
                    event.name,
                    event.global_step,
                )
            return
        with self._lock:
            self._pending[event.name].append((event.global_step, event.value))
            if event.global_step > self._last_event_step:
                self._last_event_step = event.global_step

    def log_batch_events(self, events: list[RawMetricEvent]) -> None:
        for e in events:
            self.log_event(e)

    def log_scalar(self, name: str, value: float, step: int = 0) -> None:
        """Convenience: log a bare scalar without building an event."""
        self.log_event(RawMetricEvent(name=name, value=value, global_step=step))

    # --- aggregation ticks ------------------------------------------------

    def process_and_log(self, global_step: int) -> dict[str, float]:
        """Flush pending events: mean per metric, written at `global_step`.

        Returns the aggregated means (name -> mean) for callers/tests.
        """
        with self._lock:
            pending, self._pending = self._pending, defaultdict(list)
            dropped = sum(self._nonfinite.values())
        if dropped:
            pending["Stats/nonfinite_dropped"].append(
                (global_step, float(dropped))
            )
        means: dict[str, float] = {}
        for name, obs in pending.items():
            if not obs:
                continue
            mean = float(np.mean([v for _, v in obs]))
            means[name] = mean
            self._history[name].append((global_step, mean))
            if self._writer is not None:
                self._writer.add_scalar(name, mean, global_step)
        if self._writer is not None and means:
            self._writer.flush()
        if self._live_path is not None and means:
            try:
                with self._live_path.open("a") as f:
                    f.write(
                        json.dumps(
                            {
                                "step": global_step,
                                "time": time.time(),
                                "means": means,
                            }
                        )
                        + "\n"
                    )
            except OSError:  # observability is never fatal
                logger.exception("live-metrics append failed")
        if self._mlflow is not None and means:
            try:
                self._mlflow.log_metrics(
                    {k.replace("/", "."): v for k, v in means.items()},
                    step=global_step,
                )
            except Exception:  # metrics are best-effort, never fatal
                logger.exception("MLflow log_metrics failed")
        if self._tick_sink is not None and means:
            try:
                self._tick_sink(global_step, means)
            except Exception:  # durable sink is best-effort too
                logger.exception("metrics tick sink failed")
        return means

    def force_process_and_log(self, global_step: int) -> dict[str, float]:
        """Final flush (reference `runner.py:288` semantics)."""
        return self.process_and_log(global_step)

    # --- experiment params --------------------------------------------------

    def log_params(self, configs: dict[str, object]) -> None:
        """Record experiment parameters in TensorBoard (text summaries).

        Equivalent of the reference's MLflow param dump
        (`training/logging_utils.py:13-35`); MLflow is absent here so
        params land as one markdown text card per config model.
        """
        for name, cfg in configs.items():
            payload = cfg.model_dump() if hasattr(cfg, "model_dump") else cfg
            if self._writer is not None:
                text = "```json\n" + json.dumps(
                    payload, indent=2, default=str
                ) + "\n```"
                self._writer.add_text(f"config/{name}", text, 0)
            if self._mlflow is not None and isinstance(payload, dict):
                try:
                    self._mlflow.log_params(
                        {f"{name}.{k}": str(v) for k, v in payload.items()}
                    )
                except Exception:
                    logger.exception("MLflow log_params failed")
        if self._writer is not None:
            self._writer.flush()

    # --- introspection ----------------------------------------------------

    def get_series(self, name: str) -> list[tuple[int, float]]:
        """Aggregated (step, mean) history of one metric."""
        return list(self._history.get(name, []))

    def latest(self, name: str) -> float | None:
        series = self._history.get(name)
        return series[-1][1] if series else None

    def nonfinite_dropped(self) -> dict[str, int]:
        """Cumulative non-finite drop count per metric name."""
        with self._lock:
            return dict(self._nonfinite)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            atexit.unregister(self._atexit_cb)
        except Exception:  # pragma: no cover - interpreter teardown
            pass
        # Final flush: events logged since the last tick (trailing
        # sub-interval metrics) land at the newest step seen instead of
        # silently evaporating with the process.
        with self._lock:
            has_pending = any(self._pending.values())
            step = self._last_event_step
        if has_pending:
            try:
                self.process_and_log(step)
            except Exception:
                logger.exception("final stats flush failed")
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self._mlflow is not None:
            try:
                self._mlflow.end_run()
            except Exception:
                logger.exception("MLflow end_run failed")
            self._mlflow = None
            self._mlflow_run = None
