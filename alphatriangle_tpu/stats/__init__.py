"""Stats + persistence (trieye equivalent, SURVEY.md §2b).

The reference delegates metrics aggregation and checkpoint/resume to a
detached Ray actor (`trieye`). Here the same responsibilities are an
in-process `StatsCollector` (lock-guarded event sink -> TensorBoard on
`process_and_log` ticks) and an Orbax-backed `CheckpointManager`
(jax-pytree train state + dense buffer spill + auto-resume) — no actor
runtime required, and checkpoints are standard Orbax trees any JAX tool
can read.

The persistence re-exports resolve lazily (PEP 562): `CheckpointManager`
drags in Orbax (and with it JAX), but this package also hosts
`stats/watch.py`, which JAX-free reader processes (`cli watch/mem/...`
beside a wedged chip) import through here.
"""

from .collector import StatsCollector
from .events import RawMetricEvent

_PERSISTENCE_EXPORTS = frozenset(
    {"CheckpointManager", "LoadedTrainingState"}
)


def __getattr__(name: str):
    if name in _PERSISTENCE_EXPORTS:
        from . import persistence

        return getattr(persistence, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


__all__ = [
    "CheckpointManager",
    "LoadedTrainingState",
    "RawMetricEvent",
    "StatsCollector",
]
