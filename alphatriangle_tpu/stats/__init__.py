"""Stats + persistence (trieye equivalent, SURVEY.md §2b).

The reference delegates metrics aggregation and checkpoint/resume to a
detached Ray actor (`trieye`). Here the same responsibilities are an
in-process `StatsCollector` (lock-guarded event sink -> TensorBoard on
`process_and_log` ticks) and an Orbax-backed `CheckpointManager`
(jax-pytree train state + dense buffer spill + auto-resume) — no actor
runtime required, and checkpoints are standard Orbax trees any JAX tool
can read.
"""

from .collector import StatsCollector
from .events import RawMetricEvent
from .persistence import CheckpointManager, LoadedTrainingState

__all__ = [
    "CheckpointManager",
    "LoadedTrainingState",
    "RawMetricEvent",
    "StatsCollector",
]
