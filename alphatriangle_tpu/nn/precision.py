"""Inference precision policy: bf16 / int8 params for rollout + serve.

`ModelConfig.INFERENCE_PRECISION` selects the representation the
INFERENCE family (self-play chunk programs, `serve/b<B>` dispatch,
arena/eval through the service) reads the network parameters at. The
learner family is excluded by construction: the trainer holds and
updates the f32 `TrainState`, and the fused megastep casts a reduced
copy of the params for its in-program rollout phase while the
learner-step phase keeps consuming the f32 originals.

What the reduced paths cover and what stays f32 (docs/KERNELS.md
"Precision policy"): the cast applies to floating-point
param/batch-stats leaves only. PER priorities, the cumsum the sampler
searches, value targets, IS weights, optimizer state and gradients are
untouched — priority ratios and learner math are precision-sensitive in
ways an Elo-neutral forward pass is not (KataGo, arXiv:1902.10565,
ships reduced-precision *inference* while training full-precision for
exactly this reason). The model's value/policy heads already compute
their final Dense in f32 (nn/model.py MLPHead), so logits keep f32
dynamic range even under a bf16 trunk.

The int8 path is WEIGHT-ONLY quantization with per-channel symmetric
absmax calibration: every floating matrix leaf (ndim >= 2) is replaced
by a `{"q": int8, "scale": f32}` marker dict where `scale` is the
absmax over all axes except the last (the output-channel axis of Dense
kernels and the feature axis of conv kernels) divided by 127, and
`q = round(x / scale)` clipped to [-127, 127]. Vector leaves (biases,
norm gains/offsets) carry negligible bytes and quantization-sensitive
semantics, so they cast to bf16 like the bf16 path. The forward trunk
dequantizes to bf16 at its single evaluation choke point
(`BatchedMCTS._evaluate`, `NeuralNetwork._apply_eval`), so activations
and heads follow the exact bf16 policy and the strength gate for bf16
bounds int8's additional error on top of it.

Caching: callers thread the cast through the AOT compile-cache
signature for free — reduced param avals change leaf dtypes (and, for
int8, the tree structure) in the program signature, and
`config_digest(model_config)` (which includes INFERENCE_PRECISION) is
part of every inference family's `extra` tag, so f32 / bf16 / int8
programs cache as distinct entries with their own `.mem.json` sidecars.
Host-side consumers (`PolicyService._serve_variables`, the rollout
engine's `_inference_variables`) memoize the quantized tree per weights
version, so the program genuinely reads int8 tensors from HBM —
roughly a 4x param-bytes-read reduction against f32 (2x against bf16)
on every leaf-evaluation wave. The megastep calls
`cast_params_for_inference` inside its traced body, where the same
code becomes fake-quant (quantize + dequant fused by XLA) with
bit-identical numerics to the host-side path.
"""

import jax
import jax.numpy as jnp

from ..config.model_config import ModelConfig

# Marker-dict keys for one int8-quantized leaf. The dict is an ordinary
# pytree node, so quantized trees flow through jit/device_put/tree_map
# unchanged and their int8/f32 leaf avals key the compile cache.
_QUANT_KEYS = frozenset({"q", "scale"})

# Symmetric int8 range; scales are clamped so all-zero channels
# round-trip to exact zeros instead of dividing by zero.
_Q_MAX = 127.0
_SCALE_EPS = 1e-12


def inference_dtype(model_config: ModelConfig) -> jnp.dtype:
    """The dtype the inference trunk COMPUTES at: bf16 under both the
    bf16 cast and the int8 weight-only path (which dequantizes to
    bf16), f32 otherwise. `== jnp.float32` is the callers' "identity
    policy, skip the cast memo" test."""
    return jnp.dtype(
        jnp.bfloat16
        if model_config.INFERENCE_PRECISION in ("bfloat16", "int8")
        else jnp.float32
    )


def is_quantized_leaf(x) -> bool:
    """True for one `{"q", "scale"}` marker dict (an int8 leaf)."""
    return isinstance(x, dict) and set(x.keys()) == _QUANT_KEYS


def _quantize_leaf(x):
    """Per-channel symmetric absmax int8 for one matrix leaf.

    The channel axis is the LAST axis (Dense kernels are (in, out),
    conv kernels (kh, kw, in, out) — last is the output-feature axis
    in both), so each output channel gets its own scale and a single
    hot channel cannot crush the resolution of the rest.
    """
    xf = x.astype(jnp.float32)
    reduce_axes = tuple(range(x.ndim - 1))
    absmax = jnp.max(jnp.abs(xf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax / _Q_MAX, _SCALE_EPS)
    q = jnp.clip(jnp.round(xf / scale), -_Q_MAX, _Q_MAX).astype(jnp.int8)
    return {"q": q, "scale": scale}


def quantize_params_for_inference(variables):
    """Weight-only int8 quantization of a variables pytree: floating
    matrix leaves (ndim >= 2) become `{"q": int8, "scale": f32}`
    marker dicts; floating vector leaves cast to bf16; everything else
    passes through. `dequantize_params` inverts the representation."""

    def quant(x):
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        if x.ndim >= 2:
            return _quantize_leaf(x)
        return x.astype(jnp.bfloat16)

    return jax.tree_util.tree_map(quant, variables)


def dequantize_params(variables):
    """Reconstitute a (possibly) quantized variables pytree for the
    forward pass: marker dicts dequantize to bf16
    (`q * scale -> bf16`), all other leaves pass through untouched.
    Identity-shaped (and nearly free) on unquantized trees, so the
    evaluation choke points call it unconditionally."""

    def dequant(x):
        if is_quantized_leaf(x):
            return (
                x["q"].astype(jnp.float32) * x["scale"]
            ).astype(jnp.bfloat16)
        return x

    return jax.tree_util.tree_map(
        dequant, variables, is_leaf=is_quantized_leaf
    )


def cast_params_for_inference(variables, model_config: ModelConfig):
    """Apply the inference precision policy to a variables pytree:
    identity (same object, no copy) under f32, bf16 cast of floating
    leaves under bf16, weight-only int8 quantization under int8."""
    if model_config.INFERENCE_PRECISION == "int8":
        return quantize_params_for_inference(variables)
    dtype = inference_dtype(model_config)
    if dtype == jnp.float32:
        return variables
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        variables,
    )


def quantized_param_bytes(variables) -> int:
    """Total bytes of a variables pytree as the serve program reads it
    (marker dicts count their int8 + scale buffers) — the
    param-bytes-read number bench's precision A/B section reports."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(variables):
        total += int(leaf.size) * int(leaf.dtype.itemsize)
    return total
