"""Inference precision policy: bf16 params for rollout + serve forward.

`ModelConfig.INFERENCE_PRECISION` selects the dtype the INFERENCE
family (self-play chunk programs, `serve/b<B>` dispatch, arena/eval
through the service) reads the network parameters at. The learner
family is excluded by construction: the trainer holds and updates the
f32 `TrainState`, and the fused megastep casts a bf16 *copy* of the
params for its in-program rollout phase while the learner-step phase
keeps consuming the f32 originals.

What bf16 covers and what stays f32 (docs/KERNELS.md "Precision
policy"): the cast applies to floating-point param/batch-stats leaves
only. PER priorities, the cumsum the sampler searches, value targets,
IS weights, optimizer state and gradients are untouched — priority
ratios and learner math are precision-sensitive in ways an Elo-neutral
forward pass is not (KataGo, arXiv:1902.10565, ships reduced-precision
*inference* while training full-precision for exactly this reason).
The model's value/policy heads already compute their final Dense in
f32 (nn/model.py MLPHead), so logits keep f32 dynamic range even under
a bf16 trunk.

Caching: callers thread the cast through the AOT compile-cache
signature for free — bf16 param avals change every leaf dtype in the
program signature, and `config_digest(model_config)` (which now
includes INFERENCE_PRECISION) is part of every inference family's
`extra` tag, so f32 and bf16 programs cache as distinct entries with
their own `.mem.json` sidecars.
"""

import jax
import jax.numpy as jnp

from ..config.model_config import ModelConfig


def inference_dtype(model_config: ModelConfig) -> jnp.dtype:
    """The dtype the inference family reads params at."""
    return jnp.dtype(
        jnp.bfloat16
        if model_config.INFERENCE_PRECISION == "bfloat16"
        else jnp.float32
    )


def cast_params_for_inference(variables, model_config: ModelConfig):
    """Cast the floating leaves of a variables pytree to the inference
    dtype; identity (same object, no copy) under f32 policy."""
    dtype = inference_dtype(model_config)
    if dtype == jnp.float32:
        return variables
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        variables,
    )
