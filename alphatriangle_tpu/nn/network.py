"""Evaluation wrapper around `AlphaTriangleNet` (reference `NeuralNetwork`).

Parity surface per `alphatriangle/nn/network.py:32-336`:
`evaluate_state` / `evaluate_batch` (the `trimcts.AlphaZeroNetworkInterface`
contract: policy dict + expected scalar value, finiteness guards,
renormalization with uniform-over-valid-actions fallback) and
`get_weights` / `set_weights`.

TPU-native shape: the model is a pure Flax module; this wrapper owns a
`variables` pytree and a single jitted batched apply. `torch.compile`
gymnastics (`network.py:69-102`) disappear — jit is the default — and
the uncompiled `_orig_model` aliasing (`network.py:53-54`) becomes
simply "weights are an immutable pytree". `set_weights` bumps a version
counter, the TPU replacement for the reference's Ray weight broadcast
(SURVEY.md §2c: workers query device-resident params by version).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..config.env_config import EnvConfig
from ..config.model_config import ModelConfig
from ..env.game_state import GameState
from ..features.core import get_feature_extractor
from ..features.extractor import extract_state_features
from ..utils.types import ActionType
from .model import AlphaTriangleNet, expected_value_from_logits, value_support

logger = logging.getLogger(__name__)


class NetworkEvaluationError(Exception):
    """Raised when network evaluation produces unusable outputs."""


class NeuralNetwork:
    """Owns model variables + jitted eval; presents the parity surface."""

    def __init__(
        self,
        model_config: ModelConfig,
        env_config: EnvConfig,
        seed: int = 0,
        variables: dict | None = None,
        attention_fn=None,
    ):
        """`attention_fn`: optional sequence-parallel attention kernel
        (`parallel/ring_attention.make_sp_attention`) threaded into the
        model's transformer; params are identical either way, so a net
        can be built dense and evaluated sequence-sharded or vice versa.
        """
        self.model_config = model_config
        self.env_config = env_config
        self.action_dim = env_config.action_dim
        self.model = AlphaTriangleNet(
            model_config, self.action_dim, attention_fn=attention_fn
        )

        self.num_atoms = model_config.NUM_VALUE_ATOMS
        self.v_min = model_config.VALUE_MIN
        self.v_max = model_config.VALUE_MAX
        self.delta_z = (self.v_max - self.v_min) / (self.num_atoms - 1)
        self.support = value_support(model_config)

        if variables is None:
            dummy_grid = jnp.zeros(
                (
                    1,
                    model_config.GRID_INPUT_CHANNELS,
                    env_config.ROWS,
                    env_config.COLS,
                ),
                dtype=jnp.float32,
            )
            dummy_other = jnp.zeros(
                (1, model_config.OTHER_NN_INPUT_FEATURES_DIM), dtype=jnp.float32
            )
            variables = self.model.init(
                jax.random.PRNGKey(seed), dummy_grid, dummy_other, train=False
            )
        self.variables = variables
        # Bumped by set_weights; self-play readers poll this instead of
        # receiving broadcasts (replaces worker_manager.py:169-209).
        self.weights_version = 0

        # Jit a per-instance closure (not a method with static self):
        # the compile cache then dies with the instance instead of
        # pinning every instance's weights in the class-level jit cache.
        def _apply(variables, grid, other):
            from .precision import dequantize_params

            policy_logits, value_logits = self.model.apply(
                dequantize_params(variables), grid, other, train=False
            )
            policy_probs = jax.nn.softmax(policy_logits, axis=-1)
            values = expected_value_from_logits(value_logits, self.support)
            return policy_logits, policy_probs, values

        self._apply_eval = jax.jit(_apply)

    def evaluate_features(self, grid, other) -> tuple[np.ndarray, np.ndarray]:
        """Batched (B,C,H,W)+(B,F) arrays (np or jnp) ->
        (policy_probs (B,A), values (B,)) as NumPy.

        Raises NetworkEvaluationError on non-finite network output
        (reference guard semantics, `network.py:176-189`).
        """
        logits, probs, values = self._apply_eval(self.variables, grid, other)
        logits_np = np.asarray(logits)
        probs_np = np.asarray(probs)
        values_np = np.asarray(values)
        if not np.all(np.isfinite(logits_np)):
            raise NetworkEvaluationError(
                f"Non-finite policy logits (shape {logits_np.shape})."
            )
        if not np.all(np.isfinite(probs_np)) or not np.all(np.isfinite(values_np)):
            raise NetworkEvaluationError("Non-finite policy probs or values.")
        return probs_np, values_np

    # --- parity surface ---------------------------------------------------

    def _normalize_policy(
        self, probs: np.ndarray, state: GameState, label: str
    ) -> np.ndarray:
        probs = np.maximum(probs, 0.0)
        total = float(probs.sum())
        if abs(total - 1.0) <= 1e-5:
            return probs
        if total > 1e-9:
            return probs / total
        valid = state.valid_actions()
        if not valid:
            raise NetworkEvaluationError(
                f"{label}: policy sum near zero with no valid actions."
            )
        logger.warning("%s: policy sum near zero; uniform over valid.", label)
        out = np.zeros_like(probs)
        out[np.asarray(valid)] = 1.0 / len(valid)
        return out

    def evaluate_state(self, state: GameState) -> tuple[dict[ActionType, float], float]:
        """Single-state eval -> (full {action: prob} mapping, expected value)."""
        feats = extract_state_features(state, self.model_config)
        probs, values = self.evaluate_features(
            feats["grid"][None], feats["other_features"][None]
        )
        p = self._normalize_policy(probs[0], state, "evaluate_state")
        return {i: float(x) for i, x in enumerate(p)}, float(values[0])

    def evaluate_batch(
        self, states: list[GameState]
    ) -> list[tuple[dict[ActionType, float], float]]:
        """Batch eval; one (policy dict, value) per input state.

        Inputs are padded to the next power-of-two batch size so jitted
        shapes come from a small bucket set instead of recompiling for
        every distinct len(states) an MCTS leaf wave produces.
        """
        if not states:
            return []
        n = len(states)
        bucket = 1 << (n - 1).bit_length()
        padded = [s._state for s in states]
        padded.extend([states[0]._state] * (bucket - n))
        fe = get_feature_extractor(states[0]._env, self.model_config)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *padded
        )
        grids, others = fe.extract_batch(stacked)
        probs, values = self.evaluate_features(grids, others)
        probs, values = probs[:n], values[:n]
        out: list[tuple[dict[ActionType, float], float]] = []
        for i, state in enumerate(states):
            p = self._normalize_policy(probs[i], state, f"evaluate_batch[{i}]")
            out.append(({a: float(x) for a, x in enumerate(p)}, float(values[i])))
        return out

    def get_weights(self) -> dict:
        """Model variables as a host (NumPy) pytree."""
        return jax.tree_util.tree_map(np.asarray, self.variables)

    def set_weights(self, weights: dict) -> None:
        """Install a variables pytree; bumps `weights_version`."""
        self.variables = jax.tree_util.tree_map(jnp.asarray, weights)
        self.weights_version += 1

    @property
    def params(self):
        return self.variables["params"]
