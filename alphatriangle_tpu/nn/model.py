"""Flax policy/value network (`AlphaTriangleNet` equivalent).

Capability parity with the reference PyTorch architecture
(`alphatriangle/nn/model.py:109-297`): conv trunk -> residual blocks ->
optional pre-norm TransformerEncoder over the flattened spatial sequence
(sinusoidal positional encoding) -> flatten -> concat `other_features`
-> shared FC -> policy-logit head + C51 distributional value head.

TPU-first redesign, not a translation:
- NHWC conv layout (grid arrives (B, C, H, W) for API parity and is
  transposed once on entry) so convs tile onto the MXU.
- bfloat16 compute / float32 params via `ModelConfig.COMPUTE_DTYPE`;
  logits are returned in float32.
- Stateless GroupNorm by default (`NORM_TYPE="group"`): BatchNorm's
  cross-example running statistics are hostile to dp-sharded pjit;
  "batch" is still supported for parity (uses a `batch_stats`
  collection and per-shard statistics).
- Optional `jax.checkpoint` rematerialization of the residual and
  transformer blocks (`ModelConfig.REMAT`) to trade FLOPs for HBM.
- The spatial sequence is H*W tokens; positional encodings are baked as
  a trace-time constant (reference: `nn/model.py:63-106`).
"""

from collections.abc import Callable

import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax import Array

from ..config.model_config import ModelConfig

_ACTIVATIONS: dict[str, Callable[[Array], Array]] = {
    "ReLU": nn.relu,
    "GELU": nn.gelu,
    "SiLU": nn.silu,
    "Tanh": jnp.tanh,
    "Sigmoid": nn.sigmoid,
}


def _group_count(features: int, preferred: int = 8) -> int:
    """Largest divisor of `features` that is <= preferred."""
    g = min(preferred, features)
    while features % g != 0:
        g -= 1
    return g


def sinusoidal_positional_encoding(seq_len: int, dim: int) -> np.ndarray:
    """(seq_len, dim) float32 sin/cos table (reference `nn/model.py:63-88`)."""
    position = np.arange(seq_len, dtype=np.float32)[:, None]
    div_term = np.exp(
        np.arange(0, dim, 2, dtype=np.float32) * (-np.log(10000.0) / dim)
    )
    pe = np.zeros((seq_len, dim), dtype=np.float32)
    pe[:, 0::2] = np.sin(position * div_term)
    pe[:, 1::2] = np.cos(position * div_term[: pe[:, 1::2].shape[1]])
    return pe


class _Norm(nn.Module):
    """Norm layer selected by `ModelConfig.NORM_TYPE`."""

    norm_type: str
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        if self.norm_type == "group":
            return nn.GroupNorm(
                num_groups=_group_count(x.shape[-1]), dtype=self.dtype
            )(x)
        if self.norm_type == "layer":
            return nn.LayerNorm(dtype=self.dtype)(x)
        if self.norm_type == "batch":
            return nn.BatchNorm(
                use_running_average=not train, dtype=self.dtype, axis_name=None
            )(x)
        return x  # "none"


class ConvBlock(nn.Module):
    """Conv -> norm -> activation (reference `conv_block`, model.py:15-38)."""

    features: int
    kernel: int
    stride: int
    norm_type: str
    act: Callable[[Array], Array]
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        x = nn.Conv(
            self.features,
            (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            padding="SAME",
            dtype=self.dtype,
        )(x)
        x = _Norm(self.norm_type, self.dtype)(x, train)
        return self.act(x)


class ResidualBlock(nn.Module):
    """Two 3x3 convs with skip connection (reference model.py:41-60)."""

    features: int
    norm_type: str
    act: Callable[[Array], Array]
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        residual = x
        x = nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = _Norm(self.norm_type, self.dtype)(x, train)
        x = self.act(x)
        x = nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = _Norm(self.norm_type, self.dtype)(x, train)
        return self.act(x + residual)


class TransformerEncoderLayer(nn.Module):
    """Pre-norm encoder layer (reference model.py:179-202, norm_first=True).

    `attention_fn` swaps the dense attention kernel for a
    sequence-parallel one (`parallel/ring_attention.make_sp_attention`);
    attention-weight dropout is disabled in that case (blockwise
    kernels don't support it) — the residual dropouts still apply.
    """

    dim: int
    heads: int
    mlp_dim: int
    act: Callable[[Array], Array]
    dtype: jnp.dtype
    dropout_rate: float = 0.1
    attention_fn: Callable | None = None

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.heads,
            dtype=self.dtype,
            dropout_rate=(
                0.0 if self.attention_fn is not None else self.dropout_rate
            ),
            deterministic=not train,
            attention_fn=self.attention_fn or nn.dot_product_attention,
        )(y, y)
        x = x + nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = self.act(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        y = nn.Dense(self.dim, dtype=self.dtype)(y)
        return x + nn.Dropout(self.dropout_rate, deterministic=not train)(y)


class MLPHead(nn.Module):
    """Dense stack with norm/act, then a linear output layer."""

    hidden_dims: tuple[int, ...]
    out_dim: int
    norm_type: str
    act: Callable[[Array], Array]
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        for h in self.hidden_dims:
            x = nn.Dense(h, dtype=self.dtype)(x)
            x = _Norm(self.norm_type, self.dtype)(x, train)
            x = self.act(x)
        # Output layer in float32 for stable softmax/loss.
        return nn.Dense(self.out_dim, dtype=jnp.float32)(x)


class AlphaTriangleNet(nn.Module):
    """Policy + C51 value network over (grid, other_features).

    `attention_fn`: optional sequence-parallel attention kernel for the
    transformer stack (see `parallel/ring_attention.make_sp_attention`);
    None = dense single-device attention.
    """

    config: ModelConfig
    action_dim: int
    attention_fn: Callable | None = None

    @nn.compact
    def __call__(
        self, grid: Array, other_features: Array, train: bool = False
    ) -> tuple[Array, Array]:
        """(B, C, H, W) grid + (B, F) extras -> (B, A) policy logits,
        (B, NUM_VALUE_ATOMS) value-distribution logits (both float32)."""
        cfg = self.config
        dtype = jnp.dtype(cfg.COMPUTE_DTYPE)
        act = _ACTIVATIONS[cfg.ACTIVATION_FUNCTION]

        x = jnp.transpose(grid, (0, 2, 3, 1)).astype(dtype)  # NCHW -> NHWC

        for f, k, s in zip(
            cfg.CONV_FILTERS, cfg.CONV_KERNEL_SIZES, cfg.CONV_STRIDES, strict=True
        ):
            x = ConvBlock(f, k, s, cfg.NORM_TYPE, act, dtype)(x, train)

        if cfg.NUM_RESIDUAL_BLOCKS > 0:
            if x.shape[-1] != cfg.RESIDUAL_BLOCK_FILTERS:
                x = ConvBlock(
                    cfg.RESIDUAL_BLOCK_FILTERS, 1, 1, cfg.NORM_TYPE, act, dtype
                )(x, train)
            block = ResidualBlock
            if cfg.REMAT:
                block = nn.remat(ResidualBlock, static_argnums=(2,))
            for _ in range(cfg.NUM_RESIDUAL_BLOCKS):
                x = block(cfg.RESIDUAL_BLOCK_FILTERS, cfg.NORM_TYPE, act, dtype)(
                    x, train
                )

        if cfg.USE_TRANSFORMER and cfg.TRANSFORMER_LAYERS > 0:
            if x.shape[-1] != cfg.TRANSFORMER_DIM:
                x = nn.Conv(cfg.TRANSFORMER_DIM, (1, 1), dtype=dtype)(x)
            b, h, w, d = x.shape
            tokens = x.reshape(b, h * w, d)
            pe = jnp.asarray(
                sinusoidal_positional_encoding(h * w, d), dtype=dtype
            )
            tokens = tokens + pe[None, :, :]
            layer = TransformerEncoderLayer
            if cfg.REMAT:
                layer = nn.remat(TransformerEncoderLayer, static_argnums=(2,))
            for _ in range(cfg.TRANSFORMER_LAYERS):
                tokens = layer(
                    cfg.TRANSFORMER_DIM,
                    cfg.TRANSFORMER_HEADS,
                    cfg.TRANSFORMER_FC_DIM,
                    act,
                    dtype,
                    attention_fn=self.attention_fn,
                )(tokens, train)
            tokens = nn.LayerNorm(dtype=dtype)(tokens)
            flat = tokens.reshape(b, -1)
        else:
            flat = x.reshape(x.shape[0], -1)

        combined = jnp.concatenate(
            [flat, other_features.astype(dtype)], axis=-1
        )

        shared = combined
        for hdim in cfg.FC_DIMS_SHARED:
            shared = nn.Dense(hdim, dtype=dtype)(shared)
            shared = _Norm(cfg.NORM_TYPE, dtype)(shared, train)
            shared = act(shared)

        policy_logits = MLPHead(
            tuple(cfg.POLICY_HEAD_DIMS),
            self.action_dim,
            cfg.NORM_TYPE,
            act,
            dtype,
        )(shared, train)
        value_logits = MLPHead(
            tuple(cfg.VALUE_HEAD_DIMS),
            cfg.NUM_VALUE_ATOMS,
            cfg.NORM_TYPE,
            act,
            dtype,
        )(shared, train)
        return policy_logits.astype(jnp.float32), value_logits.astype(jnp.float32)


def value_support(cfg: ModelConfig) -> Array:
    """(NUM_VALUE_ATOMS,) float32 C51 atom support z_i."""
    return jnp.linspace(
        cfg.VALUE_MIN, cfg.VALUE_MAX, cfg.NUM_VALUE_ATOMS, dtype=jnp.float32
    )


def expected_value_from_logits(value_logits: Array, support: Array) -> Array:
    """(..., atoms) logits -> (...,) expected scalar value sum(p_i * z_i)."""
    probs = nn.softmax(value_logits, axis=-1)
    return jnp.sum(probs * support, axis=-1)


def count_parameters(params) -> int:
    """Total scalar parameter count of a params pytree."""
    import jax

    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
