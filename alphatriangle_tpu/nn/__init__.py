"""Neural network layer: Flax model + evaluation wrapper.

Reference surface: `alphatriangle/nn/` (`AlphaTriangleNet`,
`NeuralNetwork`). See `model.py` / `network.py` docstrings for the
TPU-first design notes.
"""

from .model import (
    AlphaTriangleNet,
    count_parameters,
    expected_value_from_logits,
    sinusoidal_positional_encoding,
    value_support,
)
from .network import NetworkEvaluationError, NeuralNetwork

__all__ = [
    "AlphaTriangleNet",
    "NetworkEvaluationError",
    "NeuralNetwork",
    "count_parameters",
    "expected_value_from_logits",
    "sinusoidal_positional_encoding",
    "value_support",
]
