"""Static-shape MCTS root promotion: subtree reuse across moves.

After a move plays action `a`, the chosen child `c0 = children[b, 0, a]`
roots the subtree worth keeping; everything else (the old root, the
siblings' subtrees, orphan slots) is dead weight. The reference keeps
that subtree behind an opaque C++ tree handle
(`alphatriangle/rl/self_play/worker.py:273-280`); here the same reuse
is a batched, jittable *relabeling* over the fixed `(B, N, A)` edge
planes — no dynamic shapes, no host round trip:

1. **Reachability + BFS rank** (shared plan, plain XLA): seed depth 0
   at `c0`, then `bfs_rounds` rounds of scatter-min relaxation over the
   `children` edges give each node its BFS depth from `c0` (the
   expanded tree is a forest — every slot has at most one parent edge —
   so depths are exact after as many rounds as the tree is deep).
   Sorting `depth * N + node_id` yields a stable BFS-order compaction:
   rank 0 is `c0` itself, parents always rank before their children.
2. **Budget truncation**: ranks >= `max_retained` are dropped (their
   parent edges revert to unexpanded `-1`, keeping the edge statistics
   — the slot is simply re-expandable). Parent-before-child ranking
   makes the truncation frontier consistent: a kept node's parent is
   always kept.
3. **Row reorder** (the backend split): the six f32 edge planes are
   gathered into BFS-rank order with freed rows re-zeroed (children
   rows to -1). Two lowerings — `"xla"` (`take_along_axis` gathers)
   and `"pallas"` (one fused per-game kernel that streams the planes
   through VMEM once, emitting all six in a single pass). Both are
   pure copies of identical values, so they are bit-identical by
   construction; parity tests pin them anyway (tests/test_ops.py).

`MCTSConfig.tree_reuse_backend` selects the lowering. The caller
(`mcts/search.py`) re-seats root statistics by construction — the
promoted row 0 *is* the chosen child's edge row — and re-applies
fresh root priors + Dirichlet noise on the next search's init.

Shapes: planes `(B, N, A)` f32, `terminal` `(B, N)` bool, `actions`
`(B,)` int32. Returns the promoted planes plus `state_index` `(B, N)`
int32 (the old-layout row each promoted `node_state` row should be
gathered from; freed rows point at `c0` so they mirror
`_init_tree`'s root broadcast), `promo_valid` `(B,)` bool (False when
the chosen child was never expanded — nothing to reuse) and
`retained` `(B,)` int32 (rows kept = the next search's per-game
insertion base).
"""

import functools

import jax
import jax.numpy as jnp

try:  # Pallas TPU lowering; interpret mode covers CPU tests.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _promotion_plan(
    children: jax.Array,
    actions: jax.Array,
    max_retained: int,
    bfs_rounds: int,
):
    """BFS-rank compaction plan over the `children` forest.

    Returns `(order, state_index, keep_mask, new_children, promo_valid,
    retained)`: `order[b, r]` is the old row id at BFS rank r,
    `keep_mask[b, r]` whether output row r is live (`r < retained[b]`),
    `new_children` the children plane remapped to new ids in the OLD
    row layout (gathered by `order` in the reorder step).
    """
    b, n, a = children.shape
    barange = jnp.arange(b)
    node_ids = jnp.arange(n, dtype=jnp.int32)[None, :]
    b3 = barange[:, None, None]

    c0 = children[barange, 0, actions].astype(jnp.int32)  # (B,)
    promo_valid = c0 >= 0
    c0c = jnp.maximum(c0, 0)

    child_ids = children.astype(jnp.int32)  # (B, N, A); -1 = none
    has_child = child_ids >= 0
    tgt = jnp.maximum(child_ids, 0)

    # BFS depth from c0 by scatter-min relaxation. `n` is the
    # unreachable sentinel (any real depth is < n). Invalid lanes seed
    # nothing and retain nothing.
    big = jnp.int32(n)
    depth = jnp.full((b, n), big, jnp.int32).at[barange, c0c].set(
        jnp.where(promo_valid, 0, big)
    )

    def relax(_, d):
        pd = d[:, :, None]  # (B, N, 1) parent depth
        cand = jnp.where(has_child & (pd < big), pd + 1, big)
        return d.at[b3, tgt].min(cand)

    depth = jax.lax.fori_loop(0, bfs_rounds, relax, depth)

    reached = depth < big  # (B, N)
    # Stable BFS order: depth-major, old node id minor (keys unique).
    key = jnp.where(reached, depth * n + node_ids, jnp.int32(n * n))
    order = jnp.argsort(key, axis=1).astype(jnp.int32)  # (B, N)
    # Inverse permutation: rank[old_id] = new row id.
    rank = (
        jnp.zeros((b, n), jnp.int32)
        .at[barange[:, None], order]
        .set(jnp.broadcast_to(node_ids, (b, n)))
    )
    retained = jnp.where(
        promo_valid,
        jnp.minimum(
            reached.sum(axis=1, dtype=jnp.int32), jnp.int32(max_retained)
        ),
        0,
    )
    keep_old = reached & (rank < max_retained) & promo_valid[:, None]

    # Remap child pointers to new ids in the old layout; edges to
    # dropped children revert to unexpanded (-1) but keep their stats.
    keep_c = keep_old[barange[:, None, None], tgt] & has_child
    new_children = jnp.where(
        keep_c, rank[barange[:, None, None], tgt].astype(jnp.float32), -1.0
    )

    keep_mask = node_ids < retained[:, None]  # (B, N) over NEW rows
    # node_state gather targets: freed rows mirror the root broadcast.
    state_index = jnp.where(keep_mask, order, c0c[:, None])
    return order, state_index, keep_mask, new_children, promo_valid, retained


def _reorder_planes_xla(order, keep_mask, planes, fills):
    """out[b, r] = planes[b, order[b, r]] where keep, else fill."""
    idx = jnp.where(keep_mask, order, 0)[:, :, None]
    out = []
    for plane, fill in zip(planes, fills):
        gathered = jnp.take_along_axis(plane, idx, axis=1)
        out.append(jnp.where(keep_mask[:, :, None], gathered, fill))
    return tuple(out)


def _promote_kernel(
    order_ref,
    retained_ref,
    v_ref,
    q_ref,
    r_ref,
    c_ref,
    p_ref,
    m_ref,
    ov_ref,
    oq_ref,
    or_ref,
    oc_ref,
    op_ref,
    om_ref,
):
    """One grid program per game: emit all six planes in BFS-rank order
    in a single VMEM pass; rows past `retained` are the zeroed frees
    (children rows -1)."""
    n = v_ref.shape[1]
    ret = retained_ref[0, 0]

    def row(r, _):
        src = order_ref[0, r]
        take = r < ret
        ov_ref[0, pl.ds(r, 1), :] = jnp.where(
            take, v_ref[0, pl.ds(src, 1), :], 0.0
        )
        oq_ref[0, pl.ds(r, 1), :] = jnp.where(
            take, q_ref[0, pl.ds(src, 1), :], 0.0
        )
        or_ref[0, pl.ds(r, 1), :] = jnp.where(
            take, r_ref[0, pl.ds(src, 1), :], 0.0
        )
        oc_ref[0, pl.ds(r, 1), :] = jnp.where(
            take, c_ref[0, pl.ds(src, 1), :], -1.0
        )
        op_ref[0, pl.ds(r, 1), :] = jnp.where(
            take, p_ref[0, pl.ds(src, 1), :], 0.0
        )
        om_ref[0, pl.ds(r, 1), :] = jnp.where(
            take, m_ref[0, pl.ds(src, 1), :], 0.0
        )
        return 0

    jax.lax.fori_loop(0, n, row, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _reorder_planes_pallas(
    order, retained, e_visits, e_value, e_reward, children, prior, valid,
    interpret: bool = False,
):
    """Fused per-game row reorder of the six edge planes (VMEM)."""
    b, n, a = e_visits.shape
    smem_order = pl.BlockSpec(
        (1, n), lambda i: (i, 0), memory_space=pltpu.SMEM
    )
    smem_ret = pl.BlockSpec(
        (1, 1), lambda i: (i, 0), memory_space=pltpu.SMEM
    )
    vmem_plane = pl.BlockSpec(
        (1, n, a), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    plane = jax.ShapeDtypeStruct((b, n, a), jnp.float32)
    return pl.pallas_call(
        _promote_kernel,
        grid=(b,),
        in_specs=[smem_order, smem_ret] + [vmem_plane] * 6,
        out_specs=(vmem_plane,) * 6,
        out_shape=(plane,) * 6,
        interpret=interpret,
    )(
        order.astype(jnp.int32),
        retained.astype(jnp.int32).reshape(b, 1),
        e_visits,
        e_value,
        e_reward,
        children,
        prior,
        valid,
    )


def subtree_promote(
    e_visits: jax.Array,
    e_value: jax.Array,
    e_reward: jax.Array,
    children: jax.Array,
    prior: jax.Array,
    valid: jax.Array,
    terminal: jax.Array,
    actions: jax.Array,
    max_retained: int,
    bfs_rounds: int,
    mode: str = "xla",
) -> tuple[
    jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array,
    jax.Array, jax.Array, jax.Array, jax.Array,
]:
    """Promote each game's chosen child to the root row (see module doc).

    Dispatch by mode ("xla" | "pallas"). Returns
    `(e_visits, e_value, e_reward, children, prior, valid, terminal,
    state_index, promo_valid, retained)` — the six planes + terminal in
    BFS-rank layout with freed rows zeroed, plus the node_state gather
    plan and per-game validity/row counts.
    """
    order, state_index, keep_mask, new_children, promo_valid, retained = (
        _promotion_plan(children, actions, max_retained, bfs_rounds)
    )
    planes = (e_visits, e_value, e_reward, new_children, prior, valid)
    if mode == "xla":
        out = _reorder_planes_xla(
            order, keep_mask, planes, (0.0, 0.0, 0.0, -1.0, 0.0, 0.0)
        )
    elif mode == "pallas":
        if _HAS_PALLAS:
            # The Pallas TPU lowering needs a TPU backend; everywhere
            # else (CPU tests, CPU fallback runs) use the interpreter.
            interpret = jax.default_backend() != "tpu"
            out = _reorder_planes_pallas(
                order, retained, *planes, interpret=interpret
            )
        else:  # pragma: no cover
            out = _reorder_planes_xla(
                order, keep_mask, planes, (0.0, 0.0, 0.0, -1.0, 0.0, 0.0)
            )
    else:
        raise ValueError(f"unknown subtree_promote mode: {mode!r}")
    # terminal is bool (and cheap): shared XLA epilogue for both modes.
    term = jnp.take_along_axis(
        terminal, jnp.where(keep_mask, order, 0), axis=1
    )
    term = keep_mask & term
    return out + (term, state_index, promo_valid, retained)
