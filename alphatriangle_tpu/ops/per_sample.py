"""Stratified proportional PER draw: idx[s, j] ~ priorities / total.

The megastep samples its K learner batches on device with an
inclusive-cumsum + stratified searchsorted over the priority array
(rl/megastep.py `_sample_indices`, rl/sharded_device_buffer.py
`sample_local`) — the vectorized equivalent of the host SumTree's
stratified descent. Two interchangeable lowerings for the index
search:

- "xla": `jnp.searchsorted(cum, u)` — XLA's native binary-search
  lowering over the (cap,) cumsum.
- "pallas": a Pallas kernel computing the identical quantity through
  the exact identity `searchsorted(cum, u, side="left") ==
  #{i : cum[i] < u}` — one grid program per step row streams the
  cumsum through VMEM in lane-width tiles and counts elements below
  each stratum draw (this file). Float compares are exact, so the two
  lowerings agree bit-for-bit.

The cumsum and the stratum draws themselves are computed ONCE in the
shared wrapper (not per lowering): strata boundaries depend on
f32 summation order, so sharing the prefix-sum is what makes the
index parity exact by construction rather than tolerance-based.

`TrainConfig.PER_SAMPLE_BACKEND` selects the lowering; parity tests
pin them against each other (tests/test_ops.py) and benchmarking on
real hardware decides the default.
"""

import functools

import jax
import jax.numpy as jnp

try:  # Pallas TPU lowering; interpret mode covers CPU tests.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# Cumsum tile streamed per inner step: lane-width multiple so the
# (b, _TILE) compare block stays small regardless of ring capacity.
_TILE = 512


def count_below_xla(cum: jax.Array, u: jax.Array) -> jax.Array:
    """(n,) sorted, (k, b) -> (k, b) int32 first-index-not-less-than."""
    return jnp.searchsorted(cum, u).astype(jnp.int32)


def _count_below_kernel(cum_ref, u_ref, out_ref):
    """One grid program per step row: out[j] = #{i : cum[i] < u[j]}."""
    b = u_ref.shape[1]
    n_pad = cum_ref.shape[1]
    u = u_ref[0, :]

    def tile(t, acc):
        seg = cum_ref[0, pl.ds(t * _TILE, _TILE)]
        return acc + jnp.sum(
            (seg[None, :] < u[:, None]).astype(jnp.int32), axis=1
        )

    out_ref[0, :] = jax.lax.fori_loop(
        0, n_pad // _TILE, tile, jnp.zeros((b,), jnp.int32)
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def count_below_pallas(
    cum: jax.Array, u: jax.Array, interpret: bool = False
) -> jax.Array:
    """(n,) sorted, (k, b) -> (k, b) int32 via a tiled compare-count.

    The cumsum is padded with +inf to a tile multiple (inf < u is
    always False, so padding contributes zero) and kept whole in VMEM;
    each program handles one step row's b strata. `interpret=True`
    runs the kernel in the Pallas interpreter (CPU tests).
    """
    if not _HAS_PALLAS:  # pragma: no cover
        return count_below_xla(cum, u)
    n = cum.shape[0]
    k, b = u.shape
    n_pad = -(-n // _TILE) * _TILE
    cum_p = jnp.pad(cum, (0, n_pad - n), constant_values=jnp.inf)[None, :]
    return pl.pallas_call(
        _count_below_kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec(
                (1, n_pad),
                lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, b),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, b),
            lambda i: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((k, b), jnp.int32),
        interpret=interpret,
    )(cum_p, u)


def per_sample(
    priorities: jax.Array,
    cap: int,
    k: int,
    b: int,
    key: jax.Array,
    mode: str = "xla",
) -> tuple[jax.Array, jax.Array]:
    """Stratified proportional draw of (k, b) slots from
    `priorities[:cap]`; returns (idx int32, probs f32).

    Stratum j of step row s draws uniformly from
    [j/b * total, (j+1)/b * total) — zero-priority (empty/trash) slots
    have empty cumsum segments and are never selected. Importance
    weights stay at the call sites (beta annealing and normalization
    scope differ between the single-device and dp-sharded paths).
    """
    cum = jnp.cumsum(priorities[:cap])
    total = cum[-1]
    u = (
        (
            jnp.arange(b, dtype=jnp.float32)[None, :]
            + jax.random.uniform(key, (k, b))
        )
        / b
        * total
    )
    if mode == "xla":
        idx = count_below_xla(cum, u)
    elif mode == "pallas":
        # The Pallas TPU lowering needs a TPU backend; everywhere else
        # (CPU tests, CPU fallback runs) use the interpreter.
        interpret = jax.default_backend() != "tpu"
        idx = count_below_pallas(cum, u, interpret=interpret)
    else:
        raise ValueError(f"unknown PER sample mode: {mode!r}")
    idx = jnp.clip(idx, 0, cap - 1).astype(jnp.int32)
    probs = jnp.maximum(priorities[idx], 1e-12) / jnp.maximum(total, 1e-12)
    return idx, probs
