"""Batched row gather: out[b, w, :] = stats[b, idx[b, w], :].

The MCTS descent reads W tree rows per game per level
(`mcts/search.py:_descend_wave`). Three interchangeable lowerings:

- "einsum": one-hot matmul `(B,W,N) x (B,N,K)` — rides the MXU, burns
  2*W*N*K FLOPs per game per level but avoids TPU gather lowerings.
- "pallas": a Pallas kernel that DMAs each game's stat block into VMEM
  once and copies the W selected rows — same HBM traffic as the
  einsum's stat read, zero MXU work (this file).
- "take": `jnp.take_along_axis` — XLA's native gather lowering.

All three are numerically exact row selects (the einsum uses HIGHEST
precision, f32 row-select is exact), so parity tests pin them against
each other; `MCTSConfig.descent_gather` selects the implementation and
benchmarking on real hardware decides the default.
"""

import functools

import jax
import jax.numpy as jnp

try:  # Pallas TPU lowering; interpret mode covers CPU tests.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def gather_rows_einsum(stats: jax.Array, idx: jax.Array) -> jax.Array:
    """(B, N, K), (B, W) int32 -> (B, W, K) via one-hot matmul."""
    n = stats.shape[1]
    onehot = (idx[..., None] == jnp.arange(n, dtype=idx.dtype)).astype(
        stats.dtype
    )
    return jnp.einsum(
        "bwn,bnk->bwk", onehot, stats, precision=jax.lax.Precision.HIGHEST
    )


def gather_rows_take(stats: jax.Array, idx: jax.Array) -> jax.Array:
    """(B, N, K), (B, W) -> (B, W, K) via XLA gather."""
    return jnp.take_along_axis(stats, idx[..., None], axis=1)


def _gather_kernel(idx_ref, stats_ref, out_ref):
    """One grid program per game: copy W dynamically-indexed rows."""
    w = out_ref.shape[1]
    for j in range(w):  # static unroll; W is small (<= wave size)
        row = idx_ref[0, j]
        out_ref[0, j, :] = stats_ref[0, row, :]


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_rows_pallas(
    stats: jax.Array, idx: jax.Array, interpret: bool = False
) -> jax.Array:
    """(B, N, K), (B, W) -> (B, W, K) with a per-game VMEM-block kernel.

    Each program streams its game's (N, K) stat block HBM->VMEM once
    (what the einsum also reads) and emits the W selected rows without
    touching the MXU. `interpret=True` runs the kernel in the Pallas
    interpreter (CPU tests).
    """
    if not _HAS_PALLAS:  # pragma: no cover
        return gather_rows_take(stats, idx)
    b, n, k = stats.shape
    w = idx.shape[1]
    return pl.pallas_call(
        _gather_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec(
                (1, w),
                lambda i: (i, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(
                (1, n, k),
                lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, w, k),
            lambda i: (i, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, w, k), stats.dtype),
        interpret=interpret,
    )(idx.astype(jnp.int32), stats)


def gather_rows(
    stats: jax.Array, idx: jax.Array, mode: str = "einsum"
) -> jax.Array:
    """Dispatch by mode ("einsum" | "pallas" | "take")."""
    if mode == "einsum":
        return gather_rows_einsum(stats, idx)
    if mode == "pallas":
        # The Pallas TPU lowering needs a TPU backend; everywhere else
        # (CPU tests, CPU fallback runs) use the interpreter.
        interpret = jax.default_backend() != "tpu"
        return gather_rows_pallas(stats, idx, interpret=interpret)
    if mode == "take":
        return gather_rows_take(stats, idx)
    raise ValueError(f"unknown gather mode: {mode!r}")
