"""Custom TPU ops (Pallas kernels with portable fallbacks)."""

from .gather_rows import gather_rows

__all__ = ["gather_rows"]
