"""Custom TPU ops (Pallas kernels with portable fallbacks).

Every op follows one pattern (docs/KERNELS.md): a Pallas TPU lowering
plus interchangeable XLA lowerings, numerically pinned against each
other by parity tests, with a config knob selecting the backend.
"""

from .gather_rows import gather_rows
from .mcts_backup import backup_update
from .per_sample import per_sample
from .subtree_reuse import subtree_promote

__all__ = ["backup_update", "gather_rows", "per_sample", "subtree_promote"]
