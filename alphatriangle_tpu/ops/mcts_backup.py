"""Fused MCTS edge-plane update: insertion + discounted backup.

`mcts/search.py:_wave` ends every wave with a round of (B, W)-sized
scatter updates into the (B, N, A) edge planes: child-slot insertion
(`children.at[].max`, `e_reward.at[].set`) followed by max_depth
rounds of visit/value scatter-adds along the recorded descent paths.
XLA lowers each `.at[]` op as its own scatter over the full plane —
2*depth+2 passes over (B, N, A) HBM per wave. Two interchangeable
lowerings:

- "xla": the scatter chain exactly as `_wave` originally spelled it
  (this is the reference lowering — bit-identical to the pre-kernel
  code by construction).
- "pallas": ONE kernel pass per game. Each grid program keeps its
  game's four edge planes in VMEM, applies the W insertions and the
  W x depth backup updates as sequential one-hot row
  read-modify-writes, and emits the updated planes (this file). The
  per-(level, member) update order matches the XLA scatter's update
  order, so duplicate-edge accumulation associates identically.

`MCTSConfig.backup_update` selects the lowering; parity tests pin
them against each other on CPU interpret mode, including a
fixed-seed self-play chunk (tests/test_ops.py).

Shapes: planes (B, N, A) f32; `parents`/`actions`/`new_child`/
`rewards` (B, W); `rec_node`/`rec_action`/`rec_active`/`returns`
(B, W, D). `new_child` is the pre-computed insertion value
`where(is_new, slot_id, -1.0)` and `returns[:, :, lvl]` the
discounted suffix return at level lvl, so both lowerings are pure
scatter math over identical operands.
"""

import functools

import jax
import jax.numpy as jnp

try:  # Pallas TPU lowering; interpret mode covers CPU tests.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def backup_update_xla(
    e_visits: jax.Array,
    e_value: jax.Array,
    children: jax.Array,
    e_reward: jax.Array,
    parents: jax.Array,
    actions: jax.Array,
    new_child: jax.Array,
    rewards: jax.Array,
    rec_node: jax.Array,
    rec_action: jax.Array,
    rec_active: jax.Array,
    returns: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """The original `_wave` scatter chain, verbatim."""
    batch = e_visits.shape[0]
    depth = rec_node.shape[-1]
    bcol = jnp.arange(batch)[:, None]
    children = children.at[bcol, parents, actions].max(new_child)
    e_reward = e_reward.at[bcol, parents, actions].set(rewards)
    for lvl in range(depth):
        act_mask = rec_active[:, :, lvl]
        nd = jnp.maximum(rec_node[:, :, lvl], 0)
        ac = jnp.maximum(rec_action[:, :, lvl], 0)
        e_visits = e_visits.at[bcol, nd, ac].add(
            act_mask.astype(jnp.float32)
        )
        e_value = e_value.at[bcol, nd, ac].add(
            jnp.where(act_mask, returns[:, :, lvl], 0.0)
        )
    return e_visits, e_value, children, e_reward


def _backup_kernel(
    parents_ref,
    actions_ref,
    new_child_ref,
    rewards_ref,
    rec_node_ref,
    rec_action_ref,
    rec_active_ref,
    returns_ref,
    e_visits_ref,
    e_value_ref,
    children_ref,
    e_reward_ref,
    out_visits_ref,
    out_value_ref,
    out_children_ref,
    out_reward_ref,
):
    """One grid program per game: copy the planes, then apply the W
    insertions and W x depth backup updates as one-hot row RMWs.

    Update order (members ascending within each level, levels
    ascending) reproduces the XLA scatters' duplicate-index semantics:
    `.set` last-write-wins to the highest member, `.max` is
    order-free, and the visit/value adds associate in the same order
    as the reference scatter-adds.
    """
    w = parents_ref.shape[1]
    depth = rec_node_ref.shape[2]
    a = out_visits_ref.shape[2]
    out_visits_ref[...] = e_visits_ref[...]
    out_value_ref[...] = e_value_ref[...]
    out_children_ref[...] = children_ref[...]
    out_reward_ref[...] = e_reward_ref[...]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, a), 1)
    for j in range(w):  # static unroll; W is small (<= wave size)
        p = parents_ref[0, j]
        onehot = lane == actions_ref[0, j]
        row = out_children_ref[0, pl.ds(p, 1), :]
        out_children_ref[0, pl.ds(p, 1), :] = jnp.where(
            onehot, jnp.maximum(row, new_child_ref[0, j]), row
        )
        row = out_reward_ref[0, pl.ds(p, 1), :]
        out_reward_ref[0, pl.ds(p, 1), :] = jnp.where(
            onehot, rewards_ref[0, j], row
        )
    for lvl in range(depth):
        for j in range(w):
            active = rec_active_ref[0, j, lvl] > 0
            nd = jnp.maximum(rec_node_ref[0, j, lvl], 0)
            onehot = lane == jnp.maximum(rec_action_ref[0, j, lvl], 0)
            cnt = jnp.where(active, 1.0, 0.0)
            val = jnp.where(active, returns_ref[0, j, lvl], 0.0)
            row = out_visits_ref[0, pl.ds(nd, 1), :]
            out_visits_ref[0, pl.ds(nd, 1), :] = row + jnp.where(
                onehot, cnt, 0.0
            )
            row = out_value_ref[0, pl.ds(nd, 1), :]
            out_value_ref[0, pl.ds(nd, 1), :] = row + jnp.where(
                onehot, val, 0.0
            )


@functools.partial(jax.jit, static_argnames=("interpret",))
def backup_update_pallas(
    e_visits: jax.Array,
    e_value: jax.Array,
    children: jax.Array,
    e_reward: jax.Array,
    parents: jax.Array,
    actions: jax.Array,
    new_child: jax.Array,
    rewards: jax.Array,
    rec_node: jax.Array,
    rec_action: jax.Array,
    rec_active: jax.Array,
    returns: jax.Array,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-game fused insertion + backup over VMEM-resident planes.

    Each program streams its game's four (N, A) planes HBM->VMEM once
    and applies every update for the wave in place — one pass instead
    of 2*depth+2 full-plane scatters. `interpret=True` runs the
    kernel in the Pallas interpreter (CPU tests).
    """
    if not _HAS_PALLAS:  # pragma: no cover
        return backup_update_xla(
            e_visits, e_value, children, e_reward, parents, actions,
            new_child, rewards, rec_node, rec_action, rec_active, returns,
        )
    b, n, a = e_visits.shape
    w = parents.shape[1]
    depth = rec_node.shape[-1]
    smem_row = pl.BlockSpec(
        (1, w), lambda i: (i, 0), memory_space=pltpu.SMEM
    )
    smem_rec = pl.BlockSpec(
        (1, w, depth), lambda i: (i, 0, 0), memory_space=pltpu.SMEM
    )
    vmem_plane = pl.BlockSpec(
        (1, n, a), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    plane = jax.ShapeDtypeStruct((b, n, a), jnp.float32)
    return pl.pallas_call(
        _backup_kernel,
        grid=(b,),
        in_specs=[smem_row] * 4 + [smem_rec] * 4 + [vmem_plane] * 4,
        out_specs=(vmem_plane,) * 4,
        out_shape=(plane,) * 4,
        interpret=interpret,
    )(
        parents.astype(jnp.int32),
        actions.astype(jnp.int32),
        new_child.astype(jnp.float32),
        rewards.astype(jnp.float32),
        rec_node.astype(jnp.int32),
        rec_action.astype(jnp.int32),
        rec_active.astype(jnp.int32),
        returns.astype(jnp.float32),
        e_visits,
        e_value,
        children,
        e_reward,
    )


def backup_update(
    e_visits: jax.Array,
    e_value: jax.Array,
    children: jax.Array,
    e_reward: jax.Array,
    parents: jax.Array,
    actions: jax.Array,
    new_child: jax.Array,
    rewards: jax.Array,
    rec_node: jax.Array,
    rec_action: jax.Array,
    rec_active: jax.Array,
    returns: jax.Array,
    mode: str = "xla",
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Dispatch by mode ("xla" | "pallas"); returns the four updated
    edge planes (e_visits, e_value, children, e_reward)."""
    if mode == "xla":
        return backup_update_xla(
            e_visits, e_value, children, e_reward, parents, actions,
            new_child, rewards, rec_node, rec_action, rec_active, returns,
        )
    if mode == "pallas":
        # The Pallas TPU lowering needs a TPU backend; everywhere else
        # (CPU tests, CPU fallback runs) use the interpreter.
        interpret = jax.default_backend() != "tpu"
        return backup_update_pallas(
            e_visits, e_value, children, e_reward, parents, actions,
            new_child, rewards, rec_node, rec_action, rec_active, returns,
            interpret=interpret,
        )
    raise ValueError(f"unknown backup mode: {mode!r}")
