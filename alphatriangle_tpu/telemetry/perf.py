"""Live utilization accounting + cross-run performance comparison.

Before this module, MFU/FLOPs accounting ran only inside one-shot
`bench.py` snapshots; a real training run reported throughput but never
what fraction of the chip it used, and nothing could compare two runs.
Podracer (arXiv:2104.06272) and KataGo (arXiv:1902.10565) both treat
continuous utilization accounting as the steering signal for
accelerator-RL work — this is that tier:

- `UtilizationMeter` folds the training loop's cumulative counters
  (learner steps, episodes, experiences, simulations, transfer time)
  into one derived record per stats tick: steps/s, moves/s, games/h,
  achieved TFLOP/s and MFU (analytic FLOPs from `utils/flops.py`),
  buffer occupancy, host<->device transfer time, compile-cache hit
  rate. Records land in the metrics ledger (`telemetry/ledger.py`),
  the `health.json` heartbeat, and (opt-in) a Prometheus textfile.
- `summarize_utilization` renders a run's util records into the
  windowed summary `cli perf` prints (p50/p95 step time, MFU,
  throughput trend).
- `load_comparable` + `compare_summaries` align two runs (or a run
  and a `BENCH_*.json` snapshot) metric-by-metric and report
  regressions against a threshold — the CI/supervisor gate
  `cli compare` exposes as exit codes.

Nothing here imports JAX: every reader works beside a wedged chip.
"""

import json
import logging
import time
from pathlib import Path

from ..utils.flops import peak_bf16_tflops_info

logger = logging.getLogger(__name__)

SUMMARY_SCHEMA = "alphatriangle.perf.v1"

# Metrics `cli compare` aligns between two runs. Throughputs regress
# when they DROP; the memory metrics (peak bytes per device run-wide,
# composed static budget) regress when they GROW — a run that suddenly
# needs more HBM is a regression against the fit headroom even when it
# is no slower. The serve metrics (serving/service.py) are the policy
# service's SLOs: per-move latency p95 regresses when it grows,
# served requests/s when it drops. Rows compare only when BOTH sides
# carry the metric, so training-vs-training comparisons never see the
# serve rows and vice versa.
COMPARE_METRICS = (
    "games_per_hour",
    "moves_per_sec",
    "learner_steps_per_sec",
    # Leaf-equivalent search effort per second: fresh simulations plus
    # root visits inherited through MCTS subtree reuse
    # (ops/subtree_reuse.py). The headline search-throughput number —
    # higher is better; with reuse off it equals sims/s exactly.
    "leaf_evals_per_sec",
    # Fraction of leaf-eval effort that was inherited rather than
    # re-searched (0 with reuse off). Informational next to the rate:
    # a run whose fraction collapses is re-searching work it used to
    # carry (e.g. reload churn clearing lanes).
    "mcts_reused_visit_fraction",
    "mfu",
    "mem_peak_bytes_in_use",
    "memory_budget_bytes",
    "serve_move_latency_ms_p95",
    "serve_requests_per_sec",
    # League flywheel (league/flywheel.py): how fast served games turn
    # into replay rows. Only flywheel runs carry it (rows compare only
    # when both sides have the metric, like the serve SLOs).
    "league_ingested_moves_per_sec",
    # Fleet storm SLOs (serving/fleet.py): end-to-end move latency and
    # served request rate as the ROUTER saw them — retries, hedges and
    # failovers included, so a fleet that hides replica churn well
    # compares well. Only fleet runs carry them.
    "fleet_move_latency_ms_p95",
    "fleet_requests_per_sec",
    # Roofline attribution plane (telemetry/roofline.py): fraction of
    # each tick window the chip spent idle between dispatches. Lower is
    # better — a run that got faster by starving the chip less shows
    # up here even when throughput gains are marginal. Only runs
    # recorded with the dispatch-wall counter carry it.
    "chip_idle_fraction",
)

# Metrics where a LOWER candidate value is the good direction.
LOWER_IS_BETTER = frozenset(
    {
        "mem_peak_bytes_in_use",
        "memory_budget_bytes",
        "serve_move_latency_ms_p95",
        "fleet_move_latency_ms_p95",
        "chip_idle_fraction",
    }
)


class UtilizationMeter:
    """Folds cumulative run counters into per-tick utilization records.

    Counters arrive cumulative (the loop's own `episodes_played`-style
    totals) so a missed tick never loses work — the next tick's delta
    absorbs it. The first tick establishes the baseline and yields no
    record.
    """

    def __init__(
        self,
        forward_flops: int = 0,
        train_step_flops: int = 0,
        device_kind: str = "",
        buffer_capacity: int = 0,
        mesh_devices: int = 1,
        clock=time.monotonic,
    ) -> None:
        self.forward_flops = int(forward_flops)
        self.train_step_flops = int(train_step_flops)
        self.device_kind = device_kind
        self.buffer_capacity = int(buffer_capacity)
        # Width of the mesh the dispatch counters run over. The gauge
        # contract is MESH-LEVEL: one dispatch = one host-side program
        # launch, regardless of how many devices execute it (a dp=8
        # megastep iteration is still 1 dispatch, not 8) — so this is
        # recorded beside the gauge, never multiplied into it.
        self.mesh_devices = max(1, int(mesh_devices))
        peak, source = peak_bf16_tflops_info(device_kind)
        self.peak_tflops = peak
        self.peak_source = source
        self._clock = clock
        self._prev: "dict | None" = None
        # Run-wide high-water of observed bytes_in_use: the backstop
        # peak where the backend reports no peak_bytes_in_use (XLA:CPU
        # synthesized stats — telemetry/health.py).
        self._mem_high_water = 0

    def device_info(self) -> dict:
        """Static device facts for `health.json` / summaries."""
        return {
            "device_kind": self.device_kind,
            "peak_bf16_tflops": self.peak_tflops,
            "peak_source": self.peak_source,
            "mesh_devices": self.mesh_devices,
        }

    def tick(
        self,
        step: int,
        episodes: int = 0,
        experiences: int = 0,
        simulations: int = 0,
        reused_visits: int = 0,
        buffer_size: int = 0,
        transfer_h2d_s: float = 0.0,
        transfer_d2h_s: float = 0.0,
        compile_hits: int = 0,
        compile_misses: int = 0,
        device_memory: "list | None" = None,
        dispatches: int = 0,
        iterations: int = 0,
        dispatch_wall_s: "float | None" = None,
        extra: "dict | None" = None,
    ) -> "dict | None":
        """One derived utilization record, or None (first/zero-width tick).

        `extra`: caller-owned fields merged verbatim into the record —
        the policy service rides its per-window `serve_*` SLO fields
        (queue wait / move latency percentiles, occupancy) into the
        ledger this way (serving/service.py).

        `dispatch_wall_s`: cumulative sealed dispatch wall from the
        run's flight recorder (`FlightRecorder.sealed_wall_seconds`).
        When supplied on consecutive ticks, the record carries
        `chip_idle_fraction` — the fraction of the tick window the
        device spent between dispatches (telemetry/roofline.py's live
        gauge). Callers that never pass it (legacy wiring, tests) emit
        records byte-identical to the pre-roofline shape."""
        now = self._clock()
        # Memory accounting folds on EVERY tick (including the baseline
        # tick that yields no rate record) so the high-water mark never
        # misses a sample.
        mem = self._fold_memory(device_memory)
        cur = {
            "step": step,
            "episodes": episodes,
            "experiences": experiences,
            "simulations": simulations,
            "reused_visits": reused_visits,
            "transfer_h2d_s": transfer_h2d_s,
            "transfer_d2h_s": transfer_d2h_s,
            "dispatches": dispatches,
            "iterations": iterations,
        }
        if isinstance(dispatch_wall_s, (int, float)):
            cur["dispatch_wall_s"] = float(dispatch_wall_s)
        prev, self._prev = self._prev, {"t": now, **cur}
        if prev is None:
            return None
        dt = now - prev["t"]
        if dt <= 0:
            return None
        # The dispatch-wall counter may appear mid-run (flight recorder
        # attached late); a delta only exists once BOTH ticks carry it.
        d = {
            k: cur[k] - prev[k] for k in cur if k in prev
        }
        chip_idle = None
        if "dispatch_wall_s" in d:
            busy = max(0.0, d["dispatch_wall_s"])
            chip_idle = max(0.0, min(1.0, 1.0 - busy / dt))
        steps_s = max(0.0, d["step"]) / dt
        moves_s = max(0.0, d["experiences"]) / dt
        sims_s = max(0.0, d["simulations"]) / dt
        # Leaf-equivalent effort: fresh simulations plus visits carried
        # across moves by subtree reuse (MCTSConfig.tree_reuse). With
        # reuse off the delta is 0 and leaf-evals/s == sims/s exactly.
        reused_s = max(0.0, d["reused_visits"]) / dt
        leaf_s = sims_s + reused_s
        # Achieved model FLOP/s: learner steps x analytic step FLOPs +
        # self-play net evals (one per simulation leaf + ~one root eval
        # per move; experiences/s approximates moves x lanes).
        learner_fs = steps_s * self.train_step_flops
        sp_fs = (sims_s + moves_s) * self.forward_flops
        tflops = (learner_fs + sp_fs) / 1e12
        mfu = (
            tflops / self.peak_tflops
            if self.peak_tflops and tflops > 0
            else None
        )
        total_compiles = compile_hits + compile_misses
        record = {
            **(mem or {}),
            "kind": "util",
            "step": step,
            "time": time.time(),
            "window_s": round(dt, 3),
            "learner_steps_per_sec": round(steps_s, 4),
            "step_time_ms": (
                round(1000.0 / steps_s, 3) if steps_s > 0 else None
            ),
            "moves_per_sec": round(moves_s, 2),
            "games_per_hour": round(
                max(0.0, d["episodes"]) * 3600.0 / dt, 2
            ),
            "sims_per_sec": round(sims_s, 1),
            "leaf_evals_per_sec": round(leaf_s, 1),
            "mcts_reused_visit_fraction": (
                round(reused_s / leaf_s, 4) if leaf_s > 0 else None
            ),
            # 6+8 decimals: a test-sized net on CPU runs ~1e-6 TFLOP/s
            # and must not round its MFU down to an ambiguous 0.0.
            "tflops_per_sec": round(tflops, 6),
            "mfu": round(mfu, 8) if mfu is not None else None,
            "device_kind": self.device_kind,
            "peak_bf16_tflops": self.peak_tflops,
            "peak_source": self.peak_source,
            "buffer_size": buffer_size,
            "buffer_fill": (
                round(buffer_size / self.buffer_capacity, 4)
                if self.buffer_capacity
                else None
            ),
            "transfer_h2d_ms": round(
                max(0.0, d["transfer_h2d_s"]) * 1000.0, 2
            ),
            "transfer_d2h_ms": round(
                max(0.0, d["transfer_d2h_s"]) * 1000.0, 2
            ),
            "compile_cache_hits": compile_hits,
            "compile_cache_misses": compile_misses,
            "compile_cache_hit_rate": (
                round(compile_hits / total_compiles, 4)
                if total_compiles
                else None
            ),
            # Mesh-level program dispatches per loop iteration: the
            # host-round-trip gauge the fused megastep exists to
            # collapse to 1.0 (sync runs ~3: rollout + ingest + learner
            # group). Counters tick once per host launch, NOT once per
            # device execution — a dp-sharded megastep iteration is one
            # dispatch whether the mesh has 1 device or 8; mesh_devices
            # carries the width for readers that want per-device
            # executions (gauge x mesh_devices).
            "dispatches_per_iteration": (
                round(
                    max(0, d["dispatches"]) / d["iterations"], 3
                )
                if d["iterations"] > 0
                else None
            ),
            "mesh_devices": self.mesh_devices,
        }
        if chip_idle is not None:
            # Live roofline gauge (telemetry/roofline.py): the window's
            # sealed-dispatch wall over the window. Emitted ONLY when
            # the counter was supplied, so legacy records keep their
            # exact pre-roofline field set.
            record["chip_idle_fraction"] = round(chip_idle, 6)
        if extra:
            record.update(extra)
        return record

    def _fold_memory(self, device_memory: "list | None") -> "dict | None":
        """Device-memory totals for one tick (telemetry/memory.py) +
        the run-wide high-water update. None when the backend reports
        nothing (the record then simply carries no mem_* fields)."""
        from .memory import summarize_device_memory

        totals = summarize_device_memory(device_memory)
        if totals is None:
            return None
        in_use = totals["bytes_in_use"]
        self._mem_high_water = max(self._mem_high_water, in_use)
        peak = max(self._mem_high_water, totals["peak_bytes_in_use"])
        limit = totals["bytes_limit"]
        out = {
            "mem_bytes_in_use": in_use,
            "mem_peak_bytes_in_use": peak,
            "mem_bytes_limit": limit,
            "mem_utilization": (
                round(in_use / limit, 6) if limit else None
            ),
            "mem_devices": [
                {
                    k: d.get(k)
                    for k in (
                        "device",
                        "kind",
                        "bytes_in_use",
                        "peak_bytes_in_use",
                        "bytes_limit",
                    )
                }
                for d in device_memory
                if isinstance(d, dict)
            ],
        }
        return out


# --- summaries ----------------------------------------------------------


def _percentile(values: list, q: float) -> "float | None":
    """Nearest-rank percentile; None for an empty list (no numpy — this
    runs in JAX-free reader processes)."""
    vals = sorted(v for v in values if isinstance(v, (int, float)))
    if not vals:
        return None
    idx = min(len(vals) - 1, max(0, round(q * (len(vals) - 1))))
    return float(vals[idx])


def _mean(values: list) -> "float | None":
    vals = [v for v in values if isinstance(v, (int, float))]
    return sum(vals) / len(vals) if vals else None


def _trend(values: list) -> "float | None":
    """Second-half mean over first-half mean, minus 1 (signed drift)."""
    vals = [v for v in values if isinstance(v, (int, float))]
    if len(vals) < 4:
        return None
    half = len(vals) // 2
    first, second = _mean(vals[:half]), _mean(vals[half:])
    if not first:
        return None
    return second / first - 1.0


def summarize_utilization(
    records: list, window: "int | None" = None
) -> "dict | None":
    """Fold a run's util records into the `cli perf` summary.

    `window` keeps only the newest N records (the whole run otherwise).
    None when no usable records exist (schema failure for callers).

    Tolerates historical ledgers: runs recorded before the `kind`
    field (or before the serve/mem/dispatch gauges) still summarize —
    a kind-less record counts as a util tick when it carries any core
    throughput field; fields added later simply come out None and
    `compare_summaries` renders them "n/a" instead of skipping the run.
    """
    _UTIL_SIGNATURE = (
        "moves_per_sec",
        "learner_steps_per_sec",
        "games_per_hour",
        "step_time_ms",
        "mfu",
    )
    records = [
        r
        for r in records
        if isinstance(r, dict)
        and (
            r.get("kind") == "util"
            or (
                "kind" not in r
                and any(k in r for k in _UTIL_SIGNATURE)
            )
        )
    ]
    if not records:
        return None
    full_span = len(records)
    if window is not None and window > 0:
        records = records[-window:]

    def col(key: str) -> list:
        return [r.get(key) for r in records]

    last = records[-1]
    mfus = [v for v in col("mfu") if isinstance(v, (int, float))]

    def numeric(key: str) -> list:
        return [v for v in col(key) if isinstance(v, (int, float))]

    # Serve SLO summary (records written by serving/service.py ticks):
    # p50 averages across tick windows, p95 takes the WORST window —
    # the conservative bound an SLO gate wants.
    serve: dict = {}
    if numeric("serve_move_latency_ms_p95"):
        serve = {
            "serve_move_latency_ms_p50": _mean(
                numeric("serve_move_latency_ms_p50")
            ),
            "serve_move_latency_ms_p95": max(
                numeric("serve_move_latency_ms_p95")
            ),
            "serve_queue_wait_ms_p50": _mean(
                numeric("serve_queue_wait_ms_p50")
            ),
            "serve_queue_wait_ms_p95": (
                max(numeric("serve_queue_wait_ms_p95"))
                if numeric("serve_queue_wait_ms_p95")
                else None
            ),
            "serve_requests_per_sec": _mean(
                numeric("serve_requests_per_sec")
            ),
            "serve_requests_total": last.get("serve_requests_total"),
            "serve_sessions_last": last.get("serve_sessions"),
            "serve_sessions_admitted": last.get("serve_sessions_admitted"),
            "serve_sessions_retired": last.get("serve_sessions_retired"),
            "serve_slots": last.get("serve_slots"),
            "serve_batch_fill": _mean(numeric("serve_batch_fill")),
            "serve_weight_reloads": last.get("serve_weight_reloads"),
            # Bucket-ladder micro-batcher (serving/buckets.py): the
            # rung the service ended on, the windowed wave fill that
            # drives rung walking, and how many switches the run made.
            "serve_bucket": last.get("serve_bucket"),
            "serve_fill": _mean(numeric("serve_fill")),
            "serve_rung_switches": last.get("serve_rung_switches"),
        }
    # Device-stats gauges mirrored onto util records by the loop's
    # iteration tail / serve tick (telemetry/device_stats.py). Absent
    # on legacy and stats-off runs — then the block contributes nothing
    # and the summary is byte-identical to the pre-plane shape.
    devstats: dict = {}
    if numeric("root_visit_entropy") or numeric("tree_occupancy"):
        occ = numeric("tree_occupancy")
        devstats = {
            "root_visit_entropy": _mean(numeric("root_visit_entropy")),
            "tree_occupancy": _mean(occ),
            "tree_occupancy_max": max(occ) if occ else None,
            "beacons_armed": last.get("beacons_armed"),
        }
    # Roofline idle gauge (telemetry/roofline.py), mirrored the same
    # way: absent on pre-roofline runs, so legacy summaries keep their
    # exact pre-roofline key set.
    roofline: dict = {}
    idle = numeric("chip_idle_fraction")
    if idle:
        roofline = {
            "chip_idle_fraction": _mean(idle),
            "chip_idle_fraction_max": max(idle),
        }
    return {
        **serve,
        **devstats,
        **roofline,
        "schema": SUMMARY_SCHEMA,
        "ticks": len(records),
        "ticks_total": full_span,
        "first_step": records[0].get("step"),
        "last_step": last.get("step"),
        "wall_seconds": round(
            sum(
                r.get("window_s", 0.0)
                for r in records
                if isinstance(r.get("window_s"), (int, float))
            ),
            1,
        ),
        "device_kind": last.get("device_kind"),
        "peak_bf16_tflops": last.get("peak_bf16_tflops"),
        "peak_source": last.get("peak_source"),
        "step_time_ms_p50": _percentile(col("step_time_ms"), 0.50),
        "step_time_ms_p95": _percentile(col("step_time_ms"), 0.95),
        "learner_steps_per_sec": _mean(col("learner_steps_per_sec")),
        "moves_per_sec": _mean(col("moves_per_sec")),
        "games_per_hour": _mean(col("games_per_hour")),
        "sims_per_sec": _mean(col("sims_per_sec")),
        "leaf_evals_per_sec": _mean(col("leaf_evals_per_sec")),
        "mcts_reused_visit_fraction": _mean(
            col("mcts_reused_visit_fraction")
        ),
        "tflops_per_sec": _mean(col("tflops_per_sec")),
        "mfu": _mean(mfus),
        "mfu_max": max(mfus) if mfus else None,
        "buffer_fill_last": last.get("buffer_fill"),
        "transfer_h2d_ms": _mean(col("transfer_h2d_ms")),
        "transfer_d2h_ms": _mean(col("transfer_d2h_ms")),
        "compile_cache_hit_rate": last.get("compile_cache_hit_rate"),
        "dispatches_per_iteration": _mean(col("dispatches_per_iteration")),
        # Memory (telemetry/memory.py): run-wide observed peak, plus
        # the newest in-use/limit snapshot for the `cli perf` readout.
        "mem_peak_bytes_in_use": (
            max(
                (
                    v
                    for v in col("mem_peak_bytes_in_use")
                    if isinstance(v, (int, float))
                ),
                default=None,
            )
        ),
        "mem_bytes_in_use_last": last.get("mem_bytes_in_use"),
        "mem_bytes_limit": last.get("mem_bytes_limit"),
        "throughput_trend": _trend(
            col("moves_per_sec")
            if any(isinstance(v, (int, float)) and v > 0 for v in col("moves_per_sec"))
            else col("learner_steps_per_sec")
        ),
    }


def summarize_league(records: list) -> "dict | None":
    """Fold a run's `kind:"league"` records (league/flywheel.py, one
    per matchmade round) into the league block of the `cli perf`
    summary: pool size, ingest volume/rate, opponent-mix histogram,
    mean trajectory staleness, promotions. None for non-flywheel runs
    (no league records), so the block and the compare row only appear
    where the flywheel ran."""
    league = [
        r for r in records if isinstance(r, dict) and r.get("kind") == "league"
    ]
    if not league:
        return None
    last = league[-1]

    def numeric(key: str) -> list:
        return [
            r.get(key)
            for r in league
            if isinstance(r.get(key), (int, float))
            and not isinstance(r.get(key), bool)
        ]

    moves = numeric("moves_ingested")
    return {
        "league_rounds": len(league),
        "league_pool_size": last.get("pool_size"),
        "league_moves_ingested": int(sum(moves)) if moves else None,
        "league_ingested_moves_per_sec": _mean(
            numeric("ingested_moves_per_sec")
        ),
        "league_mean_staleness": _mean(numeric("mean_staleness")),
        "league_stale_dropped": last.get("stale_dropped_total"),
        "league_promotions": last.get("promotions"),
        "league_live_elo": last.get("live_elo"),
        "league_opponent_mix": last.get("opponent_mix"),
    }


def summarize_fleet(records: list) -> "dict | None":
    """Fold a fleet run's `kind:"fleet"` events (serving/fleet.py,
    fleet.jsonl) into the fleet block of the `cli perf` summary:
    lifecycle counts (deaths -> respawns -> readmissions), routing
    decisions (sheds / retries / hedge wins), rolling-reload recompile
    total, and the last storm's throughput + latency SLOs. None when
    the run never ran a fleet (no fleet events), so the block and the
    compare rows only appear where the fleet ran."""
    events = [
        r for r in records if isinstance(r, dict) and r.get("kind") == "fleet"
    ]
    if not events:
        return None

    def count(*names: str) -> int:
        return sum(1 for r in events if r.get("event") in names)

    out = {
        "fleet_events": len(events),
        "fleet_deaths": count("death"),
        "fleet_respawns": count("respawn"),
        "fleet_evictions": count("evict"),
        "fleet_readmissions": count("readmit"),
        "fleet_sheds": count("shed"),
        # Rejection codes kept distinct (serving/router.py REJECT_*):
        # queue-full is admission back-pressure, no-healthy-replica is
        # a fleet outage, retries-exhausted is a replica sickness —
        # one folded shed total hides which one is burning the budget.
        "fleet_shed_queue_full": sum(
            1
            for r in events
            if r.get("event") == "shed"
            and r.get("rejection") == "queue-full"
        ),
        "fleet_shed_no_healthy": sum(
            1
            for r in events
            if r.get("event") == "shed"
            and r.get("rejection") == "no-healthy-replica"
        ),
        "fleet_shed_retries_exhausted": count("exhausted"),
        "fleet_retries": count("retry"),
        "fleet_hedges": count("hedge"),
        "fleet_hedge_wins": count("hedge-win"),
        "fleet_reload_recompiles": sum(
            r.get("recompiles", 0)
            for r in events
            if r.get("event") == "replica-reloaded"
            and isinstance(r.get("recompiles"), int)
        ),
    }
    stop = [r for r in events if r.get("event") == "fleet-stop"]
    if stop:
        out["fleet_gaveup"] = stop[-1].get("gaveup")
    storms = [r for r in events if r.get("event") == "storm-summary"]
    if storms:
        storm = storms[-1]
        out.update(
            {
                "fleet_requests": storm.get("requests"),
                "fleet_completed": storm.get("completed"),
                "fleet_shed_requests": storm.get("shed"),
                "fleet_lost": storm.get("lost"),
                "fleet_requests_per_sec": storm.get("requests_per_sec"),
                "fleet_move_latency_ms_p50": storm.get(
                    "move_latency_ms_p50"
                ),
                "fleet_move_latency_ms_p95": storm.get(
                    "move_latency_ms_p95"
                ),
            }
        )
    return out


# --- cross-run comparison ----------------------------------------------


def _summary_from_bench(payload: dict, label: str) -> "dict | None":
    """Normalize one `bench.py` JSON line into compare metrics."""
    if payload.get("metric") != "self_play_games_per_hour":
        return None
    extra = payload.get("extra") or {}
    flops = extra.get("flops") or {}
    return {
        "schema": SUMMARY_SCHEMA,
        "source": label,
        "games_per_hour": payload.get("value"),
        "moves_per_sec": extra.get("moves_per_sec"),
        "leaf_evals_per_sec": extra.get("leaf_evals_per_sec"),
        "mcts_reused_visit_fraction": extra.get(
            "mcts_reused_visit_fraction"
        ),
        "learner_steps_per_sec": (
            extra.get("learner_steps_per_sec_fused")
            or extra.get("learner_steps_per_sec")
        ),
        "mfu": flops.get("self_play_mfu"),
        "device_kind": extra.get("device_kind"),
    }


def load_comparable(
    target: str, root_dir: "str | None" = None
) -> "tuple[dict | None, str]":
    """(normalized summary, label) for one side of `cli compare`.

    Accepts, in resolution order: a perf-summary JSON file (from
    `cli perf --json`), a bench JSON line file (`BENCH_*.json`), a
    `metrics.jsonl` path, a run directory, or a run name under the
    runs root. Returns (None, reason) when nothing usable exists.
    """
    from .ledger import read_ledger, resolve_ledger_path

    path = Path(target)
    if path.is_file() and path.suffix == ".json":
        try:
            payload = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as exc:
            return None, f"{target}: unreadable JSON ({exc})"
        if isinstance(payload, dict):
            if payload.get("schema") == SUMMARY_SCHEMA:
                payload.setdefault("source", str(path))
                return payload, str(path)
            bench = _summary_from_bench(payload, str(path))
            if bench is not None:
                return bench, str(path)
        return None, f"{target}: not a perf summary or bench JSON"
    if path.exists():
        ledger = resolve_ledger_path(path)
    else:
        run_dir = _run_dir_for(target, root_dir)
        ledger = resolve_ledger_path(run_dir) if run_dir else None
    if ledger is None:
        return None, f"{target}: no metrics ledger found"
    # Read ALL records (no kinds= pre-filter): ledgers written before
    # the `kind` field exist, and the pre-filter would drop their
    # util ticks before the tolerant summarize above ever saw them.
    summary = summarize_utilization(read_ledger(ledger))
    if summary is None:
        return None, f"{ledger}: no utilization records"
    # Static memory budget from the run's attribution records, so
    # `cli compare` can gate estimated-HBM growth next to observed peak.
    from .memory import compose_budget

    mem_records = read_ledger(ledger, kinds={"memory"})
    if mem_records:
        budget = compose_budget(mem_records)
        if budget["total_bytes"] > 0:
            summary["memory_budget_bytes"] = budget["total_bytes"]
    # League flywheel fold: flywheel runs gain the league_* fields
    # (and with them the league_ingested_moves_per_sec compare row).
    league = summarize_league(read_ledger(ledger, kinds={"league"}))
    if league is not None:
        summary.update(league)
    # Fleet fold: fleet.jsonl (serving/fleet.py decision ledger) lives
    # BESIDE the metrics ledger; fleet runs gain the fleet_* fields and
    # with them the fleet SLO compare rows.
    fleet_path = Path(ledger).parent / "fleet.jsonl"
    if fleet_path.is_file():
        fleet = summarize_fleet(read_ledger(fleet_path))
        if fleet is not None:
            summary.update(fleet)
    summary["source"] = str(ledger)
    return summary, str(ledger)


def _run_dir_for(run_name: str, root_dir: "str | None") -> "Path | None":
    from ..config.persistence_config import PersistenceConfig

    persistence = PersistenceConfig(RUN_NAME=run_name)
    if root_dir:
        persistence = persistence.model_copy(
            update={"ROOT_DATA_DIR": root_dir}
        )
    run_dir = persistence.get_run_base_dir()
    return run_dir if run_dir.is_dir() else None


def compare_summaries(
    a: dict, b: dict, threshold: float = 0.1, metrics=None
) -> tuple[list, list]:
    """(rows, regressions) comparing candidate `a` against baseline `b`.

    A row is (metric, a_value, b_value, ratio, status). For throughput
    metrics, status is "regression" when a < b * (1 - threshold) and
    "improved" when a > b * (1 + threshold); for LOWER_IS_BETTER
    metrics (peak bytes, memory budget, serve latency p95) the
    directions flip — growth past the threshold is the regression.
    "n/a" when either side is missing. `regressions` lists the
    regressed metric names. `metrics` restricts the compared set (the
    `cli compare --metrics` selector; serve-smoke gates the serve SLO
    rows alone with it); default is all of COMPARE_METRICS.
    """
    rows = []
    regressions = []
    for metric in metrics if metrics is not None else COMPARE_METRICS:
        va, vb = a.get(metric), b.get(metric)
        usable = all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in (va, vb)
        )
        if not usable or vb <= 0:
            rows.append((metric, va, vb, None, "n/a"))
            continue
        ratio = va / vb
        if metric in LOWER_IS_BETTER:
            better, worse = ratio < 1.0 - threshold, ratio > 1.0 + threshold
        else:
            better, worse = ratio > 1.0 + threshold, ratio < 1.0 - threshold
        if worse:
            status = "regression"
            regressions.append(metric)
        elif better:
            status = "improved"
        else:
            status = "ok"
        rows.append((metric, va, vb, ratio, status))
    return rows, regressions
