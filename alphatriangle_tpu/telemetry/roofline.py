"""Roofline attribution plane: compiler cost ground truth + chip-idle
gap forensics.

The ledger says how fast a run went and the flight ring says how long
each dispatch took, but neither can say how far a program sits from
what the hardware allows — MFU is computed from hand-derived analytic
FLOPs (utils/flops.py) and the wall-clock BETWEEN dispatches is
invisible. This module closes both gaps (Podracer's
hardware-utilization discipline, arXiv:2104.06272):

- **Cost capture.** Every program through the AOT compile cache
  records `compiled.cost_analysis()` — FLOPs, bytes accessed,
  transcendentals — as a `kind: "cost"` record (`program_cost_record`),
  persisted as a `.cost.json` sidecar beside the executable exactly
  like the `.mem.json` flow and drained into the run's
  `metrics.jsonl`.
- **Roofline model.** Arithmetic intensity (FLOPs / bytes accessed)
  against the device's machine balance (peak FLOP/s over peak HBM
  bandwidth, `peak_hbm_gbps_info` below) classifies each hot program
  compute- vs memory-bound; joining cost records against the flight
  ring's measured p50 dispatch walls yields achieved-vs-roofline
  fractions (`roofline_rows`).
- **Gap forensics.** A timeline pass over the flight ring
  (`attribute_gaps`) unions the intent→seal dispatch intervals into
  chip-busy time and attributes every idle gap to a named host
  category (fetch / ingest / ledger / checkpoint / other) via span
  overlap from `trace.json` — producing the `chip_idle_fraction`
  that rides util records, `cli perf`, `cli watch`, `cli compare`
  and the Prometheus textfile.

Nothing here imports JAX: `cli roofline` must render beside a wedged
chip, same contract as `cli mem` / `cli doctor`.
"""

import json
import logging
import os
import time
from pathlib import Path

logger = logging.getLogger(__name__)

COST_KIND = "cost"

# Operator-supplied peak HBM bandwidth override (GB/s): lets CPU/smoke
# runs and unlisted chips still produce a machine balance (parallel to
# utils/flops.py's ALPHATRIANGLE_PEAK_TFLOPS).
PEAK_HBM_GBPS_ENV = "ALPHATRIANGLE_PEAK_HBM_GBPS"

# "0" skips the setup-time cost pre-capture for AOT-bypassed programs
# (training/setup.py). The pre-capture is a fresh lower+compile purely
# for `cost_analysis()` — on accelerators it doubles as a warm-up, but
# on CPU it's seconds of pure overhead per process, so the test suite
# turns it off (tests/conftest.py; subprocess children inherit it).
# Programs on the AOT dispatch path capture cost regardless.
COST_PRECAPTURE_ENV = "ALPHATRIANGLE_COST_PRECAPTURE"


def cost_precapture_enabled() -> bool:
    return os.environ.get(COST_PRECAPTURE_ENV, "1").strip() != "0"

# Peak HBM bandwidth per chip, GB/s. Public figures: v4 1228, v5e
# (v5 lite) 819, v5p 2765, v6e (Trillium) 1638.
_PEAK_HBM_GBPS = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1638.0,
    "TPU v6e": 1638.0,
}

#: Named host-gap categories, attribution order. "other" absorbs every
#: idle second no span claims, so dispatch + gaps always cover the
#: whole flight timeline.
GAP_CATEGORIES = ("fetch", "ingest", "ledger", "checkpoint", "other")

# Span-name keywords -> gap category. The loop's host phases
# (docs/OBSERVABILITY.md "Spans"): result fetch/harvest lands in
# "fetch", replay fold/sampling in "ingest", telemetry/stats ticks in
# "ledger", checkpoint + weight sync in "checkpoint".
_SPAN_CATEGORY_KEYWORDS = (
    ("fetch", ("fetch", "harvest", "rollout", "d2h")),
    ("ingest", ("fold", "sample", "ingest", "enqueue", "stream", "h2d")),
    ("ledger", ("ledger", "tick", "stats", "telemetry", "health", "prom")),
    ("checkpoint", ("checkpoint", "weight_sync", "save")),
)


def peak_hbm_gbps_info(device_kind: str) -> "tuple[float | None, str]":
    """(peak HBM GB/s, source) for a `jax.Device.device_kind`.

    Source is "env" (ALPHATRIANGLE_PEAK_HBM_GBPS override — wins so
    operators can assert a bandwidth for unlisted chips or CPU
    smokes), "table" (known chip), or "unknown" (peak None — an
    explicit marker, never a guessed denominator). Mirrors
    `utils.flops.peak_bf16_tflops_info` including the space-insensitive
    longest-prefix fallback over runtime device-kind variants.
    """
    override = os.environ.get(PEAK_HBM_GBPS_ENV, "").strip()
    if override:
        try:
            value = float(override)
            if value > 0:
                return value, "env"
            logger.warning(
                "%s=%r is not positive; ignoring.", PEAK_HBM_GBPS_ENV,
                override,
            )
        except ValueError:
            logger.warning(
                "%s=%r is not a number; ignoring.", PEAK_HBM_GBPS_ENV,
                override,
            )
    kind = (device_kind or "").strip()
    if kind in _PEAK_HBM_GBPS:
        return _PEAK_HBM_GBPS[kind], "table"
    norm = kind.lower().replace(" ", "")
    best = None
    for name, peak in _PEAK_HBM_GBPS.items():
        key = name.lower().replace(" ", "")
        if norm.startswith(key) and (best is None or len(key) > best[0]):
            best = (len(key), peak)
    if best:
        return best[1], "table"
    return None, "unknown"


def machine_balance_flops_per_byte(
    peak_tflops, peak_hbm_gbps
) -> "float | None":
    """Machine balance (FLOPs per byte): programs whose arithmetic
    intensity exceeds it are compute-bound on this chip, the rest are
    bandwidth-bound. None when either peak is unknown."""
    if not _num(peak_tflops) or not _num(peak_hbm_gbps):
        return None
    if peak_tflops <= 0 or peak_hbm_gbps <= 0:
        return None
    return (peak_tflops * 1e12) / (peak_hbm_gbps * 1e9)


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


# --- cost records (writer side; called via compile_cache) ----------------


def program_cost_record(
    name: str,
    compiled,
    backend: str = "",
    key: str = "",
    origin: str = "compile",
) -> "dict | None":
    """One `kind: "cost"` record from a compiled program's
    `cost_analysis()` (FLOPs / bytes accessed / transcendentals).
    Handles both the dict and the legacy list-of-dicts return shape.
    None when the executable doesn't support the analysis — cost
    attribution degrades, nothing raises (same contract as
    `memory.program_memory_record`)."""
    analysis = getattr(compiled, "cost_analysis", None)
    if analysis is None:
        return None
    try:
        stats = analysis()
    except Exception:
        return None
    if isinstance(stats, (list, tuple)):
        stats = next((s for s in stats if isinstance(s, dict)), None)
    if not isinstance(stats, dict):
        return None

    def grab(field: str) -> "float | None":
        v = stats.get(field)
        return float(v) if _num(v) else None

    flops = grab("flops")
    bytes_accessed = grab("bytes accessed")
    transcendentals = grab("transcendentals")
    if all(v is None for v in (flops, bytes_accessed, transcendentals)):
        return None
    return {
        "kind": COST_KIND,
        "category": "program",
        "component": f"program/{name}",
        "program": name,
        "key": key,
        "backend": backend,
        "origin": origin,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "transcendentals": transcendentals,
        "time": time.time(),
    }


# --- readers (no JAX on this path) ---------------------------------------


def latest_cost_by_program(records) -> dict:
    """Newest usable cost record per program name (re-compiles re-emit;
    the roofline wants the latest of each). Non-dict and non-cost rows
    are skipped — torn/legacy ledgers degrade, never raise."""
    out: dict = {}
    for rec in records:
        if (
            isinstance(rec, dict)
            and rec.get("kind") == COST_KIND
            and rec.get("program")
        ):
            out[str(rec["program"])] = rec
    return out


def cost_flops_by_family(records) -> dict:
    """Per-family compiler-reported FLOPs per dispatch: the HOTTEST
    (max-FLOP) program of each family wins — the autotuner's
    `cost_flops` calibration source (autotune/model.py)."""
    from .flight import program_family

    out: dict = {}
    for program, rec in latest_cost_by_program(records).items():
        flops = rec.get("flops")
        if not _num(flops) or flops <= 0:
            continue
        fam = program_family(program)
        if fam not in out or flops > out[fam]:
            out[fam] = float(flops)
    return out


def roofline_rows(
    cost_records,
    flight_rows,
    peak_tflops=None,
    peak_hbm_gbps=None,
) -> list:
    """Per-program roofline rows: `summarize_flight` rows joined with
    the newest cost record per program. Every flight row yields a row;
    programs with no cost record (legacy runs, torn sidecars) come out
    with None cost fields — "n/a" in the tables, never an error.

    Row fields: program, family, count, wall_s_p50, wall_s_total,
    flops, bytes_accessed, intensity (FLOPs/byte), bound ("compute" /
    "memory" / None), achieved_tflops (compiler FLOPs over measured
    p50 wall), roofline_tflops (the ceiling at this intensity), and
    roofline_fraction (achieved / ceiling).
    """
    balance = machine_balance_flops_per_byte(peak_tflops, peak_hbm_gbps)
    by_program = latest_cost_by_program(cost_records)
    rows = []
    for fr in flight_rows or []:
        if not isinstance(fr, dict):
            continue
        program = str(fr.get("program"))
        cost = by_program.get(program)
        flops = cost.get("flops") if cost else None
        bytes_accessed = cost.get("bytes_accessed") if cost else None
        intensity = None
        if _num(flops) and _num(bytes_accessed) and bytes_accessed > 0:
            intensity = flops / bytes_accessed
        bound = None
        if intensity is not None and balance is not None:
            bound = "compute" if intensity > balance else "memory"
        wall_p50 = fr.get("wall_s_p50")
        achieved = None
        if _num(flops) and _num(wall_p50) and wall_p50 > 0:
            achieved = flops / wall_p50
        ceiling = None
        if _num(peak_tflops) and peak_tflops > 0:
            ceiling = peak_tflops * 1e12
            if intensity is not None and _num(peak_hbm_gbps):
                ceiling = min(ceiling, intensity * peak_hbm_gbps * 1e9)
        fraction = None
        if achieved is not None and ceiling is not None and ceiling > 0:
            fraction = achieved / ceiling
        rows.append(
            {
                "program": program,
                "family": fr.get("family"),
                "count": fr.get("count"),
                "wall_s_p50": wall_p50,
                "wall_s_total": fr.get("wall_s_total"),
                "flops": flops if _num(flops) else None,
                "bytes_accessed": (
                    bytes_accessed if _num(bytes_accessed) else None
                ),
                "transcendentals": (
                    cost.get("transcendentals") if cost else None
                ),
                "intensity": (
                    round(intensity, 4) if intensity is not None else None
                ),
                "bound": bound,
                "achieved_tflops": (
                    round(achieved / 1e12, 6) if achieved is not None else None
                ),
                "roofline_tflops": (
                    round(ceiling / 1e12, 6) if ceiling is not None else None
                ),
                "roofline_fraction": (
                    round(fraction, 6) if fraction is not None else None
                ),
            }
        )
    return rows


# --- gap forensics -------------------------------------------------------


def load_trace_spans(trace_path) -> list:
    """(category, begin_s, end_s) wall-clock span intervals from a
    run's `trace.json` (telemetry/tracer.py), keyword-mapped to gap
    categories; uncategorized spans are dropped (the residual lands in
    "other" anyway). Missing/corrupt traces return [] — gap
    attribution degrades to all-"other", never raises."""
    try:
        data = json.loads(Path(trace_path).read_text())
    except (OSError, ValueError):
        return []
    events = data.get("traceEvents", []) if isinstance(data, dict) else data
    if not isinstance(events, list):
        return []
    spans = []
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        ts, dur = ev.get("ts"), ev.get("dur")
        if not _num(ts) or not _num(dur) or dur <= 0:
            continue
        category = _span_category(str(ev.get("name", "")))
        if category is None:
            continue
        begin = ts / 1e6  # Chrome traces use microseconds
        spans.append((category, begin, begin + dur / 1e6))
    spans.sort(key=lambda s: s[1])
    return spans


def _span_category(name: str) -> "str | None":
    low = name.lower()
    for category, keywords in _SPAN_CATEGORY_KEYWORDS:
        if any(k in low for k in keywords):
            return category
    return None


def _merge_intervals(intervals: list) -> list:
    """Sorted (begin, end) intervals -> merged disjoint intervals."""
    merged: list = []
    for begin, end in sorted(intervals):
        if end <= begin:
            continue
        if merged and begin <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], end)
        else:
            merged.append([begin, end])
    return merged


def _overlap_seconds(merged: list, begin: float, end: float) -> float:
    """Seconds of a merged interval list that fall inside [begin, end]."""
    total = 0.0
    for b, e in merged:
        if e <= begin:
            continue
        if b >= end:
            break
        total += min(e, end) - max(b, begin)
    return total


def attribute_gaps(flight_records, spans=None) -> "dict | None":
    """Timeline attribution over a run's flight ring.

    Unions the sealed intent→seal intervals (t_mono) into chip-busy
    time; the complement within [first record, last record] is chip
    idle, attributed per gap to the named host categories via
    wall-clock span overlap (`spans` from `load_trace_spans`; the
    mono→wall offset is the median over the records that carry both
    stamps). Overclaimed gaps scale proportionally; unclaimed seconds
    land in "other" — dispatch + gaps therefore always cover the whole
    timeline (`attributed_fraction` 1.0 by construction, <1.0 only
    when intervals are unusable).

    Returns None when fewer than two timestamped records exist (a
    legacy or empty ring), else {wall_s, dispatch_s, gap_s, gaps:
    {category: s}, chip_idle_fraction, attributed_fraction,
    dispatches, unsealed}.
    """
    stamped = [
        r
        for r in flight_records or []
        if isinstance(r, dict) and _num(r.get("t_mono"))
    ]
    if len(stamped) < 2:
        return None
    t0 = min(r["t_mono"] for r in stamped)
    t1 = max(r["t_mono"] for r in stamped)
    wall = t1 - t0
    if wall <= 0:
        return None
    intents = {
        r.get("seq"): r for r in stamped if r.get("phase") == "intent"
    }
    dispatch_intervals = []
    dispatches = 0
    for r in stamped:
        if r.get("phase") != "seal":
            continue
        intent = intents.pop(r.get("seq"), None)
        if intent is None:
            continue
        dispatches += 1
        dispatch_intervals.append((intent["t_mono"], r["t_mono"]))
    busy = _merge_intervals(dispatch_intervals)
    dispatch_s = sum(e - b for b, e in busy)
    # Idle gaps: the complement of chip-busy within the timeline.
    gaps = []
    cursor = t0
    for b, e in busy:
        if b > cursor:
            gaps.append((cursor, b))
        cursor = max(cursor, e)
    if t1 > cursor:
        gaps.append((cursor, t1))
    # mono -> wall offset for span overlap (spans are wall-clock).
    offsets = sorted(
        r["time"] - r["t_mono"] for r in stamped if _num(r.get("time"))
    )
    offset = offsets[len(offsets) // 2] if offsets else None
    by_category = {}
    if spans and offset is not None:
        for category, begin, end in spans:
            by_category.setdefault(category, []).append((begin, end))
        by_category = {
            c: _merge_intervals(ivals) for c, ivals in by_category.items()
        }
    totals = {c: 0.0 for c in GAP_CATEGORIES}
    for begin, end in gaps:
        length = end - begin
        claimed = {}
        if by_category:
            wb, we = begin + offset, end + offset
            for category, merged in by_category.items():
                sec = _overlap_seconds(merged, wb, we)
                if sec > 0:
                    claimed[category] = sec
        claimed_total = sum(claimed.values())
        if claimed_total > length > 0:
            scale = length / claimed_total
            claimed = {c: s * scale for c, s in claimed.items()}
            claimed_total = length
        for category, sec in claimed.items():
            totals[category] += sec
        totals["other"] += max(0.0, length - claimed_total)
    gap_s = sum(e - b for b, e in gaps)
    return {
        "wall_s": round(wall, 6),
        "dispatch_s": round(dispatch_s, 6),
        "gap_s": round(gap_s, 6),
        "gaps": {c: round(s, 6) for c, s in totals.items()},
        "chip_idle_fraction": round(gap_s / wall, 6),
        "attributed_fraction": round((dispatch_s + gap_s) / wall, 6),
        "dispatches": dispatches,
        "unsealed": len(intents),
    }


# --- run-level summary (cli roofline / cli perf fold) --------------------


def summarize_roofline(
    cost_records,
    flight_records,
    device_kind: str = "",
    peak_tflops=None,
    trace_path=None,
) -> "dict | None":
    """The `cli roofline` payload: machine balance + per-program rows +
    gap attribution for one run. `peak_tflops` should come from the
    run's own util records (already env-resolved at run time); the HBM
    peak resolves here so `ALPHATRIANGLE_PEAK_HBM_GBPS` works at read
    time. None when the run has neither cost records nor a usable
    flight timeline (exit-2 territory for the CLI)."""
    from .flight import summarize_flight

    flight_rows = summarize_flight(flight_records or [])
    peak_gbps, hbm_source = peak_hbm_gbps_info(device_kind)
    rows = roofline_rows(
        cost_records or [],
        flight_rows,
        peak_tflops=peak_tflops,
        peak_hbm_gbps=peak_gbps,
    )
    spans = load_trace_spans(trace_path) if trace_path else []
    attribution = attribute_gaps(flight_records or [], spans=spans)
    if not rows and attribution is None:
        return None
    balance = machine_balance_flops_per_byte(peak_tflops, peak_gbps)
    return {
        "schema": "alphatriangle.roofline.v1",
        "device_kind": device_kind,
        "peak_bf16_tflops": peak_tflops if _num(peak_tflops) else None,
        "peak_hbm_gbps": peak_gbps,
        "peak_hbm_source": hbm_source,
        "machine_balance_flops_per_byte": (
            round(balance, 4) if balance is not None else None
        ),
        "programs": rows,
        "attribution": attribution,
    }
