"""Streaming training-anomaly detection over per-step metrics.

A diverging run announces itself in the per-step series long before the
aggregate TensorBoard means do: a loss spike, a grad-norm explosion, a
NaN, policy entropy pinned at zero. The detector keeps EWMA mean/variance
per metric (O(1) per observation, no history scan) and fires structured
anomalies that the telemetry layer escalates to `Anomaly/*` metrics and
log warnings with recent-window context.

Checks per observation:
- **nonfinite**: NaN/inf value (never folded into the running stats).
- **spike**: |value - ewma_mean| exceeds `z_threshold` sigmas once the
  metric has `warmup` observations. The scale gets a small absolute +
  relative floor so a near-constant series (variance ~ 0) doesn't fire
  on float jitter; a genuinely noisy-but-stationary series stays quiet
  because the EWMA variance tracks its actual spread.
- **collapse**: an entropy-like metric at/below the collapse floor
  (policy entropy hitting ~0 means the policy head has saturated and
  self-play exploration is gone). Latched: fires once per excursion,
  re-arms when the metric recovers.
"""

import math
import threading
from collections import deque
from dataclasses import dataclass, field

EPS_ABS = 1e-8  # scale floors: keep z finite on constant series
EPS_REL = 1e-3


@dataclass
class Anomaly:
    """One detected anomaly, with recent-window context for the log."""

    kind: str  # "nonfinite" | "spike" | "collapse"
    metric: str
    step: int
    value: float
    zscore: float | None = None
    mean: float | None = None
    window: list = field(default_factory=list)  # recent (step, value)

    def describe(self) -> str:
        parts = [f"{self.kind} on {self.metric} at step {self.step}"]
        if self.kind == "spike" and self.zscore is not None:
            parts.append(
                f"value {self.value:.6g} is {self.zscore:.1f} sigma from "
                f"ewma mean {self.mean:.6g}"
            )
        elif self.kind == "collapse":
            parts.append(f"value {self.value:.6g} at/below collapse floor")
        else:
            parts.append(f"value {self.value!r}")
        if self.window:
            recent = ", ".join(f"{v:.4g}" for _, v in self.window[-8:])
            parts.append(f"recent: [{recent}]")
        return "; ".join(parts)


class _MetricState:
    __slots__ = ("mean", "var", "n", "recent", "collapsed")

    def __init__(self, window: int) -> None:
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.recent: deque = deque(maxlen=window)
        self.collapsed = False


class AnomalyDetector:
    """Per-metric EWMA z-score + collapse checks, thread-safe."""

    def __init__(
        self,
        alpha: float = 0.02,
        z_threshold: float = 6.0,
        warmup: int = 20,
        window: int = 32,
        entropy_floor: float = 0.01,
        entropy_metrics: tuple[str, ...] = ("Loss/Entropy",),
    ) -> None:
        self.alpha = alpha
        self.z_threshold = z_threshold
        self.warmup = warmup
        self.window = window
        self.entropy_floor = entropy_floor
        self.entropy_metrics = set(entropy_metrics)
        self._lock = threading.Lock()
        self._state: dict[str, _MetricState] = {}

    def observe(self, metric: str, value: float, step: int) -> list[Anomaly]:
        """Fold one observation; returns anomalies fired by it."""
        value = float(value)
        with self._lock:
            st = self._state.get(metric)
            if st is None:
                st = self._state[metric] = _MetricState(self.window)
            out: list[Anomaly] = []
            ctx = list(st.recent)
            if not math.isfinite(value):
                # Not folded into the EWMA: one NaN must not poison the
                # baseline the next finite values are judged against.
                return [
                    Anomaly("nonfinite", metric, step, value, window=ctx)
                ]
            if st.n >= self.warmup:
                scale = (
                    math.sqrt(max(st.var, 0.0))
                    + EPS_ABS
                    + EPS_REL * abs(st.mean)
                )
                z = abs(value - st.mean) / scale
                if z > self.z_threshold:
                    out.append(
                        Anomaly(
                            "spike", metric, step, value,
                            zscore=z, mean=st.mean, window=ctx,
                        )
                    )
            if metric in self.entropy_metrics and st.n >= self.warmup:
                if value <= self.entropy_floor:
                    if not st.collapsed:
                        st.collapsed = True
                        out.append(
                            Anomaly(
                                "collapse", metric, step, value,
                                mean=st.mean, window=ctx,
                            )
                        )
                else:
                    st.collapsed = False
            # EWMA update. During warmup the effective alpha decays as
            # 1/(n+1), so the early estimates behave like plain sample
            # mean/variance instead of over-weighting the first value.
            a = max(self.alpha, 1.0 / (st.n + 1))
            d = value - st.mean
            st.mean += a * d
            st.var = (1.0 - a) * (st.var + a * d * d)
            st.n += 1
            st.recent.append((step, value))
            return out

    def observe_metrics(
        self, metrics: dict[str, float], step: int
    ) -> list[Anomaly]:
        out: list[Anomaly] = []
        for name, value in metrics.items():
            out.extend(self.observe(name, value, step))
        return out
