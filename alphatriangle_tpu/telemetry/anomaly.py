"""Streaming training-anomaly detection over per-step metrics.

A diverging run announces itself in the per-step series long before the
aggregate TensorBoard means do: a loss spike, a grad-norm explosion, a
NaN, policy entropy pinned at zero. The detector keeps EWMA mean/variance
per metric (O(1) per observation, no history scan) and fires structured
anomalies that the telemetry layer escalates to `Anomaly/*` metrics and
log warnings with recent-window context.

Checks per observation:
- **nonfinite**: NaN/inf value (never folded into the running stats).
- **spike**: |value - ewma_mean| exceeds `z_threshold` sigmas once the
  metric has `warmup` observations. The scale gets a small absolute +
  relative floor so a near-constant series (variance ~ 0) doesn't fire
  on float jitter; a genuinely noisy-but-stationary series stays quiet
  because the EWMA variance tracks its actual spread.
- **collapse**: an entropy-like metric at/below the collapse floor
  (policy entropy hitting ~0 means the policy head has saturated and
  self-play exploration is gone). Latched: fires once per excursion,
  re-arms when the metric recovers.
- **memory_growth** (`observe_memory`, fed per utilization tick with
  device `bytes_in_use`): fires when memory grows MONOTONICALLY for a
  configured run of ticks AND the total growth over that run exceeds a
  relative floor — the leak signature, as opposed to the sawtooth of
  a healthy allocator. Latched per excursion; any decrease re-arms and
  restarts the run.
- **search-health** (`observe_search`, fed per device-stats record from
  the in-program stat-packs — telemetry/device_stats.py): the search
  leg's `value_abs_max` goes through the ordinary nonfinite/spike
  screen (a value explosion INSIDE the fused program, attributed to the
  step that produced it); `root_entropy` at/below a floor fires a
  latched collapse (every root playing one forced move = the search's
  exploration is gone, KataGo's degenerate-search signature); and
  `occupancy` pinned at ~1.0 fires a latched `saturation` (the tree
  arrays are full — simulations past that point are wasted slots).
"""

import math
import threading
from collections import deque
from dataclasses import dataclass, field

EPS_ABS = 1e-8  # scale floors: keep z finite on constant series
EPS_REL = 1e-3


@dataclass
class Anomaly:
    """One detected anomaly, with recent-window context for the log."""

    # "nonfinite" | "spike" | "collapse" | "memory_growth" | "saturation"
    kind: str
    metric: str
    step: int
    value: float
    zscore: float | None = None
    mean: float | None = None
    window: list = field(default_factory=list)  # recent (step, value)

    def describe(self) -> str:
        parts = [f"{self.kind} on {self.metric} at step {self.step}"]
        if self.kind == "spike" and self.zscore is not None:
            parts.append(
                f"value {self.value:.6g} is {self.zscore:.1f} sigma from "
                f"ewma mean {self.mean:.6g}"
            )
        elif self.kind == "collapse":
            parts.append(f"value {self.value:.6g} at/below collapse floor")
        elif self.kind == "memory_growth":
            parts.append(
                f"bytes_in_use {self.value:,.0f} grew monotonically from "
                f"{self.mean:,.0f} (possible leak)"
            )
        elif self.kind == "saturation":
            parts.append(
                f"value {self.value:.4g} at/above saturation ceiling — "
                "tree slots exhausted, extra simulations are wasted"
            )
        else:
            parts.append(f"value {self.value!r}")
        if self.window:
            recent = ", ".join(f"{v:.4g}" for _, v in self.window[-8:])
            parts.append(f"recent: [{recent}]")
        return "; ".join(parts)


class _MetricState:
    __slots__ = ("mean", "var", "n", "recent", "collapsed")

    def __init__(self, window: int) -> None:
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.recent: deque = deque(maxlen=window)
        self.collapsed = False


class AnomalyDetector:
    """Per-metric EWMA z-score + collapse checks, thread-safe."""

    def __init__(
        self,
        alpha: float = 0.02,
        z_threshold: float = 6.0,
        warmup: int = 20,
        window: int = 32,
        entropy_floor: float = 0.01,
        entropy_metrics: tuple[str, ...] = ("Loss/Entropy",),
        memory_growth_ticks: int = 12,
        memory_growth_fraction: float = 0.05,
        search_entropy_floor: float = 0.05,
        occupancy_ceiling: float = 0.98,
    ) -> None:
        self.alpha = alpha
        self.z_threshold = z_threshold
        self.warmup = warmup
        self.window = window
        self.entropy_floor = entropy_floor
        self.entropy_metrics = set(entropy_metrics)
        self.memory_growth_ticks = memory_growth_ticks
        self.memory_growth_fraction = memory_growth_fraction
        self.search_entropy_floor = search_entropy_floor
        self.occupancy_ceiling = occupancy_ceiling
        # observe_search latches (one anomaly per excursion).
        self._search_collapsed = False
        self._search_saturated = False
        self._lock = threading.Lock()
        self._state: dict[str, _MetricState] = {}
        # Leak-detector state (observe_memory): baseline at the start
        # of the current monotonic run, its length, and the latch.
        self._mem_prev: float | None = None
        self._mem_base: float | None = None
        self._mem_run = 0
        self._mem_fired = False
        self._mem_recent: deque = deque(maxlen=window)

    def observe(self, metric: str, value: float, step: int) -> list[Anomaly]:
        """Fold one observation; returns anomalies fired by it."""
        value = float(value)
        with self._lock:
            st = self._state.get(metric)
            if st is None:
                st = self._state[metric] = _MetricState(self.window)
            out: list[Anomaly] = []
            ctx = list(st.recent)
            if not math.isfinite(value):
                # Not folded into the EWMA: one NaN must not poison the
                # baseline the next finite values are judged against.
                return [
                    Anomaly("nonfinite", metric, step, value, window=ctx)
                ]
            if st.n >= self.warmup:
                scale = (
                    math.sqrt(max(st.var, 0.0))
                    + EPS_ABS
                    + EPS_REL * abs(st.mean)
                )
                z = abs(value - st.mean) / scale
                if z > self.z_threshold:
                    out.append(
                        Anomaly(
                            "spike", metric, step, value,
                            zscore=z, mean=st.mean, window=ctx,
                        )
                    )
            if metric in self.entropy_metrics and st.n >= self.warmup:
                if value <= self.entropy_floor:
                    if not st.collapsed:
                        st.collapsed = True
                        out.append(
                            Anomaly(
                                "collapse", metric, step, value,
                                mean=st.mean, window=ctx,
                            )
                        )
                else:
                    st.collapsed = False
            # EWMA update. During warmup the effective alpha decays as
            # 1/(n+1), so the early estimates behave like plain sample
            # mean/variance instead of over-weighting the first value.
            a = max(self.alpha, 1.0 / (st.n + 1))
            d = value - st.mean
            st.mean += a * d
            st.var = (1.0 - a) * (st.var + a * d * d)
            st.n += 1
            st.recent.append((step, value))
            return out

    def observe_memory(self, bytes_in_use: float, step: int) -> list[Anomaly]:
        """Fold one tick's device `bytes_in_use`; fires `memory_growth`
        on a sustained monotonic climb (see module doc). One anomaly
        per excursion: the latch re-arms only when memory decreases."""
        value = float(bytes_in_use)
        with self._lock:
            out: list[Anomaly] = []
            if not math.isfinite(value):
                return out
            if self._mem_prev is None or value < self._mem_prev:
                # First sample, or memory released: a leak never shrinks
                # — restart the monotonic run from here and re-arm.
                self._mem_base = value
                self._mem_run = 0
                self._mem_fired = False
            elif value > self._mem_prev:
                self._mem_run += 1
            self._mem_prev = value
            base = self._mem_base or 0.0
            grown = base > 0 and value >= base * (
                1.0 + self.memory_growth_fraction
            )
            if (
                self._mem_run >= self.memory_growth_ticks
                and grown
                and not self._mem_fired
            ):
                self._mem_fired = True
                out.append(
                    Anomaly(
                        "memory_growth",
                        "Memory/bytes_in_use",
                        step,
                        value,
                        mean=base,
                        window=list(self._mem_recent),
                    )
                )
            self._mem_recent.append((step, value))
            return out

    def observe_search(self, leg: dict, step: int) -> list[Anomaly]:
        """Screen one device-stats search leg (the host fold of the
        in-program stat-pack — see module doc's search-health entry).
        Tolerates partial legs: absent keys are skipped."""
        out: list[Anomaly] = []
        if not isinstance(leg, dict):
            return out
        v = leg.get("value_abs_max")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            # Nonfinite + z-spike via the standard per-metric screen:
            # a value explosion is exactly a spike on this series.
            out.extend(self.observe("Search/value_abs_max", float(v), step))
        ent = leg.get("root_entropy")
        if (
            isinstance(ent, (int, float))
            and not isinstance(ent, bool)
            and math.isfinite(float(ent))
        ):
            with self._lock:
                if float(ent) <= self.search_entropy_floor:
                    if not self._search_collapsed:
                        self._search_collapsed = True
                        out.append(
                            Anomaly(
                                "collapse",
                                "Search/root_entropy",
                                step,
                                float(ent),
                            )
                        )
                else:
                    self._search_collapsed = False
        occ = leg.get("occupancy")
        if (
            isinstance(occ, (int, float))
            and not isinstance(occ, bool)
            and math.isfinite(float(occ))
        ):
            with self._lock:
                if float(occ) >= self.occupancy_ceiling:
                    if not self._search_saturated:
                        self._search_saturated = True
                        out.append(
                            Anomaly(
                                "saturation",
                                "Search/tree_occupancy",
                                step,
                                float(occ),
                            )
                        )
                else:
                    self._search_saturated = False
        return out

    def observe_metrics(
        self, metrics: dict[str, float], step: int
    ) -> list[Anomaly]:
        out: list[Anomaly] = []
        for name, value in metrics.items():
            out.extend(self.observe(name, value, step))
        return out
