"""W3C-style trace context for the multi-process fleet (docs/OBSERVABILITY.md
"Distributed tracing & SLOs").

PRs 14-15 made the repo a process tree — a supervisor spawning training
children, an N-replica serve fleet behind a retry/hedge router — but
every observability artifact stayed per-process. This module is the
identity layer that stitches them back together: a `(trace_id,
span_id, parent_id)` triple minted once per causal unit (a routed
request at the router, an attempt at the supervisor) and carried
across every process boundary the repo has:

- **the replica JSON-line protocol** — the router stamps the triple
  into the request payload; the replica echoes it in the reply and
  threads it through its `PolicyService` so the `serve/b<B>` flight
  intent names the trace_ids it served;
- **the env seam** — `ALPHATRIANGLE_TRACEPARENT` (the same shape as
  `ALPHATRIANGLE_SUPERVISE_OVERRIDES`: one env var, parsed by the
  child at startup) carries the parent's attempt context into spawned
  children, so a replica's or training child's flight ring links back
  to the supervisor event that spawned it;
- **the ledgers** — fleet.jsonl / supervisor.jsonl events, flight
  intents/seals and tracer spans all carry the triple as plain
  optional fields. Every reader stays tolerant of id-less legacy
  records: the fields ride `dict.get`, never a schema.

The wire form is W3C traceparent-shaped (`00-<trace>-<span>-01`) so an
external OTel collector could adopt the ids unchanged, but nothing
here imports or requires OpenTelemetry — ids are `os.urandom` hex and
the propagation is JSON fields + one env var. JAX-free by
construction (stdlib only): minting happens in the JAX-free router
and supervisor parents.
"""

import os
import re
from dataclasses import dataclass

#: Env var carrying a parent context to spawned children (the
#: supervisor's per-attempt seam; serving/fleet.py uses it per replica
#: incarnation). Same propagation idiom as `ALPHATRIANGLE_SUPERVISE_OVERRIDES`.
TRACEPARENT_ENV = "ALPHATRIANGLE_TRACEPARENT"

#: The record field names, shared by every writer so readers can grep
#: one spelling. Legacy records simply lack them.
TRACE_ID_FIELD = "trace_id"
SPAN_ID_FIELD = "span_id"
PARENT_ID_FIELD = "parent_id"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def new_trace_id() -> str:
    """128-bit random trace id, 32 lowercase hex chars (W3C width)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit random span id, 16 lowercase hex chars (W3C width)."""
    return os.urandom(8).hex()


@dataclass(frozen=True)
class TraceContext:
    """One span's identity: which trace it belongs to, its own id, and
    the span that caused it (None for a root span)."""

    trace_id: str
    span_id: str
    parent_id: "str | None" = None

    def child(self) -> "TraceContext":
        """A new span caused by this one (same trace, fresh span id)."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=new_span_id(),
            parent_id=self.span_id,
        )

    def fields(self) -> dict:
        """The ledger/payload fields for this context (parent_id only
        when set, so root spans stay two fields)."""
        out = {
            TRACE_ID_FIELD: self.trace_id,
            SPAN_ID_FIELD: self.span_id,
        }
        if self.parent_id:
            out[PARENT_ID_FIELD] = self.parent_id
        return out

    def to_traceparent(self) -> str:
        """W3C traceparent wire form (version 00, sampled flag)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, value: "str | None") -> "TraceContext | None":
        """Parse the wire form; None on anything malformed (a child
        must never crash over a corrupt env var)."""
        if not isinstance(value, str):
            return None
        m = _TRACEPARENT_RE.match(value.strip().lower())
        if m is None:
            return None
        return cls(trace_id=m.group(1), span_id=m.group(2))

    @classmethod
    def from_fields(cls, record: "dict | None") -> "TraceContext | None":
        """Recover a context from a ledgered record's fields; None when
        the record predates tracing (the legacy-tolerance contract)."""
        if not isinstance(record, dict):
            return None
        trace_id = record.get(TRACE_ID_FIELD)
        span_id = record.get(SPAN_ID_FIELD)
        if not (isinstance(trace_id, str) and trace_id):
            return None
        return cls(
            trace_id=trace_id,
            span_id=span_id if isinstance(span_id, str) and span_id else new_span_id(),
            parent_id=record.get(PARENT_ID_FIELD) or None,
        )


def mint(parent: "TraceContext | None" = None) -> TraceContext:
    """Mint a span context: a child of `parent` when given (same
    trace), else a fresh root trace (router per request, supervisor
    per attempt with no inherited context)."""
    if parent is not None:
        return parent.child()
    return TraceContext(trace_id=new_trace_id(), span_id=new_span_id())


def from_env(environ: "dict | None" = None) -> "TraceContext | None":
    """The context a parent process handed this one via the env seam,
    or None (standalone run / legacy parent)."""
    environ = os.environ if environ is None else environ
    return TraceContext.from_traceparent(environ.get(TRACEPARENT_ENV))


def child_env(
    ctx: "TraceContext | None", environ: "dict | None" = None
) -> dict:
    """A copy of `environ` with the traceparent seam set (or cleared
    when ctx is None, so a child never inherits a stale context)."""
    env = dict(os.environ if environ is None else environ)
    if ctx is None:
        env.pop(TRACEPARENT_ENV, None)
    else:
        env[TRACEPARENT_ENV] = ctx.to_traceparent()
    return env


def stamp(record: dict, ctx: "TraceContext | None") -> dict:
    """Stamp a record dict with a context's fields in place (no-op for
    None, so call sites stay unconditional). Returns the record."""
    if ctx is not None:
        record.update(ctx.fields())
    return record


def trace_fields(payload: "dict | None") -> dict:
    """Extract just the trace fields present on a payload/record —
    empty dict for legacy id-less records, so `**trace_fields(req)`
    composes with writers unconditionally."""
    if not isinstance(payload, dict):
        return {}
    out = {}
    for key in (TRACE_ID_FIELD, SPAN_ID_FIELD, PARENT_ID_FIELD):
        value = payload.get(key)
        if isinstance(value, str) and value:
            out[key] = value
    return out
