"""Device telemetry plane: in-program stat-packs + progress beacons.

The fused megastep (rl/megastep.py, Podracer arXiv:2104.06272) bought a
1-dispatch iteration at the price of opacity: rollout, search, ingest,
PER sampling and K learner steps execute inside single XLA programs
that every host-side surface (tracer spans, flight recorder, anomaly
detector) can only see from outside, as one wall-clock number between
intent and seal. This module makes the fused black boxes observable
WITHOUT adding a dispatch or a host sync, with two legs:

**Stat-packs** (``TelemetryConfig.DEVICE_STATS``). Fixed-shape bundles
of KataGo-style search-health statistics (arXiv:1902.10565: root-visit
concentration/entropy, value bounds, tree occupancy) computed where the
data already lives — inside the search waves, the rollout chunk, the
PER sample and the fused learner steps — and returned through the
EXISTING single per-iteration fetch as one more leaf of the output
pytree. The host folds them into ``kind:"device_stats"`` ledger records
(`cli perf`, `cli watch`, `bench.py extra.device_stats`) and feeds them
to `AnomalyDetector.observe_search` so a value explosion or an entropy
collapse is attributed to the exact fused step, not the iteration
aggregate.

**Progress beacons** (off by default on hot paths). `jax.debug.callback`
markers at phase boundaries — every Nth search wave, each fused learner
step, the ring scatter — appending ``(program, phase, index, monotonic)``
rows to a crash-safe per-run ``beacons.jsonl`` via the ledger writer.
Armed by env (``ALPHATRIANGLE_BEACONS=1``), by the dispatch watchdog's
near-deadline warning, or by `cli supervise` on a dispatch-hung respawn
(the ``TELEMETRY__BEACONS`` override), so the SECOND occurrence of a
wedge names its phase: `wedge_report.json` and `cli doctor`'s
dispatch-hung verdict carry a ``last_beacon`` field ("hung at
megastep/t16_k8, phase=search_wave, wave=37"). Beacon-armed programs
key differently in the AOT compile cache (`beacon_signature` joins the
extra digest) and skip executable serialization — a callback closure
does not survive `serialize_executable`.

Module-top is JAX-free on purpose: `cli doctor` / `cli perf` import the
readers here beside a wedged chip. Only `emit_beacon` (called from
traced code) imports jax, lazily.
"""

import json
import logging
import os
import threading
import time
from pathlib import Path

logger = logging.getLogger(__name__)

DEVICE_STATS_KIND = "device_stats"
BEACON_KIND = "beacon"
BEACONS_FILENAME = "beacons.jsonl"

#: Leaf-depth histogram bins in the per-wave search stat-pack. Depths at
#: or past the last bin clip into it, so the shape is static regardless
#: of max_depth.
DEPTH_BINS = 16

#: Default wave-subsampling for search beacons: every wave still calls
#: the host callback when armed, but only every Nth writes a row.
DEFAULT_BEACON_EVERY = 8

DEVICE_STATS_ENV = "ALPHATRIANGLE_DEVICE_STATS"
BEACONS_ENV = "ALPHATRIANGLE_BEACONS"
BEACON_EVERY_ENV = "ALPHATRIANGLE_BEACON_EVERY"

# --- process-global enable state -------------------------------------------
# Engines consult these at CONSTRUCTION time (the flags shape compiled
# programs, so they join the AOT cache extra digests); setup_training_
# components / the serve bring-up set them from TelemetryConfig before
# any engine is built. Env overrides exist so smokes and a respawned
# supervised child can flip them without threading a config through.

_lock = threading.Lock()
_device_stats: "bool | None" = None
_beacons_armed: "bool | None" = None
_beacon_every: "int | None" = None
_beacon_ledger = None  # telemetry.ledger.MetricsLedger once attached
_current_program: "str | None" = None


def device_stats_enabled() -> bool:
    """Whether engines should compile stat-packs into their programs.

    Defaults OFF until `set_device_stats` runs (training/serve setup
    wires it from ``TelemetryConfig.DEVICE_STATS``); the env override
    ``ALPHATRIANGLE_DEVICE_STATS=1/0`` wins over both."""
    env = os.environ.get(DEVICE_STATS_ENV)
    if env is not None and env != "":
        return env != "0"
    return bool(_device_stats)


def set_device_stats(flag: bool) -> None:
    global _device_stats
    _device_stats = bool(flag)


def beacons_armed() -> bool:
    """Whether programs built NOW should embed progress beacons."""
    global _beacons_armed
    if _beacons_armed is None:
        with _lock:
            if _beacons_armed is None:
                _beacons_armed = os.environ.get(BEACONS_ENV, "") not in (
                    "",
                    "0",
                )
    return _beacons_armed


def arm_beacons(every: "int | None" = None) -> None:
    """Arm beacons for programs built after this call.

    Called by the dispatch watchdog's near-deadline warning and by the
    runner when `cli supervise` delivers a ``TELEMETRY__BEACONS``
    override on a dispatch-hung respawn. Programs already compiled keep
    running beacon-free (re-tracing them mid-flight would risk the very
    wedge this exists to diagnose); a respawn rebuilds everything armed.
    """
    global _beacons_armed, _beacon_every
    with _lock:
        _beacons_armed = True
        if every is not None and every > 0:
            _beacon_every = int(every)
    logger.warning(
        "progress beacons ARMED (every %d search waves): programs built "
        "from now on append phase rows to %s",
        beacon_every(),
        BEACONS_FILENAME,
    )


def disarm_beacons() -> None:
    """Tests/teardown: forget the armed flag AND the env-derived cache."""
    global _beacons_armed, _beacon_ledger
    with _lock:
        _beacons_armed = False
        _beacon_ledger = None


def reset_device_stats_state() -> None:
    """Tests: back to import-time defaults (env re-read on next query)."""
    global _device_stats, _beacons_armed, _beacon_every, _beacon_ledger
    global _current_program
    with _lock:
        _device_stats = None
        _beacons_armed = None
        _beacon_every = None
        _beacon_ledger = None
        _current_program = None


def beacon_every() -> int:
    global _beacon_every
    if _beacon_every is None:
        try:
            _beacon_every = max(
                1, int(os.environ.get(BEACON_EVERY_ENV, DEFAULT_BEACON_EVERY))
            )
        except ValueError:
            _beacon_every = DEFAULT_BEACON_EVERY
    return _beacon_every


def beacon_signature() -> str:
    """AOT cache `extra` fragment for programs built under the current
    beacon state: a beacon-armed executable embeds host callbacks, so it
    must never be confused with (or deserialized as) the clean one."""
    return f"|beacons{beacon_every()}" if beacons_armed() else ""


def device_stats_signature() -> str:
    """AOT cache `extra` fragment for the stat-pack flag (it changes the
    program's output pytree)."""
    return "|devstats1" if device_stats_enabled() else ""


def attach_beacon_run_dir(run_dir) -> None:
    """Point beacon rows at ``<run_dir>/beacons.jsonl`` (RunTelemetry
    ctor). Harmless when beacons never arm — the ledger writer is only
    touched from inside an armed program's callback."""
    global _beacon_ledger
    if run_dir is None:
        return
    from .ledger import MetricsLedger

    with _lock:
        _beacon_ledger = MetricsLedger(Path(run_dir) / BEACONS_FILENAME)


def note_dispatch(program: str) -> None:
    """Best-effort program attribution for beacon rows: the dispatching
    host site names the program about to launch; the (async) callbacks
    it triggers stamp that name on their rows. Single-writer training
    loops dispatch one program at a time, so the attribution is exact
    there; overlapped streams may mis-attribute a row to the newest
    dispatch — the phase/index remain authoritative."""
    global _current_program
    _current_program = program


def _write_beacon_row(phase: str, index: int) -> None:
    ledger = _beacon_ledger
    if ledger is None:
        return
    ledger.append(
        {
            "kind": BEACON_KIND,
            "program": _current_program,
            "phase": phase,
            "index": index,
            "t_mono": time.monotonic(),
            "time": time.time(),
            "pid": os.getpid(),
        }
    )


def emit_beacon(phase: str, index, every: int = 1) -> None:
    """Trace-time beacon site. A Python-level no-op unless beacons are
    armed when the program is TRACED — the unarmed hot path compiles to
    exactly the program it compiled to before this module existed.

    When armed, inserts a `jax.debug.callback` that appends one row per
    firing (host-side subsampled to every `every`-th index — inside a
    fori_loop/scan the callback runs unordered, so the traced index is
    the authoritative sequencing, not arrival order)."""
    if not beacons_armed():
        return
    import jax

    step = max(1, int(every))

    def _cb(idx) -> None:
        try:
            i = int(idx)
            if i % step:
                return
            _write_beacon_row(phase, i)
        except Exception:  # a beacon must never kill a dispatch
            logger.debug("beacon write failed (%s)", phase, exc_info=True)

    jax.debug.callback(_cb, index, ordered=False)


# --- JAX-free readers (doctor / perf path) ---------------------------------


def read_beacons(path) -> list[dict]:
    """All parseable beacon rows from a ``beacons.jsonl`` (torn-tail
    tolerant via the ledger reader; missing file -> empty list, the
    legacy-run contract)."""
    from .ledger import iter_jsonl_records

    return list(iter_jsonl_records(path, kinds={BEACON_KIND}))


def last_beacon(run_dir_or_path) -> "dict | None":
    """The newest beacon row of a run, or None (no file / never armed).

    This is what `wedge_report.json` and the dispatch-hung doctor
    verdict carry: at wedge time the file ends with the last phase the
    hung program (or its predecessor iteration) announced."""
    if run_dir_or_path is None:
        return None
    path = Path(run_dir_or_path)
    if path.is_dir():
        path = path / BEACONS_FILENAME
    rows = read_beacons(path)
    return rows[-1] if rows else None


def describe_beacon(row: "dict | None") -> "str | None":
    """One-line rendering for doctor/wedge output: ``megastep/t16_k8
    phase=search_wave index=37 (2.1s before report)``-style."""
    if not isinstance(row, dict):
        return None
    program = row.get("program") or "?"
    return (
        f"{program} phase={row.get('phase')} index={row.get('index')}"
    )


# --- host-side folds --------------------------------------------------------


def _finite(value) -> "float | None":
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    if value != value or value in (float("inf"), float("-inf")):
        return None
    return float(value)


def fold_search_stats(stats) -> "dict | None":
    """Fold a fetched search stat-pack (possibly (T,)-stacked by the
    rollout chunk's scan) into plain floats for the ledger record.

    Accepts host numpy arrays / scalars (post-`device_get`); never
    imports jax. Scalars fold as mean over the stacking axis except the
    excursion stats (`value_abs_max` folds as max); the depth histogram
    sums."""
    if not isinstance(stats, dict) or not stats:
        return None
    import numpy as np

    out: dict = {}
    for key, reduce_fn in (
        ("root_entropy", np.mean),
        ("root_concentration", np.mean),
        ("occupancy", np.mean),
        ("reuse_frac", np.mean),
        ("value_abs_max", np.max),
    ):
        if key in stats:
            try:
                out[key] = round(float(reduce_fn(np.asarray(stats[key]))), 6)
            except (TypeError, ValueError):
                continue
    if "depth_hist" in stats:
        try:
            hist = np.asarray(stats["depth_hist"], dtype=np.float64)
            if hist.ndim > 1:  # (T, BINS) stacked by the chunk scan
                hist = hist.sum(axis=tuple(range(hist.ndim - 1)))
            out["depth_hist"] = [round(float(v), 1) for v in hist.tolist()]
        except (TypeError, ValueError):
            pass
    return out or None


def merge_search_folds(folds: list) -> "dict | None":
    """Merge several already-folded search stat-packs (the serve loop
    accumulates one per wave between `tick()` windows) into one leg:
    scalars average, `value_abs_max` maxes, depth histograms sum."""
    rows = [f for f in folds if isinstance(f, dict) and f]
    if not rows:
        return None
    out: dict = {}
    for key in ("root_entropy", "root_concentration", "occupancy", "reuse_frac"):
        vals = [v for v in (_finite(r.get(key)) for r in rows) if v is not None]
        if vals:
            out[key] = round(sum(vals) / len(vals), 6)
    vmax = [
        v for v in (_finite(r.get("value_abs_max")) for r in rows) if v is not None
    ]
    if vmax:
        out["value_abs_max"] = round(max(vmax), 6)
    hists = [r["depth_hist"] for r in rows if isinstance(r.get("depth_hist"), list)]
    if hists:
        width = max(len(h) for h in hists)
        summed = [0.0] * width
        for h in hists:
            for i, v in enumerate(h):
                f = _finite(v)
                if f is not None:
                    summed[i] += f
        out["depth_hist"] = [round(v, 1) for v in summed]
    return out or None


def rollout_chunk_stats(endings, rewards) -> "dict | None":
    """Rollout-chunk stat leg from arrays the host ALREADY fetched
    (`play_chunk`'s one device_get): per-step-of-T episode terminations
    and reward extremes. Zero program change — pure host fold."""
    import numpy as np

    try:
        ends = np.asarray(endings)
        rew = np.asarray(rewards, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    if ends.ndim < 2 or rew.size == 0:
        return None
    terms = (ends != 0).sum(axis=tuple(range(1, ends.ndim)))
    return {
        "terminations_per_step": [int(v) for v in terms.tolist()],
        "reward_min": round(float(rew.min()), 6),
        "reward_max": round(float(rew.max()), 6),
    }


def device_stats_record(
    step: int,
    program: "str | None" = None,
    search: "dict | None" = None,
    rollout: "dict | None" = None,
    per: "dict | None" = None,
    learner: "dict | None" = None,
    serve: "dict | None" = None,
    now: "float | None" = None,
) -> "dict | None":
    """One ``kind:"device_stats"`` ledger line; None when every leg is
    empty (nothing worth a record)."""
    legs = {
        k: v
        for k, v in (
            ("search", search),
            ("rollout", rollout),
            ("per", per),
            ("learner", learner),
            ("serve", serve),
        )
        if v
    }
    if not legs:
        return None
    record = {
        "kind": DEVICE_STATS_KIND,
        "step": step,
        "time": time.time() if now is None else now,
        **legs,
    }
    if program:
        record["program"] = program
    return record


def summarize_device_stats(records: list) -> "dict | None":
    """Fold a run's ``device_stats`` records into `cli perf` summary
    fields (all ``ds_``-prefixed). None for legacy runs (no records),
    so pre-PR ledgers summarize exactly as before."""
    rows = [
        r
        for r in records
        if isinstance(r, dict) and r.get("kind") == DEVICE_STATS_KIND
    ]
    if not rows:
        return None

    def leg(name: str, key: str) -> list:
        out = []
        for r in rows:
            v = _finite((r.get(name) or {}).get(key))
            if v is not None:
                out.append(v)
        return out

    def _mean(vals: list) -> "float | None":
        return round(sum(vals) / len(vals), 6) if vals else None

    def _max(vals: list) -> "float | None":
        return round(max(vals), 6) if vals else None

    def _min(vals: list) -> "float | None":
        return round(min(vals), 6) if vals else None

    return {
        "ds_records": len(rows),
        "ds_root_entropy": _mean(leg("search", "root_entropy")),
        "ds_root_entropy_min": _min(leg("search", "root_entropy")),
        "ds_root_concentration": _mean(leg("search", "root_concentration")),
        "ds_value_abs_max": _max(leg("search", "value_abs_max")),
        "ds_tree_occupancy": _mean(leg("search", "occupancy")),
        "ds_tree_occupancy_max": _max(leg("search", "occupancy")),
        "ds_reuse_frac": _mean(leg("search", "reuse_frac")),
        "ds_reward_min": _min(leg("rollout", "reward_min")),
        "ds_reward_max": _max(leg("rollout", "reward_max")),
        "ds_priority_skew": _max(leg("per", "priority_skew")),
        "ds_is_weight_min": _min(leg("per", "is_weight_min")),
        "ds_grad_norm_max": _max(leg("learner", "grad_norm_max")),
        "ds_update_norm_max": _max(leg("learner", "update_norm_max")),
        "ds_serve_root_entropy": _mean(leg("serve", "root_entropy")),
    }


def device_stats_json(records: list) -> "dict | None":
    """The `bench.py extra.device_stats` block: the perf-summary fold
    plus the newest raw record (depth histogram included) — enough for
    a BENCH snapshot to show what the searches actually did."""
    summary = summarize_device_stats(records)
    if summary is None:
        return None
    newest = next(
        (
            r
            for r in reversed(records)
            if isinstance(r, dict) and r.get("kind") == DEVICE_STATS_KIND
        ),
        None,
    )
    if newest is not None:
        # deep-copy through json so callers can mutate freely
        summary["last_record"] = json.loads(json.dumps(newest, default=str))
    return summary
