"""Durable per-run metrics ledger: `metrics.jsonl` in the run dir.

The StatsCollector's series live in memory and die with the process;
TensorBoard event files need TensorBoard to read back; the five
`BENCH_r0*.json` snapshots are the entire cross-run record. This module
is the persistence tier under all of them: every processed metric batch
(`kind: "tick"`), every derived utilization record (`kind: "util"`,
telemetry/perf.py — including per-device memory in-use/peak fields) and
every memory-attribution record (`kind: "memory"`, telemetry/memory.py
— train-state tree bytes, replay-ring bytes, per-program AOT
memory_analysis) is appended as one JSON line to
`runs/<run>/metrics.jsonl` — crash-safely, rotation-bounded, and
readable by processes that never import JAX (`cli perf`, `cli compare`,
`cli mem`, `cli watch`, a rsync'd laptop shell).

Crash-safety model (KataGo/Podracer-style continuous accounting needs
the record to survive the run dying at ANY instant):

- each `append` opens the file in append mode, writes ONE complete
  line, flushes, and closes — there is no buffered state to lose and
  no partially-interleaved writes from the single writer;
- a crash mid-`write` leaves at most one torn final line, which every
  reader here tolerates (skips) and the next append simply writes
  after — the torn line stays as a scar, the ledger stays parseable;
- rotation renames `metrics.jsonl` -> `.1` -> `.2` ... atomically
  BETWEEN appends, so no record spans files.

Readers (`read_ledger`, `iter_ledger_records`) walk rotations oldest
first and skip unparseable lines instead of raising: a live writer, a
torn tail, or a junk byte must never take down `cli watch`/`perf`.
"""

import json
import logging
import os
import time
from pathlib import Path

logger = logging.getLogger(__name__)

METRICS_FILENAME = "metrics.jsonl"
PROM_FILENAME = "metrics.prom"

# Rotation defaults: ~16 MiB per file, 2 rotated generations kept. A
# tick is a few hundred bytes, so this bounds the run dir at ~50 MiB of
# ledger while still holding days of 1 Hz ticks.
DEFAULT_MAX_BYTES = 16 * 1024 * 1024
DEFAULT_KEEP = 2


class MetricsLedger:
    """Append-only JSONL writer with size-based rotation.

    Stateless between appends (open/write/flush/close per record): the
    single-writer training loop appends a few records per second at
    most, and statelessness is what makes the crash story trivial —
    there is never an open handle holding unflushed records.
    """

    def __init__(
        self,
        path: Path | str,
        max_bytes: int = DEFAULT_MAX_BYTES,
        keep: int = DEFAULT_KEEP,
        fsync: bool = False,
    ) -> None:
        self.path = Path(path)
        self.max_bytes = max_bytes
        self.keep = keep
        self.fsync = fsync
        # First append of this process checks whether a previous
        # process died mid-write and left a torn (newline-less) tail;
        # if so the tail is terminated first, so OUR first record does
        # not glue onto it and vanish with it.
        self._tail_checked = False

    def append(self, record: dict) -> bool:
        """Append one record as a complete JSON line; True on success.

        Failures are logged and swallowed — the ledger is observability,
        never a reason to kill a training run.
        """
        try:
            line = json.dumps(record, default=str) + "\n"
        except (TypeError, ValueError):
            logger.exception("ledger record not serializable; dropped")
            return False
        try:
            self._maybe_rotate(len(line))
            self.path.parent.mkdir(parents=True, exist_ok=True)
            if not self._tail_checked:
                self._tail_checked = True
                if self._tail_is_torn():
                    line = "\n" + line
            with self.path.open("a") as f:
                f.write(line)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            return True
        except OSError:
            logger.exception("ledger append to %s failed", self.path)
            return False

    def _tail_is_torn(self) -> bool:
        """True when the file ends without a newline (a prior process
        died mid-write). Checked once per process, not per append: a
        single writer always leaves its own appends terminated."""
        try:
            with self.path.open("rb") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() == 0:
                    return False
                f.seek(-1, os.SEEK_END)
                return f.read(1) != b"\n"
        except OSError:
            return False

    def _maybe_rotate(self, incoming: int) -> None:
        """Shift `metrics.jsonl` -> `.1` -> ... -> `.keep` when the next
        append would cross `max_bytes`. Renames only — no record is
        rewritten, so a crash between renames loses nothing."""
        if self.max_bytes <= 0:
            return
        try:
            size = self.path.stat().st_size
        except OSError:
            return
        if size + incoming <= self.max_bytes:
            return
        if self.keep <= 0:
            self.path.unlink(missing_ok=True)
            return
        oldest = self.path.with_name(self.path.name + f".{self.keep}")
        oldest.unlink(missing_ok=True)
        for i in range(self.keep - 1, 0, -1):
            src = self.path.with_name(self.path.name + f".{i}")
            if src.exists():
                src.replace(self.path.with_name(self.path.name + f".{i + 1}"))
        self.path.replace(self.path.with_name(self.path.name + ".1"))

    def close(self) -> None:
        """No-op (no persistent handle); kept for lifecycle symmetry."""


def ledger_paths(path: Path | str) -> list[Path]:
    """Ledger files for `path`, oldest rotation first, live file last."""
    path = Path(path)
    rotated = []
    i = 1
    while True:
        p = path.with_name(path.name + f".{i}")
        if not p.exists():
            break
        rotated.append(p)
        i += 1
    out = list(reversed(rotated))
    if path.exists():
        out.append(path)
    return out


def iter_jsonl_records(path: Path | str, kinds: "set[str] | None" = None):
    """Yield parsed dict records from ONE JSONL file, skipping torn/junk
    lines. The single tolerant reader under every crash-safe artifact
    here: the metrics ledger walks it per rotation, and the dispatch
    flight ring (telemetry/flight.py) reads through it instead of
    duplicating the torn-tail handling."""
    try:
        with Path(path).open("r", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn write / junk byte: skip, never raise
                if not isinstance(rec, dict):
                    continue
                if kinds is not None and rec.get("kind") not in kinds:
                    continue
                yield rec
    except OSError:
        return


def iter_ledger_records(path: Path | str, kinds: "set[str] | None" = None):
    """Yield parsed records across rotations, skipping torn/junk lines."""
    for p in ledger_paths(path):
        yield from iter_jsonl_records(p, kinds=kinds)


def read_ledger(path: Path | str, kinds: "set[str] | None" = None) -> list[dict]:
    """All parseable records (optionally filtered by `kind`), in order."""
    return list(iter_ledger_records(path, kinds=kinds))


def resolve_ledger_path(target: Path | str) -> "Path | None":
    """Map a run dir / ledger file / arbitrary path to its ledger file."""
    target = Path(target)
    if target.is_dir():
        target = target / METRICS_FILENAME
    return target if target.exists() else None


# --- Prometheus textfile export -----------------------------------------

_PROM_HELP = {
    "learner_steps_per_sec": "Learner SGD steps per second (tick window)",
    "moves_per_sec": "Self-play experiences produced per second",
    "games_per_hour": "Self-play episodes completed per hour",
    "sims_per_sec": "MCTS simulations per second",
    "step_time_ms": "Mean learner step time over the tick window, ms",
    "tflops_per_sec": "Achieved model TFLOP/s (learner + self-play)",
    "mfu": "Model FLOP/s utilization: achieved / peak bf16",
    "buffer_fill": "Replay buffer occupancy fraction",
    "buffer_size": "Replay buffer size, experiences",
    "transfer_h2d_ms": "Host->device staging time this tick, ms",
    "transfer_d2h_ms": "Device->host fetch time this tick, ms",
    "compile_cache_hit_rate": "AOT executable cache hit rate so far",
    "mem_bytes_in_use": "Device memory in use across local devices, bytes",
    "mem_peak_bytes_in_use": "Run-wide peak device memory in use, bytes",
    "mem_bytes_limit": "Device memory limit across local devices, bytes",
    "mem_utilization": "Device memory in use / limit",
    "step": "Learner global step",
    # Policy-service SLO gauges (serving/service.py serve ticks).
    "serve_sessions": "Live serving sessions occupying slots",
    "serve_queue_depth": "Move requests waiting for the next dispatch",
    "serve_requests_per_sec": "Served move requests per second",
    "serve_move_latency_ms_p50": "Per-move serve latency p50 this window, ms",
    "serve_move_latency_ms_p95": "Per-move serve latency p95 this window, ms",
    "serve_queue_wait_ms_p95": "Queue wait p95 this window, ms",
    "serve_batch_fill": "Real sessions per dispatch / slot count",
    "serve_weight_reloads": "Hot weight reloads served so far",
    # Bucket-ladder micro-batcher gauges (serving/buckets.py).
    "serve_bucket": "Current serve-shape ladder rung (slot count)",
    "serve_fill": "Latest dispatch wave fill (drives rung walking)",
    "serve_rung_switches": "Ladder rung switches since startup",
    # Device-telemetry plane gauges (telemetry/device_stats.py): the
    # loop mirrors the latest stat-pack fold onto its util records.
    "root_visit_entropy": "Mean MCTS root visit entropy, nats (stat-pack)",
    "tree_occupancy": "Mean search tree slot occupancy fraction (stat-pack)",
    "beacons_armed": "1 when progress beacons are armed in this process",
    # Roofline attribution plane (telemetry/roofline.py).
    "chip_idle_fraction": "Fraction of the tick window with no dispatch in flight",
}


def write_prometheus_textfile(
    path: Path | str, record: dict, run_name: str = ""
) -> bool:
    """Render one utilization record as Prometheus textfile gauges.

    Atomic (tmp + replace) so a scraper never reads a half-written
    exposition; numeric fields only, prefixed `alphatriangle_`.
    """
    path = Path(path)
    label = f'{{run="{run_name}"}}' if run_name else ""
    lines = []
    for key, help_text in _PROM_HELP.items():
        value = record.get(key)
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        lines.append(f"# HELP alphatriangle_{key} {help_text}")
        lines.append(f"# TYPE alphatriangle_{key} gauge")
        lines.append(f"alphatriangle_{key}{label} {value}")
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text("\n".join(lines) + "\n")
        tmp.replace(path)
        return True
    except OSError:
        logger.exception("prometheus textfile write to %s failed", path)
        return False


def tick_record(step: int, means: dict, now: "float | None" = None) -> dict:
    """The ledger line for one processed metric batch."""
    return {
        "kind": "tick",
        "step": step,
        "time": time.time() if now is None else now,
        "means": means,
    }
