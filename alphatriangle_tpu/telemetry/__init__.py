"""Run telemetry: span tracing, health/watchdog monitoring, anomaly
detection (docs/OBSERVABILITY.md).

Three cooperating pieces, all host-side and off the device dispatch
path, bundled behind the `RunTelemetry` facade the training loop talks
to:

- `tracer.SpanTracer` — thread-aware begin/end spans (rollout chunk,
  sample, learner dispatch/train, weight sync, checkpoint, fold),
  ring-buffered and exported as Chrome/Perfetto `trace.json`.
- `health.HealthMonitor` + `health.Watchdog` — a `health.json`
  heartbeat updated each loop tick, and a stall watchdog that dumps all
  thread stacks and flushes the span buffer when nothing progresses for
  a deadline.
- `anomaly.AnomalyDetector` — streaming EWMA/z-score checks over
  per-step training metrics (loss spikes, grad-norm explosions,
  non-finite values, policy-entropy collapse) escalated to `Anomaly/*`
  metrics and warnings with recent-window context; plus a
  monotonic-growth memory leak detector (`Anomaly/memory_growth`) fed
  per utilization tick.
- `memory` — per-program HBM attribution (AOT `memory_analysis()`
  capture via compile_cache), train-state/replay-ring byte accounting,
  and the static pre-flight budget behind `cli fit`/`cli mem`
  (docs/OBSERVABILITY.md "Memory").
- `roofline` — per-program `cost_analysis()` capture (FLOPs, bytes
  accessed), the arithmetic-intensity roofline model behind
  `cli roofline`, and chip-idle gap forensics over the flight ring
  (docs/OBSERVABILITY.md "Roofline & gap attribution").

Podracer-style stacks (arXiv:2104.06272) treat this visibility as a
prerequisite for scaling an async producer/learner loop; the repo's own
round-5 "10.3h with zero healthy windows" (BASELINE.md) is the local
proof.
"""

import logging
import time
from pathlib import Path

from ..config.telemetry_config import TelemetryConfig
from .anomaly import Anomaly, AnomalyDetector
from .health import (
    HealthMonitor,
    Watchdog,
    dump_thread_stacks,
    health_verdict,
    read_health,
)
from .flight import (
    FLIGHT_FILENAME,
    WEDGE_EXIT_CODE,
    WEDGE_REPORT_FILENAME,
    DispatchWatchdog,
    FlightRecorder,
    classify_run,
    flight_span,
    read_flight,
    summarize_flight,
)
from .ledger import (
    METRICS_FILENAME,
    PROM_FILENAME,
    MetricsLedger,
    read_ledger,
    tick_record,
    write_prometheus_textfile,
)
from .memory import (
    attribution_rows,
    compose_budget,
    estimate_fit,
    fit_verdict,
    program_memory_record,
    replay_ring_bytes,
    replay_ring_record,
    summarize_device_memory,
    train_state_record,
    tree_bytes,
)
from .merge import MERGED_TRACE_FILENAME, merge_fleet_trace
from .perf import UtilizationMeter, summarize_utilization
from .roofline import (
    attribute_gaps,
    cost_flops_by_family,
    peak_hbm_gbps_info,
    program_cost_record,
    roofline_rows,
    summarize_roofline,
)
from .slo import (
    FLEET_PROM_FILENAME,
    SLO_EXIT_CODES,
    evaluate_slos,
    slo_status_line,
    write_fleet_prometheus,
)
from .tracectx import TraceContext, TRACEPARENT_ENV
from .tracer import SpanTracer, summarize_trace_file
from . import tracectx

logger = logging.getLogger(__name__)

__all__ = [
    "Anomaly",
    "AnomalyDetector",
    "DispatchWatchdog",
    "FlightRecorder",
    "FLEET_PROM_FILENAME",
    "HealthMonitor",
    "MERGED_TRACE_FILENAME",
    "MetricsLedger",
    "SLO_EXIT_CODES",
    "RunTelemetry",
    "SpanTracer",
    "TelemetryConfig",
    "TraceContext",
    "TRACEPARENT_ENV",
    "tracectx",
    "UtilizationMeter",
    "Watchdog",
    "attribute_gaps",
    "attribution_rows",
    "classify_run",
    "cost_flops_by_family",
    "peak_hbm_gbps_info",
    "program_cost_record",
    "roofline_rows",
    "summarize_roofline",
    "flight_span",
    "read_flight",
    "summarize_flight",
    "compose_budget",
    "dump_thread_stacks",
    "estimate_fit",
    "evaluate_slos",
    "fit_verdict",
    "merge_fleet_trace",
    "slo_status_line",
    "write_fleet_prometheus",
    "health_verdict",
    "program_memory_record",
    "read_health",
    "read_ledger",
    "replay_ring_bytes",
    "replay_ring_record",
    "summarize_device_memory",
    "summarize_trace_file",
    "summarize_utilization",
    "train_state_record",
    "tree_bytes",
]

TRACE_FILENAME = "trace.json"
HEALTH_FILENAME = "health.json"
STACKS_FILENAME = "stall_stacks.txt"


class RunTelemetry:
    """One run's telemetry: tracer + heartbeat + watchdog + anomalies.

    Constructed by `setup_training_components`, driven by the training
    loop: `start()` when the loop begins, `on_rollout`/`on_learner_step`
    as work lands (O(1), any thread), `on_tick` once per loop iteration
    (the only place heartbeat IO happens), `close()` in the loop's
    finally block. With `config.ENABLED` false every hook is a cheap
    no-op and no files are written.
    """

    def __init__(
        self,
        config: TelemetryConfig | None = None,
        run_dir: Path | str = ".",
        stats=None,
        run_name: str = "",
        clock=time.monotonic,
        perf: UtilizationMeter | None = None,
    ) -> None:
        self.config = config or TelemetryConfig()
        self.run_dir = Path(run_dir)
        self.stats = stats
        self.run_name = run_name
        enabled = self.config.ENABLED
        self.tracer = SpanTracer(
            capacity=self.config.SPAN_BUFFER_SIZE, enabled=enabled
        )
        self.health = HealthMonitor(
            self.run_dir / HEALTH_FILENAME,
            deadline_s=self.config.WATCHDOG_DEADLINE_S,
            run_name=run_name,
            clock=clock,
        )
        self.anomaly = AnomalyDetector(
            alpha=self.config.ANOMALY_EWMA_ALPHA,
            z_threshold=self.config.ANOMALY_Z_THRESHOLD,
            warmup=self.config.ANOMALY_WARMUP_STEPS,
            window=self.config.ANOMALY_WINDOW,
            entropy_floor=self.config.ENTROPY_COLLAPSE_THRESHOLD,
            memory_growth_ticks=self.config.MEMORY_GROWTH_TICKS,
            memory_growth_fraction=self.config.MEMORY_GROWTH_MIN_FRACTION,
        )
        # Durable metrics ledger + live utilization accounting (the
        # persistence-and-analysis tier under the span/heartbeat
        # surfaces; docs/OBSERVABILITY.md "Ledger").
        self.perf = perf
        self.ledger: MetricsLedger | None = None
        if enabled and self.config.LEDGER_ENABLED:
            self.ledger = MetricsLedger(
                self.run_dir / METRICS_FILENAME,
                max_bytes=self.config.LEDGER_MAX_BYTES,
                keep=self.config.LEDGER_KEEP_ROTATIONS,
                fsync=self.config.LEDGER_FSYNC,
            )
        if perf is not None:
            self.health.set_device_info(
                perf.device_kind, perf.peak_tflops, perf.peak_source
            )
        self.watchdog: Watchdog | None = None
        if enabled and self.config.WATCHDOG_ENABLED:
            self.watchdog = Watchdog(
                self.health,
                deadline_s=self.config.WATCHDOG_DEADLINE_S,
                poll_s=self.config.WATCHDOG_POLL_S,
                on_stall=self._on_stall,
                clock=clock,
            )
        # Dispatch flight recorder + per-dispatch deadline watchdog
        # (telemetry/flight.py): the black box that survives a dead
        # process. Components pick the recorder up as a `flight`
        # attribute (training/setup.py, serving/service.py).
        self.flight: FlightRecorder | None = None
        self.dispatch_watchdog: DispatchWatchdog | None = None
        if enabled and self.config.FLIGHT_ENABLED:
            if self.config.DISPATCH_WATCHDOG_ENABLED:
                self.dispatch_watchdog = DispatchWatchdog(
                    self.run_dir,
                    poll_s=self.config.DISPATCH_WATCHDOG_POLL_S,
                    on_wedge=self._on_wedge,
                    exit_on_wedge=self.config.DISPATCH_EXIT_ON_WEDGE,
                    clock=clock,
                    warn_fraction=self.config.DISPATCH_WARN_FRACTION,
                    on_warn=self._on_dispatch_warn,
                )
            # A parent (supervisor attempt / fleet spawn) may have
            # handed this process a trace context via the traceparent
            # env seam; adopting it as the ring's base trace links
            # every dispatch here back to the spawning attempt.
            parent_ctx = tracectx.from_env()
            self.flight = FlightRecorder(
                self.run_dir / FLIGHT_FILENAME,
                max_bytes=self.config.FLIGHT_MAX_BYTES,
                keep=self.config.FLIGHT_KEEP_ROTATIONS,
                deadline_factor=self.config.DISPATCH_DEADLINE_FACTOR,
                min_deadline_s=self.config.DISPATCH_MIN_DEADLINE_S,
                first_deadline_s=self.config.DISPATCH_FIRST_DEADLINE_S,
                watchdog=self.dispatch_watchdog,
                base_trace=(
                    parent_ctx.fields() if parent_ctx is not None else None
                ),
            )
        # Device-telemetry plane (telemetry/device_stats.py): point the
        # beacon writer at this run's beacons.jsonl. No file is created
        # until an armed program's callback actually fires.
        if enabled:
            try:
                from .device_stats import attach_beacon_run_dir

                attach_beacon_run_dir(self.run_dir)
            except Exception:
                logger.debug("beacon run-dir attach failed", exc_info=True)
        self._step = 0
        self._memory_seen: set = set()
        self._cost_seen: set = set()
        self._last_write_mono = None
        self._last_written_step: int | None = None
        self._clock = clock
        self._closed = False

    @property
    def enabled(self) -> bool:
        return self.config.ENABLED

    # --- loop lifecycle ----------------------------------------------

    def start(self) -> None:
        if self.watchdog is not None:
            self.watchdog.start()
        if self.dispatch_watchdog is not None:
            self.dispatch_watchdog.start()

    def close(self, step: int | None = None) -> None:
        """Stop the watchdog, write the final heartbeat + trace export."""
        if self._closed:
            return
        self._closed = True
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.dispatch_watchdog is not None:
            self.dispatch_watchdog.stop()
        if self.flight is not None:
            self.flight.close()
        if not self.enabled:
            return
        if step is not None:
            self._step = step
        # Programs that compiled after the last util tick still land in
        # the ledger's attribution record.
        self._ledger_compile_memory()
        self.health.write()
        n = self.tracer.export(self.run_dir / TRACE_FILENAME)
        logger.info(
            "Telemetry: %d span(s) -> %s, heartbeat -> %s",
            n,
            self.run_dir / TRACE_FILENAME,
            self.health.path,
        )

    # --- beats (any thread, O(1) — no IO) ----------------------------

    def on_rollout(self, experiences: int = 0, episodes: int = 0) -> None:
        if self.enabled:
            self.health.note_rollout(experiences, episodes)

    def on_learner_step(self, step: int, metrics: dict) -> list[Anomaly]:
        """Record learner progress and screen this step's metrics.

        `metrics` uses the stats-pipeline names (`Loss/total_loss`,
        `Loss/Grad_Norm`, `Loss/Entropy`, ...). Returns the anomalies
        (already escalated to `Anomaly/*` metrics + warnings).
        """
        self._step = step
        if not self.enabled:
            return []
        self.health.note_learner_step(step)
        if not self.config.ANOMALY_ENABLED:
            return []
        anomalies = []
        for name, value in metrics.items():
            try:
                value = float(value)
            except (TypeError, ValueError):
                continue
            anomalies.extend(self.anomaly.observe(name, value, step))
        for a in anomalies:
            logger.warning("Training anomaly: %s", a.describe())
            if self.stats is not None:
                self.stats.log_scalar(f"Anomaly/{a.kind}", 1.0, step)
        return anomalies

    # --- metrics ledger (durable per-run timeseries) -------------------

    def record_metrics(self, step: int, means: dict) -> None:
        """Ledger one processed metric batch (the StatsCollector's tick
        sink — wired in setup so EVERY flush lands, including the final
        force flush and the collector's own close-time flush)."""
        if self.ledger is not None and means:
            self.ledger.append(tick_record(step, means))

    def record_device_stats(
        self, step: int, program: "str | None" = None, **legs
    ) -> "dict | None":
        """Ledger one ``kind:"device_stats"`` record from the legs the
        host just folded out of the one per-iteration fetch (search /
        rollout / per / learner / serve — telemetry/device_stats.py),
        and screen the search leg for device-side anomalies (value
        explosion, root-entropy collapse, occupancy saturation).
        Returns the record, or None when every leg was empty."""
        if not self.enabled:
            return None
        from .device_stats import device_stats_record

        record = device_stats_record(step, program=program, **legs)
        if record is None:
            return None
        if self.ledger is not None:
            self.ledger.append(record)
        search_leg = record.get("search") or record.get("serve")
        if self.config.ANOMALY_ENABLED and search_leg:
            for a in self.anomaly.observe_search(search_leg, step):
                logger.warning("Training anomaly: %s", a.describe())
                if self.stats is not None:
                    self.stats.log_scalar(f"Anomaly/{a.kind}", 1.0, step)
        return record

    def record_memory(self, record: "dict | None") -> None:
        """Ledger one static memory-attribution record (train-state
        tree bytes, replay-ring bytes, program memory_analysis —
        telemetry/memory.py; `cli mem` renders these)."""
        if self.ledger is not None and record:
            self.ledger.append(record)

    def _ledger_compile_memory(self) -> None:
        """Append program memory records the compile cache has captured
        but this run's ledger hasn't seen yet (programs compile lazily
        on first dispatch, so this runs every util tick and at close;
        the seen-set is per run — several runs in one process each get
        the full attribution)."""
        if self.ledger is None:
            return
        try:
            from ..compile_cache import get_compile_cache

            cache = get_compile_cache()
            for record in cache.memory_summary():
                rid = (record.get("program"), record.get("key"))
                if rid in self._memory_seen:
                    continue
                self._memory_seen.add(rid)
                self.ledger.append(record)
            # Same drain for compiler cost records (`kind:"cost"`,
            # telemetry/roofline.py): `cli roofline` joins these against
            # flight-seal walls without re-touching the compile cache.
            for record in cache.cost_summary():
                rid = (record.get("program"), record.get("key"))
                if rid in self._cost_seen:
                    continue
                self._cost_seen.add(rid)
                self.ledger.append(record)
        except Exception:  # accounting must never hurt the loop
            pass

    def on_util_tick(self, step: int, **counters) -> "dict | None":
        """Derive + persist one utilization record from the loop's
        cumulative counters (see UtilizationMeter.tick for the keys).
        Returns the record (tests, callers wanting the live numbers).
        """
        if not self.enabled or self.perf is None:
            return None
        if "compile_hits" not in counters:
            try:
                # Lazy: keeps this package importable without pulling
                # jax into heartbeat/ledger READER processes.
                from ..compile_cache import get_compile_cache

                cc = get_compile_cache().stats()
                counters["compile_hits"] = cc.get("hits", 0)
                counters["compile_misses"] = cc.get("misses", 0)
            except Exception:  # never let accounting hurt the loop
                pass
        if "device_memory" not in counters:
            try:
                # The writer side runs beside JAX by definition; the
                # lazy import keeps reader processes JAX-free.
                from .health import device_memory_stats

                counters["device_memory"] = device_memory_stats()
            except Exception:
                pass
        self._ledger_compile_memory()
        record = self.perf.tick(step, **counters)
        if record is None:
            return None
        if self.ledger is not None:
            self.ledger.append(record)
        self.health.note_utilization(record)
        in_use = record.get("mem_bytes_in_use")
        if self.config.ANOMALY_ENABLED and isinstance(in_use, (int, float)):
            for a in self.anomaly.observe_memory(in_use, step):
                logger.warning("Training anomaly: %s", a.describe())
                if self.stats is not None:
                    self.stats.log_scalar(f"Anomaly/{a.kind}", 1.0, step)
        if self.config.PROMETHEUS_TEXTFILE:
            write_prometheus_textfile(
                self.run_dir / PROM_FILENAME, record, self.run_name
            )
        return record

    # --- per-iteration tick (the only heartbeat IO site) --------------

    def on_tick(self, step: int, buffer_size: int = 0) -> None:
        if not self.enabled:
            return
        self._step = step
        self.health.note_buffer(buffer_size)
        now = self._clock()
        due = (
            self._last_write_mono is None
            or step != self._last_written_step
            or now - self._last_write_mono
            >= self.config.HEALTH_WRITE_INTERVAL_S
        )
        if due:
            self._last_write_mono = now
            self._last_written_step = step
            self.health.write()

    # --- stall reaction ----------------------------------------------

    def _on_stall(self, age_s: float) -> None:
        """Watchdog hook: make the stall a diagnosable artifact."""
        dump_thread_stacks(self.run_dir / STACKS_FILENAME)
        self.tracer.instant("watchdog_stall", age_s=round(age_s, 1))
        if self.stats is not None:
            # Lands in TensorBoard on the next tick IF the loop ever
            # ticks again; health.json carries the flag regardless.
            self.stats.log_scalar("Health/stall", age_s, self._step)
        if self.config.FLUSH_TRACE_ON_STALL:
            self.tracer.export(self.run_dir / TRACE_FILENAME)
        self.health.write()
        logger.warning(
            "Watchdog: thread stacks -> %s, span trace -> %s",
            self.run_dir / STACKS_FILENAME,
            self.run_dir / TRACE_FILENAME,
        )

    def _on_dispatch_warn(self, info: dict) -> None:
        """Near-deadline hook (DispatchWatchdog.warn_fraction): a
        dispatch is running long — arm progress beacons NOW, so every
        program built from here on (a supervised respawn rebuilds them
        all) phases itself into beacons.jsonl. If this dispatch
        recovers, the arming cost is a cache re-key; if it wedges, the
        respawn's programs carry the forensics the first one lacked."""
        self.tracer.instant(
            "dispatch_warn",
            program=info.get("program"),
            elapsed_s=info.get("elapsed_s"),
        )
        try:
            from .device_stats import arm_beacons, beacons_armed

            if not beacons_armed():
                arm_beacons(self.config.BEACON_EVERY_N_WAVES)
        except Exception:
            logger.exception("beacon arming on dispatch warn failed")

    def _on_wedge(self, info: dict) -> None:
        """Dispatch-watchdog hook (runs BEFORE wedge_report.json lands
        and any exit): the timeline INTO the wedge must be on disk."""
        self.tracer.instant(
            "dispatch_wedge",
            program=info.get("program"),
            elapsed_s=info.get("elapsed_s"),
        )
        if self.config.FLUSH_TRACE_ON_STALL:
            self.tracer.export(self.run_dir / TRACE_FILENAME)
        # No heartbeat write here: `health.write` snapshots device
        # memory, and touching a wedged device could hang the watchdog
        # thread before the wedge report lands.
        self.health.set_stalled(True)
