"""Host-side span tracer: begin/end spans -> Chrome/Perfetto trace.json.

`PhaseTimers` (profiling.py) answers "how much time did phase X take
over the whole run" — a lossy mean that cannot say *where* a specific
stall happened. The tracer keeps the individual spans: every rollout
chunk, sample, learner dispatch/finish, weight sync and checkpoint is
recorded with its real wall-clock begin/end and thread id, ring-buffered
in memory (O(1) append under a lock, no IO on the hot path) and exported
as Chrome trace events into the run dir. Wall-clock timestamps line up
with the `jax.profiler` xplane traces written under `--profile`, so the
host timeline and the device timeline can be read side by side.

Load `trace.json` in chrome://tracing or https://ui.perfetto.dev, or
summarize it in-terminal with `alphatriangle-tpu trace <run>`.
"""

import json
import logging
import os
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from pathlib import Path

logger = logging.getLogger(__name__)

# A span record: (name, begin_ns, duration_ns, thread_id, thread_name,
# args-or-None). `kind` "X" (complete span) or "i" (instant event,
# duration 0) per the Chrome trace event format.
_COMPLETE = "X"
_INSTANT = "i"


class SpanTracer:
    """Thread-aware ring buffer of named wall-clock spans.

    Ingestion is a timestamp read plus one deque append under a lock —
    safe from any thread (rollout producers, the learner/consumer, the
    watchdog) and cheap enough to run always-on. The ring bounds memory:
    a multi-day run keeps the most recent `capacity` spans, which is
    exactly the window that matters when diagnosing where it stalled.
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max(1, capacity))
        self.recorded = 0  # total ever recorded (ring may have evicted)

    # --- ingestion (any thread, O(1)) ---------------------------------

    @contextmanager
    def span(self, name: str, **args):
        """Record one complete span around the with-body."""
        if not self.enabled:
            yield
            return
        t0 = time.time_ns()
        try:
            yield
        finally:
            dur = time.time_ns() - t0
            thread = threading.current_thread()
            with self._lock:
                self._spans.append(
                    (_COMPLETE, name, t0, dur, thread.ident, thread.name,
                     args or None)
                )
                self.recorded += 1

    def complete(
        self, name: str, begin_ns: int, end_ns: int, **args
    ) -> None:
        """Record a complete span from explicit wall timestamps — for
        spans whose begin was captured earlier than the code that
        finishes them (e.g. a serve replica records the whole episode
        span at finish, begin captured at request arrival). Duration is
        clamped non-negative so a torn clock can't corrupt the trace."""
        if not self.enabled:
            return
        thread = threading.current_thread()
        with self._lock:
            self._spans.append(
                (_COMPLETE, name, int(begin_ns),
                 max(0, int(end_ns) - int(begin_ns)), thread.ident,
                 thread.name, args or None)
            )
            self.recorded += 1

    def instant(self, name: str, **args) -> None:
        """Record a zero-duration marker (e.g. a watchdog stall)."""
        if not self.enabled:
            return
        thread = threading.current_thread()
        with self._lock:
            self._spans.append(
                (_INSTANT, name, time.time_ns(), 0, thread.ident,
                 thread.name, args or None)
            )
            self.recorded += 1

    # --- export / summary ---------------------------------------------

    def _snapshot(self) -> list:
        with self._lock:
            return list(self._spans)

    def export(self, path: Path) -> int:
        """Write the buffered spans as a Chrome trace; returns the event
        count. Atomic (tmp + rename) so a reader never sees a torn file;
        IO failures are logged, never raised (observability is not
        allowed to kill a run)."""
        spans = self._snapshot()
        pid = os.getpid()
        events = []
        thread_names: dict[int, str] = {}
        for kind, name, t0_ns, dur_ns, tid, tname, args in spans:
            thread_names.setdefault(tid, tname)
            ev = {
                "name": name,
                "ph": kind,
                "ts": t0_ns // 1000,  # Chrome traces use microseconds
                "pid": pid,
                "tid": tid,
                "cat": "host",
            }
            if kind == _COMPLETE:
                ev["dur"] = dur_ns // 1000
            else:
                ev["s"] = "g"  # global-scope instant
            if args:
                ev["args"] = args
            events.append(ev)
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
            for tid, tname in sorted(thread_names.items())
        ]
        payload = {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"recorded": self.recorded, "exported": len(events)},
        }
        try:
            path = Path(path)
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(payload))
            tmp.replace(path)
        except OSError:
            logger.exception("span trace export to %s failed", path)
            return 0
        if self.recorded > len(spans):
            logger.info(
                "span trace: ring kept the newest %d of %d spans.",
                len(spans),
                self.recorded,
            )
        return len(events)

    def summary(self) -> dict[str, dict[str, float]]:
        """Per-name aggregate of the buffered spans (count/total/mean/max)."""
        total_ns: dict[str, int] = defaultdict(int)
        max_ns: dict[str, int] = defaultdict(int)
        count: dict[str, int] = defaultdict(int)
        for kind, name, _t0, dur_ns, _tid, _tname, _args in self._snapshot():
            if kind != _COMPLETE:
                continue
            total_ns[name] += dur_ns
            max_ns[name] = max(max_ns[name], dur_ns)
            count[name] += 1
        return {
            name: {
                "count": count[name],
                "total_ms": total_ns[name] / 1e6,
                "mean_ms": total_ns[name] / 1e6 / max(count[name], 1),
                "max_ms": max_ns[name] / 1e6,
            }
            for name in sorted(total_ns)
        }


def summarize_trace_file(path: Path, top: int = 20) -> list[dict]:
    """Aggregate a `trace.json` (this tracer's or any Chrome trace) into
    per-name rows, busiest first. Accepts both the object form
    ({"traceEvents": [...]}) and the bare-array form. Raises OSError /
    ValueError on unreadable input — the CLI maps that to exit 1."""
    data = json.loads(Path(path).read_text())
    events = data.get("traceEvents", []) if isinstance(data, dict) else data
    total_us: dict[str, float] = defaultdict(float)
    max_us: dict[str, float] = defaultdict(float)
    count: dict[str, int] = defaultdict(int)
    threads: dict[str, set] = defaultdict(set)
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != _COMPLETE:
            continue
        name = ev.get("name", "?")
        dur = float(ev.get("dur", 0))
        total_us[name] += dur
        max_us[name] = max(max_us[name], dur)
        count[name] += 1
        threads[name].add(ev.get("tid"))
    rows = [
        {
            "name": name,
            "count": count[name],
            "total_ms": total_us[name] / 1e3,
            "mean_ms": total_us[name] / 1e3 / max(count[name], 1),
            "max_ms": max_us[name] / 1e3,
            "threads": len(threads[name]),
        }
        for name in total_us
    ]
    rows.sort(key=lambda r: r["total_ms"], reverse=True)
    return rows[:top]
