"""Memory ledger: per-program HBM attribution + OOM pre-flight math.

The telemetry stack answers "how fast" (perf.py) and "is it alive"
(health.py) but, before this module, not "where did the HBM go" — the
question that decides whether a bigger batch, a deeper net, or a larger
device-resident replay ring fits BEFORE a scarce TPU window is burned
on an OOM. Podracer-style pipelines (arXiv:2104.06272) and MindSpeed RL
(arXiv:2507.19017) both treat per-component memory accounting and
ahead-of-time fit checks as first-class infrastructure; this is that
tier here:

- **Static attribution.** Every program wrapped by
  `compile_cache.CachedProgram` records its AOT
  `compiled.memory_analysis()` — argument / output / temp /
  generated-code bytes — at compile time (`program_memory_record`),
  persisted beside the executable artifact and drained into the run's
  `metrics.jsonl` as `kind: "memory"` records. Model/optimizer/
  train-state bytes come from tree-size accounting (`tree_bytes`,
  `train_state_record`), replay-ring bytes from the device buffers'
  own dtype/shape math (`replay_ring_bytes` — asserted equal to the
  allocated storage in tests).
- **Budget composition.** `compose_budget` folds those records into a
  worst-case per-device budget: persistent train state + device ring +
  rollout-carry residency (chunk-program arguments minus params) +
  the worst single program's transient (temp + output). `cli fit`
  checks it against `bytes_limit`; `cli mem` renders the attribution
  table; `cli compare` gates `memory_budget_bytes` across runs.
- **Live accounting** lives in `perf.UtilizationMeter` (per-tick
  `mem_bytes_in_use`/`mem_peak_bytes_in_use` + high-water tracking)
  and `health.device_memory_stats`; the leak detector is
  `anomaly.AnomalyDetector.observe_memory` (`Anomaly/memory_growth`).

Reader functions here never import JAX — `cli mem` must render a run's
attribution from artifacts alone beside a wedged chip. Anything that
needs JAX (tree accounting, the fit estimator) imports it lazily.
"""

import logging
import math
import time

logger = logging.getLogger(__name__)

MEMORY_KIND = "memory"

# Operator-supplied per-device byte budget override: lets `cli fit`
# assert a denominator for backends that report no allocator limit
# (parallel to utils/flops.py's ALPHATRIANGLE_PEAK_TFLOPS).
BYTES_LIMIT_ENV = "ALPHATRIANGLE_DEVICE_BYTES_LIMIT"

# `cli fit` exit codes.
FIT_OK = 0  # budget fits the per-device limit
FIT_OVER = 1  # budget exceeds the limit
FIT_UNKNOWN = 2  # no device byte limit known (and no override)


def fmt_bytes(n) -> str:
    """Human bytes for tables: '1.50 GiB' / '320.0 KiB' / '—'."""
    if not isinstance(n, (int, float)) or isinstance(n, bool):
        return "—"
    n = float(n)
    for unit, scale in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if abs(n) >= scale:
            return f"{n / scale:,.2f} {unit}"
    return f"{n:,.0f} B"


# --- static attribution records -----------------------------------------


def program_memory_record(
    name: str,
    compiled,
    backend: str = "",
    key: str = "",
    origin: str = "compile",
) -> "dict | None":
    """One `kind: "memory"` record from an AOT program's
    `memory_analysis()` (argument/output/temp/generated-code bytes).
    None when the executable doesn't support the analysis (exotic
    backends) — attribution degrades, nothing raises."""
    analysis = getattr(compiled, "memory_analysis", None)
    if analysis is None:
        return None
    try:
        stats = analysis()
    except Exception:
        return None
    if stats is None:
        return None

    def grab(attr: str) -> "int | None":
        v = getattr(stats, attr, None)
        return int(v) if isinstance(v, (int, float)) else None

    b = {
        "argument": grab("argument_size_in_bytes"),
        "output": grab("output_size_in_bytes"),
        "temp": grab("temp_size_in_bytes"),
        "generated_code": grab("generated_code_size_in_bytes"),
        "alias": grab("alias_size_in_bytes"),
    }
    if all(v is None for v in b.values()):
        return None
    # TPU analyses additionally expose a whole-program peak; keep it
    # when present (it subsumes temp+output as the transient bound).
    peak = grab("peak_memory_in_bytes")
    v = {k: x or 0 for k, x in b.items()}
    rec = {
        "kind": MEMORY_KIND,
        "category": "program",
        "component": f"program/{name}",
        "program": name,
        "key": key,
        "backend": backend,
        "origin": origin,
        "bytes": b,
        "total": v["argument"] + v["output"] + v["temp"] + v["generated_code"],
        # Extra bytes one dispatch needs beyond its resident arguments:
        # temps plus the NON-aliased outputs (donated outputs reuse
        # argument buffers — `alias` bytes — and allocate nothing new).
        "transient": v["temp"] + max(0, v["output"] - v["alias"]),
        "time": time.time(),
    }
    if peak is not None:
        rec["peak"] = peak
    return rec


def tree_bytes(tree) -> int:
    """Total bytes of every array leaf in a pytree (shape x dtype
    itemsize — works on concrete arrays and ShapeDtypeStructs alike).
    Lazy JAX import: this is a writer-side helper."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        dtype = getattr(leaf, "dtype", None)
        size = getattr(leaf, "size", None)
        if dtype is None or size is None:
            continue
        try:
            total += int(size) * int(np.dtype(dtype).itemsize)
        except TypeError:
            continue
    return total


def train_state_record(state) -> dict:
    """Tree-size accounting of one TrainState: params vs optimizer
    state vs batch stats (the bytes `training/setup.py` ledgers)."""
    parts = {
        "params": tree_bytes(getattr(state, "params", None)),
        "opt_state": tree_bytes(getattr(state, "opt_state", None)),
        "batch_stats": tree_bytes(getattr(state, "batch_stats", None)),
    }
    total = tree_bytes(state)
    return {
        "kind": MEMORY_KIND,
        "category": "state",
        "component": "train_state",
        "bytes": parts,
        "total": total,
        "time": time.time(),
    }


def replay_ring_bytes(
    capacity: int,
    grid_shape: tuple,
    other_dim: int,
    action_dim: int,
    shards: int = 1,
) -> int:
    """Exact bytes of a device replay ring's storage, from the same
    dtype/shape math the buffers allocate with: one int8 grid cell per
    board cell, float32 everything else, one trash row per shard
    (rl/device_buffer.py / rl/sharded_device_buffer.py — tests assert
    this equals the allocated storage bit for bit)."""
    rows = int(capacity) + int(shards)
    row_bytes = (
        int(math.prod(grid_shape))  # grid, int8
        + 4 * int(other_dim)  # other_features, float32
        + 4 * int(action_dim)  # policy_target, float32
        + 4  # value_target, float32
        + 4  # policy_weight, float32
    )
    return rows * row_bytes


def replay_ring_record(
    total_bytes: int,
    capacity: int,
    shards: int = 1,
    location: str = "device",
) -> dict:
    """The ledger record for one replay ring (location "device" for the
    HBM-resident rings, "host" for the NumPy buffer — host rings are
    listed in the attribution table but excluded from the HBM budget)."""
    return {
        "kind": MEMORY_KIND,
        "category": "ring",
        "component": "replay_ring",
        "bytes": {"storage": int(total_bytes)},
        "total": int(total_bytes),
        "capacity": int(capacity),
        "shards": int(shards),
        "location": location,
        "time": time.time(),
    }


# --- live totals ---------------------------------------------------------


def summarize_device_memory(device_memory) -> "dict | None":
    """Fold `health.device_memory_stats()` rows into run totals:
    summed in-use/peak, summed limit (None when no device reports one).
    """
    if not device_memory:
        return None
    in_use = 0
    peak = 0
    limits = []
    for d in device_memory:
        if not isinstance(d, dict):
            continue
        u = d.get("bytes_in_use")
        if isinstance(u, (int, float)):
            in_use += int(u)
        p = d.get("peak_bytes_in_use")
        peak += int(p) if isinstance(p, (int, float)) else (
            int(u) if isinstance(u, (int, float)) else 0
        )
        lim = d.get("bytes_limit")
        if isinstance(lim, (int, float)) and lim > 0:
            limits.append(int(lim))
    return {
        "bytes_in_use": in_use,
        "peak_bytes_in_use": peak,
        "bytes_limit": sum(limits) if limits else None,
    }


# --- budget composition --------------------------------------------------


def latest_by_component(records) -> dict:
    """Newest record per component name (re-compiles and re-runs
    re-emit records; attribution wants the latest of each)."""
    out: dict = {}
    for rec in records:
        if isinstance(rec, dict) and rec.get("component"):
            out[rec["component"]] = rec
    return out


def compose_budget(records) -> dict:
    """Fold memory records into the static per-device budget.

    total = train-state bytes (params + optimizer + batch stats,
    resident for the whole run) + device replay ring + rollout carry
    residency (the chunk program's argument bytes minus the params it
    shares with the train state — game/tree state that stays resident
    between chunks) + the worst single program's transient (temp +
    output; the program-reported `peak` wins when present). Host rings
    are excluded: they live in host RAM, not HBM.

    The budget is PER DEVICE: a dp-sharded ring's record carries the
    global storage bytes over `shards` devices (each device holds only
    its cap_local = capacity/dp rows plus one trash row), so device
    ring totals divide by their shard count before entering the sum.
    """
    latest = latest_by_component(records)
    state = next(
        (r for r in latest.values() if r.get("category") == "state"), None
    )
    rings = [r for r in latest.values() if r.get("category") == "ring"]
    programs = [
        r for r in latest.values() if r.get("category") == "program"
    ]
    params_bytes = int(((state or {}).get("bytes") or {}).get("params") or 0)
    state_total = int((state or {}).get("total") or 0)
    ring_device = sum(
        int(r.get("total") or 0) // max(1, int(r.get("shards") or 1))
        for r in rings
        if r.get("location") == "device"
    )
    rollout_resident = 0
    transient = 0
    for rec in programs:
        b = rec.get("bytes") or {}
        arg = int(b.get("argument") or 0)
        if str(rec.get("program") or "").startswith("self_play"):
            rollout_resident = max(rollout_resident, max(0, arg - params_bytes))
        peak = rec.get("peak")
        t = (
            int(peak)
            if isinstance(peak, (int, float))
            else int(rec.get("transient") or 0)
        )
        transient = max(transient, t)
    return {
        "train_state_bytes": state_total,
        "replay_ring_bytes": ring_device,
        "rollout_resident_bytes": rollout_resident,
        "program_transient_bytes": transient,
        "total_bytes": state_total + ring_device + rollout_resident + transient,
        "programs": len(programs),
    }


def serve_budget_bytes(record) -> int:
    """Per-device bytes a STANDALONE policy service needs, from its
    serve program's memory record: resident arguments (net variables +
    the slot-array states) plus the dispatch transient (the
    program-reported whole-program peak wins when present). There is
    no learner state and no replay ring on a serving chip — this is
    the `cli serve` pre-flight's budget, next to `compose_budget`'s
    training-process one."""
    if not isinstance(record, dict):
        return 0
    b = record.get("bytes") or {}
    arg = int(b.get("argument") or 0)
    peak = record.get("peak")
    transient = (
        int(peak)
        if isinstance(peak, (int, float))
        else int(record.get("transient") or 0)
    )
    return arg + transient


def fit_verdict(total_bytes, bytes_limit) -> tuple:
    """(exit code, reason) for a budget against a per-device limit."""
    if not isinstance(bytes_limit, (int, float)) or bytes_limit <= 0:
        return FIT_UNKNOWN, (
            "no device byte limit known for this backend (set "
            f"{BYTES_LIMIT_ENV} to assert one)"
        )
    frac = total_bytes / bytes_limit
    if total_bytes <= bytes_limit:
        return FIT_OK, (
            f"fits: {fmt_bytes(total_bytes)} is {frac:.1%} of the "
            f"{fmt_bytes(bytes_limit)} per-device limit"
        )
    return FIT_OVER, (
        f"OVER BUDGET: {fmt_bytes(total_bytes)} is {frac:.1%} of the "
        f"{fmt_bytes(bytes_limit)} per-device limit"
    )


# --- attribution rendering (no JAX on this path) -------------------------


def attribution_rows(records) -> list:
    """(component, total bytes, detail) rows for `cli mem`'s table,
    biggest first."""
    rows = []
    for rec in latest_by_component(records).values():
        b = rec.get("bytes") or {}
        cat = rec.get("category")
        if cat == "program":
            detail = (
                f"args {fmt_bytes(b.get('argument'))}, "
                f"out {fmt_bytes(b.get('output'))}, "
                f"temp {fmt_bytes(b.get('temp'))}, "
                f"code {fmt_bytes(b.get('generated_code'))}"
            )
        elif cat == "state":
            detail = (
                f"params {fmt_bytes(b.get('params'))}, "
                f"opt {fmt_bytes(b.get('opt_state'))}, "
                f"bn {fmt_bytes(b.get('batch_stats'))}"
            )
        elif cat == "ring":
            detail = (
                f"capacity {rec.get('capacity'):,} x {rec.get('shards')} "
                f"shard(s), {rec.get('location')}"
            )
        else:
            detail = ""
        rows.append((rec.get("component") or "?", rec.get("total") or 0, detail))
    rows.sort(key=lambda r: -r[1])
    return rows


# --- pre-flight estimator (JAX-side; `cli fit`) --------------------------


def resolve_bytes_limit(
    limit_gb: "float | None", environ=None
) -> tuple:
    """(per-device byte limit, source) with the `cli fit` resolution
    order shared by fit/tune/serve: an explicit --limit-gb flag wins,
    then the ALPHATRIANGLE_DEVICE_BYTES_LIMIT env override, then the
    smallest limit any local device reports (conservative on
    heterogeneous hosts). (None, "none") when nothing is known —
    FIT_UNKNOWN territory."""
    import os

    env = os.environ if environ is None else environ
    if limit_gb is not None:
        return limit_gb * 2**30, "flag"
    override = str(env.get(BYTES_LIMIT_ENV, "") or "").strip()
    if override:
        try:
            return float(override), "env"
        except ValueError:
            logger.warning(
                "%s=%r is not a number; ignoring.", BYTES_LIMIT_ENV, override
            )
    from .health import device_memory_stats

    limits = [
        m.get("bytes_limit")
        for m in device_memory_stats()
        if isinstance(m.get("bytes_limit"), (int, float))
        and m.get("bytes_limit") > 0
    ]
    if limits:
        return min(limits), "device"
    return None, "none"


def sharded_megastep_dp(train_config) -> int:
    """dp width the sharded megastep family (`megastep/dp<D>_t<T>_k<K>`)
    would run at in THIS process: the device count when the geometry
    divides like the training-time gate (training/setup.py's
    `_make_buffer`), else 1 (the single-device family). Shared by
    `estimate_fit` and `cli warm` so pre-flight and warm target the
    program the run will actually dispatch."""
    import jax

    dp = jax.device_count()
    if (
        jax.process_count() == 1
        and dp > 1
        and train_config.BUFFER_CAPACITY % dp == 0
        and train_config.BATCH_SIZE % dp == 0
        and train_config.SELF_PLAY_BATCH_SIZE % dp == 0
    ):
        return dp
    return 1


def estimate_fit(
    env_config,
    model_config,
    mcts_config,
    train_config,
    fused_k: int = 4,
    device_replay: bool = False,
    megastep: bool = False,
    serve: bool = False,
    serve_batch: "int | None" = None,
    serve_buckets=None,
    programs: "set[str] | None" = None,
    progress=None,
) -> dict:
    """Build the run's hot programs AOT (lowered + compiled, never
    executed) and compose the static memory budget for them.

    `programs`: optional name filter (substring match against the
    program labels, same contract as `cli warm --programs`) — the
    autotuner's feasibility oracle analyzes only the programs that
    bound its candidate's budget instead of paying every compile per
    search point. Static records (train state, replay ring) are always
    composed regardless of the filter.

    Returns {"records": [...], "budget": compose_budget(...)}. The
    device-replay gather program is not lowered here — lowering it
    needs the ring allocated, which is exactly the allocation a
    pre-flight must not make; the ring is accounted statically and the
    gather's transient is bounded by the fused program's. `megastep`
    additionally analyzes the fused-megastep program (rl/megastep.py) —
    this one DOES allocate the configured ring (its storage is a
    program argument), so it is opt-in; `cli fit` enables it since its
    bench-plan capacities are small. `serve` additionally analyzes the
    policy service's `serve/b<B>` search program (serving/service.py;
    B = `serve_batch`, default the self-play lane count) and persists
    its `.mem.json` sidecar — the OOM pre-flight `cli serve` runs
    before occupying a chip. `serve_buckets` (a serving/buckets.py
    ladder spec) analyzes EVERY rung's program: the micro-batcher may
    dispatch any of them, so the pre-flight must budget the whole
    ladder, and each rung gets its own sidecar pair.
    """
    from ..env.engine import TriangleEnv
    from ..features.core import get_feature_extractor
    from ..nn.network import NeuralNetwork
    from ..rl.self_play import SelfPlayEngine
    from ..rl.trainer import Trainer

    def say(msg: str) -> None:
        logger.info(msg)
        if progress is not None:
            progress(msg)

    env = TriangleEnv(env_config)
    extractor = get_feature_extractor(env, model_config)
    net = NeuralNetwork(model_config, env_config, seed=0)
    engine = SelfPlayEngine(
        env, extractor, net, mcts_config, train_config, seed=0
    )
    trainer = Trainer(net, train_config)

    records = [train_state_record(trainer.state)]
    ring_bytes = replay_ring_bytes(
        train_config.BUFFER_CAPACITY,
        (model_config.GRID_INPUT_CHANNELS, env_config.ROWS, env_config.COLS),
        extractor.other_dim,
        env_config.action_dim,
    )
    records.append(
        replay_ring_record(
            ring_bytes,
            train_config.BUFFER_CAPACITY,
            location="device" if device_replay else "host",
        )
    )
    if getattr(mcts_config, "descent_gather", "einsum") == "einsum":
        # The einsum descent gather materializes a (B, W, N) f32
        # one-hot every level (mcts/search.py `_descend_wave`,
        # ops/gather_rows.py). XLA's memory analysis can fuse that
        # temp out of the reported footprint entirely (CPU analyses
        # often report temp=0), so the composed transient silently
        # undercounted the rollout program. This analytic record
        # floors the budget with the one-hot bytes; when the
        # program-reported peak is larger it still wins (max over
        # records in `compose_budget`). The "pallas"/"take" gathers
        # never build the one-hot, so no floor applies there.
        wave = max(
            1,
            min(mcts_config.mcts_batch_size, mcts_config.max_simulations),
        )
        while mcts_config.max_simulations % wave:
            wave -= 1
        onehot_bytes = (
            4
            * train_config.SELF_PLAY_BATCH_SIZE
            * wave
            * (mcts_config.max_simulations + 1)
        )
        records.append(
            {
                "kind": MEMORY_KIND,
                "category": "program",
                "component": "program/descent_gather_onehot",
                "program": "descent_gather_onehot",
                "origin": "analytic",
                "bytes": {"temp": onehot_bytes},
                "total": onehot_bytes,
                "transient": onehot_bytes,
                "time": time.time(),
            }
        )
    chunk = train_config.ROLLOUT_CHUNK_MOVES
    lbatch = train_config.BATCH_SIZE
    targets = [
        (f"self_play_chunk/t{chunk}", lambda: engine.analyze_chunk(chunk)),
        (f"learner_step/b{lbatch}", lambda: trainer.analyze_step(lbatch)),
        (
            f"learner_fused/k{fused_k}",
            lambda: trainer.analyze_steps(fused_k, lbatch),
        ),
    ]
    if megastep:
        from ..rl.megastep import MegastepRunner

        grid_shape = (
            model_config.GRID_INPUT_CHANNELS,
            env_config.ROWS,
            env_config.COLS,
        )
        mega_dp = sharded_megastep_dp(train_config)
        if mega_dp > 1:
            # dp-sharded family: analyze the program a multi-device run
            # will actually dispatch, with dedicated mesh-built
            # components mirroring training/setup.py's wiring. The
            # ring record carries shards=dp so `compose_budget`
            # charges each device its cap_local slice, not the global
            # capacity.
            from ..config.mesh_config import MeshConfig
            from ..rl.sharded_device_buffer import (
                ShardedDeviceReplayBuffer,
            )

            mesh = MeshConfig(DP_SIZE=mega_dp).build_mesh()
            mega_engine = SelfPlayEngine(
                env, extractor, net, mcts_config, train_config,
                seed=0, mesh=mesh,
            )
            mega_trainer = Trainer(net, train_config, mesh=mesh)
            mega_buffer = ShardedDeviceReplayBuffer(
                train_config,
                grid_shape=grid_shape,
                other_dim=extractor.other_dim,
                action_dim=env_config.action_dim,
                mesh=mesh,
            )
            records.append(mega_buffer.memory_record())
            runner = MegastepRunner(
                mega_engine, mega_trainer, mega_buffer, train_config
            )
            targets.append(
                (
                    f"megastep/dp{mega_dp}_t{chunk}_k{fused_k}",
                    lambda: runner.analyze_megastep(chunk, fused_k),
                )
            )
        else:
            from ..rl.device_buffer import DeviceReplayBuffer

            mega_buffer = DeviceReplayBuffer(
                train_config,
                grid_shape=grid_shape,
                other_dim=extractor.other_dim,
                action_dim=env_config.action_dim,
            )
            runner = MegastepRunner(
                engine, trainer, mega_buffer, train_config
            )
            targets.append(
                (
                    f"megastep/t{chunk}_k{fused_k}",
                    lambda: runner.analyze_megastep(chunk, fused_k),
                )
            )
    if serve:
        from ..serving import PolicyService, serve_program_name

        slots = int(serve_batch or train_config.SELF_PLAY_BATCH_SIZE)
        serve_gumbel = (
            getattr(mcts_config, "root_selection", "puct") == "gumbel"
        )
        if serve_gumbel:
            from ..mcts import GumbelMCTS

            serve_mcts = GumbelMCTS(
                env, extractor, net.model, mcts_config, net.support,
                exploit=True,
            )
        else:
            serve_mcts = engine.mcts
        service = PolicyService(
            env, extractor, net, serve_mcts, slots=slots,
            use_gumbel=serve_gumbel, ladder=serve_buckets,
        )
        # One analysis per ladder rung (a fixed-shape service is a
        # one-rung ladder): the micro-batcher dispatches whichever
        # rung fits demand, so the budget must cover all of them.
        # persist=True: each rung's sidecar survives into the cache
        # dir so a later `cli serve` pre-flight reads it without
        # re-lowering.
        for rung in service.ladder.rungs:
            targets.append(
                (
                    serve_program_name(rung),
                    lambda r=rung: service.analyze(persist=True, rung=r),
                )
            )
    if programs:
        targets = [
            (label, fn)
            for label, fn in targets
            if any(p in label for p in programs)
        ]
    for label, fn in targets:
        t0 = time.time()
        try:
            rec = fn()
        except Exception as exc:  # one unanalyzable program != no report
            logger.warning("fit: %s analysis failed (%s)", label, exc)
            rec = None
        if rec is not None:
            records.append(rec)
            say(
                f"fit: {label}: args {fmt_bytes(rec['bytes'].get('argument'))}"
                f" temp {fmt_bytes(rec['bytes'].get('temp'))}"
                f" ({time.time() - t0:.1f}s)"
            )
        else:
            say(f"fit: {label}: no memory analysis available")
    return {"records": records, "budget": compose_budget(records)}
