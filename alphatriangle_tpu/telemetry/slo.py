"""Fleet SLO engine: error budgets + multi-window burn-rate alerts.

"Is the fleet meeting its SLO" gets one answer here, computed purely
from records the fleet already ledgers (no new hot-path accounting):

- **availability** — 1 - rejected/requests. Rejections are the
  router's shed (`queue-full` / `no-healthy-replica`) and
  `retries-exhausted` events in `fleet.jsonl`; the request volume is
  integrated from the fleet parent's `kind:"util"` ticks
  (`serve_requests_per_sec * window_s`, windowed per tick).
- **move latency** — the fraction of served requests that fell in a
  replica tick window whose `serve_move_latency_ms_p95` met the
  threshold, over every `replica_*/metrics.jsonl`.
- **dispatch success** — ok seals / all seals of `serve` family
  dispatches across the replica flight rings (a crashed or faulted
  device program is a failed dispatch even when the router recovered
  it).

Each SLO is a good/total ratio against a declared objective, evaluated
over multiple trailing windows with the classic burn-rate alert pair
(Google SRE workbook): a fast window at high burn (page: the budget is
bleeding NOW) and a slow window at moderate burn (ticket: it will be
gone in days). `burn_rate = error_rate / (1 - objective)`; a window
alerts when its burn rate crosses its threshold. "Now" is the newest
record time, so a finished run is judged at its end, not against the
wall clock of whoever runs the CLI.

Surfaces: `cli slo <run>` (exit 0 within budget / 1 burning / 2 no
data — pinned by tests), the `cli watch` fleet+SLO line, and an
aggregated whole-fleet Prometheus textfile (`fleet.prom`: rejection
codes as distinct counters, burn rates as gauges). JAX-free by
construction, like every reader beside a dead fleet.
"""

import logging
from dataclasses import dataclass
from pathlib import Path

from .flight import FLIGHT_FILENAME, read_flight
from .ledger import read_ledger

logger = logging.getLogger(__name__)

FLEET_PROM_FILENAME = "fleet.prom"

#: status -> `cli slo` exit code (documented in OBSERVABILITY.md; 1 is
#: shared with argparse usage errors, as for doctor).
SLO_EXIT_CODES = {"ok": 0, "burning": 1, "no-data": 2}

#: (window_s, burn-rate threshold) pairs — the SRE-workbook fast-page /
#: slow-ticket alert pair, scaled to smoke-length runs by the caller
#: when needed.
DEFAULT_BURN_WINDOWS = ((300.0, 14.4), (3600.0, 6.0))

#: Default objectives: availability and dispatch success burn 1% error
#: budgets; the latency SLO targets 95% of requests under threshold.
DEFAULT_OBJECTIVES = {
    "availability": 0.99,
    "move-latency-p95": 0.95,
    "dispatch-success": 0.99,
}

DEFAULT_LATENCY_THRESHOLD_MS = 500.0


@dataclass(frozen=True)
class SLO:
    """One declarative objective over a good/total event stream."""

    name: str
    objective: float  # target good/total ratio in (0, 1)
    description: str
    #: (t, good, bad) samples, each counted once.
    samples: tuple

    def evaluate(self, now: float, windows) -> dict:
        budget = max(1e-9, 1.0 - self.objective)
        rows = []
        burning = False
        any_data = False
        for window_s, threshold in windows:
            good = bad = 0.0
            for t, g, b in self.samples:
                if t > now - window_s:
                    good += g
                    bad += b
            total = good + bad
            error_rate = (bad / total) if total > 0 else 0.0
            burn_rate = error_rate / budget
            window_burning = total > 0 and burn_rate >= threshold
            burning = burning or window_burning
            any_data = any_data or total > 0
            rows.append(
                {
                    "window_s": window_s,
                    "burn_threshold": threshold,
                    "total": round(total, 3),
                    "bad": round(bad, 3),
                    "error_rate": round(error_rate, 6),
                    "burn_rate": round(burn_rate, 3),
                    "burning": window_burning,
                }
            )
        status = (
            "burning" if burning else ("ok" if any_data else "no-data")
        )
        return {
            "name": self.name,
            "objective": self.objective,
            "error_budget": round(budget, 6),
            "description": self.description,
            "status": status,
            "windows": rows,
        }


def _times(samples) -> list:
    return [t for t, _g, _b in samples if isinstance(t, (int, float))]


def collect_slos(
    run_dir: "Path | str",
    *,
    latency_threshold_ms: float = DEFAULT_LATENCY_THRESHOLD_MS,
    objectives: "dict | None" = None,
) -> list[SLO]:
    """Build the fleet's SLO set from a fleet-parent run dir's ledgers
    (tolerant readers throughout — a legacy or partial run dir yields
    SLOs with empty sample streams, which evaluate to no-data)."""
    from ..serving.fleet import read_fleet_events

    run_dir = Path(run_dir)
    obj = {**DEFAULT_OBJECTIVES, **(objectives or {})}

    # availability: served volume from parent util ticks, rejections
    # from router events.
    avail: list = []
    for rec in read_ledger(run_dir / "metrics.jsonl", kinds={"util"}):
        rate = rec.get("serve_requests_per_sec")
        window = rec.get("window_s")
        t = rec.get("time")
        if (
            isinstance(rate, (int, float))
            and isinstance(window, (int, float))
            and isinstance(t, (int, float))
        ):
            avail.append((float(t), float(rate) * float(window), 0.0))
    for e in read_fleet_events(run_dir):
        if e.get("event") in ("shed", "exhausted") and isinstance(
            e.get("time"), (int, float)
        ):
            avail.append((float(e["time"]), 0.0, 1.0))

    latency: list = []
    dispatch: list = []
    for rdir in sorted(run_dir.glob("replica_*")):
        if not rdir.is_dir():
            continue
        for rec in read_ledger(rdir / "metrics.jsonl", kinds={"util"}):
            p95 = rec.get("serve_move_latency_ms_p95")
            t = rec.get("time")
            if not (
                isinstance(p95, (int, float))
                and isinstance(t, (int, float))
            ):
                continue
            n = rec.get("serve_window_requests")
            n = float(n) if isinstance(n, (int, float)) and n > 0 else 1.0
            if float(p95) <= latency_threshold_ms:
                latency.append((float(t), n, 0.0))
            else:
                latency.append((float(t), 0.0, n))
        for rec in read_flight(rdir / FLIGHT_FILENAME):
            if rec.get("phase") != "seal" or rec.get("family") != "serve":
                continue
            t = rec.get("time")
            if not isinstance(t, (int, float)):
                continue
            if rec.get("ok", True):
                dispatch.append((float(t), 1.0, 0.0))
            else:
                dispatch.append((float(t), 0.0, 1.0))

    return [
        SLO(
            name="availability",
            objective=obj["availability"],
            description="1 - (shed + retries-exhausted) / routed requests",
            samples=tuple(avail),
        ),
        SLO(
            name="move-latency-p95",
            objective=obj["move-latency-p95"],
            description=(
                "requests served in replica tick windows with "
                f"p95 move latency <= {latency_threshold_ms:g} ms"
            ),
            samples=tuple(latency),
        ),
        SLO(
            name="dispatch-success",
            objective=obj["dispatch-success"],
            description="ok serve/b<B> dispatch seals / all seals",
            samples=tuple(dispatch),
        ),
    ]


def evaluate_slos(
    run_dir: "Path | str",
    *,
    windows=DEFAULT_BURN_WINDOWS,
    now: "float | None" = None,
    latency_threshold_ms: float = DEFAULT_LATENCY_THRESHOLD_MS,
    objectives: "dict | None" = None,
) -> dict:
    """The `cli slo` report: every SLO evaluated over every window,
    plus the roll-up status and exit code.

    `now` defaults to the newest sample time across all SLOs (a
    finished run's budget is judged at the moment it ended); pass an
    explicit epoch time to replay the alert state at a point in time
    (the brownout-window check in benchmarks/trace_smoke.py).
    """
    run_dir = Path(run_dir)
    slos = collect_slos(
        run_dir,
        latency_threshold_ms=latency_threshold_ms,
        objectives=objectives,
    )
    newest = max(
        (t for slo in slos for t in _times(slo.samples)), default=None
    )
    eval_now = now if now is not None else newest
    results = [
        slo.evaluate(eval_now, windows) if eval_now is not None else {
            "name": slo.name,
            "objective": slo.objective,
            "error_budget": round(max(1e-9, 1.0 - slo.objective), 6),
            "description": slo.description,
            "status": "no-data",
            "windows": [],
        }
        for slo in slos
    ]
    if all(r["status"] == "no-data" for r in results):
        status = "no-data"
    elif any(r["status"] == "burning" for r in results):
        status = "burning"
    else:
        status = "ok"
    return {
        "schema": "alphatriangle.slo.v1",
        "run_dir": str(run_dir),
        "now": eval_now,
        "windows": [list(w) for w in windows],
        "slos": results,
        "status": status,
        "exit_code": SLO_EXIT_CODES[status],
    }


def slo_status_line(report: dict) -> str:
    """One-line roll-up for `cli watch` / `cli slo` headers:
    per-SLO status with the worst window's burn rate."""
    parts = []
    for slo in report.get("slos", []):
        worst = max(
            (w.get("burn_rate", 0.0) for w in slo.get("windows", [])),
            default=None,
        )
        flag = {"ok": "+", "burning": "!", "no-data": "?"}.get(
            slo.get("status"), "?"
        )
        burn = f" burn x{worst:.1f}" if worst is not None else ""
        parts.append(f"{flag}{slo.get('name')}{burn}")
    return f"slo[{report.get('status', '?')}] " + "  ".join(parts)


# --- aggregated whole-fleet Prometheus textfile --------------------------

#: counter name -> (summarize_fleet key, help text). Counters, not
#: gauges: these only ever grow over a run, and rejection codes stay
#: DISTINCT series so an alert can tell back-pressure (queue-full)
#: from an outage (no-healthy-replica) from replica sickness
#: (retries-exhausted).
_FLEET_COUNTERS = {
    "fleet_sheds_total": (
        "fleet_sheds",
        "Requests shed by the router (all rejection codes)",
    ),
    "fleet_shed_queue_full_total": (
        "fleet_shed_queue_full",
        "Requests shed with rejection=queue-full (admission bound)",
    ),
    "fleet_shed_no_healthy_replica_total": (
        "fleet_shed_no_healthy",
        "Requests shed with rejection=no-healthy-replica",
    ),
    "fleet_shed_retries_exhausted_total": (
        "fleet_shed_retries_exhausted",
        "Requests failed after exhausting every retry",
    ),
    "fleet_retries_total": ("fleet_retries", "Retry attempts dispatched"),
    "fleet_hedges_total": ("fleet_hedges", "Hedged dispatches launched"),
    "fleet_hedge_wins_total": (
        "fleet_hedge_wins",
        "Requests won by the hedge copy",
    ),
    "fleet_deaths_total": ("fleet_deaths", "Replica process deaths"),
    "fleet_respawns_total": ("fleet_respawns", "Replica respawns"),
    "fleet_evictions_total": (
        "fleet_evictions",
        "Replica evictions from routing admission",
    ),
}

_FLEET_GAUGES = {
    "fleet_requests_per_sec": (
        "fleet_requests_per_sec",
        "Completed routed requests per second (last storm)",
    ),
    "fleet_move_latency_ms_p95": (
        "fleet_move_latency_ms_p95",
        "Per-move latency p95 across the fleet (last storm), ms",
    ),
}


def write_fleet_prometheus(
    path: "Path | str",
    fleet_summary: "dict | None",
    slo_report: "dict | None" = None,
    run_name: str = "",
) -> bool:
    """Render the whole-fleet exposition: lifecycle/rejection counters
    from a `summarize_fleet` block + per-SLO burn-rate gauges from an
    `evaluate_slos` report. Atomic tmp+replace, mirror of
    `ledger.write_prometheus_textfile`."""
    path = Path(path)
    label = f'{{run="{run_name}"}}' if run_name else ""
    lines = []
    summary = fleet_summary or {}
    for name, (key, help_text) in _FLEET_COUNTERS.items():
        value = summary.get(key)
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        lines.append(f"# HELP alphatriangle_{name} {help_text}")
        lines.append(f"# TYPE alphatriangle_{name} counter")
        lines.append(f"alphatriangle_{name}{label} {value}")
    for name, (key, help_text) in _FLEET_GAUGES.items():
        value = summary.get(key)
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        lines.append(f"# HELP alphatriangle_{name} {help_text}")
        lines.append(f"# TYPE alphatriangle_{name} gauge")
        lines.append(f"alphatriangle_{name}{label} {value}")
    if slo_report:
        lines.append(
            "# HELP alphatriangle_slo_burn_rate SLO error-budget burn "
            "rate per trailing window"
        )
        lines.append("# TYPE alphatriangle_slo_burn_rate gauge")
        lines.append(
            "# HELP alphatriangle_slo_burning 1 when the SLO has a "
            "window past its burn threshold"
        )
        lines.append("# TYPE alphatriangle_slo_burning gauge")
        for slo in slo_report.get("slos", []):
            slo_name = slo.get("name")
            for w in slo.get("windows", []):
                wl = (
                    f'{{run="{run_name}",slo="{slo_name}",'
                    f'window_s="{w.get("window_s"):g}"}}'
                    if run_name
                    else f'{{slo="{slo_name}",'
                    f'window_s="{w.get("window_s"):g}"}}'
                )
                lines.append(
                    f"alphatriangle_slo_burn_rate{wl} "
                    f"{w.get('burn_rate', 0.0)}"
                )
            sl = (
                f'{{run="{run_name}",slo="{slo_name}"}}'
                if run_name
                else f'{{slo="{slo_name}"}}'
            )
            lines.append(
                f"alphatriangle_slo_burning{sl} "
                f"{1 if slo.get('status') == 'burning' else 0}"
            )
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text("\n".join(lines) + "\n")
        tmp.replace(path)
        return True
    except OSError:
        logger.exception("fleet prometheus write to %s failed", path)
        return False
