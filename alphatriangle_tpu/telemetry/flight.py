"""Dispatch flight recorder: a crash-surviving black box per device
dispatch, plus the postmortem readers behind `cli doctor`.

Everything built before this module (tracer, ledger, heartbeat)
observes a *live* process; round 5 burned 10.3 h on a wedged chip that
left no record of what it was doing when it died (BASELINE.md). The
fused megastep makes the blind spot worse: the whole
rollout+ingest+K-step iteration is ONE opaque device program. This
module closes it:

- `FlightRecorder`: every dispatch in the four hot families — rollout
  chunk, learner step/fused/from-ring, megastep, serve batch — writes
  an *intent* record (program, avals digest, expected duration from
  this run's own sealed history, deadline) to `flight.jsonl` BEFORE
  the dispatch, and a *seal* record with the measured wall time (the
  dispatch + its blocking fetch, i.e. device-inclusive) on completion.
  Appends ride `MetricsLedger` (open/write/flush/close per record), so
  SIGKILL at any instant loses at most one line and an intent without
  a seal is a signed confession naming the exact hung program.
- `DispatchWatchdog`: armed per intent, disarmed per seal. Past the
  deadline it dumps faulthandler stacks, runs the caller hook (span
  trace flush, wired in `RunTelemetry`), writes `wedge_report.json`,
  and exits with `WEDGE_EXIT_CODE` so a supervisor (tpu_watch.sh)
  reclassifies the window in minutes instead of hours.
- Readers (`read_flight`, `summarize_flight`, `classify_run`): NO JAX
  anywhere on this path — `cli doctor` runs beside a wedged chip, like
  `cli mem`. Sealed per-program times feed `cli perf` (p50/p95 per
  program) and the autotuner's `--calibrate` (per program family).

Record schema (docs/OBSERVABILITY.md "Flight recorder & forensics"):

    {"kind": "flight", "phase": "intent", "seq": N, "program": ...,
     "family": ..., "avals": ..., "expected_s": ..., "deadline_s": ...,
     "t_mono": ..., "time": ..., "pid": ...}
    {"kind": "flight", "phase": "seal", "seq": N, "program": ...,
     "family": ..., "wall_s": ..., "ok": true, "t_mono": ..., "time": ...}

A failed dispatch seals with `ok: false` + `error`; a process that died
mid-dispatch leaves the intent unsealed (the torn-intent signature the
doctor classifies on).
"""

import contextlib
import json
import logging
import os
import threading
import time
from pathlib import Path

from .ledger import MetricsLedger, iter_jsonl_records, ledger_paths

logger = logging.getLogger(__name__)

FLIGHT_FILENAME = "flight.jsonl"
WEDGE_REPORT_FILENAME = "wedge_report.json"
WEDGE_STACKS_FILENAME = "wedge_stacks.txt"
PREEMPT_REPORT_FILENAME = "preempt_report.json"

# Distinct exit code for a dispatch-deadline wedge, chosen outside the
# shell/signal ranges (1/2, 126-165): a supervisor seeing it KNOWS the
# process killed itself over a hung device program, not a crash.
WEDGE_EXIT_CODE = 113

# Exit code for a SIGTERM preemption the training loop absorbed: the
# emergency checkpoint + buffer spill + ledger flush all completed and
# preempt_report.json is on disk. A supervisor seeing 114 restarts (or
# doesn't — the host is being reclaimed) without treating it as a crash.
PREEMPT_EXIT_CODE = 114

# Exit code `cli supervise` uses when its restart budget / circuit
# breaker trips: the child is sick in a way restarts don't fix, and the
# caller (tpu_watch.sh) should stop burning window on it.
SUPERVISOR_GIVEUP_EXIT_CODE = 115

# Memory pressure at/above this fraction of the device limit makes the
# doctor call a wedged/stalled run OOM rather than generically hung.
OOM_UTILIZATION = 0.92

# EWMA weight for per-program expected durations: heavy enough to track
# a run warming up, light enough that one slow dispatch doesn't triple
# the next deadline.
_EWMA_ALPHA = 0.3


def program_family(program: str) -> str:
    """Dispatch family of a compile-cache program name: the four hot
    families get stable labels; anything else keys by its name head."""
    head = str(program).split("/", 1)[0]
    if head == "self_play_chunk":
        return "rollout"
    if head.startswith("learner"):
        return "learner"
    if head == "megastep":
        return "megastep"
    if head == "serve":
        return "serve"
    if head == "fleet":
        # Router dispatch brackets (`fleet/route`, serving/router.py):
        # host-side fan-out, but bracketed the same way so an unsealed
        # route names the request the fleet parent died holding.
        return "fleet"
    if head == "reuse":
        # Standalone subtree-promotion programs (`reuse/promote_*`,
        # ops/subtree_reuse.py): the training/serve paths fuse the
        # promotion into their own dispatches, but the parity bench and
        # smoke run it as its own hot program — same forensics contract.
        return "reuse"
    return head


class FlightSpan:
    """One armed dispatch: seal exactly once (idempotent)."""

    __slots__ = (
        "recorder", "seq", "program", "family", "t0", "trace", "_sealed",
    )

    def __init__(
        self,
        recorder,
        seq: int,
        program: str,
        family: str,
        t0: float,
        trace: "dict | None" = None,
    ):
        self.recorder = recorder
        self.seq = seq
        self.program = program
        self.family = family
        self.t0 = t0
        self.trace = trace
        self._sealed = False

    def seal(self, error: "str | None" = None) -> None:
        if self._sealed:
            return
        self._sealed = True
        self.recorder._seal(self, error=error)


class FlightRecorder:
    """Intent/seal writer + per-program expected-duration model.

    Thread-safe: async-rollout producers and the learner may dispatch
    concurrently; state updates and appends are lock-guarded. The hot
    path per dispatch is two `MetricsLedger.append`s (open/write/flush/
    close each) — `overhead_seconds` accumulates the measured cost so
    `make perf-smoke` can assert it stays under ~1% of iteration time.
    """

    def __init__(
        self,
        path: Path | str,
        max_bytes: int = 8 * 1024 * 1024,
        keep: int = 1,
        deadline_factor: float = 10.0,
        min_deadline_s: float = 60.0,
        first_deadline_s: float = 900.0,
        watchdog: "DispatchWatchdog | None" = None,
        base_trace: "dict | None" = None,
    ) -> None:
        self.path = Path(path)
        # Default trace fields for every bracket that doesn't pass its
        # own: RunTelemetry sets this from the env seam so a spawned
        # child's dispatches link back to the supervisor attempt that
        # spawned it (telemetry/tracectx.py).
        self.base_trace = dict(base_trace) if base_trace else None
        self._ledger = MetricsLedger(self.path, max_bytes=max_bytes, keep=keep)
        self.deadline_factor = deadline_factor
        self.min_deadline_s = min_deadline_s
        self.first_deadline_s = first_deadline_s
        self.watchdog = watchdog
        self.overhead_seconds = 0.0
        self.sealed_wall_seconds = 0.0
        self.dispatches = 0
        self._lock = threading.Lock()
        self._seq = 0
        self._expected: dict[str, float] = {}
        # A resumed run inherits its predecessors' measured durations:
        # the first dispatch of a warm program gets a calibrated
        # deadline instead of the generous compile allowance.
        for rec in read_flight(self.path):
            if rec.get("phase") == "seal" and rec.get("ok", True):
                wall = rec.get("wall_s")
                if isinstance(wall, (int, float)) and wall > 0:
                    self._fold_expected(str(rec.get("program")), float(wall))

    def _fold_expected(self, program: str, wall_s: float) -> None:
        prev = self._expected.get(program)
        self._expected[program] = (
            wall_s
            if prev is None
            else (1 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * wall_s
        )

    def expected_s(self, program: str) -> "float | None":
        with self._lock:
            return self._expected.get(program)

    def deadline_s(self, expected: "float | None") -> float:
        """Watchdog deadline for one dispatch: N x the expected wall
        (floored), or the generous first-dispatch allowance when no
        history exists — a first dispatch includes its compile."""
        if expected is None:
            return self.first_deadline_s
        return max(self.min_deadline_s, self.deadline_factor * expected)

    def begin(
        self,
        family: str,
        program: str,
        avals: "str | None" = None,
        trace: "dict | None" = None,
    ) -> FlightSpan:
        """Write the intent record and arm the watchdog; call BEFORE
        the dispatch. Returns the span to `seal()` after the fetch.

        `trace` is an optional dict of trace-context fields
        (trace_id/span_id/... or trace_ids for a batched wave) merged
        into BOTH the intent and the seal record, so an unsealed
        intent names not just the hung program but the exact request(s)
        it was serving (telemetry/tracectx.py)."""
        t_host = time.perf_counter()
        with self._lock:
            self._seq += 1
            seq = self._seq
            expected = self._expected.get(program)
        deadline = self.deadline_s(expected)
        trace = trace if trace else self.base_trace
        record = {
            "kind": "flight",
            "phase": "intent",
            "seq": seq,
            "program": program,
            "family": family,
            "avals": avals,
            "expected_s": (
                round(expected, 6) if expected is not None else None
            ),
            "deadline_s": round(deadline, 3),
            "t_mono": time.monotonic(),
            "time": time.time(),
            "pid": os.getpid(),
        }
        if trace:
            record.update(trace)
        self._ledger.append(record)
        if self.watchdog is not None:
            self.watchdog.arm(
                seq,
                program=program,
                family=family,
                deadline_s=deadline,
                expected_s=expected,
                avals=avals,
            )
        if os.environ.get("ALPHATRIANGLE_FAULTS"):
            # Fault-injection hook (supervise/faults.py): fires AFTER
            # the intent is durable and the watchdog is armed, so an
            # injected hang dies exactly like a real wedged dispatch.
            from ..supervise.faults import fault_point

            fault_point("dispatch", seq, flight_path=self.path)
        span = FlightSpan(
            self, seq, program, family, time.perf_counter(), trace=trace
        )
        self.overhead_seconds += span.t0 - t_host
        return span

    def _seal(self, span: FlightSpan, error: "str | None" = None) -> None:
        t_host = time.perf_counter()
        wall = t_host - span.t0
        if self.watchdog is not None:
            self.watchdog.disarm(span.seq)
        record = {
            "kind": "flight",
            "phase": "seal",
            "seq": span.seq,
            "program": span.program,
            "family": span.family,
            "wall_s": round(wall, 6),
            "ok": error is None,
            "t_mono": time.monotonic(),
            "time": time.time(),
        }
        if span.trace:
            record.update(span.trace)
        if error is not None:
            record["error"] = error
        self._ledger.append(record)
        with self._lock:
            if error is None:
                self._fold_expected(span.program, wall)
                self.sealed_wall_seconds += wall
                self.dispatches += 1
        self.overhead_seconds += time.perf_counter() - t_host

    def close(self) -> None:
        """Append the run's overhead summary (perf-smoke reads it to
        hold the hot-path cost under ~1% of iteration time)."""
        with self._lock:
            self._ledger.append(
                {
                    "kind": "flight_overhead",
                    "overhead_s": round(self.overhead_seconds, 6),
                    "sealed_wall_s": round(self.sealed_wall_seconds, 6),
                    "dispatches": self.dispatches,
                    "time": time.time(),
                }
            )


@contextlib.contextmanager
def flight_span(
    recorder: "FlightRecorder | None",
    family: str,
    program: str,
    avals: "str | None" = None,
    trace: "dict | None" = None,
):
    """Intent/seal bracket for a synchronous dispatch site; a no-op
    when the component has no recorder attached (tests, telemetry
    disabled). A raising dispatch seals `ok: false` with the error —
    an *unsealed* intent therefore always means the process died or
    wedged inside the bracket. `trace` rides through to both the
    intent and the seal (see `FlightRecorder.begin`)."""
    if recorder is None:
        yield None
        return
    span = recorder.begin(family, program, avals=avals, trace=trace)
    try:
        yield span
    except BaseException as exc:
        span.seal(error=repr(exc))
        raise
    else:
        span.seal()


class DispatchWatchdog:
    """Per-dispatch deadline enforcement (the stall watchdog's sharper
    sibling: `health.Watchdog` asks "is anything progressing?", this
    asks "is THIS dispatch overdue?").

    Armed by `FlightRecorder.begin`, disarmed by the seal. A dispatch
    past its deadline fires ONCE: faulthandler stacks into
    `wedge_stacks.txt`, the caller hook (trace flush), an atomic
    `wedge_report.json`, then — unless `exit_on_wedge` is off (tests,
    doctor-smoke) — `os._exit(WEDGE_EXIT_CODE)`. `os._exit` because the
    thread that would run normal shutdown is the one blocked inside the
    hung dispatch. The clock is injectable so tests freeze it.

    A near-deadline WARNING precedes the wedge: when a dispatch has
    been in flight past `warn_fraction` of its deadline, `on_warn`
    fires once for that dispatch (telemetry uses it to arm progress
    beacons — device_stats.arm_beacons — so if the dispatch does wedge
    and the supervisor respawns, or if it recovers and a LATER one
    wedges, the rebuilt programs carry phase beacons).
    """

    def __init__(
        self,
        run_dir: Path | str,
        poll_s: float = 5.0,
        on_wedge=None,
        exit_on_wedge: bool = True,
        clock=time.monotonic,
        warn_fraction: "float | None" = None,
        on_warn=None,
    ) -> None:
        self.run_dir = Path(run_dir)
        self.poll_s = poll_s
        self.on_wedge = on_wedge
        self.exit_on_wedge = exit_on_wedge
        self.warn_fraction = warn_fraction
        self.on_warn = on_warn
        self.warn_count = 0
        self._clock = clock
        self._lock = threading.Lock()
        self._armed: dict[int, dict] = {}
        self._fired = False
        self.wedge_count = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def arm(self, seq: int, **info) -> None:
        with self._lock:
            self._armed[seq] = {"seq": seq, "armed_at": self._clock(), **info}

    def disarm(self, seq: int) -> None:
        with self._lock:
            self._armed.pop(seq, None)

    def check(self, now: "float | None" = None) -> "dict | None":
        """One deadline evaluation; returns the wedge info when a
        dispatch is overdue (having fired the full reaction), else
        None. Called by the poll thread, and directly by tests."""
        now = self._clock() if now is None else now
        warnings: list[dict] = []
        with self._lock:
            if self._fired:
                return None
            overdue = None
            for info in self._armed.values():
                elapsed = now - info["armed_at"]
                deadline = float(info.get("deadline_s") or 0.0)
                if (
                    self.warn_fraction is not None
                    and not info.get("warned")
                    and deadline > 0.0
                    and elapsed > self.warn_fraction * deadline
                ):
                    # Near-deadline: warn once per dispatch, before the
                    # wedge reaction (arming beacons here is what gives
                    # the SECOND hang a phase attribution).
                    info["warned"] = True
                    self.warn_count += 1
                    warnings.append(dict(info, elapsed_s=round(elapsed, 3)))
                if elapsed > deadline and (
                    overdue is None or elapsed > overdue[1]
                ):
                    overdue = (info, elapsed)
            if overdue is not None:
                self._fired = True
                self.wedge_count += 1
        for winfo in warnings:
            logger.warning(
                "DispatchWatchdog: %s (%s) at %.0f%% of its %.0fs "
                "deadline (%.0fs elapsed) — near-deadline warning.",
                winfo.get("program"),
                winfo.get("family"),
                100.0 * winfo["elapsed_s"] / float(winfo["deadline_s"]),
                float(winfo.get("deadline_s") or 0.0),
                winfo["elapsed_s"],
            )
            if self.on_warn is not None:
                try:
                    self.on_warn(winfo)
                except Exception:
                    logger.exception("on_warn hook failed")
        if overdue is None:
            return None
        info, elapsed = overdue
        return self._fire(dict(info), elapsed)

    def _fire(self, info: dict, elapsed: float) -> dict:
        info["elapsed_s"] = round(elapsed, 3)
        logger.error(
            "DispatchWatchdog: %s (%s) in flight %.0fs past its %.0fs "
            "deadline — the device program is wedged.",
            info.get("program"),
            info.get("family"),
            elapsed,
            float(info.get("deadline_s") or 0.0),
        )
        stacks_path = self.run_dir / WEDGE_STACKS_FILENAME
        try:
            from .health import dump_thread_stacks

            dump_thread_stacks(stacks_path)
        except Exception:
            logger.exception("wedge stack dump failed")
        if self.on_wedge is not None:
            try:
                self.on_wedge(info)
            except Exception:
                logger.exception("on_wedge hook failed")
        report = {
            "kind": "wedge",
            "time": time.time(),
            "pid": os.getpid(),
            "program": info.get("program"),
            "family": info.get("family"),
            "seq": info.get("seq"),
            "avals": info.get("avals"),
            "expected_s": info.get("expected_s"),
            "deadline_s": info.get("deadline_s"),
            "elapsed_s": info.get("elapsed_s"),
            "stacks_file": str(stacks_path),
            "exit_code": WEDGE_EXIT_CODE if self.exit_on_wedge else None,
        }
        try:
            # Phase forensics: the newest progress-beacon row (None
            # unless beacons were armed) names where the hung program —
            # or its predecessor iteration — last reported.
            from .device_stats import last_beacon

            report["last_beacon"] = last_beacon(self.run_dir)
        except Exception:
            report["last_beacon"] = None
        write_wedge_report(self.run_dir / WEDGE_REPORT_FILENAME, report)
        if self.exit_on_wedge:
            # Flush logging/stdio by hand: _exit skips atexit and
            # buffered writers, and the report above is already durable.
            logging.shutdown()
            os._exit(WEDGE_EXIT_CODE)
        return report

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dispatch-watchdog", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def write_wedge_report(path: Path | str, report: dict) -> bool:
    """Atomic wedge-report write (tmp + replace); never raises."""
    path = Path(path)
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text(json.dumps(report, indent=2))
        tmp.replace(path)
        return True
    except OSError:
        logger.exception("wedge report write to %s failed", path)
        return False


# --- postmortem readers (no JAX import anywhere on this path) -----------


def resolve_flight_path(target: Path | str) -> Path:
    """Map a run dir / flight file path to the flight ring file."""
    target = Path(target)
    return target / FLIGHT_FILENAME if target.is_dir() else target


def read_flight(path: Path | str) -> list[dict]:
    """All parseable flight records across rotations, oldest first —
    the shared tolerant reader (`iter_jsonl_records`) + the ledger's
    rotation walk; torn tails and junk bytes are skipped, never raised."""
    out = []
    for p in ledger_paths(Path(path)):
        out.extend(iter_jsonl_records(p, kinds={"flight"}))
    return out


def read_wedge_report(path: Path | str) -> "dict | None":
    try:
        report = json.loads(Path(path).read_text())
        return report if isinstance(report, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def write_preempt_report(path: Path | str, report: dict) -> bool:
    """Atomic preempt-report write — same tmp+replace discipline (and
    never-raises contract) as the wedge report."""
    return write_wedge_report(path, report)


def read_preempt_report(path: Path | str) -> "dict | None":
    return read_wedge_report(path)


def unsealed_intents(records: list) -> list[dict]:
    """Intent records with no seal (any outcome) for their seq — the
    dispatches that were in flight when the process died."""
    sealed = {
        r.get("seq") for r in records if r.get("phase") == "seal"
    }
    return [
        r
        for r in records
        if r.get("phase") == "intent" and r.get("seq") not in sealed
    ]


def summarize_flight(records: list) -> list[dict]:
    """Per-program measured-dispatch summary rows from sealed records:
    count, wall p50/p95/total (seconds), family — newest expectation
    last. Rows sort by total wall, busiest program first (`cli perf`'s
    per-program table and `--json` `programs` field)."""
    from .perf import _percentile

    by_program: dict[str, list[float]] = {}
    family: dict[str, str] = {}
    errors: dict[str, int] = {}
    for r in records:
        if r.get("phase") != "seal":
            continue
        program = str(r.get("program"))
        family.setdefault(program, str(r.get("family")))
        if not r.get("ok", True):
            errors[program] = errors.get(program, 0) + 1
            continue
        wall = r.get("wall_s")
        if isinstance(wall, (int, float)):
            by_program.setdefault(program, []).append(float(wall))
    rows = []
    for program in set(by_program) | set(errors):
        walls = by_program.get(program, [])
        rows.append(
            {
                "program": program,
                "family": family.get(program, program_family(program)),
                "count": len(walls),
                "errors": errors.get(program, 0),
                "wall_s_p50": _percentile(walls, 0.50),
                "wall_s_p95": _percentile(walls, 0.95),
                "wall_s_total": round(sum(walls), 6) if walls else 0.0,
            }
        )
    rows.sort(key=lambda r: -r["wall_s_total"])
    return rows


def family_seconds(records: list) -> dict:
    """Per-family p50 measured dispatch seconds from sealed records —
    the per-program-family term the autotuner's `--calibrate` folds in
    (autotune/model.py)."""
    from .perf import _percentile

    by_family: dict[str, list[float]] = {}
    for r in records:
        if r.get("phase") != "seal" or not r.get("ok", True):
            continue
        wall = r.get("wall_s")
        if isinstance(wall, (int, float)):
            by_family.setdefault(str(r.get("family")), []).append(float(wall))
    return {
        fam: _percentile(walls, 0.50) for fam, walls in by_family.items()
    }


#: verdict -> `cli doctor` exit code (documented in OBSERVABILITY.md;
#: 1 is left to argparse/usage errors).
DOCTOR_EXIT_CODES = {
    "clean": 0,
    "never-started": 2,
    "compile-hung": 3,
    "dispatch-hung": 4,
    "host-stall": 5,
    "oom": 6,
    "preempted": 7,
}


def _memory_pressure(health: "dict | None", utils: list) -> "float | None":
    """Device memory utilization from the freshest evidence available:
    the last util record's gauge, else the heartbeat's device table."""
    for u in reversed(utils or []):
        frac = u.get("mem_utilization")
        if isinstance(frac, (int, float)):
            return float(frac)
    for mem in (health or {}).get("device_memory") or []:
        in_use, limit = mem.get("bytes_in_use"), mem.get("bytes_limit")
        if isinstance(in_use, (int, float)) and limit:
            return float(in_use) / float(limit)
    return None


def classify_run(
    flight_records: list,
    health: "dict | None" = None,
    utils: "list | None" = None,
    wedge: "dict | None" = None,
    now: "float | None" = None,
    preempt: "dict | None" = None,
    beacon: "dict | None" = None,
) -> dict:
    """Pure postmortem classifier over a run's on-disk evidence.

    Verdicts, strongest evidence first:

    - `dispatch-hung` / `compile-hung`: a wedge report, or an unsealed
      intent in the flight ring — the exact program is named; "compile"
      when that program never sealed before (its first dispatch, which
      includes the compile), "dispatch" when it had completed before.
    - `oom`: the hang/stall happened with device memory at >=92% of the
      limit — the wedge is a symptom, the allocator is the cause.
    - `host-stall`: every dispatch sealed but the heartbeat says the
      process stalled (or kept beating long after the last seal) — the
      device finished its work and the HOST stopped feeding it.
    - `preempted`: a preempt report is on disk — the loop absorbed a
      SIGTERM, emergency-checkpointed, and exited on purpose. Only a
      hang outranks it (a wedge mid-preemption is still a wedge).
    - `never-started`: no dispatch was ever attempted (no flight
      records) — death before the first dispatch (imports, init,
      checkpoint restore).
    - `clean`: all intents sealed, no stall evidence.

    `beacon` is the run's newest progress-beacon row (``last_beacon``,
    device_stats.py; the wedge report's embedded copy wins when both
    exist) — a hung verdict then carries it, naming the phase the
    wedged program last announced.

    Returns {verdict, exit_code, program, family, detail, evidence};
    hung verdicts add `last_beacon` when a beacon row exists.
    """
    records = flight_records or []
    seals_by_program: dict[str, int] = {}
    for r in records:
        if r.get("phase") == "seal" and r.get("ok", True):
            p = str(r.get("program"))
            seals_by_program[p] = seals_by_program.get(p, 0) + 1
    torn = unsealed_intents(records)
    pressure = _memory_pressure(health, utils or [])
    evidence = {
        "intents": sum(1 for r in records if r.get("phase") == "intent"),
        "seals": sum(1 for r in records if r.get("phase") == "seal"),
        "unsealed": len(torn),
        "mem_utilization": pressure,
        "wedge_report": wedge is not None,
        "preempt_report": preempt is not None,
        "stalled": bool((health or {}).get("stalled")),
    }

    def result(verdict, program=None, family=None, detail=""):
        return {
            "verdict": verdict,
            "exit_code": DOCTOR_EXIT_CODES[verdict],
            "program": program,
            "family": family,
            "detail": detail,
            "evidence": evidence,
        }

    hung = None  # (program, family, detail)
    if wedge is not None:
        program = str(wedge.get("program"))
        hung = (
            program,
            wedge.get("family") or program_family(program),
            "watchdog wedge report: in flight "
            f"{wedge.get('elapsed_s')}s past a "
            f"{wedge.get('deadline_s')}s deadline",
        )
    elif torn:
        intent = torn[-1]
        program = str(intent.get("program"))
        expected = intent.get("expected_s")
        hung = (
            program,
            intent.get("family") or program_family(program),
            "unsealed intent (seq "
            f"{intent.get('seq')}, avals {intent.get('avals')}, "
            f"expected {expected}s)",
        )
    if hung is not None:
        program, family, detail = hung
        # Phase forensics: prefer the beacon row the wedge report froze
        # at fire time; fall back to the caller-read beacons file.
        beacon_row = (wedge or {}).get("last_beacon") or beacon
        if isinstance(beacon_row, dict):
            from .device_stats import describe_beacon

            described = describe_beacon(beacon_row)
            if described:
                detail = f"{detail}; last beacon: {described}"
        if pressure is not None and pressure >= OOM_UTILIZATION:
            verdict_dict = result(
                "oom",
                program,
                family,
                f"{detail}; device memory at {pressure:.0%} of limit",
            )
        else:
            verdict = (
                "dispatch-hung"
                if seals_by_program.get(program, 0) > 0
                else "compile-hung"
            )
            verdict_dict = result(verdict, program, family, detail)
        if isinstance(beacon_row, dict):
            verdict_dict["last_beacon"] = beacon_row
        return verdict_dict
    if preempt is not None:
        ckpt = preempt.get("checkpointed_step")
        return result(
            "preempted",
            detail="preempt report: SIGTERM absorbed at step "
            f"{preempt.get('step')}, emergency checkpoint at step "
            f"{ckpt} — restart resumes there",
        )
    if not records:
        return result(
            "never-started",
            detail="no flight records: the run died before its first "
            "dispatch (imports, init, or checkpoint restore)",
        )
    if health is not None:
        if health.get("stalled"):
            if pressure is not None and pressure >= OOM_UTILIZATION:
                return result(
                    "oom",
                    detail="stall flagged with device memory at "
                    f"{pressure:.0%} of limit",
                )
            return result(
                "host-stall",
                detail="every dispatch sealed but the watchdog flagged "
                "a stall — the host stopped feeding the device",
            )
        deadline = float(health.get("watchdog_deadline_s") or 300.0)
        last_seal_t = max(
            (
                r.get("time")
                for r in records
                if r.get("phase") == "seal"
                and isinstance(r.get("time"), (int, float))
            ),
            default=None,
        )
        beat_t = health.get("time")
        if (
            last_seal_t is not None
            and isinstance(beat_t, (int, float))
            and beat_t - last_seal_t > 2 * deadline
        ):
            return result(
                "host-stall",
                detail="heartbeat kept beating "
                f"{beat_t - last_seal_t:.0f}s past the last sealed "
                "dispatch — the host loop ran without dispatching",
            )
    return result("clean", detail="every recorded dispatch sealed")
