"""Fleet trace merge: one Perfetto timeline from router to chip.

Every process in a fleet run writes its own observability artifacts —
the JAX-free parent a `fleet.jsonl` + `flight.jsonl` (its `fleet/route`
brackets), each replica a `trace.json` span ring + its own
`flight.jsonl` (`serve/b<B>` dispatch brackets). This module fuses
them into ONE Chrome/Perfetto trace (`cli trace <run> --fleet`) with:

- **per-process lanes** — the parent and every replica incarnation get
  their own pid group with `process_name` metadata; concurrent
  `fleet/route` spans are laid onto a minimal set of synthetic router
  lanes (greedy interval packing) so overlapping requests never stack
  on one track;
- **clock alignment** — flight records carry `(t_mono, time)` pairs
  and replicas report the same pair at ready/ping, so each process's
  monotonic clock is calibrated onto the shared wall clock by
  `offset = median(time - t_mono)` over that process's samples. Span
  *placement* uses calibrated monotonic time and span *duration* uses
  monotonic deltas, so deliberately skewed monotonic epochs (the
  clock-skew test) cannot produce negative durations or acausal
  ordering;
- **flow arrows** — the trace_id minted per routed request
  (telemetry/tracectx.py) links the parent's `fleet/route` span to the
  replica spans that served it (`replica/episode` tracer spans and
  `serve/b<B>` flight brackets whose `trace_ids` name the wave), drawn
  as Chrome flow events (`ph: s/t/f`) so Perfetto renders router ->
  replica arrows per request;
- **lifecycle instants** — shed/retry/hedge/death/respawn events from
  `fleet.jsonl` land as instants on the parent's lifecycle lane, each
  carrying its trace_id.

All readers are tolerant: legacy id-less records merge fine (they just
draw no arrows), a replica SIGKILLed before exporting its trace.json
still contributes its flight-ring spans, and a missing artifact skips
that lane rather than failing the merge. JAX-free by construction —
the merge runs beside a dead fleet, like `cli doctor`.
"""

import json
import logging
from collections import defaultdict
from pathlib import Path

from .flight import FLIGHT_FILENAME, read_flight

logger = logging.getLogger(__name__)

MERGED_TRACE_FILENAME = "trace_fleet.json"

#: cat shared by every flow event of the merge — the smoke greps it.
FLOW_CAT = "fleet-flow"

_PARENT_PID_FALLBACK = 1


def _median_offset(samples: list) -> "float | None":
    """median(wall - mono) over (t_mono, time) pairs — one process's
    monotonic->wall calibration constant. Median, not mean: a single
    sample taken across a descheduling blip must not tilt the lane."""
    diffs = sorted(
        float(w) - float(m)
        for m, w in samples
        if isinstance(m, (int, float)) and isinstance(w, (int, float))
    )
    if not diffs:
        return None
    return diffs[len(diffs) // 2]


def _pair_flight(records: list) -> "tuple[list, list]":
    """(sealed intent/seal pairs, unsealed intents) from one flight
    ring, tolerant of legacy and torn records."""
    intents: dict = {}
    pairs = []
    for r in records:
        phase = r.get("phase")
        if phase == "intent":
            intents[r.get("seq")] = r
        elif phase == "seal":
            intent = intents.pop(r.get("seq"), None)
            if intent is not None:
                pairs.append((intent, r))
    return pairs, list(intents.values())


def _clock_samples(records: list) -> dict:
    """pid -> [(t_mono, time)] calibration samples from flight records
    (seals inherit their intent's pid via the pair walk)."""
    samples: dict = defaultdict(list)
    pairs, torn = _pair_flight(records)
    for intent, seal in pairs:
        pid = intent.get("pid")
        samples[pid].append((intent.get("t_mono"), intent.get("time")))
        samples[pid].append((seal.get("t_mono"), seal.get("time")))
    for intent in torn:
        samples[intent.get("pid")].append(
            (intent.get("t_mono"), intent.get("time"))
        )
    return samples


def _assign_lanes(spans: list) -> list:
    """Greedy interval packing: returns one lane index per (ts, dur)
    span so overlapping spans never share a lane (Chrome complete
    events on one tid must nest, and concurrent routed requests
    don't)."""
    order = sorted(range(len(spans)), key=lambda i: spans[i][0])
    lane_end: list = []
    lanes = [0] * len(spans)
    for i in order:
        ts, dur = spans[i]
        for lane, end in enumerate(lane_end):
            if ts >= end:
                lane_end[lane] = ts + dur
                lanes[i] = lane
                break
        else:
            lane_end.append(ts + dur)
            lanes[i] = len(lane_end) - 1
    return lanes


def _flight_lane_events(
    records: list,
    *,
    pid: int,
    tid_base: int,
    offsets: dict,
    span_index: "dict | None" = None,
    lane_pack: bool = False,
):
    """Chrome events for one process's flight ring: calibrated complete
    spans for sealed pairs, instants for unsealed intents. When
    `span_index` is given, every span with trace ids registers itself
    there (trace_id -> [(pid, tid, ts_us, dur_us)]) for flow drawing."""
    pairs, torn = _pair_flight(records)
    placed = []
    for intent, seal in pairs:
        rec_pid = intent.get("pid", pid)
        offset = offsets.get(rec_pid)
        t_mono = intent.get("t_mono")
        if offset is not None and isinstance(t_mono, (int, float)):
            ts = float(t_mono) + offset
        else:
            ts = float(intent.get("time") or 0.0)
        dur = max(
            0.0,
            float(seal.get("t_mono") or 0.0) - float(t_mono or 0.0),
        )
        placed.append((intent, seal, ts, dur))
    lanes = (
        _assign_lanes([(ts, dur) for _, _, ts, dur in placed])
        if lane_pack
        else None
    )
    events = []
    max_tid = tid_base
    for i, (intent, seal, ts, dur) in enumerate(placed):
        rec_pid = intent.get("pid", pid) or pid
        tid = tid_base + (lanes[i] if lanes is not None else 0)
        max_tid = max(max_tid, tid)
        ts_us = int(ts * 1e6)
        dur_us = int(dur * 1e6)
        args = {
            "family": intent.get("family"),
            "seq": intent.get("seq"),
            "ok": seal.get("ok", True),
        }
        trace_ids = []
        for key in ("trace_id", "span_id", "parent_id"):
            if intent.get(key):
                args[key] = intent[key]
        if intent.get("trace_id"):
            trace_ids.append(str(intent["trace_id"]))
        if isinstance(intent.get("trace_ids"), list):
            args["trace_ids"] = intent["trace_ids"]
            trace_ids.extend(str(t) for t in intent["trace_ids"])
        if intent.get("avals"):
            args["avals"] = intent["avals"]
        events.append(
            {
                "name": str(intent.get("program")),
                "ph": "X",
                "ts": ts_us,
                "dur": dur_us,
                "pid": rec_pid,
                "tid": tid,
                "cat": "flight",
                "args": args,
            }
        )
        if span_index is not None:
            for trace_id in trace_ids:
                span_index[trace_id].append((rec_pid, tid, ts_us, dur_us))
    for intent in torn:
        offset = offsets.get(intent.get("pid", pid))
        t_mono = intent.get("t_mono")
        if offset is not None and isinstance(t_mono, (int, float)):
            ts = float(t_mono) + offset
        else:
            ts = float(intent.get("time") or 0.0)
        events.append(
            {
                "name": f"unsealed:{intent.get('program')}",
                "ph": "i",
                "s": "t",
                "ts": int(ts * 1e6),
                "pid": intent.get("pid", pid) or pid,
                "tid": tid_base,
                "cat": "flight",
                "args": {
                    k: intent[k]
                    for k in ("seq", "family", "trace_id")
                    if intent.get(k) is not None
                },
            }
        )
    return events, max_tid


def _load_trace_events(path: Path) -> list:
    """traceEvents from one replica's trace.json (object or bare-array
    form); [] when missing/corrupt — a SIGKILLed replica never exported
    one, and its flight ring still draws the lane."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return []
    events = data.get("traceEvents") if isinstance(data, dict) else data
    return [e for e in (events or []) if isinstance(e, dict)]


def merge_fleet_trace(
    run_dir: "Path | str", out_path: "Path | str | None" = None
) -> dict:
    """Fuse a fleet-parent run dir into one Perfetto trace file.

    Returns a summary dict: output path, per-lane event counts, flow
    arrow count, the distinct trace_ids linked across processes, and
    the per-process clock offsets used. Raises FileNotFoundError when
    the dir shows no fleet evidence (no fleet.jsonl) — `cli trace
    --fleet` maps that to exit 1.
    """
    from ..serving.fleet import FLEET_FILENAME, read_fleet_events

    run_dir = Path(run_dir)
    if not (run_dir / FLEET_FILENAME).exists():
        raise FileNotFoundError(
            f"{run_dir / FLEET_FILENAME} not found — not a fleet-parent "
            "run dir"
        )
    out_path = (
        Path(out_path) if out_path else run_dir / MERGED_TRACE_FILENAME
    )
    fleet_events = read_fleet_events(run_dir)
    parent_flight = read_flight(run_dir / FLIGHT_FILENAME)

    # --- clock calibration: pid -> median(wall - mono) ----------------
    samples = _clock_samples(parent_flight)
    replica_dirs = sorted(
        p for p in run_dir.glob("replica_*") if p.is_dir()
    )
    replica_flight: dict = {}
    for rdir in replica_dirs:
        records = read_flight(rdir / FLIGHT_FILENAME)
        replica_flight[rdir.name] = records
        for pid, pairs in _clock_samples(records).items():
            samples[pid].extend(pairs)
        try:
            health = json.loads((rdir / "health.json").read_text())
            samples[health.get("pid")].append(
                (health.get("monotonic"), health.get("time"))
            )
        except (OSError, ValueError):
            pass
    # Replica ready lines, ledgered by the parent with the replica's
    # own clock pair — the calibration source that exists even for an
    # incarnation whose ring stayed empty.
    for e in fleet_events:
        if e.get("event") == "replica-ready" and e.get("replica_pid"):
            samples[e.get("replica_pid")].append(
                (e.get("t_mono"), e.get("replica_time"))
            )
    offsets = {
        pid: off
        for pid, off in (
            (pid, _median_offset(pairs)) for pid, pairs in samples.items()
        )
        if off is not None
    }

    events: list = []
    meta: list = []
    # trace_id -> [(pid, tid, ts_us, dur_us)] of parent route spans.
    route_index: dict = defaultdict(list)
    # trace_id -> [(pid, tid, ts_us, dur_us)] of replica-side spans.
    replica_index: dict = defaultdict(list)

    # --- parent lane ---------------------------------------------------
    parent_pid = next(
        (
            e.get("pid")
            for e in fleet_events
            if isinstance(e.get("pid"), int)
        ),
        None,
    ) or next(
        (
            r.get("pid")
            for r in parent_flight
            if isinstance(r.get("pid"), int)
        ),
        _PARENT_PID_FALLBACK,
    )
    meta.append(
        {
            "name": "process_name",
            "ph": "M",
            "pid": parent_pid,
            "args": {"name": f"fleet parent ({run_dir.name})"},
        }
    )
    route_events, max_router_tid = _flight_lane_events(
        parent_flight,
        pid=parent_pid,
        tid_base=1,
        offsets=offsets,
        span_index=route_index,
        lane_pack=True,
    )
    events.extend(route_events)
    for tid in range(1, max_router_tid + 1):
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": parent_pid,
                "tid": tid,
                "args": {"name": f"router lane {tid - 1}"},
            }
        )
    lifecycle_tid = max_router_tid + 1
    meta.append(
        {
            "name": "thread_name",
            "ph": "M",
            "pid": parent_pid,
            "tid": lifecycle_tid,
            "args": {"name": "fleet lifecycle"},
        }
    )
    for e in fleet_events:
        t = e.get("time")
        if not isinstance(t, (int, float)):
            continue
        args = {
            k: e[k]
            for k in (
                "replica",
                "rejection",
                "verdict",
                "attempt",
                "primary",
                "backup",
                "trace_id",
                "request_kind",
            )
            if e.get(k) is not None
        }
        events.append(
            {
                "name": f"fleet/{e.get('event')}",
                "ph": "i",
                "s": "t",
                "ts": int(float(t) * 1e6),
                "pid": parent_pid,
                "tid": lifecycle_tid,
                "cat": "fleet",
                "args": args,
            }
        )

    # --- replica lanes --------------------------------------------------
    for rdir in replica_dirs:
        records = replica_flight.get(rdir.name, [])
        lane_pids = sorted(
            {
                r.get("pid")
                for r in records
                if r.get("phase") == "intent"
                and isinstance(r.get("pid"), int)
            }
        )
        tracer_events = _load_trace_events(rdir / "trace.json")
        tracer_pids = {
            e.get("pid")
            for e in tracer_events
            if isinstance(e.get("pid"), int)
        }
        for pid in sorted(set(lane_pids) | tracer_pids):
            meta.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": f"replica {rdir.name} (pid {pid})"},
                }
            )
        if records:
            flight_events, _ = _flight_lane_events(
                records,
                pid=lane_pids[0] if lane_pids else _PARENT_PID_FALLBACK,
                tid_base=0,
                offsets=offsets,
                span_index=replica_index,
            )
            events.extend(flight_events)
        for ev in tracer_events:
            events.append(ev)
            args = ev.get("args") or {}
            trace_id = args.get("trace_id")
            if ev.get("ph") == "X" and trace_id:
                replica_index[str(trace_id)].append(
                    (
                        ev.get("pid"),
                        ev.get("tid"),
                        int(ev.get("ts") or 0),
                        int(ev.get("dur") or 0),
                    )
                )

    # --- flow arrows: router span -> replica spans ----------------------
    flows = 0
    flow_trace_ids = []
    for trace_id, targets in sorted(replica_index.items()):
        sources = route_index.get(trace_id)
        if not sources:
            continue
        src = min(sources, key=lambda s: s[2])
        flow_trace_ids.append(trace_id)
        events.append(
            {
                "name": "route",
                "ph": "s",
                "id": trace_id,
                "ts": src[2],
                "pid": src[0],
                "tid": src[1],
                "cat": FLOW_CAT,
            }
        )
        ordered = sorted(targets, key=lambda t: t[2])
        floor_ts = src[2]
        for j, (pid, tid, ts_us, _dur) in enumerate(ordered):
            # Flow steps must be non-decreasing in ts; clamping keeps a
            # calibration-residual jitter from breaking causal order.
            floor_ts = max(floor_ts, ts_us)
            events.append(
                {
                    "name": "route",
                    "ph": "t" if j < len(ordered) - 1 else "f",
                    "bp": "e",
                    "id": trace_id,
                    "ts": floor_ts,
                    "pid": pid,
                    "tid": tid,
                    "cat": FLOW_CAT,
                }
            )
            flows += 1

    payload = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merge": "alphatriangle.fleet.v1",
            "run_dir": str(run_dir),
            "clock_offsets": {
                str(pid): round(off, 6) for pid, off in offsets.items()
            },
        },
    }
    out_path.parent.mkdir(parents=True, exist_ok=True)
    tmp = out_path.with_suffix(out_path.suffix + ".tmp")
    tmp.write_text(json.dumps(payload))
    tmp.replace(out_path)
    return {
        "path": str(out_path),
        "events": len(events),
        "processes": len(
            {m["pid"] for m in meta if m["name"] == "process_name"}
        ),
        "replicas": len(replica_dirs),
        "route_spans": sum(len(v) for v in route_index.values()),
        "flows": flows,
        "flow_trace_ids": flow_trace_ids,
        "clock_offsets": {
            str(pid): round(off, 6) for pid, off in offsets.items()
        },
    }
