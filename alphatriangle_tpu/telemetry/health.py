"""Run liveness: heartbeat file + stall watchdog.

The round-5 baseline recorded a 10.3-hour window in which nothing
progressed and nothing said so (BASELINE.md). This module turns that
silent failure mode into a diagnosable artifact:

- `HealthMonitor`: subsystems beat it (learner step landed, rollout
  harvest folded) with O(1) lock-guarded field updates off the device
  path; each loop tick it writes `health.json` into the run dir — last
  learner step, last-progress ages, buffer size, per-device memory via
  `jax.local_devices()[*].memory_stats()`, wall + monotonic stamps.
  Written atomically, readable by processes that never import JAX
  (`alphatriangle-tpu health`, `cli watch`, the bench supervisor).
- `Watchdog`: a daemon thread that compares monotonic now against the
  last recorded progress; past the deadline it fires ONCE per stall —
  dumping every thread's stack via `faulthandler` into the run dir,
  marking the heartbeat stalled, and running a caller hook (metric +
  span-buffer flush, wired in `RunTelemetry`) — then re-arms when
  progress resumes. The clock is injectable so tests freeze it.

File readers: a heartbeat older than the deadline means the *process*
is dead or wedged (even the tick loop stopped); a fresh heartbeat with
`stalled: true` means the process is alive but neither the learner nor
the producers have made progress for a deadline.
"""

import faulthandler
import json
import logging
import os
import threading
import time
from pathlib import Path

logger = logging.getLogger(__name__)


def _host_ram_bytes() -> "int | None":
    """Total host RAM (the XLA:CPU 'device' allocates from it)."""
    try:
        return os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return None


def device_memory_stats() -> list[dict]:
    """Per-device memory snapshot. Imports jax lazily so heartbeat
    READERS never pay for (or hang on) accelerator init.

    Accelerator backends report through the allocator
    (`device.memory_stats()`: bytes_in_use / peak_bytes_in_use /
    bytes_limit). XLA:CPU reports nothing there, so the CPU fallback
    synthesizes `bytes_in_use` from `jax.live_arrays()` (exact array
    bytes, no allocator slop; `source: "live_arrays"`) with host RAM as
    the limit — which is what makes the whole memory-observability
    pipeline exercisable in tier-1. Peak is left to the meter's
    high-water tracker (telemetry/perf.py)."""
    try:
        import jax

        out = []
        devices = jax.local_devices()
        for d in devices:
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            out.append(
                {
                    "device": d.id,
                    "kind": getattr(d, "device_kind", d.platform),
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                    "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                }
            )
        if out or not devices:
            return out
        # No device reported an allocator: synthesize from live arrays.
        in_use = {d.id: 0 for d in devices}
        for a in jax.live_arrays():
            try:
                devs = [d for d in a.devices() if d.id in in_use]
                nbytes = int(a.nbytes)
            except Exception:
                continue
            if not devs:
                continue
            share = nbytes // len(devs)
            for d in devs:
                in_use[d.id] += share
        ram = _host_ram_bytes()
        return [
            {
                "device": d.id,
                "kind": getattr(d, "device_kind", d.platform),
                "bytes_in_use": in_use[d.id],
                "bytes_limit": ram if d.platform == "cpu" else None,
                "peak_bytes_in_use": None,
                "source": "live_arrays",
            }
            for d in devices
        ]
    except Exception:
        return []


class HealthMonitor:
    """Lock-guarded liveness state + atomic `health.json` writer."""

    def __init__(
        self,
        path: Path,
        deadline_s: float = 300.0,
        run_name: str = "",
        clock=time.monotonic,
    ) -> None:
        self.path = Path(path)
        self.deadline_s = deadline_s
        self.run_name = run_name
        self._clock = clock
        self._lock = threading.Lock()
        self._started = clock()
        self._learner_step = 0
        self._last_learner: float | None = None
        self._last_rollout: float | None = None
        self._buffer_size = 0
        self._episodes = 0
        self._experiences = 0
        self._stalled = False
        self._stall_count = 0
        # Device identity + live utilization (telemetry/perf.py): the
        # heartbeat carries what the chip is and how hard it is being
        # driven, so `cli health` answers "alive AND useful?".
        self._device_kind: str | None = None
        self._peak_tflops: float | None = None
        self._peak_source: str | None = None
        self._utilization: dict | None = None

    # --- beats (any thread, O(1)) -------------------------------------

    def note_learner_step(self, step: int) -> None:
        with self._lock:
            self._learner_step = step
            self._last_learner = self._clock()

    def note_rollout(self, experiences: int = 0, episodes: int = 0) -> None:
        with self._lock:
            self._last_rollout = self._clock()
            self._experiences += experiences
            self._episodes += episodes

    def note_buffer(self, size: int) -> None:
        with self._lock:
            self._buffer_size = size

    def set_device_info(
        self,
        device_kind: str,
        peak_tflops: float | None,
        peak_source: str | None = None,
    ) -> None:
        with self._lock:
            self._device_kind = device_kind
            self._peak_tflops = peak_tflops
            self._peak_source = peak_source

    def note_utilization(self, record: dict) -> None:
        """Latest derived utilization record (telemetry/perf.py); the
        heartbeat carries a trimmed copy."""
        keep = (
            "step",
            "learner_steps_per_sec",
            "step_time_ms",
            "moves_per_sec",
            "games_per_hour",
            "tflops_per_sec",
            "mfu",
            "buffer_fill",
            "transfer_h2d_ms",
            "transfer_d2h_ms",
            "compile_cache_hit_rate",
            "mem_bytes_in_use",
            "mem_peak_bytes_in_use",
            "mem_bytes_limit",
            "mem_utilization",
            # Policy-service SLO fields (serving/service.py): the serve
            # heartbeat answers "alive AND inside latency budget?".
            "serve_sessions",
            "serve_queue_depth",
            "serve_requests_per_sec",
            "serve_move_latency_ms_p50",
            "serve_move_latency_ms_p95",
            "serve_queue_wait_ms_p95",
            "serve_batch_fill",
            "serve_weight_reloads",
        )
        trimmed = {k: record.get(k) for k in keep if k in record}
        with self._lock:
            self._utilization = trimmed

    def set_stalled(self, stalled: bool) -> None:
        with self._lock:
            if stalled and not self._stalled:
                self._stall_count += 1
            self._stalled = stalled

    # --- queries ------------------------------------------------------

    def last_progress(self) -> float:
        """Monotonic time of the most recent learner/rollout progress
        (run start before either has happened)."""
        with self._lock:
            return max(
                self._started,
                self._last_learner or self._started,
                self._last_rollout or self._started,
            )

    def snapshot(self) -> dict:
        """The heartbeat payload (ages computed at snapshot time)."""
        now = self._clock()
        with self._lock:
            return {
                "run": self.run_name,
                "pid": os.getpid(),
                "time": time.time(),
                "monotonic": now,
                "uptime_s": round(now - self._started, 3),
                "learner_step": self._learner_step,
                "learner_age_s": (
                    round(now - self._last_learner, 3)
                    if self._last_learner is not None
                    else None
                ),
                "rollout_age_s": (
                    round(now - self._last_rollout, 3)
                    if self._last_rollout is not None
                    else None
                ),
                "buffer_size": self._buffer_size,
                "episodes_played": self._episodes,
                "experiences_added": self._experiences,
                "stalled": self._stalled,
                "stall_count": self._stall_count,
                "watchdog_deadline_s": self.deadline_s,
                "device_kind": self._device_kind,
                "peak_bf16_tflops": self._peak_tflops,
                "peak_source": self._peak_source,
                "utilization": self._utilization,
                "device_memory": device_memory_stats(),
            }

    def write(self) -> None:
        """Atomic heartbeat write; failures logged, never raised."""
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            tmp = self.path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(self.snapshot(), indent=2))
            tmp.replace(self.path)
        except OSError:
            logger.exception("heartbeat write to %s failed", self.path)


class Watchdog:
    """Fires once per stall when no progress beats for `deadline_s`."""

    def __init__(
        self,
        health: HealthMonitor,
        deadline_s: float,
        poll_s: float = 10.0,
        on_stall=None,
        on_recover=None,
        clock=time.monotonic,
    ) -> None:
        self.health = health
        self.deadline_s = deadline_s
        self.poll_s = poll_s
        self.on_stall = on_stall
        self.on_recover = on_recover
        self._clock = clock
        self._stalled = False
        self.stall_count = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def check(self, now: float | None = None) -> bool:
        """One stall evaluation; returns whether currently stalled.
        Called by the poll thread, and directly by tests (frozen clock).
        """
        now = self._clock() if now is None else now
        age = now - self.health.last_progress()
        if age > self.deadline_s:
            if not self._stalled:
                self._stalled = True
                self.stall_count += 1
                self.health.set_stalled(True)
                logger.warning(
                    "Watchdog: no learner/rollout progress for %.0fs "
                    "(deadline %.0fs).",
                    age,
                    self.deadline_s,
                )
                if self.on_stall is not None:
                    try:
                        self.on_stall(age)
                    except Exception:
                        logger.exception("watchdog on_stall hook failed")
        elif self._stalled:
            self._stalled = False
            self.health.set_stalled(False)
            logger.info("Watchdog: progress resumed; stall cleared.")
            if self.on_recover is not None:
                try:
                    self.on_recover()
                except Exception:
                    logger.exception("watchdog on_recover hook failed")
        return self._stalled

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-watchdog", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def dump_thread_stacks(path: Path) -> None:
    """Append every thread's current stack to `path` (faulthandler)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("a") as f:
        f.write(
            f"=== stall at {time.strftime('%Y-%m-%d %H:%M:%S')} "
            f"(pid {os.getpid()}) ===\n"
        )
        faulthandler.dump_traceback(file=f, all_threads=True)
        f.write("\n")


# --- heartbeat readers (no JAX import anywhere on this path) ------------


def read_health(path: Path) -> dict | None:
    """Parse a heartbeat file; None when missing or torn."""
    try:
        return json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError):
        return None


def health_verdict(
    payload: dict,
    now: float | None = None,
    deadline_s: float | None = None,
) -> tuple[bool, float, str]:
    """(live, heartbeat_age_s, reason) for a heartbeat payload.

    Stale heartbeat => the writing process is dead or fully wedged;
    fresh heartbeat with `stalled` set => alive but making no progress.
    Either way the run needs attention (CLI exits non-zero).
    """
    now = time.time() if now is None else now
    deadline = (
        deadline_s
        if deadline_s is not None
        else float(payload.get("watchdog_deadline_s") or 300.0)
    )
    age = max(0.0, now - float(payload.get("time") or 0.0))
    if age > deadline:
        return False, age, f"no heartbeat for {age:.0f}s"
    if payload.get("stalled"):
        return False, age, "watchdog flagged a stall (no training progress)"
    return True, age, "live"


# One probe implementation shared by `cli health --probe`, the fleet
# router's admission gate (serving/fleet.py), and external orchestrators
# (k8s-style readiness): exit-code contract in docs/OBSERVABILITY.md.
PROBE_LIVE = 0
PROBE_UNHEALTHY = 1  # stale heartbeat or watchdog-flagged stall
PROBE_MISSING = 2  # no readable health.json
PROBE_DISPATCH_OVERDUE = 3  # unsealed flight intent past its deadline


def probe_run(
    run_dir: Path,
    now: float | None = None,
    deadline_s: float | None = None,
    dispatch_slack_s: float = 2.0,
) -> dict:
    """Machine-readable liveness probe for one run dir (no JAX).

    Combines the two independent death signals this repo records:
    heartbeat freshness (`health.json`, written by RunTelemetry) and
    the flight ring's unsealed-intent-past-deadline check — a process
    can heartbeat happily from a side thread while its dispatch thread
    is wedged inside a device program, and only the flight ring sees
    that. Returns a one-line-JSON-able payload whose `code` field is
    the process exit code contract above; `dispatch_slack_s` grace
    keeps the probe from racing the in-process DispatchWatchdog."""
    from .flight import FLIGHT_FILENAME, read_flight, unsealed_intents

    run_dir = Path(run_dir)
    now = time.time() if now is None else now
    out: dict = {
        "schema": "alphatriangle.probe.v1",
        "run_dir": str(run_dir),
        "time": now,
    }
    payload = read_health(run_dir / "health.json")
    if payload is None:
        out.update(
            code=PROBE_MISSING,
            verdict="missing",
            reason="no readable health.json",
            heartbeat_age_s=None,
        )
        return out
    live, age, reason = health_verdict(payload, now=now, deadline_s=deadline_s)
    out.update(
        heartbeat_age_s=round(age, 3),
        pid=payload.get("pid"),
        stalled=bool(payload.get("stalled")),
    )
    overdue = []
    health_pid = payload.get("pid")
    for intent in unsealed_intents(read_flight(run_dir / FLIGHT_FILENAME)):
        intent_deadline = intent.get("deadline_s")
        intent_t = intent.get("time")
        if intent_deadline is None or intent_t is None:
            continue
        # A dead incarnation's unsealed intent is the doctor's death
        # evidence, not a verdict on the CURRENT process: without this
        # pid gate a respawned replica would probe dispatch-overdue
        # forever on its predecessor's wedge confession.
        intent_pid = intent.get("pid")
        if (
            health_pid is not None
            and intent_pid is not None
            and intent_pid != health_pid
        ):
            continue
        intent_age = now - float(intent_t)
        if intent_age > float(intent_deadline) + dispatch_slack_s:
            overdue.append(
                {
                    "program": intent.get("program"),
                    "seq": intent.get("seq"),
                    "age_s": round(intent_age, 3),
                    "deadline_s": float(intent_deadline),
                }
            )
    out["overdue"] = overdue
    if overdue:
        out.update(
            code=PROBE_DISPATCH_OVERDUE,
            verdict="dispatch-overdue",
            reason=(
                f"unsealed dispatch past deadline: {overdue[0]['program']} "
                f"({overdue[0]['age_s']:.1f}s > {overdue[0]['deadline_s']:.0f}s)"
            ),
        )
    elif not live:
        out.update(
            code=PROBE_UNHEALTHY,
            verdict="stalled" if payload.get("stalled") else "stale",
            reason=reason,
        )
    else:
        out.update(code=PROBE_LIVE, verdict="live", reason=reason)
    return out
