"""Search space for the fit-driven autotuner (docs/AUTOTUNE.md).

A candidate is one point in the
`(SELF_PLAY_BATCH_SIZE, BUFFER_CAPACITY, rollout chunk T, fused K, dp,
geometry preset)` space the ROADMAP names. Everything here is pure
config math — no JAX import, so candidate enumeration and gate
pruning run instantly and are unit-testable without a backend.

Two prune families run before any expensive feasibility work:

- **Divisibility gates** mirror `sharded_megastep_dp`
  (telemetry/memory.py) and the training-time buffer gate
  (training/setup.py): a dp-sharded candidate whose capacity / learner
  batch / lane count does not divide dp would silently fall back to
  the single-device program at run time, so the search refuses to
  score it as a dp candidate at all.
- **Monotone-in-B dominance**: with every other axis fixed, both the
  composed memory budget and the predicted throughput are monotone
  non-decreasing in the lane count B (throughput model:
  autotune/model.py; memory: more lanes = strictly more rollout
  residency and transient). So within a group only the LARGEST
  feasible B can win — the search walks B descending and marks the
  rest dominated without ever consulting the feasibility oracle.
"""

from dataclasses import dataclass, field

# Row statuses the search assigns to candidates (stdout table + JSON).
STATUS_FIT = "fit"  # oracle-confirmed feasible
STATUS_OVER = "over"  # oracle says over the byte limit
STATUS_GATE = "gate"  # failed a divisibility/geometry gate
STATUS_DOMINATED = "dominated"  # smaller B than a feasible sibling
STATUS_RING = "ring-over"  # ring math alone exceeds the limit
STATUS_SKIPPED = "skipped"  # search ended before evaluation


@dataclass(frozen=True)
class Candidate:
    """One point in the autotuner's search space."""

    geometry: str  # named board geometry (config/presets.py)
    sp_batch: int  # SELF_PLAY_BATCH_SIZE (lockstep lanes)
    capacity: int  # BUFFER_CAPACITY (replay ring rows)
    chunk: int  # ROLLOUT_CHUNK_MOVES (T)
    fused_k: int  # FUSED_LEARNER_STEPS (K)
    dp: int  # data-parallel mesh width tuned for

    def group_key(self) -> tuple:
        """Axes held fixed under monotone-in-B dominance."""
        return (self.geometry, self.capacity, self.chunk, self.fused_k, self.dp)

    def label(self) -> str:
        return (
            f"{self.geometry}/B{self.sp_batch}/cap{self.capacity}"
            f"/t{self.chunk}/k{self.fused_k}/dp{self.dp}"
        )


@dataclass
class SearchSpace:
    """Axis values the tuner enumerates (geometry names must exist in
    `config.presets.GEOMETRY_PRESETS` or equal the sentinel "plan",
    meaning the resolved bench plan's own board)."""

    geometries: list = field(default_factory=lambda: ["plan"])
    batches: list = field(default_factory=lambda: [256, 512, 1024])
    capacities: list = field(default_factory=lambda: [50_000, 100_000])
    chunks: list = field(default_factory=lambda: [8, 16])
    fused_ks: list = field(default_factory=lambda: [8, 16])
    dps: list = field(default_factory=lambda: [1])

    def candidates(self) -> list:
        """Every lattice point, B descending within each group so the
        dominance walk can early-exit on the first feasible lane count."""
        out = []
        for geometry in self.geometries:
            for capacity in sorted({int(c) for c in self.capacities}):
                for chunk in sorted({int(t) for t in self.chunks}):
                    for k in sorted({int(k) for k in self.fused_ks}):
                        for dp in sorted({int(d) for d in self.dps}):
                            for b in sorted(
                                {int(b) for b in self.batches}, reverse=True
                            ):
                                out.append(
                                    Candidate(
                                        geometry=geometry,
                                        sp_batch=b,
                                        capacity=capacity,
                                        chunk=chunk,
                                        fused_k=k,
                                        dp=dp,
                                    )
                                )
        return out

    def size(self) -> int:
        return (
            len(self.geometries)
            * len({int(b) for b in self.batches})
            * len({int(c) for c in self.capacities})
            * len({int(t) for t in self.chunks})
            * len({int(k) for k in self.fused_ks})
            * len({int(d) for d in self.dps})
        )


def divisibility_gate(
    candidate: Candidate, lbatch: int, min_buffer: int
) -> "str | None":
    """Reason string when a candidate fails a hard config gate, else
    None. Mirrors `sharded_megastep_dp` (telemetry/memory.py) plus the
    TrainConfig validators, so gated candidates are exactly the ones a
    run would reject or silently de-shard."""
    c = candidate
    if c.sp_batch < 1 or c.capacity < 1 or c.chunk < 1 or c.fused_k < 1:
        return "non-positive axis"
    if lbatch > c.capacity:
        return f"BATCH_SIZE {lbatch} > BUFFER_CAPACITY {c.capacity}"
    if min_buffer > c.capacity:
        return (
            f"MIN_BUFFER_SIZE_TO_TRAIN {min_buffer} > "
            f"BUFFER_CAPACITY {c.capacity}"
        )
    if c.dp > 1:
        # The sharded-megastep gate: every sharded dimension must
        # divide dp or the run falls back to the single-device family.
        for name, value in (
            ("BUFFER_CAPACITY", c.capacity),
            ("BATCH_SIZE", lbatch),
            ("SELF_PLAY_BATCH_SIZE", c.sp_batch),
        ):
            if value % c.dp != 0:
                return f"{name} {value} % dp {c.dp} != 0"
    return None


def prune_dominated(candidates: list, feasible: set) -> dict:
    """{candidate: status} marking every candidate whose group already
    holds a feasible sibling with a larger-or-equal B as dominated.

    `feasible` is the set of candidates the oracle confirmed. Used by
    the search to label rows; the search itself never oracle-checks a
    candidate once a bigger sibling fit (monotone-in-B dominance)."""
    best_b: dict = {}
    for c in feasible:
        key = c.group_key()
        if key not in best_b or c.sp_batch > best_b[key]:
            best_b[key] = c.sp_batch
    out = {}
    for c in candidates:
        top = best_b.get(c.group_key())
        if top is not None and c.sp_batch < top:
            out[c] = STATUS_DOMINATED
    return out
