"""Search space for the fit-driven autotuner (docs/AUTOTUNE.md).

A candidate is one point in the
`(SELF_PLAY_BATCH_SIZE, BUFFER_CAPACITY, rollout chunk T, fused K, dp,
geometry preset)` space the ROADMAP names. Everything here is pure
config math — no JAX import, so candidate enumeration and gate
pruning run instantly and are unit-testable without a backend.

Two prune families run before any expensive feasibility work:

- **Divisibility gates** mirror `sharded_megastep_dp`
  (telemetry/memory.py) and the training-time buffer gate
  (training/setup.py): a dp-sharded candidate whose capacity / learner
  batch / lane count does not divide dp would silently fall back to
  the single-device program at run time, so the search refuses to
  score it as a dp candidate at all.
- **Monotone-in-B dominance**: with every other axis fixed, both the
  composed memory budget and the predicted throughput are monotone
  non-decreasing in the lane count B (throughput model:
  autotune/model.py; memory: more lanes = strictly more rollout
  residency and transient). So within a group only the LARGEST
  feasible B can win — the search walks B descending and marks the
  rest dominated without ever consulting the feasibility oracle.
"""

from dataclasses import dataclass, field

# Row statuses the search assigns to candidates (stdout table + JSON).
STATUS_FIT = "fit"  # oracle-confirmed feasible
STATUS_OVER = "over"  # oracle says over the byte limit
STATUS_GATE = "gate"  # failed a divisibility/geometry gate
STATUS_DOMINATED = "dominated"  # smaller B than a feasible sibling
STATUS_RING = "ring-over"  # ring math alone exceeds the limit
STATUS_SKIPPED = "skipped"  # search ended before evaluation


@dataclass(frozen=True)
class Candidate:
    """One point in the autotuner's search space.

    The kernel axes (docs/KERNELS.md) select interchangeable lowerings
    of the hot kernels plus the rollout inference precision. They are
    parity-pinned rewrites of the same math, so they never change WHAT
    a run computes — only how fast — and all but two are memory-free:
    `descent_gather="einsum"` adds a one-hot transient and
    `inference_precision="bfloat16"` a cast parameter copy, which is
    why exactly those two appear in `oracle_key()`."""

    geometry: str  # named board geometry (config/presets.py)
    sp_batch: int  # SELF_PLAY_BATCH_SIZE (lockstep lanes)
    capacity: int  # BUFFER_CAPACITY (replay ring rows)
    chunk: int  # ROLLOUT_CHUNK_MOVES (T)
    fused_k: int  # FUSED_LEARNER_STEPS (K)
    dp: int  # data-parallel mesh width tuned for
    descent_gather: str = "einsum"  # MCTSConfig.descent_gather
    backup_update: str = "xla"  # MCTSConfig.backup_update
    per_sample: str = "xla"  # TrainConfig.PER_SAMPLE_BACKEND
    inference_precision: str = "float32"  # ModelConfig.INFERENCE_PRECISION
    # Serve-shape ladder spec (serving/buckets.py): CSV rung list, ""
    # meaning a single fixed rung at the plan's serve batch. A serve-
    # side axis — it never changes training residency, so it is absent
    # from oracle_key() (free axis: ladders share feasibility answers).
    serve_buckets: str = ""
    # MCTSConfig.tree_reuse: NOT memory-free — reuse widens every tree
    # plane from max_simulations+1 to ~2x that many node slots, so it
    # appears in oracle_key() alongside the other residency-changing
    # axes. It is also the one kernel axis that changes per-move search
    # behavior (carried visits), not just lowering speed.
    tree_reuse: bool = False

    def group_key(self) -> tuple:
        """Axes held fixed under monotone-in-B dominance."""
        return (
            self.geometry,
            self.capacity,
            self.chunk,
            self.fused_k,
            self.dp,
            self.descent_gather,
            self.backup_update,
            self.per_sample,
            self.inference_precision,
            self.serve_buckets,
            self.tree_reuse,
        )

    def oracle_key(self) -> tuple:
        """Axes the feasibility oracle's answer can depend on. Kernel
        axes that only reorder the same buffer traffic (backup_update,
        per_sample) are deliberately absent: candidates differing only
        there share one oracle result (a free axis for the search)."""
        return (
            self.geometry,
            self.sp_batch,
            self.capacity,
            self.chunk,
            self.fused_k,
            self.dp,
            self.descent_gather,
            self.inference_precision,
            self.tree_reuse,
        )

    def kernels(self) -> dict:
        """The kernel-axis block (tuned_preset.json provenance)."""
        return {
            "descent_gather": self.descent_gather,
            "backup_update": self.backup_update,
            "per_sample": self.per_sample,
            "inference_precision": self.inference_precision,
            "serve_buckets": self.serve_buckets,
            "tree_reuse": self.tree_reuse,
        }

    def label(self) -> str:
        base = (
            f"{self.geometry}/B{self.sp_batch}/cap{self.capacity}"
            f"/t{self.chunk}/k{self.fused_k}/dp{self.dp}"
        )
        tags = [
            tag
            for tag, default in (
                (f"g-{self.descent_gather}", "g-einsum"),
                (f"b-{self.backup_update}", "b-xla"),
                (f"s-{self.per_sample}", "s-xla"),
                (f"p-{self.inference_precision}", "p-float32"),
                (f"sb-{self.serve_buckets}", "sb-"),
                (f"r-{'on' if self.tree_reuse else 'off'}", "r-off"),
            )
            if tag != default
        ]
        return base + (f"/{'+'.join(tags)}" if tags else "")


@dataclass
class SearchSpace:
    """Axis values the tuner enumerates (geometry names must exist in
    `config.presets.GEOMETRY_PRESETS` or equal the sentinel "plan",
    meaning the resolved bench plan's own board)."""

    geometries: list = field(default_factory=lambda: ["plan"])
    batches: list = field(default_factory=lambda: [256, 512, 1024])
    capacities: list = field(default_factory=lambda: [50_000, 100_000])
    chunks: list = field(default_factory=lambda: [8, 16])
    fused_ks: list = field(default_factory=lambda: [8, 16])
    dps: list = field(default_factory=lambda: [1])
    # Kernel axes (docs/KERNELS.md). Single-valued by default, so the
    # lattice only grows when a caller opts into the comparison; axes
    # sharing an oracle_key reuse the same feasibility answer.
    descent_gathers: list = field(default_factory=lambda: ["einsum"])
    backup_updates: list = field(default_factory=lambda: ["xla"])
    per_samples: list = field(default_factory=lambda: ["xla"])
    precisions: list = field(default_factory=lambda: ["float32"])
    # Serve-shape ladders ("" = fixed single rung; "64,256,1024" =
    # the micro-batcher's rung set). Free axis for the oracle.
    serve_bucket_ladders: list = field(default_factory=lambda: [""])
    tree_reuses: list = field(default_factory=lambda: [False])

    def candidates(self) -> list:
        """Every lattice point, B descending within each group so the
        dominance walk can early-exit on the first feasible lane count."""
        kernel_points = [
            (g, bu, ps, pr, sb, tr)
            for g in self.descent_gathers
            for bu in self.backup_updates
            for ps in self.per_samples
            for pr in self.precisions
            for sb in self.serve_bucket_ladders
            for tr in self.tree_reuses
        ]
        out = []
        for geometry in self.geometries:
            for capacity in sorted({int(c) for c in self.capacities}):
                for chunk in sorted({int(t) for t in self.chunks}):
                    for k in sorted({int(k) for k in self.fused_ks}):
                        for dp in sorted({int(d) for d in self.dps}):
                            for (
                                gather,
                                backup,
                                sample,
                                prec,
                                buckets,
                                reuse,
                            ) in kernel_points:
                                for b in sorted(
                                    {int(b) for b in self.batches},
                                    reverse=True,
                                ):
                                    out.append(
                                        Candidate(
                                            geometry=geometry,
                                            sp_batch=b,
                                            capacity=capacity,
                                            chunk=chunk,
                                            fused_k=k,
                                            dp=dp,
                                            descent_gather=gather,
                                            backup_update=backup,
                                            per_sample=sample,
                                            inference_precision=prec,
                                            serve_buckets=buckets,
                                            tree_reuse=reuse,
                                        )
                                    )
        return out

    def size(self) -> int:
        return (
            len(self.geometries)
            * len({int(b) for b in self.batches})
            * len({int(c) for c in self.capacities})
            * len({int(t) for t in self.chunks})
            * len({int(k) for k in self.fused_ks})
            * len({int(d) for d in self.dps})
            * len(self.descent_gathers)
            * len(self.backup_updates)
            * len(self.per_samples)
            * len(self.precisions)
            * len(self.serve_bucket_ladders)
            * len(self.tree_reuses)
        )


def divisibility_gate(
    candidate: Candidate, lbatch: int, min_buffer: int
) -> "str | None":
    """Reason string when a candidate fails a hard config gate, else
    None. Mirrors `sharded_megastep_dp` (telemetry/memory.py) plus the
    TrainConfig validators, so gated candidates are exactly the ones a
    run would reject or silently de-shard."""
    c = candidate
    if c.sp_batch < 1 or c.capacity < 1 or c.chunk < 1 or c.fused_k < 1:
        return "non-positive axis"
    if lbatch > c.capacity:
        return f"BATCH_SIZE {lbatch} > BUFFER_CAPACITY {c.capacity}"
    if min_buffer > c.capacity:
        return (
            f"MIN_BUFFER_SIZE_TO_TRAIN {min_buffer} > "
            f"BUFFER_CAPACITY {c.capacity}"
        )
    if c.dp > 1:
        # The sharded-megastep gate: every sharded dimension must
        # divide dp or the run falls back to the single-device family.
        for name, value in (
            ("BUFFER_CAPACITY", c.capacity),
            ("BATCH_SIZE", lbatch),
            ("SELF_PLAY_BATCH_SIZE", c.sp_batch),
        ):
            if value % c.dp != 0:
                return f"{name} {value} % dp {c.dp} != 0"
    return None


def prune_dominated(candidates: list, feasible: set) -> dict:
    """{candidate: status} marking every candidate whose group already
    holds a feasible sibling with a larger-or-equal B as dominated.

    `feasible` is the set of candidates the oracle confirmed. Used by
    the search to label rows; the search itself never oracle-checks a
    candidate once a bigger sibling fit (monotone-in-B dominance)."""
    best_b: dict = {}
    for c in feasible:
        key = c.group_key()
        if key not in best_b or c.sp_batch > best_b[key]:
            best_b[key] = c.sp_batch
    out = {}
    for c in candidates:
        top = best_b.get(c.group_key())
        if top is not None and c.sp_batch < top:
            out[c] = STATUS_DOMINATED
    return out
